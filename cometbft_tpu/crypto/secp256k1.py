"""secp256k1 ECDSA for non-validator keys (reference: crypto/secp256k1/secp256k1.go).

Pure-Python curve math (verification is not in the consensus hot path).
Matches the reference contract: 33-byte compressed pubkeys, 64-byte R||S
signatures with low-S enforcement on both sign and verify (the malleability
check at secp256k1.go:204-215), RFC 6979 deterministic nonces (btcec behavior),
message pre-hash SHA-256, and Bitcoin-style addresses
RIPEMD160(SHA256(pubkey)) (secp256k1.go:155-167).
"""

from __future__ import annotations

import hashlib
import hmac
import os

from cometbft_tpu import crypto

KEY_TYPE = "secp256k1"
PUB_KEY_SIZE = 33
PRIV_KEY_SIZE = 32
SIGNATURE_LENGTH = 64

PRIV_KEY_NAME = "tendermint/PrivKeySecp256k1"
PUB_KEY_NAME = "tendermint/PubKeySecp256k1"

# Curve parameters
_P = 2**256 - 2**32 - 977
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

_HALF_N = _N // 2


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


def _point_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if (y1 + y2) % _P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1, _P) % _P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, _P) % _P
    x3 = (lam * lam - x1 - x2) % _P
    y3 = (lam * (x1 - x3) - y1) % _P
    return (x3, y3)


def _scalar_mult(k: int, p):
    r = None
    while k > 0:
        if k & 1:
            r = _point_add(r, p)
        p = _point_add(p, p)
        k >>= 1
    return r


_G = (_GX, _GY)


def _compress(p) -> bytes:
    x, y = p
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def _decompress(b: bytes):
    if len(b) != 33 or b[0] not in (2, 3):
        return None
    x = int.from_bytes(b[1:], "big")
    if x >= _P:
        return None
    y2 = (pow(x, 3, _P) + 7) % _P
    y = pow(y2, (_P + 1) // 4, _P)
    if y * y % _P != y2:
        return None
    if y & 1 != b[0] & 1:
        y = _P - y
    return (x, y)


def _rfc6979_nonce(privkey: int, msg_hash: bytes) -> int:
    """Deterministic k per RFC 6979 with SHA-256."""
    x = privkey.to_bytes(32, "big")
    v = b"\x01" * 32
    key = b"\x00" * 32
    key = hmac.new(key, v + b"\x00" + x + msg_hash, hashlib.sha256).digest()
    v = hmac.new(key, v, hashlib.sha256).digest()
    key = hmac.new(key, v + b"\x01" + x + msg_hash, hashlib.sha256).digest()
    v = hmac.new(key, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(key, v, hashlib.sha256).digest()
        k = int.from_bytes(v, "big")
        if 1 <= k < _N:
            return k
        key = hmac.new(key, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(key, v, hashlib.sha256).digest()


class PubKey(crypto.PubKey):
    def __init__(self, data: bytes):
        self._bytes = bytes(data)

    def address(self) -> bytes:
        """RIPEMD160(SHA256(pubkey)) (secp256k1.go:155-167)."""
        sha = hashlib.sha256(self._bytes).digest()
        h = hashlib.new("ripemd160")
        h.update(sha)
        return h.digest()

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """R||S, rejecting high-S (secp256k1.go:190-217)."""
        if len(sig) != SIGNATURE_LENGTH:
            return False
        pub = _decompress(self._bytes)
        if pub is None:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (1 <= r < _N and 1 <= s < _N):
            return False
        if s > _HALF_N:  # malleability check
            return False
        e = int.from_bytes(hashlib.sha256(msg).digest(), "big") % _N
        w = _inv(s, _N)
        u1 = e * w % _N
        u2 = r * w % _N
        pt = _point_add(_scalar_mult(u1, _G), _scalar_mult(u2, pub))
        if pt is None:
            return False
        return pt[0] % _N == r

    def type(self) -> str:
        return KEY_TYPE


class PrivKey(crypto.PrivKey):
    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(f"secp256k1 privkey must be {PRIV_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._scalar = int.from_bytes(self._bytes, "big")
        if not (1 <= self._scalar < _N):
            raise ValueError("invalid secp256k1 scalar")

    def bytes(self) -> bytes:
        return self._bytes

    def sign(self, msg: bytes) -> bytes:
        """64-byte R||S with low-S normalization (secp256k1.go:135-146)."""
        e_bytes = hashlib.sha256(msg).digest()
        e = int.from_bytes(e_bytes, "big") % _N
        k = _rfc6979_nonce(self._scalar, e_bytes)
        while True:
            pt = _scalar_mult(k, _G)
            r = pt[0] % _N
            if r != 0:
                s = _inv(k, _N) * (e + r * self._scalar) % _N
                if s != 0:
                    break
            k = (k + 1) % _N or 1
        if s > _HALF_N:
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> PubKey:
        return PubKey(_compress(_scalar_mult(self._scalar, _G)))

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    """secp256k1.go:76-103 (rejection sampling)."""
    while True:
        raw = os.urandom(PRIV_KEY_SIZE)
        v = int.from_bytes(raw, "big")
        if 1 <= v < _N:
            return PrivKey(raw)


def gen_priv_key_from_secret(secret: bytes) -> PrivKey:
    """secp256k1.go:106-118: seed = SHA256(secret), must be in range."""
    seed = hashlib.sha256(secret).digest()
    v = int.from_bytes(seed, "big")
    if not (1 <= v < _N):
        raise ValueError("secret was not compatible with secp256k1")
    return PrivKey(seed)

"""SHA-256 wrappers with the 20-byte truncated variant.

Reference: crypto/tmhash/hash.go (Size=32, TruncatedSize=20).
"""

import hashlib

SIZE = 32
BLOCK_SIZE = 64
TRUNCATED_SIZE = 20


def new():
    return hashlib.sha256()


def sum(bz: bytes) -> bytes:  # noqa: A001 - mirrors reference name tmhash.Sum
    return hashlib.sha256(bz).digest()


def sum_truncated(bz: bytes) -> bytes:
    return hashlib.sha256(bz).digest()[:TRUNCATED_SIZE]

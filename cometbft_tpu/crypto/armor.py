"""ASCII armor for key serialization (reference: crypto/armor/armor.go).

OpenPGP-style armored blocks: header line, key/value headers, base64 body,
CRC-24 checksum, footer.
"""

from __future__ import annotations

import base64


def _crc24(data: bytes) -> int:
    crc = 0xB704CE
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= 0x1864CFB
    return crc & 0xFFFFFF


def encode_armor(block_type: str, headers: dict[str, str], data: bytes) -> str:
    lines = [f"-----BEGIN {block_type}-----"]
    for k, v in headers.items():
        lines.append(f"{k}: {v}")
    lines.append("")
    b64 = base64.b64encode(data).decode()
    for i in range(0, len(b64), 64):
        lines.append(b64[i : i + 64])
    crc = base64.b64encode(_crc24(data).to_bytes(3, "big")).decode()
    lines.append("=" + crc)
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


def decode_armor(armor_str: str) -> tuple[str, dict[str, str], bytes]:
    lines = [ln for ln in armor_str.strip().splitlines()]
    if not lines or not lines[0].startswith("-----BEGIN ") or not lines[0].endswith("-----"):
        raise ValueError("invalid armor: missing BEGIN line")
    block_type = lines[0][len("-----BEGIN ") : -len("-----")]
    if not lines[-1] == f"-----END {block_type}-----":
        raise ValueError("invalid armor: missing END line")
    headers: dict[str, str] = {}
    i = 1
    while i < len(lines) - 1 and lines[i].strip():
        if ":" not in lines[i]:
            break
        k, v = lines[i].split(":", 1)
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(lines) - 1 and not lines[i].strip():
        i += 1
    body_lines = []
    crc_line = None
    for ln in lines[i:-1]:
        if ln.startswith("="):
            crc_line = ln[1:]
        else:
            body_lines.append(ln)
    data = base64.b64decode("".join(body_lines))
    if crc_line is None:
        raise ValueError("invalid armor: missing CRC-24 checksum line")
    want = int.from_bytes(base64.b64decode(crc_line), "big")
    if _crc24(data) != want:
        raise ValueError("invalid armor: CRC mismatch")
    return block_type, headers, data

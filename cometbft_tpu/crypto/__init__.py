"""Crypto interfaces and address derivation.

Mirrors the reference's `crypto` package contract (crypto/crypto.go:22-54):
`PubKey`/`PrivKey` duck-typed interfaces, `BatchVerifier` — the seam through
which the TPU sidecar is selected — and `address = SHA256-20(pubkey bytes)`
(crypto/crypto.go:18-20).
"""

from __future__ import annotations

import abc
import hashlib
import os

from cometbft_tpu.crypto import tmhash

ADDRESS_SIZE = tmhash.TRUNCATED_SIZE  # crypto/crypto.go:10-12


def address_hash(bz: bytes) -> bytes:
    """SHA256-20 address of arbitrary bytes (crypto/crypto.go:18)."""
    return tmhash.sum_truncated(bz)


def sha256(bz: bytes) -> bytes:
    """crypto.Sha256 (crypto/hash.go)."""
    return hashlib.sha256(bz).digest()


def c_random(n: int) -> bytes:
    """Cryptographically secure random bytes (crypto.CReader, crypto/random.go)."""
    return os.urandom(n)


class PubKey(abc.ABC):
    """crypto.PubKey (crypto/crypto.go:27-33)."""

    @abc.abstractmethod
    def address(self) -> bytes: ...

    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...

    @abc.abstractmethod
    def type(self) -> str: ...

    def equals(self, other: "PubKey") -> bool:
        return type(self) is type(other) and self.bytes() == other.bytes()

    def __eq__(self, other) -> bool:
        return isinstance(other, PubKey) and self.equals(other)

    def __hash__(self) -> int:
        return hash((self.type(), self.bytes()))


class PrivKey(abc.ABC):
    """crypto.PrivKey (crypto/crypto.go:35-41)."""

    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abc.abstractmethod
    def pub_key(self) -> PubKey: ...

    @abc.abstractmethod
    def type(self) -> str: ...

    def equals(self, other: "PrivKey") -> bool:
        return type(self) is type(other) and self.bytes() == other.bytes()


class BatchVerifier(abc.ABC):
    """crypto.BatchVerifier (crypto/crypto.go:46-54).

    `add()` appends an entry; `verify()` returns (all_valid, per_entry_valid)
    in insertion order. The TPU device tier plugs in at this seam.
    """

    @abc.abstractmethod
    def add(self, key: PubKey, message: bytes, signature: bytes) -> None: ...

    @abc.abstractmethod
    def verify(self) -> tuple[bool, list[bool]]: ...

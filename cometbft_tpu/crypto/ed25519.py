"""Ed25519 key types with ZIP-215 verification (reference: crypto/ed25519/ed25519.go).

Key layout matches the reference: PrivKey = 64 bytes (seed || pubkey)
(ed25519.go:71-80), PubKey = 32 bytes, Signature = 64 bytes, address =
SHA256-20(pubkey) (ed25519.go:162-168).

Verification strategy (host tier): try the C-speed strict RFC 8032 verifier
from `cryptography` first — its acceptance set is a subset of ZIP-215's — and
only on rejection fall back to the pure-Python cofactored ZIP-215 check, so
honest signatures verify at library speed while adversarial edge encodings
still get exact ZIP-215 semantics (reference uses curve25519-voi with
VerifyOptionsZIP_215, ed25519.go:27-29). Bulk verification goes through the
TPU batch verifier instead (cometbft_tpu/ops/ed25519_kernel.py).
"""

from __future__ import annotations

import hashlib
import os
import threading

from cometbft_tpu import crypto
from cometbft_tpu.crypto import ed25519_pure, tmhash
from cometbft_tpu.crypto.compat import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
    InvalidSignature,
)

KEY_TYPE = "ed25519"
PUB_KEY_SIZE = 32
PRIVATE_KEY_SIZE = 64
SIGNATURE_SIZE = 64
SEED_SIZE = 32

PRIV_KEY_NAME = "tendermint/PrivKeyEd25519"
PUB_KEY_NAME = "tendermint/PubKeyEd25519"

# Expanded-pubkey verification cache analog (reference ed25519.go:31,56
# cacheSize=4096): we cache parsed `cryptography` pubkey handles.
_CACHE_SIZE = 4096
_pubkey_cache: dict[bytes, Ed25519PublicKey] = {}


def _cached_pubkey(pub: bytes) -> Ed25519PublicKey | None:
    h = _pubkey_cache.get(pub)
    if h is None:
        try:
            h = Ed25519PublicKey.from_public_bytes(pub)
        except Exception:
            return None
        if len(_pubkey_cache) >= _CACHE_SIZE:
            _pubkey_cache.pop(next(iter(_pubkey_cache)))
        _pubkey_cache[pub] = h
    return h


class PubKey(crypto.PubKey):
    def __init__(self, data: bytes):
        self._bytes = bytes(data)

    def address(self) -> bytes:
        if len(self._bytes) != PUB_KEY_SIZE:
            raise ValueError("pubkey is incorrect size")
        return tmhash.sum_truncated(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE or len(self._bytes) != PUB_KEY_SIZE:
            return False
        # The verified-triple cache serves single verifies too: the
        # consensus loop batch-pre-verifies drained vote queues and fast
        # sync pre-verifies block windows, so the per-vote/per-commit
        # checks that follow land here already proven.
        key = (self._bytes, bytes(sig), bytes(msg))
        if key in _verified:
            return True
        handle = _cached_pubkey(self._bytes)
        if handle is not None:
            try:
                handle.verify(sig, msg)
                _verified_put(key)
                return True
            except InvalidSignature:
                pass
        # Fast path rejected: settle edge cases under exact ZIP-215 rules.
        ok = ed25519_pure.verify_zip215(self._bytes, msg, sig)
        if ok:
            _verified_put(key)
        return ok

    def type(self) -> str:
        return KEY_TYPE

    def __repr__(self) -> str:
        return f"PubKeyEd25519{{{self._bytes.hex().upper()}}}"


class PrivKey(crypto.PrivKey):
    def __init__(self, data: bytes):
        if len(data) != PRIVATE_KEY_SIZE:
            raise ValueError(f"ed25519 privkey must be {PRIVATE_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._handle = Ed25519PrivateKey.from_private_bytes(self._bytes[:SEED_SIZE])

    def bytes(self) -> bytes:
        return self._bytes

    def sign(self, msg: bytes) -> bytes:
        return self._handle.sign(msg)

    def pub_key(self) -> PubKey:
        if not any(self._bytes[32:]):
            raise ValueError("expected ed25519 PrivKey to include concatenated pubkey bytes")
        return PubKey(self._bytes[32:])

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    """GenPrivKey (ed25519.go:124-135)."""
    seed = crypto.c_random(SEED_SIZE)
    return _from_seed(seed)


def gen_priv_key_from_secret(secret: bytes) -> PrivKey:
    """GenPrivKeyFromSecret (ed25519.go:141-148): seed = SHA256(secret)."""
    return _from_seed(hashlib.sha256(secret).digest())


def _from_seed(seed: bytes) -> PrivKey:
    handle = Ed25519PrivateKey.from_private_bytes(seed)
    pub = handle.public_key().public_bytes_raw()
    return PrivKey(seed + pub)


# Verified-triple cache: the device analog of the reference's caching
# verifier seam (ed25519.go:31-56 caches EXPANDED KEYS; here whole verified
# (pub, sig, msg) triples are cached, because fast sync verifies every
# commit twice — VerifyCommitLight in blocksync's trySync, then the full
# VerifyCommit in ApplyBlock's validation — and the blocksync reactor
# pre-verifies whole windows of blocks in one device dispatch). Only VALID
# results are cached (deterministic; an attacker replaying a valid triple
# gets the same answer crypto would give), keyed by the (pub, sig, msg)
# TUPLE — bytes objects hash once and cache it, so tuple keys skip the
# per-lookup concatenation a bytes key would pay (~8 MB of copies per
# 10k-commit cached verify). Bounded (`CMTPU_VERIFY_CACHE_MAX`, mirroring
# the _CACHE_SIZE pubkey-cache pattern): oldest quarter evicted on
# overflow, re-verified triples refreshed to the young end, so a
# long-running node under heavy traffic holds its working set instead of
# growing without limit.
_VERIFIED_MAX = int(os.environ.get("CMTPU_VERIFY_CACHE_MAX", "") or 131072)
_verified: dict[tuple, None] = {}
_verified_lock = threading.Lock()


def _verified_put_many(keys: list[tuple]) -> None:
    """Insert verified triples under one lock acquisition (10k inserts after
    a commit verify would otherwise take the lock 10k times).  Writers race
    from multiple threads (blocksync pool routine, consensus, light client);
    eviction shares the lock so list(dict) never races an insert.  The
    oldest-quarter eviction repeats until the bound holds, so even a batch
    larger than a quarter of the cache cannot push it past _VERIFIED_MAX."""
    if not keys:
        return
    with _verified_lock:
        for key in keys:
            if key in _verified:
                # LRU refresh: a re-verified triple moves to the young end
                # (dict order is insertion order), so hot validators survive
                # eviction sweeps.
                del _verified[key]
            elif len(_verified) >= _VERIFIED_MAX:
                for k in list(_verified)[: max(1, _VERIFIED_MAX // 4)]:
                    _verified.pop(k, None)
            _verified[key] = None


def _verified_put(key: tuple) -> None:
    _verified_put_many([key])


def mark_self_signed(pub: bytes, msg: bytes, sig: bytes) -> None:
    """Seed the verified cache with a signature THIS process just produced
    with its own private key. Signing is deterministic and the signer needs
    no cryptographic evidence about itself, so re-verifying an own vote on
    admission (state.go does) is pure overhead — material on the pure-Python
    scalar fallback, where one skipped verify saves milliseconds."""
    _verified_put((bytes(pub), bytes(sig), bytes(msg)))


class BatchVerifier(crypto.BatchVerifier):
    """Ed25519 batch verification (ed25519.go:196-228).

    Entries accumulate host-side; `verify()` dispatches the whole batch to the
    configured backend (TPU sidecar by default when a device is present,
    pure-CPU otherwise) — the same seam as the reference's
    cachingVerifier.AddWithOptions + BatchVerifier.Verify.
    """

    def __init__(self):
        self._pubs: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []

    def add(self, key: crypto.PubKey, message: bytes, signature: bytes) -> None:
        if not isinstance(key, PubKey):
            raise TypeError("pubkey is not Ed25519")
        pk = key.bytes()
        if len(pk) != PUB_KEY_SIZE:
            raise ValueError(
                f"pubkey size is incorrect; expected: {PUB_KEY_SIZE}, got {len(pk)}"
            )
        if len(signature) != SIGNATURE_SIZE:
            raise ValueError("invalid signature")
        self._pubs.append(pk)
        self._msgs.append(bytes(message))
        self._sigs.append(bytes(signature))

    def __len__(self) -> int:
        return len(self._pubs)

    def verify(self) -> tuple[bool, list[bool]]:
        from cometbft_tpu.sidecar.backend import get_backend
        from cometbft_tpu.sidecar.supervisor import ChainExhausted

        if not self._pubs:
            return False, []
        # Dispatch only the triples the cache cannot answer, deduplicating
        # repeats within the batch (the light client's trusting and light
        # checks of one hop share most of their triples; bisection descents
        # revisit pivot commits). lane_of records each unique uncached
        # triple's lane in the sub-batch; cached/duplicate entries resolve
        # from it after the dispatch. Membership is decided ONCE here —
        # concurrent writers may grow the cache mid-verify, and the merge
        # below must honor the filter's snapshot, not a fresher one.
        keys = list(zip(self._pubs, self._sigs, self._msgs))
        lane_of: dict[tuple, int] = {}
        lanes: list[int] = []  # per-entry lane, -1 = cache hit
        sub_pubs: list[bytes] = []
        sub_msgs: list[bytes] = []
        sub_sigs: list[bytes] = []
        for key in keys:
            if key in _verified:
                lanes.append(-1)
                continue
            lane = lane_of.get(key)
            if lane is None:
                lane = len(sub_pubs)
                lane_of[key] = lane
                sub_pubs.append(key[0])
                sub_msgs.append(key[2])
                sub_sigs.append(key[1])
            lanes.append(lane)
        if not sub_pubs:
            return True, [True] * len(keys)
        try:
            _, sub_bits = get_backend().batch_verify(sub_pubs, sub_msgs, sub_sigs)
        except ChainExhausted:
            # Every tier of the supervised chain failed (chaos runs can
            # arrange this). Consensus liveness outranks batch speed:
            # verify each signature through the scalar ZIP-215 path.
            sub_bits = [
                ed25519_pure.verify_zip215(p, m, s)
                for p, m, s in zip(sub_pubs, sub_msgs, sub_sigs)
            ]
        bits = [True if lane < 0 else sub_bits[lane] for lane in lanes]
        _verified_put_many(
            [k for k, lane in zip(keys, lanes) if lane >= 0 and sub_bits[lane]]
        )
        return all(bits), bits

"""Micro-batched scalar signature verification for the consensus hot path.

`VoteSet.add_vote` (and evidence duplicate-vote checks) verify ONE signature
at a time, but under gossip many admissions run concurrently — one per peer
connection, across every in-process node in devnet. This module gives those
scalar callers consensus-class admission into the continuous-batching
verification engine (round 14, `sidecar/engine.py`): each caller submits
its pending triples tagged CLASS_CONSENSUS and the engine merges everything
queued — across vote sets, peers AND the other traffic classes — into the
next device dispatch, draining votes ahead of bulk work under a deadline
bound. Cache semantics are unchanged: pending triples are filtered against
the verified-triple cache here and only VALID dispatched triples populate
it afterward.

When no engine is active (`CMTPU_COALESCE=0`, or a bare backend installed
by tests/bench) the round-12 private window dispatcher runs instead:
callers block on a shared window (`CMTPU_VOTE_BATCH_WINDOW_MS`, default
2 ms from the first waiter) and a dispatcher merges everything queued into
ONE `ed25519.BatchVerifier` call.

Failure containment is identical on both paths: a bad signature is just a
False lane (never poisons the window), and any dispatch-level error —
including a result not arriving within the deadline-derived timeout —
degrades each request independently to the scalar `verify_signature` path.
Window 0 (the env off switch) keeps the inline scalar behavior exactly.
"""

from __future__ import annotations

import os
import threading
import time

_DEFAULT_WINDOW_MS = 2.0
# A caller never waits forever on the dispatcher: consensus liveness
# outranks batching, so a wedged dispatch degrades to scalar verification.
# Used verbatim only when no supervisor deadline is configured — see
# _result_timeout_s().
_RESULT_TIMEOUT_S = 30.0


def _result_timeout_s() -> float:
    """How long a caller waits on a dispatch result before degrading to
    scalar verification. With a supervised per-call deadline configured
    (`CMTPU_DEADLINE_MS`), the worst honest wall is every tier of the
    chain burning its retries under that deadline — wait that long, not a
    hard-coded 30 s, so a wedge degrades in one supervised exhaustion.
    Deadline 0/unset keeps the legacy 30 s backstop."""
    try:
        deadline_ms = float(os.environ.get("CMTPU_DEADLINE_MS", "") or 0.0)
    except ValueError:
        deadline_ms = 0.0
    if deadline_ms <= 0:
        return _RESULT_TIMEOUT_S
    try:
        retries = int(os.environ.get("CMTPU_RETRIES", "") or 2)
    except ValueError:
        retries = 2
    # <= 3 tiers (grpc|tpu -> hybrid -> cpu), each (retries+1) attempts.
    return max(1.0, deadline_ms / 1000.0 * (retries + 1) * 3)


class _Req:
    __slots__ = ("pubs", "msgs", "sigs", "event", "bits")

    def __init__(self, pubs, msgs, sigs):
        self.pubs = pubs
        self.msgs = msgs
        self.sigs = sigs
        self.event = threading.Event()
        self.bits: list[bool] | None = None


class SigBatcher:
    """Window-from-first-waiter batcher over `ed25519.BatchVerifier`.

    `inline` (bench/test hook) dispatches each request through the batch
    verifier immediately with no window and no dispatcher thread — the
    "one device dispatch per vote" arm of an A/B comparison.
    """

    def __init__(self, window_ms: float | None = None, max_sigs: int = 4096,
                 inline: bool = False):
        if window_ms is None:
            window_ms = float(
                os.environ.get("CMTPU_VOTE_BATCH_WINDOW_MS", "") or _DEFAULT_WINDOW_MS
            )
        self.window_ms = window_ms
        self.max_sigs = max_sigs
        self.inline = inline
        self.result_timeout_s = _result_timeout_s()
        self._cond = threading.Condition()
        self._queue: list[_Req] = []
        self._thread: threading.Thread | None = None
        self._closed = False
        # Counters (read by the lazy node gauges; mutate under _cond).
        self.requests = 0
        self.batched = 0  # requests that rode a shared dispatch
        self.dispatches = 0
        self.dispatched_sigs = 0
        self.cache_hits = 0
        self.scalar_direct = 0
        self.fallbacks = 0
        self.max_batch = 0

    # -- public API -----------------------------------------------------------

    def verify_one(self, pub_key, msg: bytes, sig: bytes) -> bool:
        return self.verify_many([pub_key], [msg], [sig])[0]

    def verify_many(self, pub_keys, msgs, sigs) -> list[bool]:
        from cometbft_tpu.crypto import ed25519 as _ed

        n = len(pub_keys)
        bits: list[bool | None] = [None] * n
        pend: list[int] = []
        cache_hits = scalar = 0
        for i in range(n):
            pk = pub_keys[i]
            if not isinstance(pk, _ed.PubKey):
                # Only ed25519 has a batch backend; exotic key types keep
                # their own scalar verify.
                bits[i] = bool(pk.verify_signature(msgs[i], sigs[i]))
                scalar += 1
            elif (
                len(sigs[i]) != _ed.SIGNATURE_SIZE
                or len(pk.bytes()) != _ed.PUB_KEY_SIZE
            ):
                # Structurally impossible — reject without letting it poison
                # a batch (BatchVerifier.add raises on bad sizes).
                bits[i] = False
            elif (pk.bytes(), bytes(sigs[i]), bytes(msgs[i])) in _ed._verified:
                # Gossip re-delivery and own-vote echo land here: free.
                bits[i] = True
                cache_hits += 1
            else:
                pend.append(i)
        with self._cond:
            self.requests += 1
            self.cache_hits += cache_hits
            self.scalar_direct += scalar
        if not pend:
            return bits  # type: ignore[return-value]
        if self.window_ms <= 0 and not self.inline:
            # Off switch: today's inline scalar path, verbatim.
            for i in pend:
                bits[i] = bool(pub_keys[i].verify_signature(msgs[i], sigs[i]))
            with self._cond:
                self.scalar_direct += len(pend)
            return bits  # type: ignore[return-value]
        if not self.inline:
            eng = self._engine()
            if eng is not None:
                # Continuous-batching path: no private window thread — the
                # engine merges concurrent admissions (and the other
                # traffic classes) itself, votes first.
                pbits = self._engine_dispatch(eng, pub_keys, msgs, sigs, pend)
                for j, i in enumerate(pend):
                    bits[i] = pbits[j]
                return bits  # type: ignore[return-value]
        req = _Req(
            [pub_keys[i] for i in pend],
            [msgs[i] for i in pend],
            [sigs[i] for i in pend],
        )
        if self.inline:
            self._dispatch([req])
        else:
            with self._cond:
                self._queue.append(req)
                if self._thread is None or not self._thread.is_alive():
                    self._thread = threading.Thread(
                        target=self._run, name="sigbatch", daemon=True
                    )
                    self._thread.start()
                self._cond.notify_all()
            if not req.event.wait(self.result_timeout_s):
                req.bits = [
                    bool(pk.verify_signature(m, s))
                    for pk, m, s in zip(req.pubs, req.msgs, req.sigs)
                ]
        for j, i in enumerate(pend):
            bits[i] = bool(req.bits[j])
        return bits  # type: ignore[return-value]

    # -- engine path ----------------------------------------------------------

    @staticmethod
    def _engine():
        """The active continuous-batching engine, or None when the backend
        chain runs bare (`CMTPU_COALESCE=0`, or a test-installed backend) —
        the legacy private-window dispatcher serves those."""
        from cometbft_tpu.sidecar import backend as _be
        from cometbft_tpu.sidecar import engine as _engine

        try:
            return _engine.engine_of(_be.get_backend())
        except Exception:
            return None

    def _engine_dispatch(self, eng, pub_keys, msgs, sigs, pend) -> list[bool]:
        """Submit the pending triples consensus-class and wait. Decision
        path matches the legacy dispatcher bit for bit: only VALID
        dispatched triples populate the verified cache, and any failure —
        engine error, chain exhaustion surfacing as an exception, or the
        deadline-derived timeout — degrades THIS request alone to the
        scalar anchor."""
        from cometbft_tpu.crypto import ed25519 as _ed
        from cometbft_tpu.sidecar.engine import CLASS_CONSENSUS

        pubs = [pub_keys[i].bytes() for i in pend]
        ms = [bytes(msgs[i]) for i in pend]
        ss = [bytes(sigs[i]) for i in pend]
        try:
            fut = eng.submit(pubs, ms, ss, klass=CLASS_CONSENSUS)
            _, rbits = fut.result(self.result_timeout_s)
            rbits = [bool(b) for b in rbits]
        except Exception:
            with self._cond:
                self.fallbacks += 1
            return [
                bool(pub_keys[i].verify_signature(msgs[i], sigs[i]))
                for i in pend
            ]
        _ed._verified_put_many(
            [(p, s, m) for p, m, s, b in zip(pubs, ms, ss, rbits) if b]
        )
        with self._cond:
            self.dispatches += 1
            self.dispatched_sigs += len(pend)
            if fut.shared:
                self.batched += 1
            self.max_batch = max(self.max_batch, len(pend))
        return rbits

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def counters(self) -> dict:
        with self._cond:
            return {
                "requests": self.requests,
                "batched": self.batched,
                "dispatches": self.dispatches,
                "dispatched_sigs": self.dispatched_sigs,
                "cache_hits": self.cache_hits,
                "scalar_direct": self.scalar_direct,
                "fallbacks": self.fallbacks,
                "max_batch": self.max_batch,
            }

    # -- dispatcher -----------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
            # Window from the FIRST waiter (scheduler/ingress idiom): the
            # request that opened the window pays it once; everything that
            # arrives inside rides free.
            if self.window_ms > 0:
                time.sleep(self.window_ms / 1000.0)
            with self._cond:
                batch: list[_Req] = []
                total = 0
                while self._queue:
                    nxt = len(self._queue[0].pubs)
                    if batch and total + nxt > self.max_sigs:
                        break  # whole requests only; rest opens a new window
                    total += nxt
                    batch.append(self._queue.pop(0))
            if batch:
                self._dispatch(batch)

    def _dispatch(self, reqs: list[_Req]) -> None:
        from cometbft_tpu.crypto import ed25519 as _ed

        total = sum(len(r.pubs) for r in reqs)
        try:
            bv = _ed.BatchVerifier()
            for r in reqs:
                for pk, m, s in zip(r.pubs, r.msgs, r.sigs):
                    bv.add(pk, m, s)
            # BatchVerifier.verify(): cache filter + dedup, scheduler →
            # supervised chain, ZIP-215 scalar fallback on ChainExhausted.
            _, bits = bv.verify()
        except Exception:
            # Per-request isolation: degrade each request to the scalar
            # anchor independently — one hostile entry or a backend crash
            # must never reject a whole window of valid votes.
            with self._cond:
                self.fallbacks += len(reqs)
            for r in reqs:
                try:
                    r.bits = [
                        bool(pk.verify_signature(m, s))
                        for pk, m, s in zip(r.pubs, r.msgs, r.sigs)
                    ]
                except Exception:
                    r.bits = [False] * len(r.pubs)
                r.event.set()
            return
        with self._cond:
            self.dispatches += 1
            self.dispatched_sigs += total
            if len(reqs) > 1:
                self.batched += len(reqs)
            self.max_batch = max(self.max_batch, total)
        i = 0
        for r in reqs:
            n = len(r.pubs)
            r.bits = [bool(b) for b in bits[i : i + n]]
            i += n
            r.event.set()


# -- module singleton ---------------------------------------------------------

_batcher: SigBatcher | None = None
_lock = threading.Lock()


def get_batcher() -> SigBatcher:
    """The process-wide batcher (constructed lazily from env)."""
    global _batcher
    b = _batcher
    if b is None:
        with _lock:
            if _batcher is None:
                _batcher = SigBatcher()
            b = _batcher
    return b


def set_batcher(b: SigBatcher | None) -> SigBatcher | None:
    """Install a batcher (tests/bench); returns the previous one."""
    global _batcher
    with _lock:
        old, _batcher = _batcher, b
    return old


def reset() -> None:
    """Drop the singleton so the next use re-reads env knobs."""
    set_batcher(None)


def verify_vote_signature(pub_key, msg: bytes, sig: bytes) -> bool:
    return get_batcher().verify_one(pub_key, msg, sig)


def verify_triples(pub_keys, msgs, sigs) -> list[bool]:
    return get_batcher().verify_many(pub_keys, msgs, sigs)


def counters() -> dict:
    """Counters WITHOUT constructing a batcher (lazy metric scrapes)."""
    b = _batcher
    if b is None:
        return {
            "requests": 0, "batched": 0, "dispatches": 0, "dispatched_sigs": 0,
            "cache_hits": 0, "scalar_direct": 0, "fallbacks": 0, "max_batch": 0,
        }
    return b.counters()

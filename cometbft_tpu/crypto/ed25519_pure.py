"""Pure-Python edwards25519 with ZIP-215 verification semantics.

This is the framework's correctness anchor: the TPU batch kernel
(cometbft_tpu/ops/ed25519_kernel.py) and the fast host path
(cometbft_tpu/crypto/ed25519.py) are both tested against it.

Semantics mirror the reference's verifier configuration
(crypto/ed25519/ed25519.go:27-29: curve25519-voi with VerifyOptionsZIP_215):
  - A and R encodings may be non-canonical (y >= p accepted);
  - x=0 with sign bit 1 fails decoding (RFC 8032 §5.1.3 rule kept);
  - s must be canonical (s < L);
  - verification uses the cofactored equation [8][s]B = [8]R + [8][k]A.
"""

from __future__ import annotations

import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

# Extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z, T=XY/Z.
IDENTITY = (0, 1, 1, 0)

# Base point
_BY = (4 * pow(5, P - 2, P)) % P
_BX = None  # set below


def _recover_x(y: int, sign: int) -> int | None:
    """x from y via sqrt((y^2-1)/(d y^2+1)); None if no root or x=0 with sign=1."""
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # candidate root of u/v: x = u v^3 (u v^7)^((p-5)/8)
    x = (u * pow(v, 3, P)) % P * pow((u * pow(v, 7, P)) % P, (P - 5) // 8, P) % P
    vxx = v * x % P * x % P
    if vxx == u:
        pass
    elif vxx == (P - u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x == 0 and sign == 1:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
BASE = (_BX, _BY, 1, _BX * _BY % P)


def point_add(p1, p2):
    """add-2008-hwcd-3 for a=-1 twisted Edwards (unified, complete)."""
    X1, Y1, Z1, T1 = p1
    X2, Y2, Z2, T2 = p2
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * D * T1 % P * T2 % P
    Dd = 2 * Z1 * Z2 % P
    E = B - A
    F = Dd - C
    G = Dd + C
    H = B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p):
    return point_add(p, p)


def point_neg(p):
    X, Y, Z, T = p
    return ((P - X) % P, Y, Z, (P - T) % P)


def scalar_mult(k: int, p):
    """Double-and-add; variable time (verification only, not secret-dependent)."""
    q = IDENTITY
    while k > 0:
        if k & 1:
            q = point_add(q, p)
        p = point_double(p)
        k >>= 1
    return q


def point_equal(p1, p2) -> bool:
    X1, Y1, Z1, _ = p1
    X2, Y2, Z2, _ = p2
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def point_compress(p) -> bytes:
    X, Y, Z, _ = p
    zinv = pow(Z, P - 2, P)
    x = X * zinv % P
    y = Y * zinv % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def point_decompress_zip215(s: bytes):
    """Decompress allowing non-canonical y (ZIP-215 rule 1); None on failure."""
    if len(s) != 32:
        return None
    enc = int.from_bytes(s, "little")
    sign = enc >> 255
    y = (enc & ((1 << 255) - 1)) % P  # non-canonical y >= p is reduced, not rejected
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def point_decompress_canonical(s: bytes):
    """Strict RFC 8032 decoding: y must be canonical (< p)."""
    if len(s) != 32:
        return None
    enc = int.from_bytes(s, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    if y >= P:
        return None
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def sha512_mod_l(*chunks: bytes) -> int:
    h = hashlib.sha512()
    for c in chunks:
        h.update(c)
    return int.from_bytes(h.digest(), "little") % L


def secret_expand(seed: bytes) -> tuple[int, bytes]:
    """RFC 8032 §5.1.5: clamped scalar + hash prefix from a 32-byte seed."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key(seed: bytes) -> bytes:
    a, _ = secret_expand(seed)
    return point_compress(scalar_mult(a, BASE))


def sign(seed: bytes, pub: bytes, msg: bytes) -> bytes:
    """RFC 8032 §5.1.6."""
    a, prefix = secret_expand(seed)
    r = sha512_mod_l(prefix, msg)
    R = scalar_mult(r, BASE)
    Rs = point_compress(R)
    k = sha512_mod_l(Rs, pub, msg)
    s = (r + k * a) % L
    return Rs + int.to_bytes(s, 32, "little")


def verify_zip215(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Single-signature ZIP-215 verification (the acceptance set the TPU batch
    kernel and the reference's verifier share)."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    A = point_decompress_zip215(pub)
    if A is None:
        return False
    Rs = sig[:32]
    R = point_decompress_zip215(Rs)
    if R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = sha512_mod_l(Rs, pub, msg)
    # [8][s]B == [8]R + [8][k]A  ⇔  [8]([s]B - [k]A - R) == identity
    sB = scalar_mult(s, BASE)
    kA = scalar_mult(k, A)
    diff = point_add(point_add(sB, point_neg(kA)), point_neg(R))
    eight_diff = point_double(point_double(point_double(diff)))
    return point_equal(eight_diff, IDENTITY)


def batch_verify_zip215(
    pubs: list[bytes], msgs: list[bytes], sigs: list[bytes], rand_bytes=None
) -> tuple[bool, list[bool]]:
    """Batch equation with 128-bit random coefficients; falls back to
    per-signature verification to produce the validity vector on failure —
    the (bool, []bool) contract of crypto.BatchVerifier (crypto/crypto.go:46)."""
    import os

    n = len(pubs)
    assert len(msgs) == n and len(sigs) == n
    if n == 0:
        return False, []
    entries = []
    ok_shape = [True] * n
    for i in range(n):
        if len(sigs[i]) != 64 or len(pubs[i]) != 32:
            ok_shape[i] = False
            continue
        A = point_decompress_zip215(pubs[i])
        R = point_decompress_zip215(sigs[i][:32])
        s = int.from_bytes(sigs[i][32:], "little")
        if A is None or R is None or s >= L:
            ok_shape[i] = False
            continue
        k = sha512_mod_l(sigs[i][:32], pubs[i], msgs[i])
        entries.append((i, A, R, s, k))
    if not all(ok_shape):
        # Shape/decode failure: report per-signature results individually.
        results = [
            ok_shape[i] and verify_zip215(pubs[i], msgs[i], sigs[i]) for i in range(n)
        ]
        return all(results), results
    # sum_i z_i (s_i B - R_i - k_i A_i) == identity (cofactored)
    rb = rand_bytes or (lambda: os.urandom(16))
    s_acc = 0
    acc = IDENTITY
    for (_, A, R, s, k) in entries:
        z = int.from_bytes(rb(), "little") | 1
        s_acc = (s_acc + z * s) % L
        acc = point_add(acc, scalar_mult(z, point_add(R, scalar_mult(k % L, A))))
    lhs = scalar_mult(s_acc, BASE)
    diff = point_add(lhs, point_neg(acc))
    eight_diff = point_double(point_double(point_double(diff)))
    if point_equal(eight_diff, IDENTITY):
        return True, [True] * n
    results = [verify_zip215(pubs[i], msgs[i], sigs[i]) for i in range(n)]
    return all(results), results

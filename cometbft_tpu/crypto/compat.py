"""Optional-dependency shim for the `cryptography` package.

The C-backed `cryptography` wheel is the preferred provider for Ed25519
signing, X25519 ECDH, and ChaCha20-Poly1305 (library-speed hot paths), but
it is not part of the baked toolchain on every host this repo runs on.
Everything it provides here has an exact pure-Python equivalent — ed25519
via crypto/ed25519_pure (already the ZIP-215 arbiter), X25519 via RFC 7748
on the same curve field, ChaCha20-Poly1305 via RFC 8439 (the ChaCha core is
shared with crypto/xchacha20poly1305's HChaCha20) — so this module exports
one set of names and picks the provider at import time:

    from cometbft_tpu.crypto.compat import (
        HAVE_CRYPTOGRAPHY, InvalidSignature, InvalidTag,
        Ed25519PrivateKey, Ed25519PublicKey,
        X25519PrivateKey, X25519PublicKey, ChaCha20Poly1305,
    )

The pure tier is slower (≈2 ms/sign, ≈4 ms/verify, ≈1 ms per 1 KiB AEAD
frame) but correct and wire-identical; consensus at e2e block intervals
(200 ms+) is unaffected.  Nothing outside this module may import
`cryptography` directly.

For the AEAD specifically there is a middle tier: when the wheel is absent
but the interpreter's own OpenSSL (`libcrypto`, already loaded for the ssl
module) exposes `EVP_chacha20_poly1305`, a ctypes binding provides
library-speed seal/open.  The pure tier's ≈1 ms/KiB is fatal on the p2p
secret-connection hot path — every 1 KiB wire frame is sealed+opened once
per hop, so a multi-node host caps out at a few dozen KiB/s per connection
and block parts outlive the propose timeout.  The binding is cross-checked
against the pure RFC 8439 implementation at import; any mismatch (or a
libcrypto without the cipher) falls back to pure.  `AEAD_PROVIDER` names
the active tier ("cryptography" | "libcrypto" | "pure");
`CMTPU_PURE_AEAD=1` forces the pure tier for A/B and tests.
"""

from __future__ import annotations

import hmac as _hmac
import os
import struct

try:  # pragma: no cover - exercised implicitly on hosts that have the wheel
    from cryptography.exceptions import InvalidSignature, InvalidTag
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

    HAVE_CRYPTOGRAPHY = True
except ImportError:
    HAVE_CRYPTOGRAPHY = False

    class InvalidSignature(Exception):
        pass

    class InvalidTag(Exception):
        pass

    # -- Ed25519 (backed by crypto/ed25519_pure) ---------------------------

    class Ed25519PublicKey:
        def __init__(self, raw: bytes):
            from cometbft_tpu.crypto import ed25519_pure

            if len(raw) != 32:
                raise ValueError("ed25519 public key must be 32 bytes")
            # Reject encodings that don't decompress at all (parity with
            # from_public_bytes raising on malformed keys).
            if ed25519_pure.point_decompress_zip215(bytes(raw)) is None:
                raise ValueError("invalid ed25519 public key")
            self._raw = bytes(raw)

        @classmethod
        def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
            return cls(raw)

        def public_bytes_raw(self) -> bytes:
            return self._raw

        def verify(self, signature: bytes, data: bytes) -> None:
            from cometbft_tpu.crypto import ed25519_pure

            # ZIP-215 is a superset of the strict RFC 8032 acceptance set;
            # callers that need the exact strict subset (none do today — the
            # consensus arbiter IS ZIP-215) would need a dedicated check.
            if not ed25519_pure.verify_zip215(
                self._raw, bytes(data), bytes(signature)
            ):
                raise InvalidSignature("signature verification failed")

    class Ed25519PrivateKey:
        def __init__(self, seed: bytes):
            from cometbft_tpu.crypto import ed25519_pure

            if len(seed) != 32:
                raise ValueError("ed25519 seed must be 32 bytes")
            self._seed = bytes(seed)
            self._pub = ed25519_pure.public_key(self._seed)

        @classmethod
        def from_private_bytes(cls, seed: bytes) -> "Ed25519PrivateKey":
            return cls(seed)

        @classmethod
        def generate(cls) -> "Ed25519PrivateKey":
            return cls(os.urandom(32))

        def private_bytes_raw(self) -> bytes:
            return self._seed

        def public_key(self) -> Ed25519PublicKey:
            return Ed25519PublicKey(self._pub)

        def sign(self, data: bytes) -> bytes:
            from cometbft_tpu.crypto import ed25519_pure

            return ed25519_pure.sign(self._seed, self._pub, bytes(data))

    # -- X25519 (RFC 7748) -------------------------------------------------

    _P = 2**255 - 19
    _A24 = 121665

    def _x25519_scalarmult(k: bytes, u: bytes) -> bytes:
        """RFC 7748 §5 ladder: clamped scalar k times u-coordinate u."""
        scalar = bytearray(k)
        scalar[0] &= 248
        scalar[31] &= 127
        scalar[31] |= 64
        kn = int.from_bytes(bytes(scalar), "little")
        x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
        x2, z2, x3, z3 = 1, 0, x1, 1
        swap = 0
        for t in reversed(range(255)):
            kt = (kn >> t) & 1
            swap ^= kt
            if swap:
                x2, x3 = x3, x2
                z2, z3 = z3, z2
            swap = kt
            a = (x2 + z2) % _P
            aa = (a * a) % _P
            b = (x2 - z2) % _P
            bb = (b * b) % _P
            e = (aa - bb) % _P
            c = (x3 + z3) % _P
            d = (x3 - z3) % _P
            da = (d * a) % _P
            cb = (c * b) % _P
            x3 = (da + cb) % _P
            x3 = (x3 * x3) % _P
            z3 = (da - cb) % _P
            z3 = (x1 * z3 * z3) % _P
            x2 = (aa * bb) % _P
            z2 = (e * (aa + _A24 * e)) % _P
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        out = (x2 * pow(z2, _P - 2, _P)) % _P
        return out.to_bytes(32, "little")

    class X25519PublicKey:
        def __init__(self, raw: bytes):
            if len(raw) != 32:
                raise ValueError("x25519 public key must be 32 bytes")
            self._raw = bytes(raw)

        @classmethod
        def from_public_bytes(cls, raw: bytes) -> "X25519PublicKey":
            return cls(raw)

        def public_bytes_raw(self) -> bytes:
            return self._raw

    class X25519PrivateKey:
        _BASE = (9).to_bytes(32, "little")

        def __init__(self, raw: bytes):
            if len(raw) != 32:
                raise ValueError("x25519 private key must be 32 bytes")
            self._raw = bytes(raw)

        @classmethod
        def generate(cls) -> "X25519PrivateKey":
            return cls(os.urandom(32))

        @classmethod
        def from_private_bytes(cls, raw: bytes) -> "X25519PrivateKey":
            return cls(raw)

        def public_key(self) -> X25519PublicKey:
            return X25519PublicKey(_x25519_scalarmult(self._raw, self._BASE))

        def exchange(self, peer: X25519PublicKey) -> bytes:
            out = _x25519_scalarmult(self._raw, peer.public_bytes_raw())
            if out == b"\x00" * 32:
                raise ValueError("x25519 shared secret is all zeros")
            return out

    # -- ChaCha20-Poly1305 (RFC 8439) --------------------------------------

    def _rotl32(v: int, c: int) -> int:
        return ((v << c) | (v >> (32 - c))) & 0xFFFFFFFF

    def _chacha20_block(key_words, counter: int, nonce_words) -> bytes:
        init = [
            0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
            *key_words, counter & 0xFFFFFFFF, *nonce_words,
        ]
        x = list(init)
        for _ in range(10):
            for a, b, c, d in (
                (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
                (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
            ):
                x[a] = (x[a] + x[b]) & 0xFFFFFFFF
                x[d] = _rotl32(x[d] ^ x[a], 16)
                x[c] = (x[c] + x[d]) & 0xFFFFFFFF
                x[b] = _rotl32(x[b] ^ x[c], 12)
                x[a] = (x[a] + x[b]) & 0xFFFFFFFF
                x[d] = _rotl32(x[d] ^ x[a], 8)
                x[c] = (x[c] + x[d]) & 0xFFFFFFFF
                x[b] = _rotl32(x[b] ^ x[c], 7)
        return struct.pack(
            "<16I", *((xi + ii) & 0xFFFFFFFF for xi, ii in zip(x, init))
        )

    def _chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
        key_words = struct.unpack("<8I", key)
        nonce_words = struct.unpack("<3I", nonce)
        out = bytearray()
        for i in range(0, len(data), 64):
            block = _chacha20_block(key_words, counter + i // 64, nonce_words)
            chunk = data[i : i + 64]
            out += bytes(a ^ b for a, b in zip(chunk, block))
        return bytes(out)

    _P1305 = (1 << 130) - 5

    def _poly1305(key32: bytes, msg: bytes) -> bytes:
        r = int.from_bytes(key32[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
        s = int.from_bytes(key32[16:], "little")
        acc = 0
        for i in range(0, len(msg), 16):
            block = msg[i : i + 16]
            n = int.from_bytes(block + b"\x01", "little")
            acc = ((acc + n) * r) % _P1305
        return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")

    def _pad16(b: bytes) -> bytes:
        return b"\x00" * (-len(b) % 16)

    class _PureChaCha20Poly1305:
        def __init__(self, key: bytes):
            if len(key) != 32:
                raise ValueError("chacha20poly1305 key must be 32 bytes")
            self._key = bytes(key)

        def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
            otk = _chacha20_block(
                struct.unpack("<8I", self._key), 0, struct.unpack("<3I", nonce)
            )[:32]
            mac_data = (
                aad + _pad16(aad) + ct + _pad16(ct)
                + struct.pack("<QQ", len(aad), len(ct))
            )
            return _poly1305(otk, mac_data)

        def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
            if len(nonce) != 12:
                raise ValueError("nonce must be 12 bytes")
            aad = aad or b""
            ct = _chacha20_xor(self._key, 1, nonce, bytes(data))
            return ct + self._tag(nonce, ct, aad)

        def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
            if len(nonce) != 12:
                raise ValueError("nonce must be 12 bytes")
            if len(data) < 16:
                raise InvalidTag("ciphertext too short")
            aad = aad or b""
            ct, tag = bytes(data[:-16]), bytes(data[-16:])
            if not _hmac.compare_digest(self._tag(nonce, ct, aad), tag):
                raise InvalidTag("poly1305 tag mismatch")
            return _chacha20_xor(self._key, 1, nonce, ct)

    # -- ChaCha20-Poly1305 via the interpreter's own libcrypto -------------

    def _load_libcrypto_aead():
        """Bind EVP_chacha20_poly1305 from the system libcrypto via ctypes.

        Returns an AEAD class API-compatible with the `cryptography` wheel's
        ChaCha20Poly1305, or None when the library / cipher is unavailable
        or the binding fails its cross-check against the pure tier.
        """
        import ctypes
        import ctypes.util

        lib = None
        names = [ctypes.util.find_library("crypto"), "libcrypto.so.3",
                 "libcrypto.so.1.1", "libcrypto.so"]
        for cand in names:
            if not cand:
                continue
            try:
                cdll = ctypes.CDLL(cand)
            except OSError:
                continue
            if getattr(cdll, "EVP_chacha20_poly1305", None) is not None:
                lib = cdll
                break
        if lib is None:
            return None

        c_int = ctypes.c_int
        c_void_p = ctypes.c_void_p
        c_char_p = ctypes.c_char_p
        lib.EVP_chacha20_poly1305.restype = c_void_p
        lib.EVP_chacha20_poly1305.argtypes = []
        lib.EVP_CIPHER_CTX_new.restype = c_void_p
        lib.EVP_CIPHER_CTX_new.argtypes = []
        lib.EVP_CIPHER_CTX_free.restype = None
        lib.EVP_CIPHER_CTX_free.argtypes = [c_void_p]
        lib.EVP_CipherInit_ex.restype = c_int
        lib.EVP_CipherInit_ex.argtypes = [
            c_void_p, c_void_p, c_void_p, c_char_p, c_char_p, c_int,
        ]
        lib.EVP_CipherUpdate.restype = c_int
        lib.EVP_CipherUpdate.argtypes = [
            c_void_p, c_void_p, ctypes.POINTER(c_int), c_char_p, c_int,
        ]
        lib.EVP_CipherFinal_ex.restype = c_int
        lib.EVP_CipherFinal_ex.argtypes = [
            c_void_p, c_void_p, ctypes.POINTER(c_int),
        ]
        lib.EVP_CIPHER_CTX_ctrl.restype = c_int
        lib.EVP_CIPHER_CTX_ctrl.argtypes = [c_void_p, c_int, c_int, c_void_p]

        _SET_IVLEN, _GET_TAG, _SET_TAG = 0x09, 0x10, 0x11
        cipher = lib.EVP_chacha20_poly1305()
        if not cipher:
            return None

        class _LibcryptoChaCha20Poly1305:
            """RFC 8439 AEAD over the already-loaded system libcrypto."""

            def __init__(self, key: bytes):
                if len(key) != 32:
                    raise ValueError("chacha20poly1305 key must be 32 bytes")
                self._key = bytes(key)

            def _run(self, enc: int, nonce: bytes, data: bytes,
                     aad: bytes, tag: bytes | None) -> bytes:
                # Fresh context per call keeps concurrent send/recv AEADs
                # (and any other threads) isolated without locking.
                ctx = lib.EVP_CIPHER_CTX_new()
                if not ctx:
                    raise MemoryError("EVP_CIPHER_CTX_new failed")
                try:
                    outl = c_int(0)
                    out = ctypes.create_string_buffer(len(data) or 1)
                    ok = (
                        lib.EVP_CipherInit_ex(ctx, cipher, None, None, None, enc)
                        and lib.EVP_CIPHER_CTX_ctrl(ctx, _SET_IVLEN, 12, None)
                        and lib.EVP_CipherInit_ex(
                            ctx, None, None, self._key, bytes(nonce), enc
                        )
                    )
                    if ok and aad:
                        ok = lib.EVP_CipherUpdate(
                            ctx, None, ctypes.byref(outl), aad, len(aad)
                        )
                    if ok:
                        ok = lib.EVP_CipherUpdate(
                            ctx, out, ctypes.byref(outl),
                            bytes(data), len(data),
                        )
                    n = outl.value
                    if ok and not enc:
                        ok = lib.EVP_CIPHER_CTX_ctrl(
                            ctx, _SET_TAG, 16,
                            ctypes.create_string_buffer(tag, 16),
                        )
                    if ok:
                        fin = lib.EVP_CipherFinal_ex(
                            ctx, ctypes.byref(out, n), ctypes.byref(outl)
                        )
                        if not fin:
                            if not enc:
                                raise InvalidTag("poly1305 tag mismatch")
                            ok = 0
                        else:
                            n += outl.value
                    if not ok:
                        raise ValueError("libcrypto chacha20poly1305 failed")
                    if enc:
                        tagbuf = ctypes.create_string_buffer(16)
                        if not lib.EVP_CIPHER_CTX_ctrl(
                            ctx, _GET_TAG, 16, tagbuf
                        ):
                            raise ValueError("EVP_CTRL_AEAD_GET_TAG failed")
                        return out.raw[:n] + tagbuf.raw
                    return out.raw[:n]
                finally:
                    lib.EVP_CIPHER_CTX_free(ctx)

            def encrypt(self, nonce: bytes, data: bytes,
                        aad: bytes | None) -> bytes:
                if len(nonce) != 12:
                    raise ValueError("nonce must be 12 bytes")
                return self._run(1, nonce, bytes(data), aad or b"", None)

            def decrypt(self, nonce: bytes, data: bytes,
                        aad: bytes | None) -> bytes:
                if len(nonce) != 12:
                    raise ValueError("nonce must be 12 bytes")
                if len(data) < 16:
                    raise InvalidTag("ciphertext too short")
                data = bytes(data)
                return self._run(
                    0, nonce, data[:-16], aad or b"", data[-16:]
                )

        # Cross-check against the pure RFC 8439 tier before trusting the
        # binding: wire bytes must be identical and tampering must raise.
        try:
            key = bytes(range(32))
            nonce = bytes(range(12))
            for msg, aad in (
                (b"", b""),
                (b"tpu-bft frame", b"hdr"),
                (bytes(1024) + b"tail", b""),
            ):
                fast = _LibcryptoChaCha20Poly1305(key)
                pure = _PureChaCha20Poly1305(key)
                sealed = fast.encrypt(nonce, msg, aad)
                if sealed != pure.encrypt(nonce, msg, aad):
                    return None
                if fast.decrypt(nonce, sealed, aad) != msg:
                    return None
                try:
                    fast.decrypt(
                        nonce, sealed[:-1] + bytes([sealed[-1] ^ 1]), aad
                    )
                    return None
                except InvalidTag:
                    pass
        except Exception:
            return None
        return _LibcryptoChaCha20Poly1305

    _libcrypto_aead = (
        None
        if os.environ.get("CMTPU_PURE_AEAD")
        else _load_libcrypto_aead()
    )
    if _libcrypto_aead is not None:
        ChaCha20Poly1305 = _libcrypto_aead
        AEAD_PROVIDER = "libcrypto"
    else:
        ChaCha20Poly1305 = _PureChaCha20Poly1305
        AEAD_PROVIDER = "pure"

if HAVE_CRYPTOGRAPHY:
    AEAD_PROVIDER = "cryptography"


__all__ = [
    "AEAD_PROVIDER",
    "HAVE_CRYPTOGRAPHY",
    "InvalidSignature",
    "InvalidTag",
    "Ed25519PrivateKey",
    "Ed25519PublicKey",
    "X25519PrivateKey",
    "X25519PublicKey",
    "ChaCha20Poly1305",
]

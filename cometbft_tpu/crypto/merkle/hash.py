"""RFC-6962 domain-separated hashing (reference: crypto/merkle/hash.go).

leaf  = SHA256(0x00 || data)
inner = SHA256(0x01 || left || right)
empty = SHA256("")
"""

import hashlib

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def empty_hash() -> bytes:
    """tmhash of the empty string (crypto/merkle/hash.go:16-18)."""
    return hashlib.sha256(b"").digest()


def leaf_hash(leaf: bytes) -> bytes:
    """SHA256(0x00 || leaf) (crypto/merkle/hash.go:21-23)."""
    return hashlib.sha256(LEAF_PREFIX + leaf).digest()


def inner_hash(left: bytes, right: bytes) -> bytes:
    """SHA256(0x01 || left || right) (crypto/merkle/hash.go:34-40)."""
    return hashlib.sha256(INNER_PREFIX + left + right).digest()

"""RFC-6962 Merkle tree roots (reference: crypto/merkle/tree.go).

The canonical tree splits at the largest power of two strictly less than the
item count (tree.go:101-112). Pairing adjacent nodes level-by-level and
promoting an odd trailing node produces the identical tree (tree.go:68-98
proves this equivalence with a test) — we use the level-synchronous form both
here and, vectorized, in the TPU kernel (cometbft_tpu/ops/merkle_kernel.py).
"""

from __future__ import annotations

from cometbft_tpu.crypto.merkle.hash import empty_hash, inner_hash, leaf_hash


def get_split_point(length: int) -> int:
    """Largest power of 2 strictly less than length (crypto/merkle/tree.go:101)."""
    if length < 1:
        raise ValueError("Trying to split a tree with size < 1")
    k = 1 << (length.bit_length() - 1)
    if k == length:
        k >>= 1
    return k


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    """Merkle root of items, RFC-6962 (crypto/merkle/tree.go:11-27).

    Level-synchronous (iterative) so 64k+ leaf blocks don't hit Python
    recursion limits; identical output to the reference's recursive split.
    Large trees take the native C path (SHA-NI when the host has it) —
    bit-identical, cross-checked in tests/test_native.py.
    """
    if len(items) >= 32:
        from cometbft_tpu import native

        if native.ready() is not None:
            return native.merkle_root(items)
        native.ensure_built_async()  # build off-thread; pure path meanwhile
    return hash_from_byte_slices_iterative(items)


def hash_from_byte_slices_iterative(items: list[bytes]) -> bytes:
    """crypto/merkle/tree.go:68-98."""
    if len(items) == 0:
        return empty_hash()
    level = [leaf_hash(item) for item in items]
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            nxt.append(inner_hash(level[i], level[i + 1]))
        if len(level) % 2 == 1:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def hash_from_byte_slices_recursive(items: list[bytes]) -> bytes:
    """Direct transliteration of the split-point recursion (tree.go:15-27);
    kept for cross-checking the iterative form in tests."""
    n = len(items)
    if n == 0:
        return empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    k = get_split_point(n)
    return inner_hash(
        hash_from_byte_slices_recursive(items[:k]),
        hash_from_byte_slices_recursive(items[k:]),
    )

"""Generalized multi-store proof operators (reference: crypto/merkle/proof_op.go).

A chain of ProofOperators folds leaf values through successive Merkle trees
(e.g. app-store → multi-store) until the final root, checked against a trusted
root alongside a consumed key path.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field

from cometbft_tpu.crypto.merkle.proof_key_path import key_path_to_keys


@dataclass
class ProofOp:
    """Wire form of one operator (proto tendermint.crypto.ProofOp)."""

    type: str = ""
    key: bytes = b""
    data: bytes = b""


@dataclass
class ProofOps:
    ops: list[ProofOp] = field(default_factory=list)


class ProofOperator(abc.ABC):
    """crypto/merkle/proof_op.go:21-25."""

    @abc.abstractmethod
    def run(self, args: list[bytes]) -> list[bytes]: ...

    @abc.abstractmethod
    def get_key(self) -> bytes: ...

    @abc.abstractmethod
    def proof_op(self) -> ProofOp: ...


class ProofOperators(list):
    """Sequential application + root/keypath check (proof_op.go:33-70)."""

    def verify_value(self, root: bytes, keypath: str, value: bytes) -> None:
        self.verify(root, keypath, [value])

    def verify(self, root: bytes, keypath: str, args: list[bytes] | None) -> None:
        keys = key_path_to_keys(keypath)
        for i, op in enumerate(self):
            key = op.get_key()
            if len(key) != 0:
                if len(keys) == 0:
                    raise ValueError(
                        f"key path has insufficient # of parts: expected no more "
                        f"keys but got {key!r}"
                    )
                last_key = keys[-1]
                if last_key != key:
                    raise ValueError(
                        f"key mismatch on operation #{i}: expected {last_key!r} "
                        f"but got {key!r}"
                    )
                keys = keys[:-1]
            args = op.run(args or [])
        if not args or root != args[0]:
            got = args[0].hex() if args else None
            raise ValueError(
                f"calculated root hash is invalid: expected {root.hex()} but got {got}"
            )
        if len(keys) != 0:
            raise ValueError("keypath not consumed all")


class ProofRuntime:
    """Registry of op-type → decoder (crypto/merkle/proof_op.go:75-123)."""

    def __init__(self):
        self._decoders: dict[str, callable] = {}

    def register_op_decoder(self, typ: str, decoder) -> None:
        if typ in self._decoders:
            raise ValueError(f"already registered for type {typ}")
        self._decoders[typ] = decoder

    def decode(self, pop: ProofOp) -> ProofOperator:
        decoder = self._decoders.get(pop.type)
        if decoder is None:
            raise ValueError(f"unrecognized proof type {pop.type}")
        return decoder(pop)

    def decode_proof(self, proof: ProofOps) -> ProofOperators:
        poz = ProofOperators()
        for pop in proof.ops:
            poz.append(self.decode(pop))
        return poz

    def verify_value(self, proof: ProofOps, root: bytes, keypath: str, value: bytes) -> None:
        self.verify(proof, root, keypath, [value])

    def verify_absence(self, proof: ProofOps, root: bytes, keypath: str) -> None:
        self.verify(proof, root, keypath, None)

    def verify(self, proof: ProofOps, root: bytes, keypath: str, args) -> None:
        self.decode_proof(proof).verify(root, keypath, args)


def default_proof_runtime() -> ProofRuntime:
    """Knows only value proofs (proof_op.go:137-142)."""
    from cometbft_tpu.crypto.merkle.proof_value import PROOF_OP_VALUE, value_op_decoder

    prt = ProofRuntime()
    prt.register_op_decoder(PROOF_OP_VALUE, value_op_decoder)
    return prt

"""Merkle inclusion proofs (reference: crypto/merkle/proof.go).

Proof = {total, index, leaf_hash, aunts}: leaf hashes included, root excluded,
aunts ordered from the leaf's sibling up to the root's child. MaxAunts=100
bounds proof size against DoS (proof.go:12-16).
"""

from __future__ import annotations

from collections.abc import Sequence
from dataclasses import dataclass, field

from cometbft_tpu.crypto import tmhash
from cometbft_tpu.crypto.merkle.hash import inner_hash, leaf_hash
from cometbft_tpu.crypto.merkle.tree import get_split_point

MAX_AUNTS = 100


@dataclass
class Proof:
    """crypto/merkle/proof.go:26-31."""

    total: int = 0
    index: int = 0
    leaf_hash: bytes = b""
    aunts: list[bytes] = field(default_factory=list)

    def verify(self, root_hash: bytes, leaf: bytes) -> None:
        """Raises ValueError unless this proof links `leaf` to `root_hash`
        (crypto/merkle/proof.go:52-69)."""
        if self.total < 0:
            raise ValueError("proof total must be positive")
        if self.index < 0:
            raise ValueError("proof index cannot be negative")
        lh = leaf_hash(leaf)
        if self.leaf_hash != lh:
            raise ValueError(
                f"invalid leaf hash: wanted {lh.hex()} got {self.leaf_hash.hex()}"
            )
        computed = self.compute_root_hash()
        if computed != root_hash:
            raise ValueError(
                f"invalid root hash: wanted {root_hash.hex()} got "
                f"{computed.hex() if computed else None}"
            )

    def compute_root_hash(self) -> bytes | None:
        """crypto/merkle/proof.go:72-79."""
        return compute_hash_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def validate_basic(self) -> None:
        """crypto/merkle/proof.go:97-118."""
        if self.total < 0:
            raise ValueError("negative Total")
        if self.index < 0:
            raise ValueError("negative Index")
        if len(self.leaf_hash) != tmhash.SIZE:
            raise ValueError(
                f"expected LeafHash size to be {tmhash.SIZE}, got {len(self.leaf_hash)}"
            )
        if len(self.aunts) > MAX_AUNTS:
            raise ValueError(f"expected no more than {MAX_AUNTS} aunts, got {len(self.aunts)}")
        for i, aunt in enumerate(self.aunts):
            if len(aunt) != tmhash.SIZE:
                raise ValueError(f"expected Aunts#{i} size to be {tmhash.SIZE}, got {len(aunt)}")

    def to_proto(self) -> dict:
        return {
            "total": self.total,
            "index": self.index,
            "leaf_hash": self.leaf_hash,
            "aunts": list(self.aunts),
        }

    @classmethod
    def from_proto(cls, pb: dict) -> "Proof":
        p = cls(
            total=pb.get("total", 0),
            index=pb.get("index", 0),
            leaf_hash=pb.get("leaf_hash", b""),
            aunts=list(pb.get("aunts", [])),
        )
        p.validate_basic()
        return p


def compute_hash_from_aunts(
    index: int, total: int, leaf_hash_: bytes, inner_hashes: list[bytes]
) -> bytes | None:
    """Fold aunts into a root; None if the shape is wrong
    (crypto/merkle/proof.go:151-181). Iterative to handle 64k-leaf proofs."""
    if index >= total or index < 0 or total <= 0:
        return None
    # Walk the split-point recursion iteratively, recording left/right turns
    # top-down, then fold bottom-up over the aunts.
    turns: list[bool] = []  # True = we're in the left subtree at this step
    lo_total, lo_index = total, index
    depth = 0
    while lo_total > 1:
        if depth >= len(inner_hashes):
            return None
        k = get_split_point(lo_total)
        if lo_index < k:
            turns.append(True)
            lo_total = k
        else:
            turns.append(False)
            lo_index -= k
            lo_total -= k
        depth += 1
    if depth != len(inner_hashes):
        return None
    h = leaf_hash_
    for i, left in enumerate(reversed(turns)):
        aunt = inner_hashes[i]
        h = inner_hash(h, aunt) if left else inner_hash(aunt, h)
    return h


class _LazyProofs(Sequence):
    """Sequence of Proof over the native packed-aunts buffer.

    All hashing (every tree level) and aunt gathering already happened in
    one C pass; this materializes the per-leaf Proof object — 32-byte aunt
    slices included — only when indexed, because a 64k-leaf block would
    otherwise allocate ~1M small bytes objects up front that consumers
    (tx proof RPC, part-set gossip) touch one leaf at a time.
    """

    __slots__ = ("_n", "_leaf_hashes", "_packed", "_stride", "_counts")

    def __init__(self, n, leaf_hashes, packed, stride, counts):
        self._n = n
        self._leaf_hashes = leaf_hashes
        self._packed = packed
        self._stride = stride
        self._counts = counts

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> Proof:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        base = i * self._stride
        return Proof(
            total=self._n,
            index=i,
            leaf_hash=self._leaf_hashes[i],
            aunts=[
                self._packed[base + 32 * k : base + 32 * (k + 1)]
                for k in range(self._counts[i])
            ],
        )

    def __iter__(self):
        for i in range(self._n):
            yield self[i]


# Which implementation the last proofs_from_byte_slices call used:
# "device" | "host-native" | "pure-python" | "empty". Observability only
# (bench stage note) — never branch on it.
last_proofs_path = "none"


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, Sequence[Proof]]:
    """Root + one inclusion proof per item (crypto/merkle/proof.go:35-49).

    Level-synchronous construction: at each level node i's aunt is its
    neighbor i^1; an odd trailing node is promoted with no aunt. Identical
    aunt lists to the reference's trailsFromByteSlices recursion.

    Path selection: host by default — the device proof path moves every
    tree level through the tunnel and measured ~12x slower than the host
    C pass (1364 ms vs 113.6 ms at 64k leaves, tpu_bench_latest.json), so
    it is opt-in via CMTPU_DEVICE_PROOFS=1 (A/B probes, device-rich hosts).
    """
    global last_proofs_path
    import os

    n = len(items)
    if n == 0:
        from cometbft_tpu.crypto.merkle.hash import empty_hash

        last_proofs_path = "empty"
        return empty_hash(), []
    if n >= 32 and os.environ.get("CMTPU_DEVICE_PROOFS", "") == "1":
        try:
            from cometbft_tpu.ops import merkle_kernel as mk

            root, proofs = mk.proofs_from_byte_slices_device(items)
            last_proofs_path = "device"
            return root, proofs
        except Exception:
            pass  # fall through to the host paths below
    if n >= 32:
        from cometbft_tpu import native

        if native.ready() is not None:
            root, leaf_hashes, packed, stride, counts = (
                native.merkle_proof_parts(items)
            )
            last_proofs_path = "host-native"
            return root, _LazyProofs(n, leaf_hashes, packed, stride, counts)
        native.ensure_built_async()
    last_proofs_path = "pure-python"
    level = [leaf_hash(item) for item in items]
    leaf_hashes = list(level)
    aunts_per_leaf: list[list[bytes]] = [[] for _ in range(n)]
    # index of each original leaf within the current level (or -1 once merged)
    pos = list(range(n))
    while len(level) > 1:
        size = len(level)
        for leaf_i in range(n):
            idx = pos[leaf_i]
            sib = idx ^ 1
            if sib < size:
                aunts_per_leaf[leaf_i].append(level[sib])
            pos[leaf_i] = idx // 2
        nxt = []
        for i in range(0, size - 1, 2):
            nxt.append(inner_hash(level[i], level[i + 1]))
        if size % 2 == 1:
            nxt.append(level[-1])
        level = nxt
    root = level[0]
    proofs = [
        Proof(total=n, index=i, leaf_hash=leaf_hashes[i], aunts=aunts_per_leaf[i])
        for i in range(n)
    ]
    return root, proofs

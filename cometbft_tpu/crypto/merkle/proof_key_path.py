"""Key paths for generalized Merkle proofs (reference: crypto/merkle/proof_key_path.go).

Keys are /-separated, URL-escaped or upper-hex (`x:`-prefixed); both encodings
decode identically.
"""

from __future__ import annotations

import enum
import urllib.parse
from dataclasses import dataclass


class KeyEncoding(enum.IntEnum):
    URL = 0
    HEX = 1


@dataclass(frozen=True)
class Key:
    name: bytes
    enc: KeyEncoding


class KeyPath(tuple):
    def append_key(self, key: bytes, enc: KeyEncoding) -> "KeyPath":
        return KeyPath(self + (Key(key, enc),))

    def __str__(self) -> str:
        res = ""
        for key in self:
            if key.enc == KeyEncoding.URL:
                res += "/" + urllib.parse.quote(key.name.decode("utf-8"), safe="")
            elif key.enc == KeyEncoding.HEX:
                res += "/x:" + key.name.hex().upper()
            else:
                raise ValueError("unexpected key encoding type")
        return res


def key_path_to_keys(path: str) -> list[bytes]:
    """Decode a /-prefixed path into raw keys (proof_key_path.go:86-108)."""
    if not path or path[0] != "/":
        raise ValueError("key path string must start with a forward slash '/'")
    parts = path[1:].split("/")
    keys: list[bytes] = []
    for i, part in enumerate(parts):
        if part.startswith("x:"):
            try:
                keys.append(bytes.fromhex(part[2:]))
            except ValueError as e:
                raise ValueError(f"decoding hex-encoded part #{i}: /{part}: {e}") from e
        else:
            keys.append(urllib.parse.unquote(part).encode("utf-8"))
    return keys

"""Value proof operator over the SimpleMap KV tree (reference: crypto/merkle/proof_value.go).

leaf = leafHash(uvarint-len(key) || key || uvarint-len(SHA256(value)) || SHA256(value))
folded through the inclusion proof to the store root.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from cometbft_tpu.crypto.merkle.hash import leaf_hash
from cometbft_tpu.crypto.merkle.proof import Proof
from cometbft_tpu.crypto.merkle.proof_op import ProofOp, ProofOperator
from cometbft_tpu.wire.proto import encode_bytes_len_prefixed

PROOF_OP_VALUE = "simple:v"


@dataclass
class ValueOp(ProofOperator):
    """crypto/merkle/proof_value.go:23-30."""

    key: bytes
    proof: Proof

    def run(self, args: list[bytes]) -> list[bytes]:
        """proof_value.go:76-97."""
        if len(args) != 1:
            raise ValueError(f"expected 1 arg, got {len(args)}")
        vhash = hashlib.sha256(args[0]).digest()
        # Wrap <key, vhash> as a length-prefixed KVPair before leaf-hashing.
        bz = encode_bytes_len_prefixed(self.key) + encode_bytes_len_prefixed(vhash)
        kvhash = leaf_hash(bz)
        if kvhash != self.proof.leaf_hash:
            raise ValueError(
                f"leaf hash mismatch: want {self.proof.leaf_hash.hex()} got {kvhash.hex()}"
            )
        root = self.proof.compute_root_hash()
        if root is None:
            raise ValueError("invalid proof shape")
        return [root]

    def get_key(self) -> bytes:
        return self.key

    def proof_op(self) -> ProofOp:
        from cometbft_tpu.wire import types as wire_types

        data = wire_types.encode_value_op(self.key, self.proof)
        return ProofOp(type=PROOF_OP_VALUE, key=self.key, data=data)


def value_op_decoder(pop: ProofOp) -> ValueOp:
    """proof_value.go:40-55."""
    if pop.type != PROOF_OP_VALUE:
        raise ValueError(f"unexpected ProofOp.Type; got {pop.type}, want {PROOF_OP_VALUE}")
    from cometbft_tpu.wire import types as wire_types

    key, proof = wire_types.decode_value_op(pop.data)
    return ValueOp(key=pop.key, proof=proof)

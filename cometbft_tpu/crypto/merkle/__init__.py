"""RFC-6962 Merkle trees (reference: crypto/merkle/)."""

from cometbft_tpu.crypto.merkle.hash import empty_hash, inner_hash, leaf_hash
from cometbft_tpu.crypto.merkle.proof import (
    MAX_AUNTS,
    Proof,
    compute_hash_from_aunts,
    proofs_from_byte_slices,
)
from cometbft_tpu.crypto.merkle.proof_op import (
    ProofOp,
    ProofOps,
    ProofOperator,
    ProofOperators,
    ProofRuntime,
    default_proof_runtime,
)
from cometbft_tpu.crypto.merkle.proof_value import ValueOp
from cometbft_tpu.crypto.merkle.tree import (
    get_split_point,
    hash_from_byte_slices,
    hash_from_byte_slices_iterative,
)

__all__ = [
    "ProofOp",
    "ProofOps",
    "MAX_AUNTS",
    "Proof",
    "ProofOperator",
    "ProofOperators",
    "ProofRuntime",
    "ValueOp",
    "compute_hash_from_aunts",
    "default_proof_runtime",
    "empty_hash",
    "get_split_point",
    "hash_from_byte_slices",
    "hash_from_byte_slices_iterative",
    "inner_hash",
    "leaf_hash",
    "proofs_from_byte_slices",
]

"""Core data types (reference: types/, 6,964 LoC surveyed in SURVEY.md §2.2)."""

from cometbft_tpu.types.block import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    BLOCK_PART_SIZE_BYTES,
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    PROPOSAL_TYPE,
    Block,
    BlockID,
    BlockMeta,
    Commit,
    CommitSig,
    Consensus,
    Data,
    Header,
    PartSetHeader,
    SignedHeader,
)
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.evidence import (
    DuplicateVoteEvidence,
    LightBlock,
    LightClientAttackEvidence,
)
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.params import ConsensusParams
from cometbft_tpu.types.part_set import Part, PartSet
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.validation import (
    Fraction,
    verify_commit,
    verify_commit_light,
    verify_commit_light_trusting,
)
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote import Vote

__all__ = [
    "Block",
    "BlockID",
    "BlockMeta",
    "Commit",
    "CommitSig",
    "Consensus",
    "ConsensusParams",
    "Data",
    "DuplicateVoteEvidence",
    "Fraction",
    "GenesisDoc",
    "GenesisValidator",
    "Header",
    "LightBlock",
    "LightClientAttackEvidence",
    "Part",
    "PartSet",
    "PartSetHeader",
    "Proposal",
    "SignedHeader",
    "Time",
    "Validator",
    "ValidatorSet",
    "Vote",
    "verify_commit",
    "verify_commit_light",
    "verify_commit_light_trusting",
]

"""Validator (reference: types/validator.go)."""

from __future__ import annotations

from dataclasses import dataclass, field as dfield

from cometbft_tpu.crypto import encoding as key_encoding
from cometbft_tpu.wire import proto as wire


@dataclass
class Validator:
    """types/validator.go:17-35. Mutable: priority changes every round."""

    address: bytes
    pub_key: object
    voting_power: int
    proposer_priority: int = 0

    @classmethod
    def new(cls, pub_key, voting_power: int) -> "Validator":
        return cls(pub_key.address(), pub_key, voting_power, 0)

    def copy(self) -> "Validator":
        return Validator(
            self.address, self.pub_key, self.voting_power, self.proposer_priority
        )

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """types/validator.go:64-84: higher priority wins; ties break to the
        smaller address."""
        if other is None:
            return self
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise ValueError("Cannot compare identical validators")

    def bytes(self) -> bytes:
        """SimpleValidator proto bytes — the Merkle leaf of ValidatorSet.Hash
        (types/validator.go:117-133)."""
        pk = key_encoding.pub_key_to_proto(self.pub_key)
        return wire.field_message(1, pk, emit_empty=True) + wire.field_varint(
            2, self.voting_power
        )

    def validate_basic(self) -> None:
        """types/validator.go ValidateBasic."""
        if self.pub_key is None:
            raise ValueError("validator does not have a public key")
        if self.voting_power < 0:
            raise ValueError("validator has negative voting power")
        if len(self.address) != 20:
            raise ValueError("validator address is the wrong size")
        if self.address != self.pub_key.address():
            raise ValueError("validator address does not match its pubkey")

    def encode(self) -> bytes:
        """tendermint.types.Validator wire form."""
        out = wire.field_bytes(1, self.address)
        out += wire.field_message(
            2, key_encoding.pub_key_to_proto(self.pub_key), emit_empty=True
        )
        out += wire.field_varint(3, self.voting_power)
        out += wire.field_varint(4, self.proposer_priority)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Validator":
        f = wire.decode_fields(data)
        return cls(
            address=wire.get_bytes(f, 1),
            pub_key=key_encoding.pub_key_from_proto(wire.get_bytes(f, 2)),
            voting_power=wire.get_varint(f, 3),
            proposer_priority=wire.get_varint(f, 4),
        )

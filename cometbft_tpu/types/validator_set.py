"""ValidatorSet: sorted validator array with proposer-priority rotation
(reference: types/validator_set.go).

Consensus-critical integer arithmetic ported semantically: int64 overflow
clipping (safeAddClip/safeSubClip), priority rescaling to a 2*totalPower
window, and the -1.125*totalPower penalty for newly bonded validators.
Ordering invariant: validators sorted by voting power descending, ties by
address ascending (ValidatorsByVotingPower, validator_set.go:755-764).
"""

from __future__ import annotations

from cometbft_tpu.crypto import merkle
from cometbft_tpu.types.validator import Validator

INT64_MAX = 2**63 - 1
INT64_MIN = -(2**63)

MAX_TOTAL_VOTING_POWER = INT64_MAX // 8  # validator_set.go:25
PRIORITY_WINDOW_SIZE_FACTOR = 2  # validator_set.go:30


def safe_add_clip(a: int, b: int) -> int:
    v = a + b
    return min(max(v, INT64_MIN), INT64_MAX)


def safe_sub_clip(a: int, b: int) -> int:
    v = a - b
    return min(max(v, INT64_MIN), INT64_MAX)


def safe_mul(a: int, b: int) -> tuple[int, bool]:
    """(product, overflowed) with int64 semantics (libs/math/safemath.go)."""
    v = a * b
    if v > INT64_MAX or v < INT64_MIN:
        return 0, True
    return v, False


def _go_div(a: int, b: int) -> int:
    """Go integer division truncates toward zero (Python's // floors)."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def _by_voting_power_key(v: Validator):
    return (-v.voting_power, v.address)


class ValidatorSet:
    """types/validator_set.go:51-97."""

    def __init__(self, validators: list[Validator] | None = None):
        self.validators: list[Validator] = []
        self.proposer: Validator | None = None
        self._total_voting_power = 0
        # address -> index, built lazily: commit verification looks every
        # signature's validator up by address, which is O(n^2) per commit as
        # a linear scan at 4k+ validators.  Invalidated on membership change.
        self._addr_index: dict[bytes, int] | None = None
        # Merkle-root memo: the hash covers (pubkey, power) per validator in
        # order, so it shares _addr_index's invalidation points (membership/
        # power changes); proposer-priority rotation leaves it intact.
        self._hash_memo: bytes | None = None
        if validators:
            err = self._update_with_change_set(
                [v.copy() for v in validators], allow_deletes=False
            )
            if err is not None:
                raise ValueError(f"Cannot create validator set: {err}")
            self.increment_proposer_priority(1)

    # -- basic accessors ----------------------------------------------------

    def is_nil_or_empty(self) -> bool:
        return len(self.validators) == 0

    def size(self) -> int:
        return len(self.validators)

    def _index(self) -> dict[bytes, int]:
        if self._addr_index is None:
            self._addr_index = {
                v.address: i for i, v in enumerate(self.validators)
            }
        return self._addr_index

    def has_address(self, address: bytes) -> bool:
        return address in self._index()

    def get_by_address(self, address: bytes):
        i = self._index().get(address, -1)
        if i < 0:
            return -1, None
        return i, self.validators[i].copy()

    def get_by_index(self, index: int):
        if index < 0 or index >= len(self.validators):
            return None, None
        v = self.validators[index]
        return v.address, v.copy()

    def copy(self) -> "ValidatorSet":
        c = ValidatorSet()
        c.validators = [v.copy() for v in self.validators]
        c.proposer = self.proposer
        c._total_voting_power = self._total_voting_power
        return c

    def total_voting_power(self) -> int:
        if self._total_voting_power == 0:
            self._update_total_voting_power()
        return self._total_voting_power

    def _update_total_voting_power(self) -> None:
        s = 0
        for v in self.validators:
            s = safe_add_clip(s, v.voting_power)
            if s > MAX_TOTAL_VOTING_POWER:
                raise OverflowError(
                    f"Total voting power should be guarded to not exceed "
                    f"{MAX_TOTAL_VOTING_POWER}; got: {s}"
                )
        self._total_voting_power = s

    def get_proposer(self) -> Validator | None:
        if not self.validators:
            return None
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer.copy()

    def _find_proposer(self) -> Validator:
        proposer = None
        for v in self.validators:
            if proposer is None or v.address != proposer.address:
                proposer = v.compare_proposer_priority(proposer) if proposer else v
        return proposer

    def hash(self) -> bytes:
        """Merkle root over SimpleValidator leaves (validator_set.go:347)."""
        if self._hash_memo is None:
            self._hash_memo = merkle.hash_from_byte_slices(
                [v.bytes() for v in self.validators]
            )
        return self._hash_memo

    def validate_basic(self) -> None:
        if self.is_nil_or_empty():
            raise ValueError("validator set is nil or empty")
        for idx, v in enumerate(self.validators):
            try:
                v.validate_basic()
            except ValueError as e:
                raise ValueError(f"invalid validator #{idx}: {e}") from e
        if self.proposer is None:
            raise ValueError("proposer failed validate basic, error: nil validator")
        self.proposer.validate_basic()

    # -- proposer priority rotation (validator_set.go:107-247) ---------------

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        c = self.copy()
        c.increment_proposer_priority(times)
        return c

    def increment_proposer_priority(self, times: int) -> None:
        if self.is_nil_or_empty():
            raise ValueError("empty validator set")
        if times <= 0:
            raise ValueError(
                "Cannot call IncrementProposerPriority with non-positive times"
            )
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self.rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority()
        self.proposer = proposer

    def _increment_proposer_priority(self) -> Validator:
        for v in self.validators:
            v.proposer_priority = safe_add_clip(v.proposer_priority, v.voting_power)
        mostest = None
        for v in self.validators:
            mostest = v.compare_proposer_priority(mostest) if mostest else v
        mostest.proposer_priority = safe_sub_clip(
            mostest.proposer_priority, self.total_voting_power()
        )
        return mostest

    def rescale_priorities(self, diff_max: int) -> None:
        if self.is_nil_or_empty():
            raise ValueError("empty validator set")
        if diff_max <= 0:
            return
        diff = self._compute_max_min_priority_diff()
        ratio = (diff + diff_max - 1) // diff_max
        if diff > diff_max:
            for v in self.validators:
                v.proposer_priority = _go_div(v.proposer_priority, ratio)

    def _compute_max_min_priority_diff(self) -> int:
        prios = [v.proposer_priority for v in self.validators]
        diff = max(prios) - min(prios)
        return -diff if diff < 0 else diff

    def _compute_avg_proposer_priority(self) -> int:
        n = len(self.validators)
        s = sum(v.proposer_priority for v in self.validators)
        # Go big.Int Div is Euclidean-style floor for positive divisor.
        return s // n

    def _shift_by_avg_proposer_priority(self) -> None:
        avg = self._compute_avg_proposer_priority()
        for v in self.validators:
            v.proposer_priority = safe_sub_clip(v.proposer_priority, avg)

    # -- update machinery (validator_set.go:366-660) -------------------------

    def update_with_change_set(self, changes: list[Validator]) -> None:
        err = self._update_with_change_set([v.copy() for v in changes], True)
        if err is not None:
            raise ValueError(err)

    def _update_with_change_set(self, changes, allow_deletes: bool):
        if not changes:
            return None
        # processChanges: sort by address, detect duplicates, split.
        changes = sorted(changes, key=lambda v: v.address)
        updates, deletes = [], []
        prev_addr = None
        for v in changes:
            if v.address == prev_addr:
                return f"duplicate entry {v} in {changes}"
            if v.voting_power < 0:
                return f"voting power can't be negative: {v.voting_power}"
            if v.voting_power > MAX_TOTAL_VOTING_POWER:
                return (
                    f"to prevent clipping/overflow, voting power can't be higher "
                    f"than {MAX_TOTAL_VOTING_POWER}, got {v.voting_power}"
                )
            if v.voting_power == 0:
                deletes.append(v)
            else:
                updates.append(v)
            prev_addr = v.address
        if not allow_deletes and deletes:
            return f"cannot process validators with voting power 0: {deletes}"
        num_new = sum(1 for u in updates if not self.has_address(u.address))
        if num_new == 0 and len(self.validators) == len(deletes):
            return "applying the validator changes would result in empty set"
        # verifyRemovals
        removed_power = 0
        for d in deletes:
            _, val = self.get_by_address(d.address)
            if val is None:
                return f"failed to find validator {d.address.hex().upper()} to remove"
            removed_power += val.voting_power
        if len(deletes) > len(self.validators):
            raise ValueError("more deletes than validators")
        # verifyUpdates

        def delta(update: Validator) -> int:
            _, val = self.get_by_address(update.address)
            if val is not None:
                return update.voting_power - val.voting_power
            return update.voting_power

        tvp_after_removals = self.total_voting_power() - removed_power
        for upd in sorted(updates, key=delta):
            tvp_after_removals += delta(upd)
            if tvp_after_removals > MAX_TOTAL_VOTING_POWER:
                return (
                    f"total voting power of resulting valset exceeds max "
                    f"{MAX_TOTAL_VOTING_POWER}"
                )
        tvp_after_updates_before_removals = tvp_after_removals + removed_power
        # computeNewPriorities: new validators start at -1.125*totalPower.
        for upd in updates:
            _, val = self.get_by_address(upd.address)
            if val is None:
                upd.proposer_priority = -(
                    tvp_after_updates_before_removals
                    + (tvp_after_updates_before_removals >> 3)
                )
            else:
                upd.proposer_priority = val.proposer_priority
        self._apply_updates(updates)
        self._apply_removals(deletes)
        self._update_total_voting_power()
        self.rescale_priorities(PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power())
        self._shift_by_avg_proposer_priority()
        self.validators.sort(key=_by_voting_power_key)
        self._addr_index = None
        self._hash_memo = None
        return None

    def _apply_updates(self, updates: list[Validator]) -> None:
        existing = sorted(self.validators, key=lambda v: v.address)
        merged = []
        i = j = 0
        while i < len(existing) and j < len(updates):
            if existing[i].address < updates[j].address:
                merged.append(existing[i])
                i += 1
            else:
                merged.append(updates[j])
                if existing[i].address == updates[j].address:
                    i += 1
                j += 1
        merged.extend(existing[i:])
        merged.extend(updates[j:])
        self.validators = merged
        self._addr_index = None
        self._hash_memo = None

    def _apply_removals(self, deletes: list[Validator]) -> None:
        if not deletes:
            return
        dset = {d.address for d in deletes}
        self.validators = [v for v in self.validators if v.address not in dset]
        self._addr_index = None
        self._hash_memo = None

    # -- verification wrappers (validator_set.go:662-680) --------------------

    def verify_commit(self, chain_id: str, block_id, height: int, commit) -> None:
        from cometbft_tpu.types import validation

        validation.verify_commit(chain_id, self, block_id, height, commit)

    def verify_commit_light(self, chain_id: str, block_id, height: int, commit) -> None:
        from cometbft_tpu.types import validation

        validation.verify_commit_light(chain_id, self, block_id, height, commit)

    def verify_commit_light_trusting(self, chain_id: str, commit, trust_level) -> None:
        from cometbft_tpu.types import validation

        validation.verify_commit_light_trusting(chain_id, self, commit, trust_level)

    # -- wire ----------------------------------------------------------------

    def encode(self) -> bytes:
        from cometbft_tpu.wire import proto as wire

        out = b""
        for v in self.validators:
            out += wire.field_message(1, v.encode(), emit_empty=True)
        if self.proposer is not None:
            out += wire.field_message(2, self.proposer.encode(), emit_empty=True)
        out += wire.field_varint(3, self.total_voting_power() if self.validators else 0)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorSet":
        from cometbft_tpu.wire import proto as wire

        f = wire.decode_fields(data)
        vs = cls()
        vs.validators = [Validator.decode(b) for b in wire.get_repeated_bytes(f, 1)]
        if 2 in f:
            vs.proposer = Validator.decode(wire.get_bytes(f, 2))
        vs._total_voting_power = 0
        return vs

"""Block proposal (reference: types/proposal.go)."""

from __future__ import annotations

from dataclasses import dataclass, field as dfield

from cometbft_tpu.types import canonical
from cometbft_tpu.types.block import MAX_SIGNATURE_SIZE, PROPOSAL_TYPE, BlockID
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.wire import proto as wire


@dataclass(frozen=True)
class Proposal:
    """types/proposal.go:23-41."""

    type: int = PROPOSAL_TYPE
    height: int = 0
    round: int = 0
    pol_round: int = -1
    block_id: BlockID = dfield(default_factory=BlockID)
    timestamp: Time = dfield(default_factory=Time)
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        """ProposalSignBytes (types/proposal.go:80-92)."""
        return canonical.proposal_sign_bytes_from_parts(
            chain_id,
            self.height,
            self.round,
            self.pol_round,
            self.block_id,
            self.timestamp,
        )

    def validate_basic(self) -> None:
        """types/proposal.go:44-77."""
        if self.type != PROPOSAL_TYPE:
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.pol_round < -1:
            raise ValueError("negative POLRound (exception: -1)")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError(f"expected a complete, non-empty BlockID, got: {self.block_id}")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError("signature is too big")

    def encode(self) -> bytes:
        out = wire.field_varint(1, self.type)
        out += wire.field_varint(2, self.height)
        out += wire.field_varint(3, self.round)
        out += wire.field_varint(4, self.pol_round)
        out += wire.field_message(5, self.block_id.encode(), emit_empty=True)
        out += wire.field_message(6, self.timestamp.encode(), emit_empty=True)
        out += wire.field_bytes(7, self.signature)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Proposal":
        f = wire.decode_fields(data)
        return cls(
            type=wire.get_uvarint(f, 1),
            height=wire.get_varint(f, 2),
            round=wire.get_varint(f, 3),
            pol_round=wire.get_varint(f, 4),
            block_id=BlockID.decode(wire.get_bytes(f, 5)),
            timestamp=Time.decode(wire.get_bytes(f, 6)),
            signature=wire.get_bytes(f, 7),
        )

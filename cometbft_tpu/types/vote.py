"""Vote (reference: types/vote.go)."""

from __future__ import annotations

from dataclasses import dataclass, field as dfield, replace

from cometbft_tpu.crypto import tmhash
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block import (
    MAX_SIGNATURE_SIZE,
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    BlockID,
)
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.wire import proto as wire


def is_vote_type_valid(t: int) -> bool:
    return t in (PREVOTE_TYPE, PRECOMMIT_TYPE)


@dataclass(frozen=True)
class Vote:
    """types/vote.go:50-63."""

    type: int = 0
    height: int = 0
    round: int = 0
    block_id: BlockID = dfield(default_factory=BlockID)
    timestamp: Time = dfield(default_factory=Time)
    validator_address: bytes = b""
    validator_index: int = 0
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        """VoteSignBytes (types/vote.go:85-95).

        Memoized per instance: a gossiped vote is sign-bytes-checked by
        every admission path it crosses (prebatch, VoteSet, evidence), and
        in-process meshes share one Vote object across all receivers. The
        cache never enters __eq__/__hash__ (dataclass uses fields only).
        """
        cached = self.__dict__.get("_sign_bytes")
        if cached is not None and cached[0] == chain_id:
            return cached[1]
        sb = canonical.vote_sign_bytes_from_parts(
            chain_id, self.type, self.height, self.round, self.block_id, self.timestamp
        )
        object.__setattr__(self, "_sign_bytes", (chain_id, sb))
        return sb

    def verify(self, chain_id: str, pub_key) -> None:
        """types/vote.go Verify: address match + signature check."""
        if pub_key.address() != self.validator_address:
            raise VoteError("invalid validator address")
        if not pub_key.verify_signature(self.sign_bytes(chain_id), self.signature):
            raise VoteError("invalid signature")

    def is_nil(self) -> bool:
        return self.block_id.is_zero()

    def with_signature(self, sig: bytes) -> "Vote":
        return replace(self, signature=sig)

    def encode(self) -> bytes:
        out = wire.field_varint(1, self.type)
        out += wire.field_varint(2, self.height)
        out += wire.field_varint(3, self.round)
        out += wire.field_message(4, self.block_id.encode(), emit_empty=True)
        out += wire.field_message(5, self.timestamp.encode(), emit_empty=True)
        out += wire.field_bytes(6, self.validator_address)
        out += wire.field_varint(7, self.validator_index)
        out += wire.field_bytes(8, self.signature)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Vote":
        f = wire.decode_fields(data)
        return cls(
            type=wire.get_uvarint(f, 1),
            height=wire.get_varint(f, 2),
            round=wire.get_varint(f, 3),
            block_id=BlockID.decode(wire.get_bytes(f, 4)),
            timestamp=Time.decode(wire.get_bytes(f, 5)),
            validator_address=wire.get_bytes(f, 6),
            validator_index=wire.get_varint(f, 7),
            signature=wire.get_bytes(f, 8),
        )

    def validate_basic(self) -> None:
        """types/vote.go:168-210."""
        if not is_vote_type_valid(self.type):
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if not self.block_id.is_zero() and not self.block_id.is_complete():
            raise ValueError(f"blockID must be either empty or complete, got: {self.block_id}")
        self.block_id.validate_basic()
        if len(self.validator_address) != tmhash.TRUNCATED_SIZE:
            raise ValueError("expected ValidatorAddress size to be 20 bytes")
        if self.validator_index < 0:
            raise ValueError("negative ValidatorIndex")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError("signature is too big")


class VoteError(Exception):
    pass


def vote_to_commit_sig(vote: Vote | None):
    """Vote → CommitSig (types/block.go CommitSig from vote / MakeCommit path)."""
    from cometbft_tpu.types.block import CommitSig

    if vote is None:
        return CommitSig.absent()
    if vote.block_id.is_zero():
        flag = 3  # BlockIDFlagNil
    else:
        flag = 2  # BlockIDFlagCommit
    return CommitSig(
        block_id_flag=flag,
        validator_address=vote.validator_address,
        timestamp=vote.timestamp,
        signature=vote.signature,
    )

"""Transactions (reference: types/tx.go)."""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.crypto import merkle, tmhash
from cometbft_tpu.crypto.merkle.proof import Proof, proofs_from_byte_slices


def tx_hash(tx: bytes) -> bytes:
    """Tx.Hash = SHA256(tx) (types/tx.go:33)."""
    return tmhash.sum(tx)


def tx_key(tx: bytes) -> bytes:
    """TxKey: fixed 32-byte mempool cache key (types/tx.go)."""
    return tmhash.sum(tx)


def txs_hash(txs: list[bytes]) -> bytes:
    """Txs.Hash = Merkle root over raw txs (types/tx.go:47-50)."""
    return merkle.hash_from_byte_slices(list(txs))


def txs_proof(txs: list[bytes], i: int) -> "TxProof":
    """Txs.Proof(i) (types/tx.go:57-70)."""
    root, proofs = proofs_from_byte_slices(list(txs))
    return TxProof(root_hash=root, data=txs[i], proof=proofs[i])


@dataclass
class TxProof:
    """types/tx.go:75-110."""

    root_hash: bytes
    data: bytes
    proof: Proof

    def leaf(self) -> bytes:
        return self.data

    def validate(self, data_hash: bytes) -> None:
        if data_hash != self.root_hash:
            raise ValueError("proof matches different data hash")
        if self.proof.index < 0:
            raise ValueError("proof index cannot be negative")
        if self.proof.total <= 0:
            raise ValueError("proof total must be positive")
        self.proof.verify(self.root_hash, self.data)


def compute_proto_size_for_txs(txs: list[bytes]) -> int:
    """types/tx.go ComputeProtoSizeForTxs: wire size of Data{txs}."""
    from cometbft_tpu.wire import proto as wire

    total = 0
    for tx in txs:
        total += len(wire.field_bytes(1, tx, emit_default=True))
    return total

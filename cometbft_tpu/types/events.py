"""Typed event system + EventBus (reference: types/events.go, types/event_bus.go).

The EventBus bridges consensus → RPC subscribers: consensus fires typed
events, subscribers filter with the pubsub query DSL
(types/event_bus.go:33,134).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield
from typing import Any

from cometbft_tpu.libs.pubsub import Query, Server

# Reserved event types (types/events.go:15-60).
EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_EVIDENCE = "NewEvidence"
EVENT_TX = "Tx"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_LOCK = "Lock"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_POLKA = "Polka"
EVENT_RELOCK = "Relock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_UNLOCK = "Unlock"
EVENT_VALID_BLOCK = "ValidBlock"
EVENT_VOTE = "Vote"

# Event attribute keys (types/events.go:185-200).
EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"
BLOCK_HEIGHT_KEY = "block.height"


def query_for_event(event_type: str) -> Query:
    return Query(f"{EVENT_TYPE_KEY}='{event_type}'")


EVENT_QUERY_NEW_BLOCK = query_for_event(EVENT_NEW_BLOCK)
EVENT_QUERY_NEW_BLOCK_HEADER = query_for_event(EVENT_NEW_BLOCK_HEADER)
EVENT_QUERY_TX = query_for_event(EVENT_TX)
EVENT_QUERY_VOTE = query_for_event(EVENT_VOTE)
EVENT_QUERY_NEW_EVIDENCE = query_for_event(EVENT_NEW_EVIDENCE)
EVENT_QUERY_VALIDATOR_SET_UPDATES = query_for_event(EVENT_VALIDATOR_SET_UPDATES)


@dataclass
class EventDataNewBlock:
    block: Any
    block_id: Any = None
    result_begin_block: Any = None
    result_end_block: Any = None


@dataclass
class EventDataNewBlockHeader:
    header: Any
    num_txs: int = 0
    result_begin_block: Any = None
    result_end_block: Any = None


@dataclass
class EventDataTx:
    height: int
    tx: bytes
    index: int
    result: Any


@dataclass
class EventDataNewRound:
    height: int
    round: int
    step: str
    proposer_address: bytes = b""


@dataclass
class EventDataRoundState:
    height: int
    round: int
    step: str


@dataclass
class EventDataVote:
    vote: Any


@dataclass
class EventDataNewEvidence:
    evidence: Any
    height: int


@dataclass
class EventDataValidatorSetUpdates:
    validator_updates: list


@dataclass
class EventDataCompleteProposal:
    height: int
    round: int
    step: str
    block_id: Any


class EventBus:
    """types/event_bus.go: a thin typed wrapper over pubsub.Server."""

    def __init__(self):
        self._server = Server()

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()

    def subscribe(self, subscriber: str, query: Query, out_capacity: int = 100):
        return self._server.subscribe(subscriber, query, out_capacity)

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        self._server.unsubscribe(subscriber, query)

    def unsubscribe_all(self, subscriber: str) -> None:
        self._server.unsubscribe_all(subscriber)

    def num_clients(self) -> int:
        return self._server.num_clients()

    def _publish(self, event_type: str, data: Any, extra_attrs: dict | None = None) -> None:
        attrs = {EVENT_TYPE_KEY: [event_type]}
        if extra_attrs:
            for k, v in extra_attrs.items():
                attrs.setdefault(k, []).extend(v if isinstance(v, list) else [v])
        self._server.publish_with_events(data, attrs)

    # Typed publishers (event_bus.go:115-280).

    def publish_new_block(self, data: EventDataNewBlock, events: list | None = None) -> None:
        attrs = _abci_events_to_attrs(events)
        self._publish(EVENT_NEW_BLOCK, data, attrs)

    def publish_new_block_header(self, data: EventDataNewBlockHeader, events: list | None = None) -> None:
        self._publish(EVENT_NEW_BLOCK_HEADER, data, _abci_events_to_attrs(events))

    def publish_tx(self, data: EventDataTx, events: list | None = None) -> None:
        attrs = _abci_events_to_attrs(events)
        from cometbft_tpu.types.tx import tx_hash

        attrs.setdefault(TX_HASH_KEY, []).append(tx_hash(data.tx).hex().upper())
        attrs.setdefault(TX_HEIGHT_KEY, []).append(str(data.height))
        self._publish(EVENT_TX, data, attrs)

    def publish_vote(self, data: EventDataVote) -> None:
        self._publish(EVENT_VOTE, data)

    def publish_new_evidence(self, data: EventDataNewEvidence) -> None:
        self._publish(EVENT_NEW_EVIDENCE, data)

    def publish_validator_set_updates(self, data: EventDataValidatorSetUpdates) -> None:
        self._publish(EVENT_VALIDATOR_SET_UPDATES, data)

    def publish_new_round(self, data: EventDataNewRound) -> None:
        self._publish(EVENT_NEW_ROUND, data)

    def publish_new_round_step(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_NEW_ROUND_STEP, data)

    def publish_complete_proposal(self, data: EventDataCompleteProposal) -> None:
        self._publish(EVENT_COMPLETE_PROPOSAL, data)

    def publish_timeout_propose(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_TIMEOUT_PROPOSE, data)

    def publish_timeout_wait(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_TIMEOUT_WAIT, data)

    def publish_polka(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_POLKA, data)

    def publish_relock(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_RELOCK, data)

    def publish_lock(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_LOCK, data)

    def publish_unlock(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_UNLOCK, data)

    def publish_valid_block(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_VALID_BLOCK, data)


def _abci_events_to_attrs(events: list | None) -> dict:
    """Flatten ABCI events ([{type, attributes:[{key,value,index}]}]) into
    composite 'type.key' → [values] pubsub attributes."""
    attrs: dict[str, list] = {}
    for ev in events or []:
        ev_type = getattr(ev, "type", None) or (ev.get("type") if isinstance(ev, dict) else "")
        raw_attrs = getattr(ev, "attributes", None) or (
            ev.get("attributes", []) if isinstance(ev, dict) else []
        )
        if not ev_type:
            continue
        for a in raw_attrs:
            key = getattr(a, "key", None) or (a.get("key") if isinstance(a, dict) else None)
            value = getattr(a, "value", None) or (a.get("value", "") if isinstance(a, dict) else "")
            if isinstance(key, bytes):
                key = key.decode()
            if isinstance(value, bytes):
                value = value.decode()
            if key:
                attrs.setdefault(f"{ev_type}.{key}", []).append(value)
    return attrs

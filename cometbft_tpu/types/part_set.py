"""Block parts: 64 KiB chunks with Merkle inclusion proofs
(reference: types/part_set.go).

Blocks are gossiped piece-wise: the proposer splits the proto-encoded block
into parts (types/part_set.go:150,166), the PartSetHeader carries the Merkle
root over the parts, and receivers verify each part's proof before assembly
(types/part_set.go:266 AddPart).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield

from cometbft_tpu.crypto import merkle, tmhash
from cometbft_tpu.crypto.merkle.proof import Proof, proofs_from_byte_slices
from cometbft_tpu.libs.bit_array import BitArray
from cometbft_tpu.types.block import BLOCK_PART_SIZE_BYTES, PartSetHeader
from cometbft_tpu.wire import proto as wire
from cometbft_tpu.wire.types import decode_proof, encode_proof


@dataclass
class Part:
    index: int
    bytes: bytes
    proof: Proof

    def validate_basic(self) -> None:
        """types/part_set.go Part.ValidateBasic."""
        if len(self.bytes) > BLOCK_PART_SIZE_BYTES:
            raise ValueError(
                f"too big: {len(self.bytes)} bytes, max: {BLOCK_PART_SIZE_BYTES}"
            )
        self.proof.validate_basic()

    def encode(self) -> bytes:
        out = wire.field_varint(1, self.index)
        out += wire.field_bytes(2, self.bytes)
        out += wire.field_message(3, encode_proof(self.proof), emit_empty=True)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Part":
        f = wire.decode_fields(data)
        return cls(
            index=wire.get_uvarint(f, 1),
            bytes=wire.get_bytes(f, 2),
            proof=decode_proof(wire.get_bytes(f, 3)),
        )


class PartSet:
    """types/part_set.go:125-300."""

    def __init__(self, header: PartSetHeader):
        self._header = header
        self._parts: list[Part | None] = [None] * header.total
        self._bit_array = BitArray(header.total)
        self._count = 0
        self._byte_size = 0

    @classmethod
    def from_data(cls, data: bytes, part_size: int = BLOCK_PART_SIZE_BYTES) -> "PartSet":
        """NewPartSetFromData (types/part_set.go:150-180): split, build the
        Merkle proofs over the raw part bytes."""
        total = (len(data) + part_size - 1) // part_size
        if total == 0:
            total = 1
        chunks = [data[i * part_size : (i + 1) * part_size] for i in range(total)]
        root, proofs = proofs_from_byte_slices(chunks)
        ps = cls(PartSetHeader(total=total, hash=root))
        for i, chunk in enumerate(chunks):
            part = Part(index=i, bytes=chunk, proof=proofs[i])
            ps._parts[i] = part
            ps._bit_array.set_index(i, True)
            ps._byte_size += len(chunk)
        ps._count = total
        return ps

    def header(self) -> PartSetHeader:
        return self._header

    def has_header(self, header: PartSetHeader) -> bool:
        return self._header == header

    def bit_array(self) -> BitArray:
        return self._bit_array.copy()

    def hash(self) -> bytes:
        return self._header.hash

    @property
    def total(self) -> int:
        return self._header.total

    @property
    def count(self) -> int:
        return self._count

    @property
    def byte_size(self) -> int:
        return self._byte_size

    def is_complete(self) -> bool:
        return self._count == self._header.total

    def add_part(self, part: Part) -> bool:
        """types/part_set.go:266-295: proof-checked insertion."""
        if part.index >= self._header.total:
            raise ValueError("error part set unexpected index")
        if self._parts[part.index] is not None:
            return False
        # Check hash proof against the part-set root.
        if part.proof.index != part.index or part.proof.total != self._header.total:
            raise ValueError("error part set invalid proof")
        part.proof.verify(self._header.hash, part.bytes)
        self._parts[part.index] = part
        self._bit_array.set_index(part.index, True)
        self._count += 1
        self._byte_size += len(part.bytes)
        return True

    def get_part(self, index: int) -> Part | None:
        if index < 0 or index >= len(self._parts):
            return None
        return self._parts[index]

    def get_reader(self) -> bytes:
        """Assembled block bytes (only when complete)."""
        if not self.is_complete():
            raise ValueError("cannot read incomplete part set")
        return b"".join(p.bytes for p in self._parts)

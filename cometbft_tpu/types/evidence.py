"""Evidence of Byzantine behavior (reference: types/evidence.go)."""

from __future__ import annotations

from dataclasses import dataclass, field as dfield

from cometbft_tpu.crypto import merkle, tmhash
from cometbft_tpu.types.block import SignedHeader
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.wire import proto as wire


@dataclass
class DuplicateVoteEvidence:
    """Two conflicting votes from one validator (types/evidence.go:35-160)."""

    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp: Time = dfield(default_factory=Time)

    TYPE_NAME = "duplicate_vote"

    @classmethod
    def new(cls, vote1: Vote, vote2: Vote, block_time: Time, val_set: ValidatorSet):
        """NewDuplicateVoteEvidence orders votes lexically by BlockID key
        (types/evidence.go:60-85)."""
        if vote1 is None or vote2 is None or val_set is None:
            raise ValueError("missing vote or validator set")
        _, val = val_set.get_by_address(vote1.validator_address)
        if val is None:
            raise ValueError("validator is not in validator set")
        if vote1.block_id.key() < vote2.block_id.key():
            vote_a, vote_b = vote1, vote2
        else:
            vote_a, vote_b = vote2, vote1
        return cls(
            vote_a=vote_a,
            vote_b=vote_b,
            total_voting_power=val_set.total_voting_power(),
            validator_power=val.voting_power,
            timestamp=block_time,
        )

    def bytes(self) -> bytes:
        return self.encode()

    def hash(self) -> bytes:
        return tmhash.sum(self.bytes())

    def height(self) -> int:
        return self.vote_a.height

    def time(self) -> Time:
        return self.timestamp

    def validate_basic(self) -> None:
        """types/evidence.go:121-145."""
        if self.vote_a is None or self.vote_b is None:
            raise ValueError("one or both of the votes are empty")
        self.vote_a.validate_basic()
        self.vote_b.validate_basic()
        if self.vote_a.block_id.key() >= self.vote_b.block_id.key():
            raise ValueError(
                "duplicate votes in invalid order (should be lexicographically ordered)"
            )

    def encode(self) -> bytes:
        out = wire.field_message(1, self.vote_a.encode(), emit_empty=True)
        out += wire.field_message(2, self.vote_b.encode(), emit_empty=True)
        out += wire.field_varint(3, self.total_voting_power)
        out += wire.field_varint(4, self.validator_power)
        out += wire.field_message(5, self.timestamp.encode(), emit_empty=True)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "DuplicateVoteEvidence":
        f = wire.decode_fields(data)
        return cls(
            vote_a=Vote.decode(wire.get_bytes(f, 1)),
            vote_b=Vote.decode(wire.get_bytes(f, 2)),
            total_voting_power=wire.get_varint(f, 3),
            validator_power=wire.get_varint(f, 4),
            timestamp=Time.decode(wire.get_bytes(f, 5)),
        )


@dataclass
class LightClientAttackEvidence:
    """A conflicting light block trace (types/evidence.go:195-330)."""

    conflicting_block: "LightBlock"
    common_height: int
    byzantine_validators: list = dfield(default_factory=list)
    total_voting_power: int = 0
    timestamp: Time = dfield(default_factory=Time)

    TYPE_NAME = "light_client_attack"

    def bytes(self) -> bytes:
        return self.encode()

    def hash(self) -> bytes:
        """types/evidence.go:307-314: H(conflicting header hash[:31] || varint
        common height) — NOTE the reference copies only Size-1 bytes of the
        block hash (an upstream quirk preserved for hash compatibility)."""
        height_varint = _go_put_varint(self.common_height)
        bz = bytearray(tmhash.SIZE + len(height_varint))
        block_hash = self.conflicting_block.signed_header.header.hash()
        # Go copies from a possibly-nil hash (zero bytes copied) — mirror
        # that tolerance for adversarial headers with no ValidatorsHash.
        if block_hash is not None:
            bz[: tmhash.SIZE - 1] = block_hash[: tmhash.SIZE - 1]
        bz[tmhash.SIZE :] = height_varint
        return tmhash.sum(bytes(bz))

    def height(self) -> int:
        return self.common_height

    def time(self) -> Time:
        return self.timestamp

    def validate_basic(self) -> None:
        """types/evidence.go:341-371."""
        if self.conflicting_block is None:
            raise ValueError("conflicting block is nil")
        if self.conflicting_block.signed_header is None:
            raise ValueError("conflicting block missing header")
        if self.total_voting_power <= 0:
            raise ValueError("negative or zero total voting power")
        if self.common_height <= 0:
            raise ValueError("negative or zero common height")
        conflicting_height = self.conflicting_block.signed_header.header.height
        if self.common_height > conflicting_height:
            raise ValueError(
                f"common height is ahead of the conflicting block height "
                f"({self.common_height} > {conflicting_height})"
            )
        self.conflicting_block.validate_basic(
            self.conflicting_block.signed_header.header.chain_id
        )

    def encode(self) -> bytes:
        out = wire.field_message(
            1, self.conflicting_block.encode(), emit_empty=True
        )
        out += wire.field_varint(2, self.common_height)
        for v in self.byzantine_validators:
            out += wire.field_message(3, v.encode(), emit_empty=True)
        out += wire.field_varint(4, self.total_voting_power)
        out += wire.field_message(5, self.timestamp.encode(), emit_empty=True)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "LightClientAttackEvidence":
        f = wire.decode_fields(data)
        return cls(
            conflicting_block=LightBlock.decode(wire.get_bytes(f, 1)),
            common_height=wire.get_varint(f, 2),
            byzantine_validators=[
                Validator.decode(b) for b in wire.get_repeated_bytes(f, 3)
            ],
            total_voting_power=wire.get_varint(f, 4),
            timestamp=Time.decode(wire.get_bytes(f, 5)),
        )


def _go_put_varint(v: int) -> bytes:
    """Go binary.PutVarint: zigzag + uvarint."""
    uv = (v << 1) if v >= 0 else ((-v) << 1) - 1
    return wire.encode_uvarint(uv)


@dataclass
class LightBlock:
    """types/light.go LightBlock = SignedHeader + ValidatorSet."""

    signed_header: SignedHeader
    validator_set: ValidatorSet | None

    def encode(self) -> bytes:
        out = wire.field_message(1, self.signed_header.encode(), emit_empty=True)
        if self.validator_set is not None:
            out += wire.field_message(2, self.validator_set.encode(), emit_empty=True)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "LightBlock":
        f = wire.decode_fields(data)
        vs = None
        if 2 in f:
            vs = ValidatorSet.decode(wire.get_bytes(f, 2))
        return cls(
            signed_header=SignedHeader.decode(wire.get_bytes(f, 1)),
            validator_set=vs,
        )

    def validate_basic(self, chain_id: str) -> None:
        """types/light.go LightBlock.ValidateBasic."""
        if self.signed_header is None:
            raise ValueError("missing signed header")
        if self.validator_set is None:
            raise ValueError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        vs_hash = self.validator_set.hash()
        if self.signed_header.header.validators_hash != vs_hash:
            raise ValueError(
                f"expected validators hash of header to match validator set hash "
                f"({self.signed_header.header.validators_hash.hex()} != {vs_hash.hex()})"
            )


# -- evidence list wire + hashing (types/evidence.go:400-450) -----------------


def encode_evidence(ev) -> bytes:
    """tendermint.types.Evidence oneof wrapper."""
    if isinstance(ev, DuplicateVoteEvidence):
        return wire.field_message(1, ev.encode(), emit_empty=True)
    if isinstance(ev, LightClientAttackEvidence):
        return wire.field_message(2, ev.encode(), emit_empty=True)
    raise ValueError(f"evidence is not recognized: {ev}")


def decode_evidence(data: bytes):
    f = wire.decode_fields(data)
    if 1 in f:
        return DuplicateVoteEvidence.decode(wire.get_bytes(f, 1))
    if 2 in f:
        return LightClientAttackEvidence.decode(wire.get_bytes(f, 2))
    raise ValueError("evidence is not recognized")


def encode_evidence_list(evidence: list) -> bytes:
    out = b""
    for ev in evidence:
        out += wire.field_message(1, encode_evidence(ev), emit_empty=True)
    return out


def decode_evidence_list(data: bytes) -> list:
    if not data:
        return []
    f = wire.decode_fields(data)
    return [decode_evidence(b) for b in wire.get_repeated_bytes(f, 1)]


def evidence_list_hash(evidence: list) -> bytes:
    """EvidenceList.Hash: merkle over Evidence.Bytes (types/evidence.go:436)."""
    return merkle.hash_from_byte_slices([ev.bytes() for ev in evidence])


MAX_EVIDENCE_BYTES_DENOMINATOR = 10

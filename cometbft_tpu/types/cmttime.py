"""Canonical time handling (reference: types/time/time.go + gogo stdtime wire).

Times are (seconds, nanos) pairs relative to the Unix epoch, matching
google.protobuf.Timestamp. The Go zero time (0001-01-01T00:00:00Z) is
seconds = -62135596800 — it appears in canonical sign bytes of zero-valued
votes (types/vote_test.go TestVoteSignBytesTestVectors case 0), so the
distinction between "zero time" and "unix epoch" is wire-visible.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass

from cometbft_tpu.wire import proto as wire

# Seconds from 0001-01-01T00:00:00Z to the Unix epoch (Go's zero time).
GO_ZERO_SECONDS = -62135596800


@dataclass(frozen=True, order=True)
class Time:
    seconds: int = GO_ZERO_SECONDS
    nanos: int = 0

    def is_zero(self) -> bool:
        return self.seconds == GO_ZERO_SECONDS and self.nanos == 0

    def add_nanos(self, delta: int) -> "Time":
        total = self.seconds * 10**9 + self.nanos + delta
        return Time(total // 10**9, total % 10**9)

    def unix_nanos(self) -> int:
        return self.seconds * 10**9 + self.nanos

    def before(self, other: "Time") -> bool:
        return self.unix_nanos() < other.unix_nanos()

    def after(self, other: "Time") -> bool:
        return self.unix_nanos() > other.unix_nanos()

    # -- wire ---------------------------------------------------------------

    def encode(self) -> bytes:
        """google.protobuf.Timestamp {seconds=1 int64, nanos=2 int32}."""
        return wire.field_varint(1, self.seconds) + wire.field_varint(2, self.nanos)

    @classmethod
    def decode(cls, data: bytes) -> "Time":
        f = wire.decode_fields(data)
        return cls(wire.get_varint(f, 1), wire.get_varint(f, 2))

    # -- RFC3339 (genesis JSON / RPC) ---------------------------------------

    def rfc3339(self) -> str:
        secs = self.seconds
        frac = ""
        if self.nanos:
            frac = "." + f"{self.nanos:09d}".rstrip("0")
        st = _time.gmtime(secs) if secs >= 0 else _gmtime_neg(secs)
        return (
            f"{st[0]:04d}-{st[1]:02d}-{st[2]:02d}T"
            f"{st[3]:02d}:{st[4]:02d}:{st[5]:02d}{frac}Z"
        )

    @classmethod
    def parse_rfc3339(cls, s: str) -> "Time":
        import calendar
        import datetime as dt
        import re

        s = s.strip()
        offset_sec = 0
        if s.endswith(("Z", "z")):
            s = s[:-1]
        else:
            m = re.search(r"([+-])(\d{2}):(\d{2})$", s)
            if m:
                offset_sec = (int(m.group(2)) * 3600 + int(m.group(3)) * 60) * (
                    1 if m.group(1) == "+" else -1
                )
                s = s[: m.start()]
        nanos = 0
        if "." in s:
            s, frac = s.split(".")
            nanos = int((frac + "0" * 9)[:9])
        d = dt.datetime.strptime(s, "%Y-%m-%dT%H:%M:%S")
        return cls(calendar.timegm(d.timetuple()) - offset_sec, nanos)


def _gmtime_neg(secs: int):
    import datetime as dt

    d = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc) + dt.timedelta(seconds=secs)
    return (d.year, d.month, d.day, d.hour, d.minute, d.second)


ZERO = Time()

# Pluggable time source (simnet): when set, now() reads virtual time so
# block/vote timestamps are deterministic under a SimClock. Production
# never touches this — the wall clock stays the default.
_now_source = None


def set_now_source(fn) -> None:
    """Install ``fn() -> Time`` as the source behind now() (None resets).

    Process-global: only the single-threaded simnet scenario harness uses
    it, and always restores None before returning.
    """
    global _now_source
    _now_source = fn


def now() -> Time:
    """Current UTC time (types/time.Now is UTC + monotonic-stripped)."""
    if _now_source is not None:
        return _now_source()
    ns = _time.time_ns()
    return Time(ns // 10**9, ns % 10**9)


def canonical(t: Time) -> Time:
    """cmttime.Canonical: UTC, monotonic stripped — identity here."""
    return t

"""LightBlock: the light client's unit of data (reference: types/light.go).

SignedHeader (header + its commit) plus the validator set of that height —
everything needed to verify the commit and chain to the next header.
"""

from __future__ import annotations

from dataclasses import dataclass
from dataclasses import field as dfield

from cometbft_tpu.types.block import SignedHeader
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.wire import proto as wire


@dataclass
class LightBlock:
    """types/light.go LightBlock."""

    signed_header: SignedHeader
    validator_set: ValidatorSet
    # Encode memo (immutable-after-construction, the Commit._hash contract):
    # providers hand the same LightBlock to store saves and gossip encodes
    # repeatedly, and a 4k-validator block costs ~100 ms per encode.
    _enc: bytes | None = dfield(default=None, compare=False, repr=False)

    @property
    def height(self) -> int:
        return self.signed_header.header.height

    @property
    def header(self):
        return self.signed_header.header

    def hash(self) -> bytes:
        return self.signed_header.header.hash()

    def validate_basic(self, chain_id: str) -> None:
        """types/light.go LightBlock.ValidateBasic."""
        if self.signed_header is None:
            raise ValueError("missing signed header")
        if self.validator_set is None:
            raise ValueError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        if self.signed_header.header.validators_hash != self.validator_set.hash():
            raise ValueError(
                f"expected validators hash of header to match validator set "
                f"hash ({self.signed_header.header.validators_hash.hex()} != "
                f"{self.validator_set.hash().hex()})"
            )

    def encode(self) -> bytes:
        if self._enc is None:
            self._enc = wire.field_message(
                1, self.signed_header.encode(), emit_empty=True
            ) + wire.field_message(
                2, self.validator_set.encode(), emit_empty=True
            )
        return self._enc

    @classmethod
    def decode(cls, data: bytes) -> "LightBlock":
        f = wire.decode_fields(data)
        # No encode-memo from the wire input: a peer's non-canonical field
        # order must not survive as this block's canonical encoding.
        return cls(
            signed_header=SignedHeader.decode(wire.get_bytes(f, 1)),
            validator_set=ValidatorSet.decode(wire.get_bytes(f, 2)),
        )

"""Block, Header, Commit, and BlockID (reference: types/block.go).

Wire layouts follow proto/tendermint/types/types.proto; hashes follow the
reference exactly: Header.Hash is the Merkle root over the 14
protobuf-encoded header fields (types/block.go:440-475), Commit.Hash the
root over proto-encoded CommitSigs (types/block.go:895-913), and the
wrapper-value encoding of primitive fields mirrors cdcEncode
(types/encoding_helper.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield

from cometbft_tpu.crypto import merkle, tmhash
from cometbft_tpu.types import cmttime
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.wire import proto as wire

MAX_HEADER_BYTES = 626  # types/block.go MaxHeaderBytes
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3

# Blocks are gossiped in parts of this size (types/params.go:20 BlockPartSizeBytes).
BLOCK_PART_SIZE_BYTES = 65536

MAX_COMMIT_OVERHEAD_BYTES = 94  # types/block.go MaxCommitOverheadBytes
MAX_COMMIT_SIG_BYTES = 109  # types/block.go MaxCommitSigBytes


def cdc_encode_bytes(b: bytes) -> bytes:
    """cdcEncode for HexBytes: gogotypes.BytesValue{Value: b} or nil if empty
    (types/encoding_helper.go)."""
    if not b:
        return b""
    return wire.field_bytes(1, b)


def cdc_encode_string(s: str) -> bytes:
    if not s:
        return b""
    return wire.field_string(1, s)


def cdc_encode_int64(v: int) -> bytes:
    if v == 0:
        return b""
    return wire.field_varint(1, v)


@dataclass(frozen=True)
class Consensus:
    """tendermint.version.Consensus (proto/tendermint/version/types.proto)."""

    block: int = 0
    app: int = 0

    def encode(self) -> bytes:
        return wire.field_varint(1, self.block) + wire.field_varint(2, self.app)

    @classmethod
    def decode(cls, data: bytes) -> "Consensus":
        f = wire.decode_fields(data)
        return cls(wire.get_uvarint(f, 1), wire.get_uvarint(f, 2))


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and len(self.hash) == 0

    def encode(self) -> bytes:
        return wire.field_varint(1, self.total) + wire.field_bytes(2, self.hash)

    @classmethod
    def decode(cls, data: bytes) -> "PartSetHeader":
        f = wire.decode_fields(data)
        return cls(wire.get_uvarint(f, 1), wire.get_bytes(f, 2))

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError(
                f"wrong Hash: expected size {tmhash.SIZE}, got {len(self.hash)}"
            )


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = dfield(default_factory=PartSetHeader)

    def is_zero(self) -> bool:
        """Either an empty blockID (nil-vote) — types/block.go BlockID.IsZero."""
        return len(self.hash) == 0 and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return (
            len(self.hash) == tmhash.SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == tmhash.SIZE
        )

    def key(self) -> bytes:
        """Map key: hash || proto(PartSetHeader) (types/block.go Key) — the
        ordering basis for DuplicateVoteEvidence votes, so it must match the
        reference byte-for-byte."""
        return self.hash + self.part_set_header.encode()

    def encode(self) -> bytes:
        # part_set_header is gogoproto non-nullable: always marshaled, so a
        # zero BlockID encodes as b"\x12\x00" (types.pb.go BlockID
        # MarshalToSizedBuffer emits tag 0x12 unconditionally). This shapes
        # the height-1 header hash of every chain.
        return wire.field_bytes(1, self.hash) + wire.field_message(
            2, self.part_set_header.encode(), emit_empty=True
        )

    @classmethod
    def decode(cls, data: bytes) -> "BlockID":
        f = wire.decode_fields(data)
        return cls(
            wire.get_bytes(f, 1), PartSetHeader.decode(wire.get_bytes(f, 2))
        )

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("wrong Hash")
        self.part_set_header.validate_basic()


@dataclass(frozen=True)
class Header:
    """types/block.go Header."""

    version: Consensus = dfield(default_factory=Consensus)
    chain_id: str = ""
    height: int = 0
    time: Time = dfield(default_factory=Time)
    last_block_id: BlockID = dfield(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""

    def hash(self) -> bytes | None:
        """Merkle root over the 14 encoded fields (types/block.go:440-475).
        None when ValidatorsHash is missing (header not yet complete).

        Memoized per instance (frozen dataclass; the cache lives in
        __dict__, outside __eq__/__hash__): consensus compares
        proposal/locked block hashes on every vote admission, and at
        scenario scale that re-merkleization dominates the profile.
        """
        if not self.validators_hash:
            return None
        cached = self.__dict__.get("_hash_memo")
        if cached is not None:
            return cached
        hv = merkle.hash_from_byte_slices(
            [
                self.version.encode(),
                cdc_encode_string(self.chain_id),
                cdc_encode_int64(self.height),
                self.time.encode(),
                self.last_block_id.encode(),
                cdc_encode_bytes(self.last_commit_hash),
                cdc_encode_bytes(self.data_hash),
                cdc_encode_bytes(self.validators_hash),
                cdc_encode_bytes(self.next_validators_hash),
                cdc_encode_bytes(self.consensus_hash),
                cdc_encode_bytes(self.app_hash),
                cdc_encode_bytes(self.last_results_hash),
                cdc_encode_bytes(self.evidence_hash),
                cdc_encode_bytes(self.proposer_address),
            ]
        )
        object.__setattr__(self, "_hash_memo", hv)
        return hv

    def encode(self) -> bytes:
        """proto Header (non-nullable version/time/last_block_id always emitted)."""
        out = wire.field_message(1, self.version.encode(), emit_empty=True)
        out += wire.field_string(2, self.chain_id)
        out += wire.field_varint(3, self.height)
        out += wire.field_message(4, self.time.encode(), emit_empty=True)
        out += wire.field_message(5, self.last_block_id.encode(), emit_empty=True)
        out += wire.field_bytes(6, self.last_commit_hash)
        out += wire.field_bytes(7, self.data_hash)
        out += wire.field_bytes(8, self.validators_hash)
        out += wire.field_bytes(9, self.next_validators_hash)
        out += wire.field_bytes(10, self.consensus_hash)
        out += wire.field_bytes(11, self.app_hash)
        out += wire.field_bytes(12, self.last_results_hash)
        out += wire.field_bytes(13, self.evidence_hash)
        out += wire.field_bytes(14, self.proposer_address)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Header":
        f = wire.decode_fields(data)
        return cls(
            version=Consensus.decode(wire.get_bytes(f, 1)),
            chain_id=wire.get_string(f, 2),
            height=wire.get_varint(f, 3),
            time=Time.decode(wire.get_bytes(f, 4)),
            last_block_id=BlockID.decode(wire.get_bytes(f, 5)),
            last_commit_hash=wire.get_bytes(f, 6),
            data_hash=wire.get_bytes(f, 7),
            validators_hash=wire.get_bytes(f, 8),
            next_validators_hash=wire.get_bytes(f, 9),
            consensus_hash=wire.get_bytes(f, 10),
            app_hash=wire.get_bytes(f, 11),
            last_results_hash=wire.get_bytes(f, 12),
            evidence_hash=wire.get_bytes(f, 13),
            proposer_address=wire.get_bytes(f, 14),
        )

    def validate_basic(self) -> None:
        """types/block.go:376-432."""
        if len(self.chain_id) > 50:
            raise ValueError("chainID is too long")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.height == 0:
            raise ValueError("zero Height")
        self.last_block_id.validate_basic()
        _validate_hash(self.last_commit_hash, "LastCommitHash")
        _validate_hash(self.data_hash, "DataHash")
        _validate_hash(self.evidence_hash, "EvidenceHash")
        if len(self.proposer_address) not in (0, tmhash.TRUNCATED_SIZE):
            raise ValueError("invalid ProposerAddress length")
        _validate_hash(self.validators_hash, "ValidatorsHash")
        _validate_hash(self.next_validators_hash, "NextValidatorsHash")
        _validate_hash(self.consensus_hash, "ConsensusHash")
        _validate_hash(self.last_results_hash, "LastResultsHash")


def _validate_hash(h: bytes, name: str) -> None:
    """types/validation.go ValidateHash: empty or tmhash.Size."""
    if h and len(h) != tmhash.SIZE:
        raise ValueError(
            f"wrong {name}: expected size {tmhash.SIZE}, got {len(h)}"
        )


@dataclass(frozen=True)
class CommitSig:
    """types/block.go:575-660."""

    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp: Time = dfield(default_factory=Time)
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls(block_id_flag=BLOCK_ID_FLAG_ABSENT)

    @classmethod
    def for_block(cls, addr: bytes, ts: Time, sig: bytes) -> "CommitSig":
        return cls(BLOCK_ID_FLAG_COMMIT, addr, ts, sig)

    def is_absent(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_ABSENT

    def for_block_flag(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this sig endorses (types/block.go:680-695)."""
        if self.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            return commit_block_id
        return BlockID()

    def encode(self) -> bytes:
        out = wire.field_varint(1, self.block_id_flag)
        out += wire.field_bytes(2, self.validator_address)
        out += wire.field_message(3, self.timestamp.encode(), emit_empty=True)
        out += wire.field_bytes(4, self.signature)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "CommitSig":
        f = wire.decode_fields(data)
        return cls(
            block_id_flag=wire.get_uvarint(f, 1),
            validator_address=wire.get_bytes(f, 2),
            timestamp=Time.decode(wire.get_bytes(f, 3)),
            signature=wire.get_bytes(f, 4),
        )

    def validate_basic(self, aggregated: bool = False) -> None:
        """types/block.go:700-740. aggregated=True is the ISSUE-9 wire form:
        the signature bytes live in the commit-level aggregate, so a
        non-absent entry must carry an EMPTY per-sig column."""
        if self.block_id_flag not in (
            BLOCK_ID_FLAG_ABSENT,
            BLOCK_ID_FLAG_COMMIT,
            BLOCK_ID_FLAG_NIL,
        ):
            raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")
        if self.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            if self.validator_address:
                raise ValueError("validator address is present for absent CommitSig")
            if not self.timestamp.is_zero():
                raise ValueError("time is present for absent CommitSig")
            if self.signature:
                raise ValueError("signature is present for absent CommitSig")
        else:
            if len(self.validator_address) != tmhash.TRUNCATED_SIZE:
                raise ValueError("expected ValidatorAddress size to be 20 bytes")
            if aggregated:
                if self.signature:
                    raise ValueError(
                        "per-signature bytes present in aggregate commit"
                    )
            else:
                if not self.signature:
                    raise ValueError("signature is missing")
                if len(self.signature) > MAX_SIGNATURE_SIZE:
                    raise ValueError("signature is too big")


# types/signable.go MaxSignatureSize is 96, sized for compressed bn254 G2;
# this rebuild's bn254 signatures are UNCOMPRESSED G2 (crypto/bn254.py
# SIGNATURE_SIZE = 128), so per-vote bn254 commits need the extra room.
MAX_SIGNATURE_SIZE = 128
# Aggregate-commit wire form (ISSUE 9): one bn254 G2 sum. Round 10 shrinks
# new blocks to the 64-byte compressed encoding; the uncompressed 128-byte
# form stays accepted so blocks produced by earlier rounds keep validating.
AGG_SIGNATURE_SIZE = 128
AGG_SIGNATURE_SIZE_COMPRESSED = 64


@dataclass
class Commit:
    """types/block.go:745-930."""

    height: int = 0
    round: int = 0
    block_id: BlockID = dfield(default_factory=BlockID)
    signatures: list = dfield(default_factory=list)
    # Aggregate wire form (ISSUE 9, CMTPU_AGG_COMMITS): one G2 sum over every
    # non-absent signature plus a signer bitmap; the per-sig columns above
    # are then empty. Both empty = today's per-vote form, byte-identical on
    # the wire (fields 5/6 are simply not emitted).
    agg_signature: bytes = b""
    agg_bitmap: bytes = b""
    _hash: bytes | None = dfield(default=None, compare=False, repr=False)
    _sb_cache: tuple | None = dfield(default=None, compare=False, repr=False)
    _sba_cache: tuple | None = dfield(default=None, compare=False, repr=False)

    def size(self) -> int:
        return len(self.signatures)

    def is_aggregate(self) -> bool:
        return bool(self.agg_signature)

    def agg_signer(self, idx: int) -> bool:
        """Whether validator idx's signature is folded into agg_signature."""
        byte = idx >> 3
        if byte >= len(self.agg_bitmap):
            return False
        return bool(self.agg_bitmap[byte] & (1 << (idx & 7)))

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [cs.encode() for cs in self.signatures]
            )
        return self._hash

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        """Reconstruct the canonical signed vote of validator val_idx
        (types/block.go:785-813) — per-sig timestamps make every batch entry
        distinct message bytes.

        Hot path: VerifyCommitLight(10k validators) calls this once per
        signature, but type/height/round/block_id/chain_id are commit-wide
        constants — only field 5 (timestamp) varies. The canonical prefix
        (one per BlockIDFlag: commit block_id vs nil's dropped block_id) and
        the chain_id suffix are built once and cached; per call this splices
        the timestamp and re-runs only the outer length delimiter."""
        cs = self.signatures[val_idx]
        _, pre_commit, pre_nil, suffix = self._sign_bytes_cache(chain_id)
        prefix = pre_commit if cs.for_block_flag() else pre_nil
        return self._splice_sign_bytes(prefix, suffix, cs)

    def _sign_bytes_cache(self, chain_id: str) -> tuple:
        from cometbft_tpu.types import canonical

        cache = self._sb_cache
        if cache is None or cache[0] != chain_id:
            head = (
                wire.field_varint(1, PRECOMMIT_TYPE)
                + wire.field_sfixed64(2, self.height)
                + wire.field_sfixed64(3, self.round)
            )
            cbid = canonical.canonical_block_id_bytes(self.block_id)
            pre_commit = head + (
                wire.field_message(4, cbid, emit_empty=True)
                if cbid is not None
                else b""
            )
            self._sb_cache = cache = (
                chain_id, pre_commit, head, wire.field_string(6, chain_id)
            )
        return cache

    def vote_sign_bytes_all(self, chain_id: str) -> list:
        """Every validator's canonical sign bytes at once — the batch-verify
        feeder. Vectorized over the commit with numpy: per-signature work is
        two varints spliced into a shared template, so the whole 10k-row
        build is a handful of array passes grouped by byte layout
        (flag x varint widths). Byte-identical to vote_sign_bytes(i).

        Memoized per (chain_id, commit): the light client's trusting and
        light checks of one hop, plus a bisection descent revisiting pivot
        commits, would otherwise rebuild the same 4k-row list several times
        per descent. Commits are immutable after construction (the same
        contract _hash and _sb_cache rely on)."""
        cached = self._sba_cache
        if cached is not None and cached[0] == chain_id:
            return cached[1]
        n = len(self.signatures)
        if n < 64:
            out = [self.vote_sign_bytes(chain_id, i) for i in range(n)]
            self._sba_cache = (chain_id, out)
            return out
        import numpy as np

        _, pre_commit, pre_nil, suffix = self._sign_bytes_cache(chain_id)

        secs = np.fromiter(
            (cs.timestamp.seconds for cs in self.signatures), np.int64, n
        ).view(np.uint64)
        nanos = np.fromiter(
            (cs.timestamp.nanos for cs in self.signatures), np.int64, n
        ).view(np.uint64)
        flags = np.fromiter(
            (cs.for_block_flag() for cs in self.signatures), bool, n
        )

        def varint_slots(v):
            slots = np.empty((n, 10), np.uint8)
            vv = v.copy()
            lens = np.ones(n, np.int64)
            for s in range(10):
                b = (vv & np.uint64(0x7F)).astype(np.uint8)
                vv = vv >> np.uint64(7)
                cont = vv != 0
                slots[:, s] = b | (cont.astype(np.uint8) << 7)
                if s:
                    lens += (v >> np.uint64(7 * s)) != 0
            return slots, lens

        sec_slots, sec_lens = varint_slots(secs)
        nano_slots, nano_lens = varint_slots(nanos)
        has_sec = secs != 0
        has_nano = nanos != 0
        ts_lens = has_sec * (1 + sec_lens) + has_nano * (1 + nano_lens)

        out: list = [None] * n
        # Group rows with identical byte layout; realistic commits produce
        # one or two groups (same epoch -> same sec width; nano width 1..5).
        key = (
            flags.astype(np.int64) * 10000
            + has_sec * 1000
            + sec_lens * has_sec * 100
            + has_nano * 10
            + nano_lens * has_nano
        )
        for k in np.unique(key):
            rows = np.nonzero(key == k)[0]
            r0 = rows[0]
            prefix = pre_commit if flags[r0] else pre_nil
            tsl = int(ts_lens[r0])
            body_len = len(prefix) + 2 + tsl + len(suffix)
            outer = wire.encode_uvarint(body_len)
            total = len(outer) + body_len
            g = len(rows)
            m = np.empty((g, total), np.uint8)
            pos = 0
            for const in (outer, prefix, bytes([0x2A, tsl])):
                m[:, pos : pos + len(const)] = np.frombuffer(const, np.uint8)
                pos += len(const)
            if has_sec[r0]:
                m[:, pos] = 0x08
                sl = int(sec_lens[r0])
                m[:, pos + 1 : pos + 1 + sl] = sec_slots[rows, :sl]
                pos += 1 + sl
            if has_nano[r0]:
                m[:, pos] = 0x10
                nl = int(nano_lens[r0])
                m[:, pos + 1 : pos + 1 + nl] = nano_slots[rows, :nl]
                pos += 1 + nl
            m[:, pos : pos + len(suffix)] = np.frombuffer(suffix, np.uint8)
            buf = m.tobytes()
            for j, i in enumerate(rows):
                out[i] = buf[j * total : (j + 1) * total]
        self._sba_cache = (chain_id, out)
        return out

    @staticmethod
    def _splice_sign_bytes(prefix: bytes, suffix: bytes, cs) -> bytes:
        # Inline Timestamp{1: seconds varint, 2: nanos varint} + the field-5
        # and outer length delimiters: this runs once per signature in
        # VerifyCommitLight(10k), where the generic wire helpers' call
        # overhead dominates.
        ts = bytearray()
        sec = cs.timestamp.seconds
        if sec:
            if sec < 0:
                sec += 1 << 64
            ts.append(0x08)
            while sec > 0x7F:
                ts.append(sec & 0x7F | 0x80)
                sec >>= 7
            ts.append(sec)
        nano = cs.timestamp.nanos
        if nano:
            if nano < 0:
                nano += 1 << 64
            ts.append(0x10)
            while nano > 0x7F:
                ts.append(nano & 0x7F | 0x80)
                nano >>= 7
            ts.append(nano)
        out = prefix + b"\x2a" + wire.encode_uvarint(len(ts)) + ts + suffix
        return wire.encode_uvarint(len(out)) + out

    def encode(self) -> bytes:
        out = wire.field_varint(1, self.height)
        out += wire.field_varint(2, self.round)
        out += wire.field_message(3, self.block_id.encode(), emit_empty=True)
        for cs in self.signatures:
            out += wire.field_message(4, cs.encode(), emit_empty=True)
        if self.agg_signature:
            out += wire.field_bytes(5, self.agg_signature)
            out += wire.field_bytes(6, self.agg_bitmap)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Commit":
        f = wire.decode_fields(data)
        return cls(
            height=wire.get_varint(f, 1),
            round=wire.get_varint(f, 2),
            block_id=BlockID.decode(wire.get_bytes(f, 3)),
            signatures=[CommitSig.decode(b) for b in wire.get_repeated_bytes(f, 4)],
            agg_signature=wire.get_bytes(f, 5),
            agg_bitmap=wire.get_bytes(f, 6),
        )

    def validate_basic(self) -> None:
        """types/block.go:860-893, plus the aggregate-form consistency rules:
        the bitmap must mirror the non-absent entries exactly, every per-sig
        column must be empty, and the G2 point is 64 (compressed) or 128
        (uncompressed) bytes."""
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.agg_bitmap and not self.agg_signature:
            raise ValueError("aggregate bitmap without aggregate signature")
        if self.height >= 1:
            if self.block_id.is_zero():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            aggregated = self.is_aggregate()
            if aggregated:
                if len(self.agg_signature) not in (
                    AGG_SIGNATURE_SIZE,
                    AGG_SIGNATURE_SIZE_COMPRESSED,
                ):
                    raise ValueError(
                        "aggregate signature must be 64 (compressed) or "
                        "128 bytes (bn254 G2)"
                    )
                n = len(self.signatures)
                if len(self.agg_bitmap) != (n + 7) // 8:
                    raise ValueError("aggregate bitmap length mismatch")
                if n % 8 and self.agg_bitmap[-1] >> (n % 8):
                    raise ValueError(
                        "aggregate bitmap has bits past the validator count"
                    )
            for i, cs in enumerate(self.signatures):
                try:
                    cs.validate_basic(aggregated=aggregated)
                except ValueError as e:
                    raise ValueError(f"wrong CommitSig #{i}: {e}") from e
                if aggregated and self.agg_signer(i) == cs.is_absent():
                    raise ValueError(
                        f"aggregate bitmap disagrees with CommitSig #{i}"
                    )


def aggregate_commit(commit: "Commit", vals) -> "Commit":
    """Compress a per-vote commit into the aggregate wire form (one G2 sum +
    a signer bitmap) when every participating validator key is bn254
    (CMTPU_AGG_COMMITS call sites). Anything else — mixed key types, a
    malformed signature, an empty commit — returns the input unchanged: the
    per-vote form is always valid, so this can only shrink the wire.

    Only the block-embedded LastCommit goes through here; the locally stored
    seen commit keeps per-vote signatures so restart reconstruction
    (consensus._reconstruct_last_commit_if_needed) can rebuild the VoteSet.
    """
    from cometbft_tpu.crypto import bn254

    if commit.agg_signature or not commit.signatures or vals is None:
        return commit
    if vals.size() != len(commit.signatures):
        return commit
    raw: list = []
    bitmap = bytearray((len(commit.signatures) + 7) // 8)
    for i, cs in enumerate(commit.signatures):
        if cs.is_absent():
            continue
        pk = vals.validators[i].pub_key
        if pk is None or pk.type() != bn254.KEY_TYPE:
            return commit
        raw.append(cs.signature)
        bitmap[i >> 3] |= 1 << (i & 7)
    if not raw:
        return commit
    try:
        agg = bn254.aggregate_signatures_compressed(raw)
    except (ValueError, TypeError):
        # An admitted vote with an unparseable signature would be a bug
        # upstream; never let it block block production — ship per-vote.
        return commit
    stripped = [
        cs
        if cs.is_absent()
        else CommitSig(cs.block_id_flag, cs.validator_address, cs.timestamp, b"")
        for cs in commit.signatures
    ]
    return Commit(
        height=commit.height,
        round=commit.round,
        block_id=commit.block_id,
        signatures=stripped,
        agg_signature=agg,
        agg_bitmap=bytes(bitmap),
    )


# SignedMsgType values (proto/tendermint/types/types.proto).
UNKNOWN_TYPE = 0
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32


@dataclass
class Data:
    """Block transactions (types/block.go Data)."""

    txs: list = dfield(default_factory=list)
    _hash: bytes | None = dfield(default=None, compare=False, repr=False)

    def hash(self) -> bytes:
        from cometbft_tpu.types.tx import txs_hash

        if self._hash is None:
            self._hash = txs_hash(self.txs)
        return self._hash

    def encode(self) -> bytes:
        out = b""
        for tx in self.txs:
            out += wire.field_bytes(1, tx, emit_default=True)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Data":
        f = wire.decode_fields(data)
        return cls(txs=wire.get_repeated_bytes(f, 1))


@dataclass
class Block:
    """types/block.go:43-170."""

    header: Header = dfield(default_factory=Header)
    data: Data = dfield(default_factory=Data)
    evidence: list = dfield(default_factory=list)  # list of Evidence
    last_commit: Commit | None = None
    _hash: bytes | None = dfield(default=None, compare=False, repr=False)

    def hash(self) -> bytes | None:
        """Header hash (types/block.go:123)."""
        if self.last_commit is None and self.header.height > 1:
            return None
        return self.header.hash()

    def validate_basic(self) -> None:
        """Re-derives LastCommitHash/DataHash/EvidenceHash (types/block.go:56-107)."""
        self.header.validate_basic()
        if self.header.height > 1:
            if self.last_commit is None:
                raise ValueError("nil LastCommit")
            self.last_commit.validate_basic()
        if self.last_commit is not None:
            if self.header.last_commit_hash != self.last_commit.hash():
                raise ValueError("wrong Header.LastCommitHash")
        elif self.header.last_commit_hash:
            raise ValueError("wrong Header.LastCommitHash")
        if self.header.data_hash != self.data.hash():
            raise ValueError("wrong Header.DataHash")
        from cometbft_tpu.types.evidence import evidence_list_hash

        if self.header.evidence_hash != evidence_list_hash(self.evidence):
            raise ValueError("wrong Header.EvidenceHash")

    def encode(self) -> bytes:
        from cometbft_tpu.types.evidence import encode_evidence_list

        out = wire.field_message(1, self.header.encode(), emit_empty=True)
        out += wire.field_message(2, self.data.encode(), emit_empty=True)
        out += wire.field_message(3, encode_evidence_list(self.evidence), emit_empty=True)
        if self.last_commit is not None:
            out += wire.field_message(4, self.last_commit.encode(), emit_empty=True)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        from cometbft_tpu.types.evidence import decode_evidence_list

        f = wire.decode_fields(data)
        last_commit = None
        if 4 in f:
            last_commit = Commit.decode(wire.get_bytes(f, 4))
        return cls(
            header=Header.decode(wire.get_bytes(f, 1)),
            data=Data.decode(wire.get_bytes(f, 2)),
            evidence=decode_evidence_list(wire.get_bytes(f, 3)),
            last_commit=last_commit,
        )

    def make_part_set(self, part_size: int = BLOCK_PART_SIZE_BYTES):
        from cometbft_tpu.types.part_set import PartSet

        return PartSet.from_data(self.encode(), part_size)


@dataclass(frozen=True)
class BlockMeta:
    """types/block_meta.go."""

    block_id: BlockID
    block_size: int
    header: Header
    num_txs: int

    def encode(self) -> bytes:
        out = wire.field_message(1, self.block_id.encode(), emit_empty=True)
        out += wire.field_varint(2, self.block_size)
        out += wire.field_message(3, self.header.encode(), emit_empty=True)
        out += wire.field_varint(4, self.num_txs)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "BlockMeta":
        f = wire.decode_fields(data)
        return cls(
            block_id=BlockID.decode(wire.get_bytes(f, 1)),
            block_size=wire.get_varint(f, 2),
            header=Header.decode(wire.get_bytes(f, 3)),
            num_txs=wire.get_varint(f, 4),
        )


@dataclass(frozen=True)
class SignedHeader:
    """types/light.go SignedHeader: header + its commit."""

    header: Header
    commit: Commit

    def encode(self) -> bytes:
        return wire.field_message(1, self.header.encode(), emit_empty=True) + (
            wire.field_message(2, self.commit.encode(), emit_empty=True)
        )

    @classmethod
    def decode(cls, data: bytes) -> "SignedHeader":
        f = wire.decode_fields(data)
        return cls(
            Header.decode(wire.get_bytes(f, 1)), Commit.decode(wire.get_bytes(f, 2))
        )

    def validate_basic(self, chain_id: str) -> None:
        """types/light.go SignedHeader.ValidateBasic."""
        if self.header is None:
            raise ValueError("missing header")
        if self.commit is None:
            raise ValueError("missing commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header belongs to another chain {self.header.chain_id!r}, not {chain_id!r}"
            )
        if self.header.height != self.commit.height:
            raise ValueError("header and commit height mismatch")
        hhash = self.header.hash()
        if hhash != self.commit.block_id.hash:
            raise ValueError("commit signs block which doesn't match the header")

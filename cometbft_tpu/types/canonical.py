"""Canonical sign-bytes construction (reference: types/canonical.go +
proto/tendermint/types/canonical.proto).

Golden-tested against the reference's TestVoteSignBytesTestVectors
(types/vote_test.go:60). Canonicalization rules that matter on the wire:
height/round are sfixed64 (fixed-size so signing hardware can parse),
block_id is dropped entirely when zero (nil votes), the timestamp submessage
is always emitted (gogoproto non-nullable), and the result is
length-delimited (protoio MarshalDelimited — types/vote.go VoteSignBytes).
"""

from __future__ import annotations

from cometbft_tpu.types.block import (
    BlockID,
    PartSetHeader,
    PRECOMMIT_TYPE,
    PROPOSAL_TYPE,
)
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.wire import proto as wire


def canonical_block_id_bytes(block_id: BlockID) -> bytes | None:
    """CanonicalizeBlockID (types/canonical.go:18-34): None when zero."""
    if block_id.is_zero():
        return None
    psh = wire.field_varint(1, block_id.part_set_header.total) + wire.field_bytes(
        2, block_id.part_set_header.hash
    )
    return wire.field_bytes(1, block_id.hash) + wire.field_message(
        2, psh, emit_empty=True
    )


def vote_sign_bytes_from_parts(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp: Time,
) -> bytes:
    """Length-delimited CanonicalVote (types/vote.go VoteSignBytes)."""
    out = wire.field_varint(1, msg_type)
    out += wire.field_sfixed64(2, height)
    out += wire.field_sfixed64(3, round_)
    cbid = canonical_block_id_bytes(block_id)
    if cbid is not None:
        out += wire.field_message(4, cbid, emit_empty=True)
    out += wire.field_message(5, timestamp.encode(), emit_empty=True)
    out += wire.field_string(6, chain_id)
    return wire.length_delimited(out)


def decode_canonical_vote(
    sign_bytes: bytes,
) -> tuple[int, int, int, BlockID, Time]:
    """Inverse of vote_sign_bytes_from_parts: (type, height, round, block_id,
    timestamp). The privval persists only sign_bytes + signature for its last
    signed vote; crash recovery decodes them back into a Vote when the WAL
    lost the original (the privval fsyncs before the WAL does)."""
    n, pos = wire.decode_uvarint(sign_bytes, 0)
    body = sign_bytes[pos : pos + n]
    if len(body) != n:
        raise ValueError("truncated canonical vote")
    fields = wire.decode_fields(body)
    msg_type = wire.get_varint(fields, 1)
    height = wire.get_sfixed64(fields, 2)
    round_ = wire.get_sfixed64(fields, 3)
    block_id = BlockID()
    cbid = wire.get_bytes(fields, 4)
    if cbid:
        cf = wire.decode_fields(cbid)
        psh = PartSetHeader()
        psh_raw = wire.get_bytes(cf, 2)
        if psh_raw:
            pf = wire.decode_fields(psh_raw)
            psh = PartSetHeader(wire.get_varint(pf, 1), wire.get_bytes(pf, 2))
        block_id = BlockID(wire.get_bytes(cf, 1), psh)
    timestamp = Time.decode(wire.get_bytes(fields, 5))
    return msg_type, height, round_, block_id, timestamp


def proposal_sign_bytes_from_parts(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: BlockID,
    timestamp: Time,
) -> bytes:
    """Length-delimited CanonicalProposal (types/proposal.go ProposalSignBytes)."""
    out = wire.field_varint(1, PROPOSAL_TYPE)
    out += wire.field_sfixed64(2, height)
    out += wire.field_sfixed64(3, round_)
    out += wire.field_varint(4, pol_round)
    cbid = canonical_block_id_bytes(block_id)
    if cbid is not None:
        out += wire.field_message(5, cbid, emit_empty=True)
    out += wire.field_message(6, timestamp.encode(), emit_empty=True)
    out += wire.field_string(7, chain_id)
    return wire.length_delimited(out)

"""Canonical sign-bytes construction (reference: types/canonical.go +
proto/tendermint/types/canonical.proto).

Golden-tested against the reference's TestVoteSignBytesTestVectors
(types/vote_test.go:60). Canonicalization rules that matter on the wire:
height/round are sfixed64 (fixed-size so signing hardware can parse),
block_id is dropped entirely when zero (nil votes), the timestamp submessage
is always emitted (gogoproto non-nullable), and the result is
length-delimited (protoio MarshalDelimited — types/vote.go VoteSignBytes).
"""

from __future__ import annotations

from cometbft_tpu.types.block import BlockID, PRECOMMIT_TYPE, PROPOSAL_TYPE
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.wire import proto as wire


def canonical_block_id_bytes(block_id: BlockID) -> bytes | None:
    """CanonicalizeBlockID (types/canonical.go:18-34): None when zero."""
    if block_id.is_zero():
        return None
    psh = wire.field_varint(1, block_id.part_set_header.total) + wire.field_bytes(
        2, block_id.part_set_header.hash
    )
    return wire.field_bytes(1, block_id.hash) + wire.field_message(
        2, psh, emit_empty=True
    )


def vote_sign_bytes_from_parts(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp: Time,
) -> bytes:
    """Length-delimited CanonicalVote (types/vote.go VoteSignBytes)."""
    out = wire.field_varint(1, msg_type)
    out += wire.field_sfixed64(2, height)
    out += wire.field_sfixed64(3, round_)
    cbid = canonical_block_id_bytes(block_id)
    if cbid is not None:
        out += wire.field_message(4, cbid, emit_empty=True)
    out += wire.field_message(5, timestamp.encode(), emit_empty=True)
    out += wire.field_string(6, chain_id)
    return wire.length_delimited(out)


def proposal_sign_bytes_from_parts(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: BlockID,
    timestamp: Time,
) -> bytes:
    """Length-delimited CanonicalProposal (types/proposal.go ProposalSignBytes)."""
    out = wire.field_varint(1, PROPOSAL_TYPE)
    out += wire.field_sfixed64(2, height)
    out += wire.field_sfixed64(3, round_)
    out += wire.field_varint(4, pol_round)
    cbid = canonical_block_id_bytes(block_id)
    if cbid is not None:
        out += wire.field_message(5, cbid, emit_empty=True)
    out += wire.field_message(6, timestamp.encode(), emit_empty=True)
    out += wire.field_string(7, chain_id)
    return wire.length_delimited(out)

"""Commit verification engines (reference: types/validation.go).

The three modes share two engines: batch (routes whole commits to the TPU
device tier through crypto.batch) and single (per-signature host verify).
Semantics mirror the reference exactly, including which signatures are
ignored vs counted per mode and the batch→single relationship (the device
path returns the per-sig bitmap directly, so the "first bad signature"
error is produced without re-verification).
"""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.types.block import BlockID, Commit, CommitSig

BATCH_VERIFY_THRESHOLD = 2  # types/validation.go:12


@dataclass(frozen=True)
class Fraction:
    """libs/math.Fraction (trust level, e.g. 1/3)."""

    numerator: int
    denominator: int


class ErrNotEnoughVotingPowerSigned(Exception):
    def __init__(self, got: int, needed: int):
        self.got = got
        self.needed = needed
        super().__init__(
            f"invalid commit -- insufficient voting power: got {got}, needed more than {needed}"
        )


class ErrInvalidCommitHeight(Exception):
    def __init__(self, expected: int, actual: int):
        super().__init__(
            f"Invalid commit -- wrong height: {expected} vs {actual}"
        )


class ErrInvalidCommitSignatures(Exception):
    def __init__(self, expected: int, actual: int):
        super().__init__(
            f"Invalid commit -- wrong set size: {expected} vs {actual}"
        )


def _batch_key_type(vals, commit: Commit) -> str | None:
    """The single key type shared by EVERY validator in the set, if that
    type is batch-capable — else None. The reference keys this decision on
    the proposer alone (validation.go:145-150), which mis-batches a mixed
    set: a bn254 signature fed into the ed25519 batch engine is a type
    error, not a clean reject. Homogeneous sets batch; mixed sets fall back
    to the per-signature scalar engine, which dispatches per key."""
    if len(commit.signatures) < BATCH_VERIFY_THRESHOLD:
        return None
    kt = None
    for val in vals.validators:
        pk = val.pub_key
        if pk is None:
            return None
        t = pk.type()
        if kt is None:
            kt = t
        elif t != kt:
            return None
    if kt is None or not crypto_batch.supports_batch_verifier(kt):
        return None
    return kt


def _should_batch_verify(vals, commit: Commit) -> bool:
    return _batch_key_type(vals, commit) is not None


def verify_commit(chain_id: str, vals, block_id: BlockID, height: int, commit: Commit) -> None:
    """+2/3 signed AND all signatures valid (types/validation.go:25-51).
    Checks every signature: apps may reward precommit inclusion."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: c.is_absent()
    count = lambda c: c.for_block_flag()
    if commit.is_aggregate():
        _verify_commit_aggregate(
            chain_id, vals, commit, voting_power_needed, ignore, count, True
        )
    elif _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count, True, True
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count, True, True
        )


def verify_commit_light(
    chain_id: str, vals, block_id: BlockID, height: int, commit: Commit
) -> None:
    """+2/3 signed; stops counting at quorum (types/validation.go:59-84)."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: not c.for_block_flag()
    count = lambda c: True
    if commit.is_aggregate():
        _verify_commit_aggregate(
            chain_id, vals, commit, voting_power_needed, ignore, count, True
        )
    elif _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count, False, True
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count, False, True
        )


def verify_commit_light_trusting(
    chain_id: str, vals, commit: Commit, trust_level: Fraction
) -> None:
    """trustLevel of a (possibly different) validator set signed this commit
    (types/validation.go:94-135); lookups are by address."""
    from cometbft_tpu.types.validator_set import safe_mul

    if vals is None:
        raise ValueError("nil validator set")
    if trust_level.denominator == 0:
        raise ValueError("trustLevel has zero Denominator")
    if commit is None:
        raise ValueError("nil commit")
    total_mul, overflow = safe_mul(vals.total_voting_power(), trust_level.numerator)
    if overflow:
        raise OverflowError(
            "int64 overflow while calculating voting power needed. please provide "
            "smaller trustLevel numerator"
        )
    voting_power_needed = total_mul // trust_level.denominator
    ignore = lambda c: not c.for_block_flag()
    count = lambda c: True
    if commit.is_aggregate():
        _verify_commit_aggregate(
            chain_id, vals, commit, voting_power_needed, ignore, count, False
        )
    elif _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count, False, False
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count, False, False
        )


def _verify_commit_aggregate(
    chain_id: str,
    vals,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig,
    count_sig,
    look_up_by_index: bool,
) -> None:
    """One pairing product stands in for every per-signature check (ISSUE 9).

    The aggregate is indivisible, so the semantics are deliberately stricter
    than the per-vote engines: the bitmap must mirror the non-absent entries
    exactly, every aggregated signer must resolve to a bn254 key in the
    verifying set, and the whole product is checked even in the light modes
    (there is no "stop at quorum" for a single G2 sum — nil votes ride along,
    which can only make acceptance stricter, never a wrong-accept). A reject
    is loud: there is no silent downgrade to scalar verification, because a
    poisoned aggregate has no per-signature form to fall back to.

    In trusting mode (look_up_by_index=False) a signer outside the trusted
    set leaves the product uncheckable — that raises, and the light client
    degrades to bisection exactly as it does for any failed trusting check.
    """
    from cometbft_tpu.crypto import bn254

    n = len(commit.signatures)
    if len(commit.agg_bitmap) != (n + 7) // 8:
        raise ValueError("aggregate bitmap length mismatch")
    seen_vals: dict[int, int] = {}
    pubs: list[bytes] = []
    msgs: list[bytes] = []
    tallied = 0
    all_sign_bytes = commit.vote_sign_bytes_all(chain_id)
    for idx, commit_sig in enumerate(commit.signatures):
        in_agg = commit.agg_signer(idx)
        if commit_sig.is_absent():
            if in_agg:
                raise ValueError(
                    f"aggregate bitmap set for absent CommitSig #{idx}"
                )
            continue
        if not in_agg:
            raise ValueError(
                f"aggregate bitmap clear for signed CommitSig #{idx}"
            )
        if commit_sig.signature:
            raise ValueError(
                f"per-signature bytes present in aggregate commit (#{idx})"
            )
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(commit_sig.validator_address)
            if val is None:
                raise ValueError(
                    f"aggregate commit signer #{idx} unknown to the verifying set"
                )
            if val_idx in seen_vals:
                raise ValueError(
                    f"double vote from {val} ({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        pk = val.pub_key
        if pk is None or pk.type() != bn254.KEY_TYPE:
            raise ValueError(
                f"aggregate commit requires bn254 keys (validator #{idx})"
            )
        pubs.append(pk.bytes())
        msgs.append(all_sign_bytes[idx])
        if not ignore_sig(commit_sig) and count_sig(commit_sig):
            tallied += val.voting_power
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(tallied, voting_power_needed)
    if not pubs:
        raise ValueError("aggregate commit with no signers")
    if not bn254.get_bn254_backend().aggregate_verify(
        pubs, msgs, commit.agg_signature
    ):
        raise ValueError(
            f"invalid aggregate signature for commit at height {commit.height}"
        )


def _verify_commit_batch(
    chain_id: str,
    vals,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig,
    count_sig,
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """types/validation.go:152-256 — the TPU call site."""
    kt = _batch_key_type(vals, commit)
    if kt is None:
        raise ValueError(
            "unsupported signature algorithm or insufficient signatures for batch verification"
        )
    bv = crypto_batch.create_batch_verifier(kt)
    seen_vals: dict[int, int] = {}
    batch_sig_idxs: list[int] = []
    tallied = 0
    all_sign_bytes = commit.vote_sign_bytes_all(chain_id)
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise ValueError(
                    f"double vote from {val} ({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        bv.add(val.pub_key, all_sign_bytes[idx], commit_sig.signature)
        batch_sig_idxs.append(idx)
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            break
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(tallied, voting_power_needed)
    ok, valid_sigs = bv.verify()
    if ok:
        return
    for i, sig_ok in enumerate(valid_sigs):
        if not sig_ok:
            idx = batch_sig_idxs[i]
            sig = commit.signatures[idx]
            raise ValueError(
                f"wrong signature (#{idx}): {sig.signature.hex().upper()}"
            )
    raise RuntimeError("BUG: batch verification failed with no invalid signatures")


def _verify_commit_single(
    chain_id: str,
    vals,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig,
    count_sig,
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """types/validation.go:265-340."""
    seen_vals: dict[int, int] = {}
    tallied = 0
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise ValueError(
                    f"double vote from {val} ({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)
        if not val.pub_key.verify_signature(vote_sign_bytes, commit_sig.signature):
            raise ValueError(
                f"wrong signature (#{idx}): {commit_sig.signature.hex().upper()}"
            )
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            return
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(tallied, voting_power_needed)


def speculative_verify_triples(
    chain_id: str,
    trusted_vals,
    untrusted_vals,
    commit: Commit,
    trust_level: Fraction | None,
) -> list[tuple]:
    """(pub_key, sign_bytes, signature) triples a hop's commit checks WILL
    verify — the speculative-bisection feeder (light/client.py).

    A non-adjacent hop runs verify_commit_light_trusting (old set, by
    address) then verify_commit_light (new set, by index); both walk the
    commit's signatures in order and stop at their quorum, and a
    signature's verify triple is identical in both (sign bytes depend only
    on the commit and chain id, never on the verifying set). This returns
    the union prefix both engines would touch, so prewarming the
    verified-triple cache with it makes the sequential checks pure cache
    hits without changing what they decide. trust_level=None means an
    adjacent hop: only the light-check prefix applies.

    Speculation must never fail a client, so malformed input returns []
    and unresolvable entries are skipped rather than raised on.
    """
    from cometbft_tpu.types.validator_set import safe_mul

    if commit is None or untrusted_vals is None:
        return []
    if commit.is_aggregate():
        return []  # one pairing product; no per-sig triples to prewarm
    if untrusted_vals.size() != len(commit.signatures):
        return []  # light check will reject this hop; nothing to prewarm
    light_needed = untrusted_vals.total_voting_power() * 2 // 3
    trusting_needed = -1  # adjacent: trivially satisfied
    if trust_level is not None and trusted_vals is not None:
        total_mul, overflow = safe_mul(
            trusted_vals.total_voting_power(), trust_level.numerator
        )
        if overflow:
            return []
        trusting_needed = total_mul // trust_level.denominator
    all_sign_bytes = commit.vote_sign_bytes_all(chain_id)
    triples: list[tuple] = []
    light_tally = 0
    trusting_tally = 0
    seen: set[int] = set()
    for idx, commit_sig in enumerate(commit.signatures):
        if not commit_sig.for_block_flag():
            continue  # both engines ignore non-BlockIDFlagCommit entries
        light_live = light_tally <= light_needed
        trusting_live = trusting_tally <= trusting_needed
        if not light_live and not trusting_live:
            break
        val = untrusted_vals.validators[idx]
        if light_live:
            light_tally += val.voting_power
            triples.append(
                (val.pub_key, all_sign_bytes[idx], commit_sig.signature)
            )
        if trusting_live:
            t_idx, t_val = trusted_vals.get_by_address(
                commit_sig.validator_address
            )
            if t_val is not None and t_idx not in seen:
                seen.add(t_idx)
                trusting_tally += t_val.voting_power
                # The trusting engine keys its triple by the TRUSTED set's
                # pubkey (address lookup); normally identical to the new
                # set's, so the light triple above already covers it.
                if not light_live or t_val.pub_key.bytes() != val.pub_key.bytes():
                    triples.append(
                        (
                            t_val.pub_key,
                            all_sign_bytes[idx],
                            commit_sig.signature,
                        )
                    )
    return triples


def _verify_basic_vals_and_commit(vals, commit, height: int, block_id: BlockID) -> None:
    """types/validation.go:342-365."""
    if vals is None:
        raise ValueError("nil validator set")
    if commit is None:
        raise ValueError("nil commit")
    if vals.size() != len(commit.signatures):
        raise ErrInvalidCommitSignatures(vals.size(), len(commit.signatures))
    if height != commit.height:
        raise ErrInvalidCommitHeight(height, commit.height)
    if block_id != commit.block_id:
        raise ValueError(
            f"invalid commit -- wrong block ID: want {block_id}, got {commit.block_id}"
        )

"""VoteSet: real-time 2/3-majority tracking during consensus
(reference: types/vote_set.go, 635 LoC).

Two storage areas exactly as the reference documents (vote_set.go:27-58):
`votes` (canonical, one per validator) and `votes_by_block` (per-block
tallies, tracking conflicts only for blocks a peer claims have 2/3). Memory
stays bounded: a conflicting vote is kept only when its block is tracked.
"""

from __future__ import annotations

import threading

from cometbft_tpu.crypto import sigbatch
from cometbft_tpu.libs.bit_array import BitArray
from cometbft_tpu.types.block import BlockID, Commit
from cometbft_tpu.types.vote import Vote, vote_to_commit_sig

MAX_VOTES_COUNT = 10000  # types/vote_set.go:15

# One error class across vote verification and vote-set bookkeeping, so
# callers (consensus tryAddVote) can classify invalid votes uniformly.
from cometbft_tpu.types.vote import VoteError  # noqa: E402


class ErrVoteConflictingVotes(Exception):
    """Double-sign detected (types/vote.go NewConflictingVoteError)."""

    def __init__(self, vote_a: Vote, vote_b: Vote):
        self.vote_a = vote_a
        self.vote_b = vote_b
        super().__init__(
            f"conflicting votes from validator {vote_a.validator_address.hex().upper()}"
        )


class _BlockVotes:
    """votes for one block (vote_set.go blockVotes)."""

    def __init__(self, peer_maj23: bool, num_validators: int):
        self.peer_maj23 = peer_maj23
        self.bit_array = BitArray(num_validators)
        self.votes: list[Vote | None] = [None] * num_validators
        self.sum = 0

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        idx = vote.validator_index
        if self.votes[idx] is None:
            self.bit_array.set_index(idx, True)
            self.votes[idx] = vote
            self.sum += voting_power

    def get_by_index(self, idx: int) -> Vote | None:
        if 0 <= idx < len(self.votes):
            return self.votes[idx]
        return None


class VoteSet:
    """types/vote_set.go:62-470."""

    def __init__(self, chain_id: str, height: int, round_: int, signed_msg_type: int, val_set):
        if height == 0:
            raise ValueError("Cannot make VoteSet for height == 0, doesn't make sense")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        self._mtx = threading.RLock()
        self.votes_bit_array = BitArray(val_set.size())
        self.votes: list[Vote | None] = [None] * val_set.size()
        self.sum = 0
        self.maj23: BlockID | None = None
        self.votes_by_block: dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: dict[str, BlockID] = {}

    def size(self) -> int:
        return self.val_set.size()

    # -- adding votes (vote_set.go:145-315) ----------------------------------

    def add_vote(self, vote: Vote | None) -> bool:
        with self._mtx:
            return self._add_vote(vote)

    def _add_vote(self, vote: Vote | None) -> bool:
        if vote is None:
            raise VoteError("nil vote")
        val_index = vote.validator_index
        val_addr = vote.validator_address
        block_key = vote.block_id.key()
        if val_index < 0:
            raise VoteError("index < 0: invalid validator index")
        if not val_addr:
            raise VoteError("empty address: invalid validator address")
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.type != self.signed_msg_type
        ):
            raise VoteError(
                f"expected {self.height}/{self.round}/{self.signed_msg_type}, got "
                f"{vote.height}/{vote.round}/{vote.type}: unexpected step"
            )
        lookup_addr, val = self.val_set.get_by_index(val_index)
        if val is None:
            raise VoteError(
                f"cannot find validator {val_index} in valSet of size {self.val_set.size()}"
            )
        if val_addr != lookup_addr:
            raise VoteError(
                f"vote.ValidatorAddress ({val_addr.hex().upper()}) does not match "
                f"address ({lookup_addr.hex().upper()}) for vote.ValidatorIndex ({val_index})"
            )
        existing = self._get_vote(val_index, block_key)
        if existing is not None:
            if existing.signature == vote.signature:
                return False  # exact duplicate
            raise VoteError(
                f"existing vote: {existing}; new vote: {vote}: non-deterministic signature"
            )
        # Check signature. The structural checks above stay inline; the
        # crypto rides the shared micro-batch window (crypto/sigbatch.py) so
        # concurrent admissions — gossip from many peers, every in-process
        # node of a devnet — merge into one columnar dispatch. Semantics are
        # exactly vote.verify's: address binding first, then the signature,
        # with the same VoteError messages (bit-identical to the scalar
        # path; asserted by tests/test_vote_batch.py).
        if val.pub_key.address() != val_addr:
            raise VoteError("invalid validator address")
        if not sigbatch.verify_vote_signature(
            val.pub_key, vote.sign_bytes(self.chain_id), vote.signature
        ):
            raise VoteError("invalid signature")
        added, conflicting = self._add_verified_vote(vote, block_key, val.voting_power)
        if conflicting is not None:
            raise ErrVoteConflictingVotes(conflicting, vote)
        if not added:
            raise RuntimeError("Expected to add non-conflicting vote")
        return added

    def _get_vote(self, val_index: int, block_key: bytes) -> Vote | None:
        existing = self.votes[val_index]
        if existing is not None and existing.block_id.key() == block_key:
            return existing
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            return bv.get_by_index(val_index)
        return None

    def _add_verified_vote(
        self, vote: Vote, block_key: bytes, voting_power: int
    ) -> tuple[bool, Vote | None]:
        val_index = vote.validator_index
        conflicting = None
        existing = self.votes[val_index]
        if existing is not None:
            if existing.block_id == vote.block_id:
                raise RuntimeError("addVerifiedVote does not expect duplicate votes")
            conflicting = existing
            if self.maj23 is not None and self.maj23.key() == block_key:
                self.votes[val_index] = vote
                self.votes_bit_array.set_index(val_index, True)
        else:
            self.votes[val_index] = vote
            self.votes_bit_array.set_index(val_index, True)
            self.sum += voting_power

        votes_by_block = self.votes_by_block.get(block_key)
        if votes_by_block is not None:
            if conflicting is not None and not votes_by_block.peer_maj23:
                return False, conflicting
        else:
            if conflicting is not None:
                return False, conflicting
            votes_by_block = _BlockVotes(False, self.val_set.size())
            self.votes_by_block[block_key] = votes_by_block

        orig_sum = votes_by_block.sum
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        votes_by_block.add_verified_vote(vote, voting_power)
        if orig_sum < quorum <= votes_by_block.sum:
            if self.maj23 is None:
                self.maj23 = vote.block_id
                for i, v in enumerate(votes_by_block.votes):
                    if v is not None:
                        self.votes[i] = v
        return True, conflicting

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """vote_set.go:318-352."""
        with self._mtx:
            block_key = block_id.key()
            existing = self.peer_maj23s.get(peer_id)
            if existing is not None:
                if existing == block_id:
                    return
                raise VoteError(
                    f"setPeerMaj23: Received conflicting blockID from peer {peer_id}"
                )
            self.peer_maj23s[peer_id] = block_id
            votes_by_block = self.votes_by_block.get(block_key)
            if votes_by_block is not None:
                votes_by_block.peer_maj23 = True
            else:
                self.votes_by_block[block_key] = _BlockVotes(True, self.val_set.size())

    # -- queries --------------------------------------------------------------

    def bit_array(self) -> BitArray:
        with self._mtx:
            return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> BitArray | None:
        with self._mtx:
            bv = self.votes_by_block.get(block_id.key())
            return bv.bit_array.copy() if bv else None

    def get_by_index(self, val_index: int) -> Vote | None:
        with self._mtx:
            if val_index < 0 or val_index >= len(self.votes):
                return None
            return self.votes[val_index]

    def get_by_address(self, address: bytes) -> Vote | None:
        with self._mtx:
            idx, val = self.val_set.get_by_address(address)
            if val is None:
                return None
            return self.votes[idx]

    def list_votes(self) -> list[Vote]:
        with self._mtx:
            return [v for v in self.votes if v is not None]

    def has_two_thirds_majority(self) -> bool:
        with self._mtx:
            return self.maj23 is not None

    def is_commit(self) -> bool:
        from cometbft_tpu.types.block import PRECOMMIT_TYPE

        with self._mtx:
            return self.signed_msg_type == PRECOMMIT_TYPE and self.maj23 is not None

    def has_two_thirds_any(self) -> bool:
        with self._mtx:
            return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        with self._mtx:
            return self.sum == self.val_set.total_voting_power()

    def two_thirds_majority(self) -> tuple[BlockID | None, bool]:
        """(blockID, True) if 2/3 majority reached; blockID may be the zero
        BlockID for nil (vote_set.go:456-470)."""
        with self._mtx:
            if self.maj23 is not None:
                return self.maj23, True
            return None, False

    def make_commit(self) -> Commit:
        """vote_set.go:619-660: requires +2/3 precommits for a block."""
        from cometbft_tpu.types.block import PRECOMMIT_TYPE

        with self._mtx:
            if self.signed_msg_type != PRECOMMIT_TYPE:
                raise ValueError("Cannot MakeCommit() unless VoteSet.Type is PRECOMMIT_TYPE")
            if self.maj23 is None:
                raise ValueError("Cannot MakeCommit() unless a blockhash has +2/3")
            from cometbft_tpu.types.block import CommitSig

            sigs = []
            for v in self.votes:
                cs = vote_to_commit_sig(v)
                # Votes for a different block than maj23 are excluded
                # (vote_set.go:635-638).
                if cs.for_block_flag() and v.block_id != self.maj23:
                    cs = CommitSig.absent()
                sigs.append(cs)
            return Commit(
                height=self.height,
                round=self.round,
                block_id=self.maj23,
                signatures=sigs,
            )

"""Genesis document (reference: types/genesis.go)."""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field as dfield

from cometbft_tpu.crypto import encoding as key_encoding
from cometbft_tpu.types import cmttime
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.params import ConsensusParams, DEFAULT_CONSENSUS_PARAMS
from cometbft_tpu.types.validator import Validator

MAX_CHAIN_ID_LEN = 50

_KEY_TYPE_TO_JSON_NAME = {
    "ed25519": "tendermint/PubKeyEd25519",
    "secp256k1": "tendermint/PubKeySecp256k1",
    "sr25519": "tendermint/PubKeySr25519",
    "bn254": "tendermint/PubKeyBn254",
}
_JSON_NAME_TO_KEY_TYPE = {v: k for k, v in _KEY_TYPE_TO_JSON_NAME.items()}


@dataclass
class GenesisValidator:
    """types/genesis.go GenesisValidator."""

    address: bytes
    pub_key: object
    power: int
    name: str = ""
    # BLS proof of possession (round 10): required for bn254 keys — plain
    # BLS aggregation without one is open to the rogue-key attack, so
    # validate_and_complete rejects a bn254 validator whose proof is
    # missing or invalid. Empty for non-aggregating key types.
    pop: bytes = b""

    def to_json(self) -> dict:
        d = {
            "address": self.address.hex().upper(),
            "pub_key": {
                "type": _KEY_TYPE_TO_JSON_NAME[self.pub_key.type()],
                "value": base64.b64encode(self.pub_key.bytes()).decode(),
            },
            "power": str(self.power),
            "name": self.name,
        }
        if self.pop:
            d["proof_of_possession"] = base64.b64encode(self.pop).decode()
        return d

    @classmethod
    def from_json(cls, d: dict) -> "GenesisValidator":
        pk = d["pub_key"]
        key_type = _JSON_NAME_TO_KEY_TYPE.get(pk["type"], pk["type"])
        pub_key = key_encoding.pub_key_from_type_and_bytes(
            key_type, base64.b64decode(pk["value"])
        )
        addr = bytes.fromhex(d["address"]) if d.get("address") else pub_key.address()
        return cls(
            address=addr,
            pub_key=pub_key,
            power=int(d["power"]),
            name=d.get("name", ""),
            pop=base64.b64decode(d.get("proof_of_possession", "") or ""),
        )


@dataclass
class GenesisDoc:
    """types/genesis.go GenesisDoc."""

    chain_id: str
    genesis_time: Time = dfield(default_factory=cmttime.now)
    initial_height: int = 1
    consensus_params: ConsensusParams | None = dfield(
        default_factory=lambda: DEFAULT_CONSENSUS_PARAMS
    )
    validators: list = dfield(default_factory=list)
    app_hash: bytes = b""
    app_state: dict | list | str | None = None

    def validate_and_complete(self) -> None:
        """types/genesis.go ValidateAndComplete."""
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError(f"chain_id in genesis doc is too long (max: {MAX_CHAIN_ID_LEN})")
        if self.initial_height < 0:
            raise ValueError("initial_height cannot be negative")
        if self.initial_height == 0:
            self.initial_height = 1
        if self.consensus_params is None:
            self.consensus_params = DEFAULT_CONSENSUS_PARAMS
        else:
            self.consensus_params.validate_basic()
        for i, v in enumerate(self.validators):
            if v.power == 0:
                raise ValueError(f"the genesis file cannot contain validators with no voting power: {v}")
            if v.address and v.pub_key.address() != v.address:
                raise ValueError(f"incorrect address for validator {i}")
            if not v.address:
                v.address = v.pub_key.address()
            if v.pub_key.type() == "bn254":
                from cometbft_tpu.crypto import bn254

                if not v.pop:
                    raise ValueError(
                        f"validator {i} ({v.name or v.address.hex()}): bn254 "
                        "keys require a proof_of_possession in genesis — "
                        "without one a registrant can mount the rogue-key "
                        "attack against aggregate BLS commits"
                    )
                if not bn254.verify_possession(v.pub_key.bytes(), v.pop):
                    raise ValueError(
                        f"validator {i} ({v.name or v.address.hex()}): "
                        "invalid bn254 proof_of_possession — rejecting "
                        "possible rogue key"
                    )
        if self.genesis_time.is_zero():
            self.genesis_time = cmttime.now()

    def validator_hash(self) -> bytes:
        from cometbft_tpu.types.validator_set import ValidatorSet

        vals = [Validator.new(v.pub_key, v.power) for v in self.validators]
        return ValidatorSet(vals).hash()

    # -- JSON (genesis.json) -------------------------------------------------

    def to_json(self) -> str:
        d = {
            "genesis_time": self.genesis_time.rfc3339(),
            "chain_id": self.chain_id,
            "initial_height": str(self.initial_height),
            "consensus_params": _params_to_json(self.consensus_params),
            "validators": [v.to_json() for v in self.validators],
            "app_hash": self.app_hash.hex().upper(),
        }
        if self.app_state is not None:
            d["app_state"] = self.app_state
        return json.dumps(d, indent=2)

    @classmethod
    def from_json(cls, s: str) -> "GenesisDoc":
        d = json.loads(s)
        if not isinstance(d, dict):
            raise ValueError("genesis doc must be a JSON object")
        vals = d.get("validators") or []
        if not isinstance(vals, list) or not all(isinstance(v, dict) for v in vals):
            raise ValueError("genesis validators must be a list of objects")
        doc = cls(
            chain_id=d["chain_id"],
            genesis_time=Time.parse_rfc3339(d["genesis_time"]),
            initial_height=int(d.get("initial_height", 1)),
            consensus_params=_params_from_json(d.get("consensus_params")),
            validators=[GenesisValidator.from_json(v) for v in vals],
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_state=d.get("app_state"),
        )
        doc.validate_and_complete()
        return doc

    @classmethod
    def from_file(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())

    def save_as(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())


def _params_to_json(p: ConsensusParams | None) -> dict | None:
    if p is None:
        return None
    return {
        "block": {"max_bytes": str(p.block.max_bytes), "max_gas": str(p.block.max_gas)},
        "evidence": {
            "max_age_num_blocks": str(p.evidence.max_age_num_blocks),
            "max_age_duration": str(p.evidence.max_age_duration_ns),
            "max_bytes": str(p.evidence.max_bytes),
        },
        "validator": {"pub_key_types": list(p.validator.pub_key_types)},
        "version": {"app": str(p.version.app)},
    }


def _params_from_json(d: dict | None) -> ConsensusParams | None:
    if d is None:
        return None
    from cometbft_tpu.types.params import (
        BlockParams,
        EvidenceParams,
        ValidatorParams,
        VersionParams,
    )

    return ConsensusParams(
        block=BlockParams(
            int(d["block"]["max_bytes"]), int(d["block"]["max_gas"])
        ),
        evidence=EvidenceParams(
            int(d["evidence"]["max_age_num_blocks"]),
            int(d["evidence"]["max_age_duration"]),
            int(d["evidence"].get("max_bytes", 1048576)),
        ),
        validator=ValidatorParams(tuple(d["validator"]["pub_key_types"])),
        version=VersionParams(int(d.get("version", {}).get("app", 0))),
    )

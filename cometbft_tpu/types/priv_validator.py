"""PrivValidator interface + in-memory signer (reference: types/priv_validator.go)."""

from __future__ import annotations

from dataclasses import replace

from cometbft_tpu.crypto import ed25519
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote


class PrivValidator:
    """types/priv_validator.go:14-22: signer abstraction used by consensus."""

    def get_pub_key(self):
        raise NotImplementedError

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        """Returns the vote with signature set (mutating in Go; functional here)."""
        raise NotImplementedError

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        raise NotImplementedError


class MockPV(PrivValidator):
    """In-memory signer for tests (types/priv_validator.go:47-130)."""

    def __init__(self, priv_key=None, break_proposal_sig=False, break_vote_sig=False):
        self.priv_key = priv_key or ed25519.gen_priv_key()
        self.break_proposal_sig = break_proposal_sig
        self.break_vote_sig = break_vote_sig

    def get_pub_key(self):
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        use_chain_id = "incorrect-chain-id" if self.break_vote_sig else chain_id
        sig = self.priv_key.sign(vote.sign_bytes(use_chain_id))
        return replace(vote, signature=sig)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        use_chain_id = "incorrect-chain-id" if self.break_proposal_sig else chain_id
        sig = self.priv_key.sign(proposal.sign_bytes(use_chain_id))
        return replace(proposal, signature=sig)

    def address(self) -> bytes:
        return self.get_pub_key().address()

"""Consensus parameters (reference: types/params.go)."""

from __future__ import annotations

from dataclasses import dataclass, field as dfield, replace

from cometbft_tpu.crypto import merkle
from cometbft_tpu.wire import proto as wire

MAX_BLOCK_SIZE_BYTES = 104857600  # 100 MiB (types/params.go:14)
BLOCK_PART_SIZE_BYTES = 65536  # types/params.go:20
MAX_BLOCK_PARTS_COUNT = (MAX_BLOCK_SIZE_BYTES // BLOCK_PART_SIZE_BYTES) + 1

ABCI_PUBKEY_TYPE_ED25519 = "ed25519"
ABCI_PUBKEY_TYPE_SECP256K1 = "secp256k1"
ABCI_PUBKEY_TYPE_SR25519 = "sr25519"
ABCI_PUBKEY_TYPE_BN254 = "bn254"  # fork addition (types/params.go:27)

# MaxVotesCount caps the validator-set size (types/params.go MaxVotesCount).
MAX_VOTES_COUNT = 10000


@dataclass(frozen=True)
class BlockParams:
    max_bytes: int = 22020096  # 21 MiB default (types/params.go DefaultBlockParams)
    max_gas: int = -1

    def encode(self) -> bytes:
        return wire.field_varint(1, self.max_bytes) + wire.field_varint(2, self.max_gas)

    @classmethod
    def decode(cls, data: bytes) -> "BlockParams":
        f = wire.decode_fields(data)
        return cls(wire.get_varint(f, 1), wire.get_varint(f, 2))


@dataclass(frozen=True)
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * 10**9  # 48h, proto Duration
    max_bytes: int = 1048576

    def encode(self) -> bytes:
        dur = wire.field_varint(1, self.max_age_duration_ns // 10**9) + wire.field_varint(
            2, self.max_age_duration_ns % 10**9
        )
        return (
            wire.field_varint(1, self.max_age_num_blocks)
            + wire.field_message(2, dur, emit_empty=True)
            + wire.field_varint(3, self.max_bytes)
        )

    @classmethod
    def decode(cls, data: bytes) -> "EvidenceParams":
        f = wire.decode_fields(data)
        df = wire.decode_fields(wire.get_bytes(f, 2))
        dur = wire.get_varint(df, 1) * 10**9 + wire.get_varint(df, 2)
        return cls(wire.get_varint(f, 1), dur, wire.get_varint(f, 3))


@dataclass(frozen=True)
class ValidatorParams:
    pub_key_types: tuple = (ABCI_PUBKEY_TYPE_ED25519,)

    def encode(self) -> bytes:
        out = b""
        for t in self.pub_key_types:
            out += wire.field_string(1, t, emit_default=True)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorParams":
        f = wire.decode_fields(data)
        return cls(tuple(b.decode() for b in wire.get_repeated_bytes(f, 1)))


@dataclass(frozen=True)
class VersionParams:
    app: int = 0

    def encode(self) -> bytes:
        return wire.field_varint(1, self.app)

    @classmethod
    def decode(cls, data: bytes) -> "VersionParams":
        f = wire.decode_fields(data)
        return cls(wire.get_uvarint(f, 1))


@dataclass(frozen=True)
class ConsensusParams:
    """types/params.go ConsensusParams."""

    block: BlockParams = dfield(default_factory=BlockParams)
    evidence: EvidenceParams = dfield(default_factory=EvidenceParams)
    validator: ValidatorParams = dfield(default_factory=ValidatorParams)
    version: VersionParams = dfield(default_factory=VersionParams)

    def hash(self) -> bytes:
        """HashConsensusParams (types/params.go): SHA-256 of HashedParams
        {block_max_bytes, block_max_gas}. NOTE: the reference hashes only the
        block-size subset (params.go HashedParams)."""
        hp = wire.field_varint(1, self.block.max_bytes) + wire.field_varint(
            2, self.block.max_gas
        )
        from cometbft_tpu.crypto import tmhash

        return tmhash.sum(hp)

    def validate_basic(self) -> None:
        """types/params.go ValidateBasic."""
        if self.block.max_bytes == 0:
            raise ValueError("block.MaxBytes cannot be 0")
        if self.block.max_bytes < -1:
            raise ValueError(
                f"block.MaxBytes must be -1 or greater than 0. Got {self.block.max_bytes}"
            )
        if self.block.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValueError(
                f"block.MaxBytes is too big. {self.block.max_bytes} > {MAX_BLOCK_SIZE_BYTES}"
            )
        if self.block.max_gas < -1:
            raise ValueError(f"block.MaxGas must be greater or equal to -1. Got {self.block.max_gas}")
        if self.evidence.max_age_num_blocks <= 0:
            raise ValueError(
                f"evidence.MaxAgeNumBlocks must be greater than 0. Got {self.evidence.max_age_num_blocks}"
            )
        if self.evidence.max_age_duration_ns <= 0:
            raise ValueError(
                "evidence.MaxAgeDuration must be greater than 0 if provided"
            )
        max_bytes = self.block.max_bytes
        if max_bytes == -1:
            max_bytes = MAX_BLOCK_SIZE_BYTES
        if self.evidence.max_bytes > max_bytes:
            raise ValueError(
                f"evidence.MaxBytesEvidence is greater than upper bound, {self.evidence.max_bytes} > {max_bytes}"
            )
        if self.evidence.max_bytes < 0:
            raise ValueError(
                f"evidence.MaxBytes must be non negative. Got: {self.evidence.max_bytes}"
            )
        if not self.pub_key_types_valid():
            raise ValueError(f"invalid pub key types: {self.validator.pub_key_types}")

    def pub_key_types_valid(self) -> bool:
        if not self.validator.pub_key_types:
            return False
        valid = {
            ABCI_PUBKEY_TYPE_ED25519,
            ABCI_PUBKEY_TYPE_SECP256K1,
            ABCI_PUBKEY_TYPE_SR25519,
            ABCI_PUBKEY_TYPE_BN254,
        }
        return all(t in valid for t in self.validator.pub_key_types)

    def update(self, updates) -> "ConsensusParams":
        """ConsensusParams.Update from an ABCI param-change (types/params.go).
        `updates` is an abci.ConsensusParams-shaped object with optional
        block/evidence/validator/version sections."""
        res = self
        if updates is None:
            return res
        if getattr(updates, "block", None) is not None:
            res = replace(
                res,
                block=BlockParams(updates.block.max_bytes, updates.block.max_gas),
            )
        if getattr(updates, "evidence", None) is not None:
            res = replace(
                res,
                evidence=EvidenceParams(
                    updates.evidence.max_age_num_blocks,
                    updates.evidence.max_age_duration_ns,
                    updates.evidence.max_bytes,
                ),
            )
        if getattr(updates, "validator", None) is not None:
            res = replace(
                res,
                validator=ValidatorParams(tuple(updates.validator.pub_key_types)),
            )
        if getattr(updates, "version", None) is not None:
            res = replace(res, version=VersionParams(updates.version.app))
        return res

    def encode(self) -> bytes:
        return (
            wire.field_message(1, self.block.encode(), emit_empty=True)
            + wire.field_message(2, self.evidence.encode(), emit_empty=True)
            + wire.field_message(3, self.validator.encode(), emit_empty=True)
            + wire.field_message(4, self.version.encode(), emit_empty=True)
        )

    @classmethod
    def decode(cls, data: bytes) -> "ConsensusParams":
        f = wire.decode_fields(data)
        return cls(
            block=BlockParams.decode(wire.get_bytes(f, 1)),
            evidence=EvidenceParams.decode(wire.get_bytes(f, 2)),
            validator=ValidatorParams.decode(wire.get_bytes(f, 3)),
            version=VersionParams.decode(wire.get_bytes(f, 4)),
        )


DEFAULT_CONSENSUS_PARAMS = ConsensusParams()

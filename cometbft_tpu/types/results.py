"""ABCI results hashing (reference: types/results.go).

LastResultsHash = Merkle root over deterministic subsets of the DeliverTx
responses (code, data, gas_wanted, gas_used — types/results.go:41-56).
"""

from __future__ import annotations

from cometbft_tpu.crypto import merkle
from cometbft_tpu.wire import proto as wire


def deterministic_response_deliver_tx(code: int, data: bytes, gas_wanted: int, gas_used: int) -> bytes:
    """ResponseDeliverTx stripped of non-deterministic fields
    (types/results.go deterministicResponseDeliverTx): {code=1, data=2,
    gas_wanted=5, gas_used=6}."""
    out = wire.field_varint(1, code)
    out += wire.field_bytes(2, data)
    out += wire.field_varint(5, gas_wanted)
    out += wire.field_varint(6, gas_used)
    return out


def results_hash(deliver_txs: list) -> bytes:
    """ABCIResults.Hash (types/results.go:19-39). deliver_txs: list of
    abci ResponseDeliverTx-shaped objects."""
    leaves = [
        deterministic_response_deliver_tx(
            r.code, r.data, r.gas_wanted, r.gas_used
        )
        for r in deliver_txs
    ]
    return merkle.hash_from_byte_slices(leaves)

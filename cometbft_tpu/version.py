"""Version constants (reference: version/version.go:6-13)."""

__version__ = "0.1.0"

# Version of the replicated capability surface we target.
CMT_SEM_VER = "0.38.0-dev"

# ABCI semantic version implemented by the ABCI boundary (reference:
# version/version.go:9 ABCISemVer = "1.0.0").
ABCI_SEM_VER = "1.0.0"
ABCI_VERSION = ABCI_SEM_VER

# P2P and Block protocol versions (reference: version/version.go:17-24).
P2P_PROTOCOL = 8
BLOCK_PROTOCOL = 11

"""Seeded randomized-testnet generator (reference: test/e2e/generator).

The reference's generator turns one RNG seed into a valid runner manifest,
sampling the testnet dimensions the e2e harness can exercise — topology,
sync modes, ABCI boundaries, key types, perturbations — under the
constraints that keep the result runnable (quorum at genesis, snapshot
sources for statesync, a stable node-0 reference).  ``generate(seed)`` here
is that: a pure function from an integer seed to TOML text, byte-identical
across runs, loadable by :class:`cometbft_tpu.e2e_runner.Manifest`.

Profiles mirror the reference's groups:

* ``full`` — the whole sampling space: up to 6 validators plus full/seed
  nodes, mixed consensus key types, socket/grpc ABCI boundaries, late
  joins via blocksync or verified statesync, validator churn, hybrid
  backend, any perturbation — including ``backend_faults``, which
  restarts a node with a chaos-injected supervised verification chain
  (CMTPU_FAULTS, sidecar/chaos.py) and demands it keeps committing, and
  ``vote_batch``, which restarts a node with a widened vote-admission
  micro-batch window (CMTPU_VOTE_BATCH_WINDOW_MS) on top of that faulted
  chain and demands the validator's precommit lands in a fresh commit —
  batching under faults must degrade, never drop, valid votes.
* ``small`` — the CI-sized corner (≤4 validators, ≤6 target blocks, ≤1
  perturbation, ed25519 only, cpu backend): what ``e2e matrix`` smokes in
  the test tier.

``run_matrix(seeds, out_dir)`` sweeps seeds through the runner
(generator.go's Makefile loop + runner invocation).  Every run gets its
own directory; a failure freezes the evidence as ``repro.json`` — seed,
manifest text, error, per-node log tails — so one file reproduces the
testnet that broke.
"""

from __future__ import annotations

import json
import os
import random
import traceback

from cometbft_tpu.e2e_runner import Manifest

PROFILES = ("full", "small", "sim")

# Weighted sampling tables (generator/generate.go's uniformChoice /
# weightedChoice analogs).  Non-ed25519 verification is pure Python here —
# heavy key types stay out of the small profile so the CI tier keeps its
# 0.2s commit cadence.
_KEY_TYPES_FULL = (
    ("ed25519",) * 11 + ("secp256k1",) * 3 + ("sr25519",) * 3 + ("bn254",) * 3
)
_ABCI_FULL = ("local",) * 5 + ("socket",) * 3 + ("grpc",) * 2
_ABCI_SMALL = ("local",) * 7 + ("socket",) * 3
_PERTURB_FULL = (
    "kill", "pause", "disconnect", "restart", "backend_faults",
    "concurrent_light_clients", "tx_flood", "vote_batch",
    "light_gateway", "mixed_load", "recv_flood", "bundle_cold_sync",
)
# _PERTURB_SMALL is FROZEN: the matrix regression suite pins small-profile
# seeds by number (the round-15 stall forensics and the round-18 un-pinned
# seeds 2/3/9), and any change here reshuffles every seed's draw sequence,
# silently swapping which manifests those seed numbers denote.  New
# perturbations go in _PERTURB_FULL only.
_PERTURB_SMALL = ("pause", "restart", "backend_faults", "tx_flood")


def generate(seed: int, profile: str = "full") -> str:
    """One integer seed -> one deterministic, runnable TOML manifest."""
    spec = generate_spec(seed, profile)
    text = render_toml(spec)
    return text


def generate_spec(seed: int, profile: str = "full") -> dict:
    """The structured form of the sampled manifest (render_toml emits it).

    Everything flows from ``random.Random(seed)`` — no clocks, no global
    RNG — so the same (seed, profile) always yields the same testnet.
    """
    if profile not in PROFILES:
        raise ValueError(f"unknown profile {profile!r} (want one of {PROFILES})")
    rng = random.Random(f"{profile}:{seed}")
    if profile == "sim":
        return _generate_sim_spec(seed, rng)
    small = profile == "small"

    n_validators = rng.choice((2, 3, 4) if small else (2, 3, 4, 4, 5, 6))
    n_full = rng.choice((0, 1) if small else (0, 0, 1, 2))
    with_seed_node = (not small) and rng.random() < 0.2

    # Late-join validators must leave > 2/3 of the (equal-power) genesis
    # set online; (V-1)//3 is the largest count that keeps 3*(V-L) > 2*V.
    late_validators = 0
    if not small and (n_validators - 1) // 3 > 0 and rng.random() < 0.3:
        late_validators = 1

    backend = "cpu" if small else rng.choice(("cpu",) * 3 + ("hybrid",))
    validator_churn = rng.random() < (0.2 if small else 0.25)
    if validator_churn:
        app = "persistent_kvstore"
    else:
        app = rng.choice(("kvstore", "kvstore", "persistent_kvstore"))
    light_client = rng.random() < 0.3
    load_tx_rate = rng.choice((10, 25) if small else (10, 25, 50, 100))
    target_blocks = rng.randint(4, 6) if small else rng.randint(8, 16)

    nodes: list[dict] = []
    abci_table = _ABCI_SMALL if small else _ABCI_FULL
    for i in range(n_validators):
        nodes.append({
            "name": f"validator{i + 1:02d}",
            "mode": "validator",
            "key_type": "ed25519" if small else rng.choice(_KEY_TYPES_FULL),
            "start_at": 0,
            "state_sync": False,
            "abci": rng.choice(abci_table),
            "perturb": [],
        })
    for i in range(n_full):
        late = rng.random() < 0.5
        nodes.append({
            "name": f"full{i + 1:02d}",
            "mode": "full",
            "key_type": "ed25519",
            "start_at": rng.choice((2, 3) if small else (3, 5, 8)) if late else 0,
            "state_sync": False,
            "abci": rng.choice(abci_table),
            "perturb": [],
        })
    if with_seed_node:
        nodes.append({
            "name": "seed01",
            "mode": "seed",
            "key_type": "ed25519",
            "start_at": 0,
            "state_sync": False,
            "abci": "local",
            "perturb": [],
        })

    # Late validators come last among the validators -> node 0 stays a
    # genesis validator (the runner's height/load/trust reference).
    for node in reversed(nodes):
        if late_validators and node["mode"] == "validator":
            node["start_at"] = rng.choice((3, 5))
            late_validators -= 1
            break

    # Statesync only where a snapshot source exists; full profile only.
    snapshot_interval = 0
    if not small:
        late_nodes = [n for n in nodes if n["start_at"] > 0]
        wants_sync = [n for n in late_nodes if rng.random() < 0.5]
        if wants_sync:
            snapshot_interval = rng.choice((2, 3, 4))
            for n in wants_sync:
                n["state_sync"] = True
        elif rng.random() < 0.3:
            snapshot_interval = 3  # snapshots taken, nobody restores: still valid

    # Perturbations never hit node 0 (the heal check's reference) and the
    # small profile keeps at most one in total.
    budget = 1 if small else 3
    table = _PERTURB_SMALL if small else _PERTURB_FULL
    for node in nodes[1:]:
        if budget <= 0:
            break
        if rng.random() < 0.4:
            count = 1 if small else rng.choice((1, 1, 2))
            count = min(count, budget)
            node["perturb"] = [rng.choice(table) for _ in range(count)]
            budget -= count

    # Leave headroom past the last join; the small bump keeps target_blocks
    # within the profile's ≤6-block ceiling (late starts there are ≤3).
    max_start = max((n["start_at"] for n in nodes), default=0)
    target_blocks = max(target_blocks, max_start + (2 if small else 4))

    return {
        "seed": seed,
        "profile": profile,
        "initial_height": 1,
        "load_tx_rate": load_tx_rate,
        "target_blocks": target_blocks,
        "backend": backend,
        "app": app,
        "snapshot_interval": snapshot_interval,
        "validator_churn": validator_churn,
        "light_client": light_client,
        "nodes": nodes,
    }


def _generate_sim_spec(seed: int, rng: random.Random) -> dict:
    """The ``sim`` profile: one 50–200 node virtual-clock scenario.

    Samples the WAN shape (zones, jitter, drop), one quorum-breaking
    partition + heal, and optional churn.  The zone latency matrix itself
    is synthesized inside the scenario from the same seed, so the manifest
    stays small while the resolved schedule still lands in repro.json.
    """
    validators = rng.choice((50, 50, 75, 100, 100, 150, 200))
    blocks = rng.randint(6, 10)
    part_at = round(rng.uniform(15.0, 35.0), 1)
    sim = {
        "seed": seed,
        "validators": validators,
        "blocks": blocks,
        "zones": rng.randint(2, 6),
        "jitter_ms": round(rng.uniform(5.0, 25.0), 1),
        "drop_p": rng.choice((0.0, 0.0, round(rng.uniform(0.002, 0.02), 4))),
        "vote_window_ms": rng.choice((0.0, 25.0, 50.0)),
        "max_sim_s": float(blocks * 40 + 120),
        "partitions": [{
            "at_s": part_at,
            "heal_s": round(part_at + rng.uniform(10.0, 30.0), 1),
            "fraction": 0.5,
        }],
        "churn": (
            [{
                "at_s": round(rng.uniform(10.0, 30.0), 1),
                "down_s": round(rng.uniform(10.0, 25.0), 1),
                "nodes": rng.randint(1, max(1, validators // 10)),
            }]
            if rng.random() < 0.4
            else []
        ),
    }
    # Byzantine window (round 19): about half the seeds run one adversary.
    # Equivocators are biased toward the partition window with
    # only_partitioned set — the accountability path (heal -> vote-knowledge
    # merge -> DuplicateVoteEvidence committed) is the property under test.
    if rng.random() < 0.5:
        role = rng.choice(("equivocator", "equivocator", "withholder", "flooder"))
        entry = {
            "role": role,
            "node": rng.randint(1, validators - 1),
            "from_s": round(rng.uniform(5.0, part_at), 1),
            "until_s": round(sim["partitions"][0]["heal_s"]
                             + rng.uniform(5.0, 20.0), 1),
        }
        if role == "equivocator":
            entry["only_partitioned"] = rng.random() < 0.5
        sim["byzantine"] = [entry]
    # Occasional in-sim blocksync late-join, never colliding with the
    # adversary (a byzantine joiner is rejected by the scenario).
    if rng.random() < 0.3:
        taken = {e["node"] for e in sim.get("byzantine", [])}
        candidates = [i for i in range(1, validators) if i not in taken]
        sim["joins"] = [{
            "node": rng.choice(candidates),
            "at_s": round(rng.uniform(30.0, 60.0), 1),
        }]
    return {"seed": seed, "profile": "sim", "network": "sim", "sim": sim}


def render_toml(spec: dict) -> str:
    """Stable TOML rendering: fixed key order, no timestamps — the
    determinism contract is byte-identical output per (seed, profile)."""
    if spec.get("network") == "sim":
        return _render_sim_toml(spec)
    lines = [
        "# Randomized e2e testnet manifest "
        f"(seed {spec['seed']}, profile {spec['profile']}).",
        "# Regenerate: python -m cometbft_tpu.cmd e2e generate "
        f"--seed {spec['seed']} --profile {spec['profile']}",
        "",
        f"seed = {spec['seed']}",
        f"initial_height = {spec['initial_height']}",
        f"load_tx_rate = {spec['load_tx_rate']}",
        f"target_blocks = {spec['target_blocks']}",
        f'backend = "{spec["backend"]}"',
        f'app = "{spec["app"]}"',
        f"snapshot_interval = {spec['snapshot_interval']}",
        f"validator_churn = {_toml_bool(spec['validator_churn'])}",
        f"light_client = {_toml_bool(spec['light_client'])}",
    ]
    for node in spec["nodes"]:
        lines.append("")
        lines.append(f"[node.{node['name']}]")
        if node["mode"] != "validator":
            lines.append(f'mode = "{node["mode"]}"')
        if node["key_type"] != "ed25519":
            lines.append(f'key_type = "{node["key_type"]}"')
        if node["start_at"]:
            lines.append(f"start_at = {node['start_at']}")
        if node["state_sync"]:
            lines.append("state_sync = true")
        if node["abci"] != "local":
            lines.append(f'abci = "{node["abci"]}"')
        if node["perturb"]:
            quoted = ", ".join(f'"{p}"' for p in node["perturb"])
            lines.append(f"perturb = [{quoted}]")
    return "\n".join(lines) + "\n"


def _render_sim_toml(spec: dict) -> str:
    """network = "sim" manifests: scalars + flat parallel arrays only (the
    partition/churn schedules are unzipped — the repo's TOML subset has no
    inline tables; Manifest._load_sim zips them back)."""
    sim = spec["sim"]
    lines = [
        "# Randomized simnet scenario manifest "
        f"(seed {spec['seed']}, profile sim).",
        "# Regenerate: python -m cometbft_tpu.cmd e2e generate "
        f"--seed {spec['seed']} --profile sim",
        "",
        f"seed = {spec['seed']}",
        'network = "sim"',
        "",
        "[sim]",
        f"seed = {sim['seed']}",
        f"validators = {sim['validators']}",
        f"blocks = {sim['blocks']}",
        f"zones = {sim['zones']}",
        f"jitter_ms = {sim['jitter_ms']}",
        f"drop_p = {sim['drop_p']}",
        f"vote_window_ms = {sim['vote_window_ms']}",
        f"max_sim_s = {sim['max_sim_s']}",
    ]
    parts = sim.get("partitions", [])
    if parts:
        lines.append(
            "partition_at_s = [" + ", ".join(str(p["at_s"]) for p in parts) + "]"
        )
        lines.append(
            "partition_heal_s = ["
            + ", ".join(str(p["heal_s"]) for p in parts) + "]"
        )
        lines.append(
            "partition_fraction = ["
            + ", ".join(str(p["fraction"]) for p in parts) + "]"
        )
    churn = sim.get("churn", [])
    if churn:
        lines.append(
            "churn_at_s = [" + ", ".join(str(c["at_s"]) for c in churn) + "]"
        )
        lines.append(
            "churn_down_s = [" + ", ".join(str(c["down_s"]) for c in churn) + "]"
        )
        lines.append(
            "churn_nodes = [" + ", ".join(str(c["nodes"]) for c in churn) + "]"
        )
    byz = sim.get("byzantine", [])
    if byz:
        lines.append(
            "byz_role = [" + ", ".join(f'"{b["role"]}"' for b in byz) + "]"
        )
        lines.append(
            "byz_node = [" + ", ".join(str(b["node"]) for b in byz) + "]"
        )
        lines.append(
            "byz_from_s = [" + ", ".join(str(b["from_s"]) for b in byz) + "]"
        )
        lines.append(
            "byz_until_s = [" + ", ".join(str(b["until_s"]) for b in byz) + "]"
        )
        lines.append(
            "byz_only_partitioned = ["
            + ", ".join(
                _toml_bool(bool(b.get("only_partitioned", False))) for b in byz
            )
            + "]"
        )
    joins = sim.get("joins", [])
    if joins:
        lines.append(
            "join_node = [" + ", ".join(str(j["node"]) for j in joins) + "]"
        )
        lines.append(
            "join_at_s = [" + ", ".join(str(j["at_s"]) for j in joins) + "]"
        )
    return "\n".join(lines) + "\n"


def _toml_bool(b: bool) -> str:
    return "true" if b else "false"


def run_matrix(
    seeds,
    out_dir: str,
    profile: str = "small",
    runner_cls=None,
    log=print,
) -> dict:
    """Sweep seeds through the runner (the reference generator's CI loop).

    Per seed: ``<out_dir>/seed<N>/manifest.toml`` + ``net/`` homes.  Hash
    agreement (and every other invariant the runner enforces) failing
    freezes ``repro.json`` alongside — seed, frozen manifest, error, and
    per-node log tails — the whole repro in one artifact.
    """
    if runner_cls is None:
        from cometbft_tpu.e2e_runner import E2ERunner as runner_cls  # noqa: N813

    results: dict[int, dict] = {}
    for seed in seeds:
        sdir = os.path.join(out_dir, f"seed{seed}")
        os.makedirs(sdir, exist_ok=True)
        text = generate(seed, profile)
        manifest_path = os.path.join(sdir, "manifest.toml")
        with open(manifest_path, "w") as f:
            f.write(text)
        # The generator's own output must satisfy the runner's schema —
        # fail loudly here, not three minutes into a testnet.
        Manifest.load(manifest_path)
        log(f"matrix seed {seed}: starting")
        runner = runner_cls(manifest_path, os.path.join(sdir, "net"), log=log)
        try:
            report = runner.run()
        except Exception as e:
            repro_path = _write_repro(sdir, seed, profile, text, e, runner)
            # A wait_height deadline (TimeoutError) is the stall signature —
            # height stopped advancing, i.e. a consensus livelock or a dead
            # node — distinct from invariant failures (hash disagreement...).
            stalled = isinstance(e, TimeoutError)
            log(f"matrix seed {seed}: FAILED ({e!r}); repro at {repro_path}")
            results[seed] = {
                "ok": False,
                "stalled": stalled,
                "error": repr(e),
                "repro": repro_path,
            }
        else:
            results[seed] = {"ok": True, "report": report}
            log(f"matrix seed {seed}: ok at height {report['agreed_height']}")
    passed = sorted(s for s, r in results.items() if r["ok"])
    failed = sorted(s for s, r in results.items() if not r["ok"])
    stalled = sorted(s for s, r in results.items() if r.get("stalled"))
    # One grep-able line per sweep for tpu_watch.log: per-seed verdicts.
    verdicts = " ".join(
        f"seed{s}:" + (
            "ok" if results[s]["ok"]
            else ("stall" if results[s].get("stalled") else "fail")
        )
        for s in sorted(results)
    )
    log(
        f"e2e matrix summary [{profile}]: {len(passed)}/{len(results)} passed,"
        f" {len(stalled)} stalled | {verdicts}"
    )
    return {
        "profile": profile,
        "passed": passed,
        "failed": failed,
        "stalled": stalled,
        "results": {str(s): r for s, r in results.items()},
    }


def _write_repro(sdir, seed, profile, manifest_text, exc, runner) -> str:
    """Freeze everything needed to replay a failing seed into one JSON."""
    logs = {}
    try:
        for name, path in runner.node_logs().items():
            logs[name] = {"path": path, "tail": _tail(path)}
    except Exception:
        pass  # a half-constructed runner must not mask the real failure
    repro = {
        "seed": seed,
        "profile": profile,
        "regenerate": (
            f"python -m cometbft_tpu.cmd e2e generate --seed {seed} "
            f"--profile {profile}"
        ),
        "manifest": manifest_text,
        "error": repr(exc),
        "traceback": traceback.format_exc(),
        "node_logs": logs,
        # Per-node consensus round-state at the moment the stall was
        # detected (None for non-stall failures): height/round/step,
        # per-round vote bitmaps, peer round views.
        "round_states": getattr(runner, "last_round_states", None),
        # network = "sim": the scenario's full resolved schedule (latency
        # matrix, partition/churn timeline, seeds) — this artifact alone
        # replays the failing run bit-identically.
        "sim_schedule": getattr(runner, "sim_schedule", None),
    }
    path = os.path.join(sdir, "repro.json")
    with open(path, "w") as f:
        json.dump(repro, f, indent=2)
    return path


def _tail(path: str, max_bytes: int = 8192) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return ""

"""`python -m cometbft_tpu.cmd` — the node CLI
(reference: cmd/cometbft/main.go:16-36 command registry).

Subcommands: init, start, devnet, testnet, gen-validator, gen-node-key,
show-validator, show-node-id, rollback, reset-state, unsafe-reset-all,
version, inspect.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys
import time


def _default_home() -> str:
    return os.environ.get("CMTHOME", os.path.expanduser("~/.cometbft_tpu"))


def _load_config(home: str):
    """default_config + config.toml (if present) + CMT_* env overrides —
    the reference's viper layering (cmd/cometbft/main.go ParseConfig)."""
    from cometbft_tpu.config import default_config
    from cometbft_tpu.config.toml import apply_env_overrides, load_toml

    cfg = default_config()
    toml_path = os.path.join(home, "config", "config.toml")
    if os.path.exists(toml_path):
        cfg = load_toml(toml_path, cfg)
    cfg.set_root(home)
    return apply_env_overrides(cfg)


def _genesis_pop(pv) -> bytes:
    """Proof of possession for a genesis validator's key: required for
    bn254 (rogue-key defence at registration), empty for everything else."""
    from cometbft_tpu.crypto import bn254

    if pv.priv_key.type() != bn254.KEY_TYPE:
        return b""
    return bn254.prove_possession(pv.priv_key)


def cmd_version(args) -> int:
    from cometbft_tpu.version import VERSION

    print(VERSION)
    return 0


def cmd_init(args) -> int:
    """cmd/cometbft/commands/init.go: genesis + validator key + node key."""
    from cometbft_tpu.config import default_config
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types import cmttime
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    home = args.home
    cfg = default_config().set_root(home)
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    pv = FilePV.load_or_generate(
        cfg.base.priv_validator_key_path(), cfg.base.priv_validator_state_path()
    )
    genesis_path = cfg.base.genesis_path()
    if not os.path.exists(genesis_path):
        pub = pv.get_pub_key()
        doc = GenesisDoc(
            chain_id=args.chain_id or f"test-chain-{os.urandom(3).hex()}",
            genesis_time=cmttime.now(),
            validators=[
                GenesisValidator(pub.address(), pub, 10, "", _genesis_pop(pv))
            ],
        )
        doc.validate_and_complete()
        doc.save_as(genesis_path)
        print(f"Generated genesis file: {genesis_path}")
    _write_node_key(cfg.base.node_key_path())
    toml_path = os.path.join(home, "config", "config.toml")
    if not os.path.exists(toml_path):
        from cometbft_tpu.config.toml import write_config_file

        write_config_file(toml_path, cfg)
        print(f"Generated config file: {toml_path}")
    print(f"Initialized node in {home}")
    return 0


def _write_node_key(path: str) -> None:
    if os.path.exists(path):
        return
    from cometbft_tpu.crypto import ed25519

    key = ed25519.gen_priv_key()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(
            {
                "priv_key": {
                    "type": "tendermint/PrivKeyEd25519",
                    "value": base64.b64encode(key.bytes()).decode(),
                }
            },
            f,
        )


def cmd_start(args) -> int:
    """cmd/cometbft/commands/run_node.go: run one node until interrupted."""
    from cometbft_tpu.node import default_new_node

    cfg = _load_config(args.home)
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    node = default_new_node(cfg)
    node.start()
    print(f"Node started; RPC on {cfg.rpc.laddr}")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        node.stop()
    return 0


def cmd_devnet(args) -> int:
    """In-process multi-validator devnet — the minimum end-to-end slice
    (SURVEY.md §7): N validators over an in-memory switch, RPC on node 0."""
    # Default to host-tier verification: lazily compiling the TPU kernels in
    # the middle of a live consensus round would stall block production.
    # Opt into the device tier with --backend tpu (pre-warms before starting).
    os.environ.setdefault("CMTPU_BACKEND", args.backend)
    if getattr(args, "faults", None):
        # Chaos devnet: inject seeded backend faults and let the supervised
        # chain (CMTPU_BACKEND=auto is the only mode that supervises) prove
        # the devnet keeps committing through them.
        from cometbft_tpu.sidecar.chaos import parse_faults

        parse_faults(args.faults)  # fail on a bad spec before boot, not mid-run
        os.environ["CMTPU_BACKEND"] = "auto"
        os.environ["CMTPU_FAULTS"] = args.faults
        os.environ.setdefault("CMTPU_FAULTS_SEED", "0")
        os.environ.setdefault("CMTPU_DEADLINE_MS", "2000")
        print(f"devnet: backend faults armed ({args.faults}), supervised auto chain")
    if os.environ["CMTPU_BACKEND"] == "tpu":
        from cometbft_tpu.ops import ed25519_kernel as _ek

        print("pre-warming TPU verify kernel...")
        _ek.batch_verify([b"\x00" * 32] * 8, [b""] * 8, [b"\x00" * 64] * 8)

    from cometbft_tpu.abci.example.kvstore import KVStoreApplication
    from cometbft_tpu.abci.client import LocalClientCreator
    from cometbft_tpu.config import test_config
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types import cmttime
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.privval.file import _BY_KEY_TYPE, KEY_TYPES

    n = args.validators
    key_types = [
        k.strip() for k in getattr(args, "key_types", "ed25519").split(",")
        if k.strip()
    ]
    for k in key_types:
        if k not in KEY_TYPES:
            print(
                f"unknown key type {k!r} (want one of {KEY_TYPES})",
                file=sys.stderr,
            )
            return 1
    pvs = [
        FilePV(_BY_KEY_TYPE[key_types[i % len(key_types)]].gen_priv_key())
        for i in range(n)
    ]
    doc = GenesisDoc(
        chain_id="devnet",
        genesis_time=cmttime.now(),
        validators=[
            GenesisValidator(
                pv.get_pub_key().address(),
                pv.get_pub_key(),
                10,
                f"v{i}",
                _genesis_pop(pv),
            )
            for i, pv in enumerate(pvs)
        ],
    )
    doc.validate_and_complete()
    nodes = []
    for i, pv in enumerate(pvs):
        cfg = test_config()
        cfg.p2p.laddr = ""  # in-memory broadcast mesh, no sockets
        cfg.base.db_backend = "memdb"
        cfg.consensus.timeout_commit = args.block_interval
        cfg.consensus.skip_timeout_commit = False
        cfg.rpc.laddr = f"tcp://127.0.0.1:{args.rpc_port}" if i == 0 else ""
        node = Node(cfg, doc, pv, LocalClientCreator(KVStoreApplication()))
        nodes.append(node)

    def make_broadcast(src):
        def bcast(msg):
            for j, other in enumerate(nodes):
                if j != src:
                    other.consensus_state.send_peer_message(msg, peer_id=f"node{src}")
        return bcast

    for i, node in enumerate(nodes):
        node.consensus_state.set_broadcast(make_broadcast(i))
    for node in nodes:
        node.start()
    print(f"devnet: {n} validators, RPC http://127.0.0.1:{args.rpc_port}")
    cs0 = nodes[0].consensus_state
    target = args.blocks
    t0 = time.time()
    try:
        last = 0
        while target <= 0 or cs0.rs.height <= target:
            time.sleep(0.2)
            if cs0.rs.height != last:
                last = cs0.rs.height
                print(f"height={last - 1} committed  ({(last - 1) / max(time.time() - t0, 1e-9):.2f} blocks/s)")
            if target > 0 and cs0.rs.height > target:
                break
    except KeyboardInterrupt:
        pass
    for node in nodes:
        node.stop()
    print(f"devnet done at height {cs0.rs.height - 1}")
    from cometbft_tpu.sidecar import backend as _backend_mod

    live = _backend_mod._backend
    if live is not None and hasattr(live, "counters"):
        print(f"backend counters: {live.counters()}")
    return 0


def cmd_light(args) -> int:
    """cmd/cometbft/commands/light.go: run a verifying light-client proxy
    against a full node's RPC."""
    from cometbft_tpu.libs.db import MemDB
    from cometbft_tpu.light.client import Client, TrustOptions
    from cometbft_tpu.light.provider import HTTPProvider
    from cometbft_tpu.light.proxy import LightProxy
    from cometbft_tpu.light.store import LightStore
    from cometbft_tpu.rpc.client import HTTPClient

    primary = HTTPProvider(args.chain_id, HTTPClient(args.primary))
    witnesses = [
        HTTPProvider(args.chain_id, HTTPClient(w))
        for w in args.witnesses.split(",")
        if w
    ]
    if args.trusted_height > 0 and args.trusted_hash:
        trust = TrustOptions(
            period_ns=int(args.trust_period * 10**9),
            height=args.trusted_height,
            hash=bytes.fromhex(args.trusted_hash),
        )
    else:
        # Trust-on-first-use bootstrap from the primary's latest header.
        lb = primary.light_block(0)
        trust = TrustOptions(
            period_ns=int(args.trust_period * 10**9), height=lb.height, hash=lb.hash()
        )
        print(f"trusting header {lb.height} ({lb.hash().hex().upper()}) from primary")
    client = Client(
        args.chain_id, trust, primary, witnesses, LightStore(MemDB()),
        skip_verification="sequential" if args.sequential else "skipping",
    )
    host, _, port = args.laddr.split("://")[-1].rpartition(":")
    proxy = LightProxy(client, HTTPClient(args.primary), host or "127.0.0.1", int(port))
    proxy.start()
    print(f"light proxy for {args.chain_id} on http://{host or '127.0.0.1'}:{proxy.port}")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        proxy.stop()
    return 0


def cmd_show_validator(args) -> int:
    from cometbft_tpu.privval import FilePV

    cfg = _load_config(args.home)
    pv = FilePV.load(
        cfg.base.priv_validator_key_path(), cfg.base.priv_validator_state_path()
    )
    pub = pv.get_pub_key()
    print(
        json.dumps(
            {"type": "tendermint/PubKeyEd25519", "value": base64.b64encode(pub.bytes()).decode()}
        )
    )
    return 0


def cmd_show_node_id(args) -> int:
    cfg = _load_config(args.home)
    with open(cfg.base.node_key_path()) as f:
        d = json.load(f)
    from cometbft_tpu.crypto import ed25519

    key = ed25519.PrivKey(base64.b64decode(d["priv_key"]["value"]))
    print(key.pub_key().address().hex())
    return 0


def cmd_gen_validator(args) -> int:
    from cometbft_tpu.crypto import ed25519

    key = ed25519.gen_priv_key()
    pub = key.pub_key()
    print(
        json.dumps(
            {
                "address": pub.address().hex().upper(),
                "pub_key": {"type": "tendermint/PubKeyEd25519", "value": base64.b64encode(pub.bytes()).decode()},
                "priv_key": {"type": "tendermint/PrivKeyEd25519", "value": base64.b64encode(key.bytes()).decode()},
            },
            indent=2,
        )
    )
    return 0


def cmd_rollback(args) -> int:
    """cmd rollback (state/rollback.go): undo one height of state."""
    from cometbft_tpu.libs.db import new_db
    from cometbft_tpu.state.rollback import rollback_state
    from cometbft_tpu.state.store import StateStore
    from cometbft_tpu.store import BlockStore

    cfg = _load_config(args.home)
    state_store = StateStore(new_db("state", cfg.base.db_backend, cfg.base.db_path()))
    block_store = BlockStore(new_db("blockstore", cfg.base.db_backend, cfg.base.db_path()))
    height, app_hash = rollback_state(state_store, block_store)
    print(f"Rolled back state to height {height} and hash {app_hash.hex().upper()}")
    return 0


def cmd_reset_state(args) -> int:
    import shutil

    data = os.path.join(args.home, "data")
    if os.path.isdir(data):
        shutil.rmtree(data)
    os.makedirs(data, exist_ok=True)
    print(f"Removed all blockchain data in {data}")
    return 0


def cmd_gen_node_key(args) -> int:
    """cmd gen_node_key.go: print a fresh node key (and persist if absent)."""
    from cometbft_tpu.p2p.key import NodeKey

    cfg = _load_config(args.home)
    nk = NodeKey.load_or_gen(cfg.base.node_key_path())
    print(nk.id)
    return 0


def cmd_inspect(args) -> int:
    """cmd inspect (inspect/inspect.go): read-only RPC over a stopped node's
    data directory."""
    from cometbft_tpu.inspect import Inspector

    cfg = _load_config(args.home)
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    ins = Inspector(cfg)
    ins.start()
    print(f"inspect RPC on http://127.0.0.1:{ins.port} (read-only)")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        ins.stop()
    return 0


def cmd_compact_db(args) -> int:
    """cmd compact_goleveldb.go analog: compact every data-dir database."""
    from cometbft_tpu.libs.db import new_db

    cfg = _load_config(args.home)
    if cfg.base.db_backend == "memdb":
        print("memdb backend: nothing to compact")
        return 0
    for name in ("blockstore", "state", "tx_index", "block_index", "evidence"):
        db = new_db(name, cfg.base.db_backend, cfg.base.db_path())
        db.compact()
        print(f"compacted {name}")
    return 0


def cmd_reindex_event(args) -> int:
    """cmd reindex_event.go: rebuild tx + block indexes from the block store
    and the persisted ABCI responses."""
    from cometbft_tpu.libs.db import new_db
    from cometbft_tpu.state import StateStore
    from cometbft_tpu.state.execution import decode_responses
    from cometbft_tpu.state.txindex import KVBlockIndexer, KVTxIndexer
    from cometbft_tpu.store import BlockStore
    from cometbft_tpu.types.events import _abci_events_to_attrs

    cfg = _load_config(args.home)
    db_dir = cfg.base.db_path()
    block_store = BlockStore(new_db("blockstore", cfg.base.db_backend, db_dir))
    state_store = StateStore(new_db("state", cfg.base.db_backend, db_dir))
    tx_indexer = KVTxIndexer(new_db("tx_index", cfg.base.db_backend, db_dir))
    block_indexer = KVBlockIndexer(new_db("block_index", cfg.base.db_backend, db_dir))
    start = args.start_height or max(block_store.base(), 1)
    end = args.end_height or block_store.height()
    if end < start:
        print(f"nothing to reindex (base {start}, height {end})")
        return 1
    n = 0
    for h in range(start, end + 1):
        block = block_store.load_block(h)
        raw = state_store.load_abci_responses(h)
        if block is None or raw is None:
            continue
        resp = decode_responses(raw)
        begin, end_blk = resp["begin_block"], resp["end_block"]
        block_indexer.index(
            h, _abci_events_to_attrs(list(begin.events) + list(end_blk.events))
        )
        for i, tx in enumerate(block.data.txs):
            res = resp["deliver_txs"][i]
            tx_indexer.index(h, i, tx, res, _abci_events_to_attrs(res.events))
        n += 1
    print(f"reindexed {n} blocks ({start}..{end})")
    return 0


def cmd_replay(args, console: bool = False) -> int:
    """cmd replay.go / replay_console.go: re-apply the WAL tail for the
    latest height against the app (through the normal handshake machinery),
    optionally stepping message-by-message."""
    from cometbft_tpu.consensus.wal import WAL
    from cometbft_tpu.node import default_new_node

    cfg = _load_config(args.home)
    cfg.rpc.laddr = ""
    cfg.p2p.laddr = ""
    wal_path = cfg.consensus.wal_path()
    if console and os.path.exists(wal_path):
        wal = WAL(wal_path)
        count = 0
        for tm in wal.iter_messages():
            count += 1
            print(f"#{count}: {type(tm.msg).__name__} {tm.msg}")
            try:
                input("> press enter to continue (ctrl-d to finish)...")
            except EOFError:
                break
        wal.stop()
    # The handshake inside Node construction IS the replay (replay.go
    # height-case analysis + WAL catchup).
    node = default_new_node(cfg)
    h = node.block_store.height()
    node.stop()
    print(f"replay done; store height {h}")
    return 0


def cmd_debug(args) -> int:
    """cmd debug kill/dump (cmd/cometbft/commands/debug): collect a node's
    status/net_info/consensus state + config into a debug archive; `kill`
    also terminates the process."""
    import urllib.request
    import zipfile

    def fetch(method):
        url = f"{args.rpc_laddr.replace('tcp://', 'http://')}"
        body = json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": {}}
        ).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.read()

    os.makedirs(os.path.dirname(args.output) or ".", exist_ok=True)
    with zipfile.ZipFile(args.output, "w") as z:
        for method in ("status", "net_info", "consensus_state", "dump_consensus_state"):
            try:
                z.writestr(f"{method}.json", fetch(method))
            except Exception as e:
                z.writestr(f"{method}.err", str(e))
        cfg_path = os.path.join(args.home, "config")
        if os.path.isdir(cfg_path):
            for name in os.listdir(cfg_path):
                p = os.path.join(cfg_path, name)
                if os.path.isfile(p) and "priv_validator_key" not in name:
                    z.write(p, f"config/{name}")
    print(f"wrote debug archive {args.output}")
    if args.debug_cmd == "kill":
        pid = int(args.pid)
        if pid <= 0:
            # os.kill(0, ...) would signal OUR OWN process group.
            print("debug kill requires the node's pid", file=sys.stderr)
            return 1
        os.kill(pid, 15)
        print(f"sent SIGTERM to {pid}")
    return 0


def cmd_testnet(args) -> int:
    """cmd/cometbft/commands/testnet.go: generate validator (+ optional
    non-validator) homes with a shared genesis.  --key-types is a comma
    list cycled across nodes (testnet.go's --key-type, generalized so the
    e2e generator can mix consensus key types in one net)."""
    from cometbft_tpu.config import default_config
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.privval.file import KEY_TYPES
    from cometbft_tpu.types import cmttime
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    n = args.validators
    total = n + getattr(args, "non_validators", 0)
    key_types = [k.strip() for k in args.key_types.split(",") if k.strip()]
    for k in key_types:
        if k not in KEY_TYPES:
            print(f"unknown key type {k!r} (want one of {KEY_TYPES})",
                  file=sys.stderr)
            return 1
    pvs = []
    for i in range(total):
        home = os.path.join(args.output_dir, f"node{i}")
        cfg = default_config().set_root(home)
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        pv = FilePV.load_or_generate(
            cfg.base.priv_validator_key_path(),
            cfg.base.priv_validator_state_path(),
            key_type=key_types[i % len(key_types)],
        )
        _write_node_key(cfg.base.node_key_path())
        pvs.append(pv)
    doc = GenesisDoc(
        chain_id=args.chain_id or "testnet",
        genesis_time=cmttime.now(),
        validators=[
            GenesisValidator(
                pv.get_pub_key().address(),
                pv.get_pub_key(),
                1,
                f"node{i}",
                _genesis_pop(pv),
            )
            for i, pv in enumerate(pvs[:n])
        ],
    )
    doc.validate_and_complete()
    for i in range(total):
        doc.save_as(os.path.join(args.output_dir, f"node{i}", "config", "genesis.json"))
    print(f"Successfully initialized {total} node directories in {args.output_dir}")
    return 0


def cmd_loadtime(args) -> int:
    """Load generator + saturation report (reference: test/loadtime +
    test/e2e/runner/benchmark.go): sustained tx load against an in-process
    devnet, mean/σ/min/max block interval and tx latency over the window."""
    from cometbft_tpu.loadtime import run_load

    rep = run_load(
        n_vals=args.validators,
        rate=args.rate,
        min_blocks=args.blocks,
        connections=args.connections,
        signed=args.signed,
        log=lambda s: print(s, file=sys.stderr),
    )
    print(rep.to_json())
    return 0


def _parse_seeds(spec: str) -> list[int]:
    """'3', '1,4,9' or inclusive '0..7' (the generator matrix convention)."""
    seeds: list[int] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ".." in part:
            lo, hi = part.split("..", 1)
            seeds.extend(range(int(lo), int(hi) + 1))
        else:
            seeds.append(int(part))
    if not seeds:
        raise ValueError(f"no seeds in {spec!r}")
    return seeds


def cmd_bundle(args) -> int:
    """Checkpoint-bundle origin tooling (light/origin.py): export a
    stopped node's data dir into the flat directory any dumb HTTP cache
    replicates, serve such a directory, or verify one."""
    sub = getattr(args, "bundle_cmd", None)
    if sub == "export":
        from cometbft_tpu.libs.db import new_db
        from cometbft_tpu.light.origin import BundleOrigin
        from cometbft_tpu.light.provider import BlockStoreProvider
        from cometbft_tpu.state.store import StateStore
        from cometbft_tpu.store import BlockStore
        from cometbft_tpu.types.genesis import GenesisDoc

        cfg = _load_config(args.home)
        doc = GenesisDoc.from_file(cfg.base.genesis_path())
        db_dir = cfg.base.db_path()
        block_store = BlockStore(new_db("blockstore", cfg.base.db_backend, db_dir))
        state_store = StateStore(new_db("state", cfg.base.db_backend, db_dir))
        origin = BundleOrigin(
            doc.chain_id,
            BlockStoreProvider(doc.chain_id, block_store, state_store),
            interval=args.interval or None,
            keep=args.keep or None,
            state_path=os.path.join(db_dir, "light_mmr.state"),
        )
        index = origin.export(args.out)
        print(json.dumps({"out": args.out, **index}, sort_keys=True))
        return 0
    if sub == "serve":
        import functools
        from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

        handler = functools.partial(
            SimpleHTTPRequestHandler, directory=args.dir
        )
        httpd = ThreadingHTTPServer(("127.0.0.1", args.port), handler)
        print(f"serving bundles from {args.dir} on "
              f"http://127.0.0.1:{httpd.server_address[1]}")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            httpd.server_close()
        return 0
    if sub == "verify":
        from cometbft_tpu.light.bundle import (
            Bundle, BundleError, DirBundleSource, check_name,
        )

        src = DirBundleSource(args.dir)
        idx = src._index()
        bad = 0
        for h, name in sorted(
            idx.get("bundles", {}).items(), key=lambda kv: int(kv[0])
        ):
            try:
                with open(os.path.join(args.dir, f"{name}.bundle"), "rb") as f:
                    data = f.read()
                check_name(name, data)
                b = Bundle.decode(data)
                b.self_check(idx.get("chain_id"))
                if b.anchor.height != int(h):
                    raise BundleError(
                        f"indexed height {h} != anchor {b.anchor.height}"
                    )
                print(f"ok   {h:>10} {name[:16]}… {len(data)} bytes")
            except (OSError, BundleError) as e:
                bad += 1
                print(f"BAD  {h:>10} {name[:16]}… {e}")
        return 1 if bad else 0
    print("bundle: expected export | serve | verify", file=sys.stderr)
    return 1


def cmd_e2e(args) -> int:
    """Manifest-driven e2e testnet runs (reference: test/e2e/runner +
    test/e2e/generator): run one manifest, generate a seeded random one,
    or sweep a seed range through the runner."""
    import tempfile

    sub = getattr(args, "e2e_cmd", None)
    if sub == "generate":
        from cometbft_tpu.e2e_generator import generate

        text = generate(args.seed, profile=args.profile)
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                f.write(text)
            print(f"wrote manifest for seed {args.seed} to {args.out}")
        else:
            print(text, end="")
        return 0
    if sub == "matrix":
        from cometbft_tpu.e2e_generator import run_matrix

        out = args.output_dir or tempfile.mkdtemp(prefix="cmtpu-e2e-matrix-")
        summary = run_matrix(
            _parse_seeds(args.seeds), out, profile=args.profile,
            log=lambda s: print(s, file=sys.stderr),
        )
        print(json.dumps(summary))
        return 0 if not summary["failed"] else 1

    # `e2e run --manifest m.toml` (and the original flat `e2e --manifest`).
    from cometbft_tpu.e2e_runner import E2ERunner

    if not args.manifest:
        print("e2e: --manifest is required", file=sys.stderr)
        return 1
    out = args.output_dir or tempfile.mkdtemp(prefix="cmtpu-e2e-")
    runner = E2ERunner(
        args.manifest, out, log=lambda s: print(s, file=sys.stderr)
    )
    report = runner.run()
    print(json.dumps(report))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cometbft_tpu")
    p.add_argument("--home", default=_default_home())
    sub = p.add_subparsers(dest="command")

    sub.add_parser("version")
    sp = sub.add_parser("init")
    sp.add_argument("--chain-id", default="")
    sp = sub.add_parser("start")
    sp.add_argument("--rpc-laddr", dest="rpc_laddr", default="")
    sp = sub.add_parser("devnet")
    sp.add_argument("--validators", type=int, default=4)
    sp.add_argument("--blocks", type=int, default=10)
    sp.add_argument("--rpc-port", type=int, default=26657)
    sp.add_argument("--block-interval", type=float, default=1.0)
    sp.add_argument("--backend", default="cpu", choices=["cpu", "tpu", "hybrid", "auto"])
    sp.add_argument(
        "--key-types",
        default="ed25519",
        dest="key_types",
        help="comma list of consensus key types cycled across validators "
        "(e.g. ed25519,bn254); with CMTPU_AGG_COMMITS=1 an all-bn254 net "
        "ships aggregate commits",
    )
    sp.add_argument(
        "--faults",
        default=None,
        metavar="SPEC",
        help="chaos devnet: CMTPU_FAULTS spec (latency:p:ms,error:p,wedge:p,"
        "flip:p) injected into the supervised auto backend chain",
    )
    sp = sub.add_parser("light")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True, help="primary node RPC URL")
    sp.add_argument("--witnesses", default="", help="comma-separated witness RPC URLs")
    sp.add_argument("--trusted-height", type=int, default=0)
    sp.add_argument("--trusted-hash", default="")
    sp.add_argument("--trust-period", type=float, default=168 * 3600.0)
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp.add_argument("--sequential", action="store_true")
    sub.add_parser("show-validator")
    sub.add_parser("show-node-id")
    sub.add_parser("gen-validator")
    sub.add_parser("rollback")
    sub.add_parser("reset-state")
    sub.add_parser("unsafe-reset-all")
    sub.add_parser("gen-node-key")
    sp = sub.add_parser("inspect")
    sp.add_argument("--rpc-laddr", dest="rpc_laddr", default="")
    sub.add_parser("compact-db")
    sp = sub.add_parser("reindex-event")
    sp.add_argument("--start-height", type=int, default=0)
    sp.add_argument("--end-height", type=int, default=0)
    sub.add_parser("replay")
    sub.add_parser("replay-console")
    sp = sub.add_parser("debug")
    sp.add_argument("debug_cmd", choices=["kill", "dump"])
    sp.add_argument("pid", nargs="?", default="0")
    sp.add_argument("--output", default="debug.zip")
    sp.add_argument("--rpc-laddr", dest="rpc_laddr", default="tcp://127.0.0.1:26657")
    sp = sub.add_parser("testnet")
    sp.add_argument("--validators", type=int, default=4)
    sp.add_argument("--non-validators", type=int, default=0,
                    help="extra full-node homes not in the genesis valset")
    sp.add_argument("--key-types", default="ed25519",
                    help="comma list of consensus key types, cycled per node")
    sp.add_argument("--output-dir", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp = sub.add_parser("loadtime")
    sp.add_argument("--rate", type=int, default=200, help="target tx/s")
    sp.add_argument("--connections", type=int, default=1)
    sp.add_argument("--blocks", type=int, default=100)
    sp.add_argument("--validators", type=int, default=4)
    sp.add_argument("--signed", action="store_true",
                    help="emit SignedTxEnvelopes through the QoS ingress")
    sp = sub.add_parser("bundle")
    bundle_sub = sp.add_subparsers(dest="bundle_cmd")
    bp = bundle_sub.add_parser(
        "export", help="export checkpoint bundles from a node data dir"
    )
    bp.add_argument("--out", required=True, help="flat output directory")
    bp.add_argument("--interval", type=int, default=0,
                    help="checkpoint interval (default CMTPU_BUNDLE_INTERVAL)")
    bp.add_argument("--keep", type=int, default=0,
                    help="newest checkpoints to export (default CMTPU_BUNDLE_KEEP)")
    bp = bundle_sub.add_parser(
        "serve", help="dumb HTTP file server over an exported directory"
    )
    bp.add_argument("--dir", required=True)
    bp.add_argument("--port", type=int, default=0)
    bp = bundle_sub.add_parser(
        "verify", help="content-address + self-check every indexed bundle"
    )
    bp.add_argument("--dir", required=True)
    sp = sub.add_parser("e2e")
    # Flat flags keep `e2e --manifest m.toml` working; the nested
    # subcommands mirror the reference's runner/generator split.
    sp.add_argument("--manifest", default="", help="TOML testnet manifest")
    sp.add_argument("--output-dir", default="")
    e2e_sub = sp.add_subparsers(dest="e2e_cmd")
    ep = e2e_sub.add_parser("run", help="run one manifest through the runner")
    ep.add_argument("--manifest", required=True, help="TOML testnet manifest")
    ep.add_argument("--output-dir", default="")
    ep = e2e_sub.add_parser(
        "generate", help="emit a seeded randomized testnet manifest"
    )
    ep.add_argument("--seed", type=int, required=True)
    ep.add_argument("--profile", default="full", choices=["full", "small", "sim"])
    ep.add_argument("--out", default="", help="output path (default stdout)")
    ep = e2e_sub.add_parser(
        "matrix", help="generate + run a seed range, collect repro artifacts"
    )
    ep.add_argument("--seeds", required=True,
                    help="seed spec: N, 'A..B' (inclusive) or comma list")
    ep.add_argument("--profile", default="small", choices=["full", "small", "sim"])
    ep.add_argument("--output-dir", default="")

    args = p.parse_args(argv)
    handlers = {
        "version": cmd_version,
        "init": cmd_init,
        "start": cmd_start,
        "devnet": cmd_devnet,
        "light": cmd_light,
        "show-validator": cmd_show_validator,
        "show-node-id": cmd_show_node_id,
        "gen-validator": cmd_gen_validator,
        "rollback": cmd_rollback,
        "reset-state": cmd_reset_state,
        "unsafe-reset-all": cmd_reset_state,
        "testnet": cmd_testnet,
        "gen-node-key": cmd_gen_node_key,
        "inspect": cmd_inspect,
        "compact-db": cmd_compact_db,
        "reindex-event": cmd_reindex_event,
        "replay": cmd_replay,
        "replay-console": lambda a: cmd_replay(a, console=True),
        "debug": cmd_debug,
        "loadtime": cmd_loadtime,
        "bundle": cmd_bundle,
        "e2e": cmd_e2e,
    }
    if args.command is None:
        p.print_help()
        return 1
    return handlers[args.command](args)


if __name__ == "__main__":
    sys.exit(main())

"""CLI (reference: cmd/cometbft/, 2,446 LoC)."""

"""Block store: block/part/commit persistence keyed by height and hash
(reference: store/store.go).

Layout (store/store.go keys): H:<height> -> BlockMeta, P:<height>:<part> ->
Part, C:<height> -> last commit, SC:<height> -> seen commit, BH:<hash> ->
height, plus a BlockStoreState {base, height} record.
"""

from __future__ import annotations

import json
import threading

from cometbft_tpu.libs.db import DB
from cometbft_tpu.types.block import Block, BlockMeta, Commit
from cometbft_tpu.types.part_set import Part, PartSet
from cometbft_tpu.wire import proto as wire

_STATE_KEY = b"blockStore"


def _meta_key(height: int) -> bytes:
    return b"H:%d" % height


def _part_key(height: int, part: int) -> bytes:
    return b"P:%d:%d" % (height, part)


def _commit_key(height: int) -> bytes:
    return b"C:%d" % height


def _seen_commit_key(height: int) -> bytes:
    return b"SC:%d" % height


def _hash_key(h: bytes) -> bytes:
    return b"BH:" + h


class BlockStore:
    """store/store.go:36-600."""

    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.RLock()
        raw = db.get(_STATE_KEY)
        if raw:
            st = json.loads(raw)
            self._base = st["base"]
            self._height = st["height"]
        else:
            self._base = 0
            self._height = 0

    def base(self) -> int:
        with self._mtx:
            return self._base

    def height(self) -> int:
        with self._mtx:
            return self._height

    def size(self) -> int:
        with self._mtx:
            return 0 if self._height == 0 else self._height - self._base + 1

    def _save_state(self) -> None:
        self._db.set(
            _STATE_KEY, json.dumps({"base": self._base, "height": self._height}).encode()
        )

    # -- loads ---------------------------------------------------------------

    def load_block_meta(self, height: int) -> BlockMeta | None:
        raw = self._db.get(_meta_key(height))
        return BlockMeta.decode(raw) if raw else None

    def load_block(self, height: int) -> Block | None:
        """store/store.go:96: reassemble from parts."""
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        parts = []
        for i in range(meta.block_id.part_set_header.total):
            part = self.load_block_part(height, i)
            if part is None:
                return None
            parts.append(part.bytes)
        return Block.decode(b"".join(parts))

    def load_block_by_hash(self, block_hash: bytes) -> Block | None:
        raw = self._db.get(_hash_key(block_hash))
        if raw is None:
            return None
        return self.load_block(int(raw))

    def load_block_part(self, height: int, index: int) -> Part | None:
        raw = self._db.get(_part_key(height, index))
        return Part.decode(raw) if raw else None

    def load_block_commit(self, height: int) -> Commit | None:
        """The commit for block at `height` stored with block height+1
        (store/store.go LoadBlockCommit)."""
        raw = self._db.get(_commit_key(height))
        return Commit.decode(raw) if raw else None

    def load_seen_commit(self, height: int) -> Commit | None:
        raw = self._db.get(_seen_commit_key(height))
        return Commit.decode(raw) if raw else None

    # -- saves ---------------------------------------------------------------

    def save_block(self, block: Block, part_set: PartSet, seen_commit: Commit) -> None:
        """store/store.go:368-430."""
        if block is None:
            raise ValueError("BlockStore can only save a non-nil block")
        height = block.header.height
        with self._mtx:
            expected = self._height + 1
            if self._height != 0 and height != expected:
                raise ValueError(
                    f"BlockStore can only save contiguous blocks. Wanted {expected}, got {height}"
                )
            if not part_set.is_complete():
                raise ValueError(
                    "BlockStore can only save complete block part sets"
                )
            from cometbft_tpu.types.block import BlockID

            block_id = BlockID(block.hash(), part_set.header())
            meta = BlockMeta(
                block_id=block_id,
                block_size=part_set.byte_size,
                header=block.header,
                num_txs=len(block.data.txs),
            )
            batch = self._db.new_batch()
            batch.set(_meta_key(height), meta.encode())
            batch.set(_hash_key(block.hash()), b"%d" % height)
            for i in range(part_set.total):
                batch.set(_part_key(height, i), part_set.get_part(i).encode())
            if block.last_commit is not None:
                batch.set(_commit_key(height - 1), block.last_commit.encode())
            batch.set(_seen_commit_key(height), seen_commit.encode())
            batch.write()
            self._height = height
            if self._base == 0:
                self._base = height
            self._save_state()

    def save_seen_commit(self, height: int, seen_commit: Commit) -> None:
        """store/store.go SaveSeenCommit: statesync bootstrap saves the
        light-client-verified commit for the restored height so consensus
        (and RPC /commit) can build on it without the block itself."""
        with self._mtx:
            self._db.set(_seen_commit_key(height), seen_commit.encode())

    def prune_blocks(self, retain_height: int) -> int:
        """store/store.go:268-330: delete blocks below retain_height, keep
        state-relevant commits. Returns number pruned."""
        if retain_height <= 0:
            raise ValueError("height must be greater than 0")
        with self._mtx:
            if self._height == 0:
                raise ValueError("no blocks to prune")
            if retain_height > self._height:
                raise ValueError(
                    f"cannot prune beyond the latest height {self._height}"
                )
            pruned = 0
            batch = self._db.new_batch()
            for h in range(self._base, retain_height):
                meta = self.load_block_meta(h)
                if meta is None:
                    continue
                batch.delete(_meta_key(h))
                batch.delete(_hash_key(meta.block_id.hash))
                batch.delete(_commit_key(h))
                batch.delete(_seen_commit_key(h))
                for i in range(meta.block_id.part_set_header.total):
                    batch.delete(_part_key(h, i))
                pruned += 1
            batch.write()
            self._base = retain_height
            self._save_state()
            return pruned

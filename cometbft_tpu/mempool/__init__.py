"""Mempool (reference: mempool/, 1,607 LoC) + QoS ingress pipeline."""

from cometbft_tpu.mempool.clist_mempool import CListMempool, TxCache

__all__ = ["CListMempool", "TxCache", "IngressPipeline", "SignedTxEnvelope"]


def __getattr__(name):
    # Lazy: ingress pulls in crypto/backend modules; keep plain mempool
    # imports cheap for consumers that never touch admission.
    if name in ("IngressPipeline", "SignedTxEnvelope"):
        from cometbft_tpu.mempool import ingress

        return getattr(ingress, name)
    raise AttributeError(name)

"""Mempool (reference: mempool/, 1,607 LoC)."""

from cometbft_tpu.mempool.clist_mempool import CListMempool, TxCache

__all__ = ["CListMempool", "TxCache"]

"""Priority lanes, weighted fair queuing, and per-sender token buckets.

These are the queueing primitives for the QoS ingress pipeline
(``mempool/ingress.py``).  They are deliberately free of any mempool or
backend dependency so the fairness properties can be unit/property tested
with a fake clock.

Semantics
---------
- ``LaneSet`` holds N bounded FIFO lanes.  Lane ``N-1`` is the highest
  priority.  Enqueue sheds (raises) when the lane is at capacity or when a
  single sender already occupies more than its fair share of the lane, so
  one spammer can neither block the RPC thread nor squat the whole queue.
- Draining uses deficit-round-robin weighted fair queuing: each drain
  cycle grants lane ``i`` a quantum of ``2**i`` txs, so higher lanes get
  geometrically more bandwidth but low lanes are never starved.
- ``TokenBucket`` is a standard rate limiter keyed by authenticated
  sender identity (the envelope pubkey).  Legacy/unattributable txs are
  not bucketed — you cannot rate-limit an identity you cannot verify.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional


class LaneFull(Exception):
    """Lane queue at capacity (or sender over its per-lane share)."""


class RateLimited(Exception):
    """Per-sender token bucket empty."""


class TokenBucket:
    """Token bucket: ``rate`` tokens/sec, capacity ``burst``.

    ``now`` is injectable for deterministic tests.
    """

    __slots__ = ("rate", "burst", "tokens", "_last", "_now")

    def __init__(self, rate: float, burst: float, now: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._now = now
        self._last = now()

    def allow(self, n: float = 1.0) -> bool:
        t = self._now()
        elapsed = t - self._last
        self._last = t
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


@dataclass
class LaneItem:
    tx: bytes
    sender: str = ""
    lane: int = 0
    meta: object = None
    seq: int = field(default=0)


class LaneSet:
    """N bounded FIFO lanes with DRR weighted-fair draining.

    Thread-safe.  ``queue_max`` bounds each lane; a single sender may hold
    at most ``max(1, queue_max // sender_share_div)`` slots per lane so a
    flood cannot squat a bounded queue ahead of honest traffic.
    """

    def __init__(
        self,
        lanes: int = 4,
        queue_max: int = 2048,
        sender_rps: float = 0.0,
        sender_burst: Optional[float] = None,
        sender_share_div: int = 4,
        now: Callable[[], float] = time.monotonic,
    ):
        if lanes < 1:
            raise ValueError("lanes must be >= 1")
        self.n_lanes = int(lanes)
        self.queue_max = int(queue_max)
        self.sender_rps = float(sender_rps)
        self.sender_burst = float(sender_burst if sender_burst is not None else max(1.0, 2 * sender_rps))
        self.sender_share = max(1, self.queue_max // max(1, sender_share_div))
        self._now = now
        self._mtx = threading.Lock()
        self._queues: List[Deque[LaneItem]] = [deque() for _ in range(self.n_lanes)]
        self._per_sender: List[Dict[str, int]] = [dict() for _ in range(self.n_lanes)]
        self._buckets: Dict[str, TokenBucket] = {}
        self._seq = 0
        # DRR state: deficit counter per lane, drained high -> low.
        self._deficit = [0] * self.n_lanes

    def clamp_lane(self, lane: int) -> int:
        return max(0, min(int(lane), self.n_lanes - 1))

    def rate_check(self, sender: str) -> bool:
        """Charge one token for ``sender``; True if admitted.

        Only authenticated (non-empty) senders are bucketed, and only when a
        positive rate is configured.
        """
        if self.sender_rps <= 0 or not sender:
            return True
        with self._mtx:
            b = self._buckets.get(sender)
            if b is None:
                b = TokenBucket(self.sender_rps, self.sender_burst, now=self._now)
                self._buckets[sender] = b
                # Opportunistic GC so a churn of one-shot senders can't grow
                # the bucket map without bound.
                if len(self._buckets) > 65536:
                    stale = [k for k, v in self._buckets.items() if v.tokens >= v.burst]
                    for k in stale[: len(stale) // 2]:
                        self._buckets.pop(k, None)
            return b.allow()

    def push(self, item: LaneItem) -> None:
        """Enqueue; raises LaneFull when shedding."""
        lane = self.clamp_lane(item.lane)
        item.lane = lane
        with self._mtx:
            q = self._queues[lane]
            if len(q) >= self.queue_max:
                raise LaneFull(f"lane {lane} full ({len(q)}/{self.queue_max})")
            held = self._per_sender[lane].get(item.sender, 0)
            if item.sender and held >= self.sender_share:
                raise LaneFull(
                    f"sender over lane share ({held}/{self.sender_share} in lane {lane})"
                )
            self._seq += 1
            item.seq = self._seq
            q.append(item)
            if item.sender:
                self._per_sender[lane][item.sender] = held + 1

    def drain(self, budget: int) -> List[LaneItem]:
        """Dequeue up to ``budget`` items in weighted-fair order.

        Deficit round robin over lanes high -> low with quantum ``2**i``
        for lane ``i``: strict enough that priority traffic wins, fair
        enough that lane 0 still drains under sustained high-lane load.
        """
        out: List[LaneItem] = []
        with self._mtx:
            if budget <= 0:
                return out
            while len(out) < budget and any(self._queues):
                progressed = False
                for lane in range(self.n_lanes - 1, -1, -1):
                    q = self._queues[lane]
                    if not q:
                        self._deficit[lane] = 0
                        continue
                    self._deficit[lane] += 1 << lane
                    while q and self._deficit[lane] > 0 and len(out) < budget:
                        item = q.popleft()
                        self._deficit[lane] -= 1
                        progressed = True
                        if item.sender:
                            cnt = self._per_sender[lane].get(item.sender, 1) - 1
                            if cnt <= 0:
                                self._per_sender[lane].pop(item.sender, None)
                            else:
                                self._per_sender[lane][item.sender] = cnt
                        out.append(item)
                    if len(out) >= budget:
                        break
                if not progressed:
                    break
        return out

    def depths(self) -> List[int]:
        with self._mtx:
            return [len(q) for q in self._queues]

    def size(self) -> int:
        with self._mtx:
            return sum(len(q) for q in self._queues)

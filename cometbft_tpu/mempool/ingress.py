"""QoS-aware transaction ingress: signed envelopes, batched pre-verification,
priority lanes, and load shedding.

The ``IngressPipeline`` sits between the tx producers (RPC
``broadcast_tx_*`` handlers and the mempool reactor's gossip receive) and
the clist mempool.  It exposes the same ``check_tx(tx, callback, sender)``
admission surface and delegates everything else to the wrapped mempool, so
node wiring can hand it anywhere a mempool is expected.

Pipeline stages::

    submit (RPC / gossip thread, never blocks)
      -> envelope decode (legacy passthrough) + duplicate short-circuit
      -> per-sender token bucket  -> reject CODE_RATE_LIMITED
      -> bounded lane enqueue     -> reject CODE_QUEUE_FULL (load shed)
    dispatcher thread (micro-batch window)
      -> WFQ drain of lanes
      -> ed25519.BatchVerifier over envelope sigs — one dispatch through
         the CoalescingScheduler -> ResilientBackend chain; the
         verified-triple LRU makes gossip re-admission free and the
         chain-exhausted fallback scalar-verifies, so a wedged device
         tier degrades admission but never drops valid txs
      -> invalid sigs rejected without waking the app
      -> survivors forwarded to mempool.check_tx (app CheckTx) lane-tagged

Rejections are delivered synchronously through the caller's callback as a
``ResponseCheckTx`` with codespace ``"ingress"`` and a distinct code per
cause — the RPC thread gets its answer immediately instead of blocking on
a full queue.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.mempool.clist_mempool import (
    ErrMempoolIsFull,
    ErrTxInCache,
)
from cometbft_tpu.mempool.lanes import LaneFull, LaneItem, LaneSet
from cometbft_tpu.sidecar import engine

# -- SignedTxEnvelope wire format (version 1) --------------------------------
#
#   [0]      magic 0xCE ("claimed envelope"); any other first byte is a
#            legacy unsigned tx and passes through untouched
#   [1]      version (1)
#   [2:34]   ed25519 pubkey (32 bytes) — the authenticated sender identity
#   [34]     priority byte (clamped into the configured lane count)
#   [35:43]  nonce, u64 big-endian (replay discrimination; two envelopes
#            differing only in nonce are distinct txs)
#   [43:-64] payload (>= 1 byte, handed to the app unchanged inside the
#            envelope bytes)
#   [-64:]   ed25519 signature over SIGN_DOMAIN || version || priority ||
#            nonce || payload

ENVELOPE_MAGIC = 0xCE
ENVELOPE_VERSION = 1
SIGN_DOMAIN = b"cmtpu/ingress/"
_HEADER_LEN = 2 + 32 + 1 + 8
_MIN_LEN = _HEADER_LEN + 1 + 64

CODESPACE_INGRESS = "ingress"
CODE_BAD_ENVELOPE = 101
CODE_INVALID_SIGNATURE = 102
CODE_RATE_LIMITED = 103
CODE_QUEUE_FULL = 104  # distinct load-shed "mempool full" code
CODE_TX_IN_CACHE = 105
CODE_MEMPOOL_FULL = 106
CODE_REJECTED = 107


class BadEnvelope(Exception):
    pass


@dataclass
class SignedTxEnvelope:
    pubkey: bytes
    priority: int
    nonce: int
    payload: bytes
    signature: bytes

    @property
    def sender(self) -> str:
        return self.pubkey.hex()

    def sign_bytes(self) -> bytes:
        return (
            SIGN_DOMAIN
            + bytes([ENVELOPE_VERSION, self.priority])
            + struct.pack(">Q", self.nonce)
            + self.payload
        )


def encode_envelope(
    priv: ed25519.PrivKey, payload: bytes, priority: int = 0, nonce: int = 0
) -> bytes:
    if not payload:
        raise ValueError("envelope payload must be non-empty")
    priority = max(0, min(int(priority), 255))
    body = bytes([priority]) + struct.pack(">Q", nonce)
    msg = SIGN_DOMAIN + bytes([ENVELOPE_VERSION]) + body + payload
    sig = priv.sign(msg)
    return (
        bytes([ENVELOPE_MAGIC, ENVELOPE_VERSION])
        + priv.pub_key().bytes()
        + body
        + payload
        + sig
    )


def decode_envelope(tx: bytes) -> Optional[SignedTxEnvelope]:
    """Decode ``tx``; None for legacy passthrough, BadEnvelope if malformed.

    A tx is only treated as an envelope when its first byte is the magic;
    from there on malformed framing is an error, not a passthrough —
    otherwise a truncated envelope would sneak past signature checks as a
    "legacy" tx.
    """
    if not tx or tx[0] != ENVELOPE_MAGIC:
        return None
    if len(tx) < _MIN_LEN:
        raise BadEnvelope(f"envelope too short ({len(tx)} < {_MIN_LEN})")
    if tx[1] != ENVELOPE_VERSION:
        raise BadEnvelope(f"unsupported envelope version {tx[1]}")
    pubkey = bytes(tx[2:34])
    priority = tx[34]
    (nonce,) = struct.unpack(">Q", tx[35:43])
    payload = bytes(tx[43:-64])
    sig = bytes(tx[-64:])
    return SignedTxEnvelope(pubkey, priority, nonce, payload, sig)


def _env_float(name: str, fallback: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else fallback
    except ValueError:
        return fallback


def _reject_response(code: int, log: str) -> abci.ResponseCheckTx:
    return abci.ResponseCheckTx(code=code, log=log, codespace=CODESPACE_INGRESS)


class IngressPipeline:
    """Admission pipeline wrapping a CListMempool.

    Knobs (env wins over the mempool config section):
      CMTPU_INGRESS_LANES      priority lane count        (default 4)
      CMTPU_INGRESS_SENDER_RPS per-sender token rate, 0 = unlimited
      CMTPU_INGRESS_QUEUE_MAX  per-lane bound             (default 2048)
      CMTPU_INGRESS_WINDOW_MS  preverify micro-batch window (default 2)
    """

    def __init__(self, config, mempool, now: Callable[[], float] = time.monotonic):
        self.mempool = mempool
        self.n_lanes = int(
            _env_float("CMTPU_INGRESS_LANES", getattr(config, "ingress_lanes", 4))
        )
        self.sender_rps = _env_float(
            "CMTPU_INGRESS_SENDER_RPS", getattr(config, "ingress_sender_rps", 0.0)
        )
        self.queue_max = int(
            _env_float(
                "CMTPU_INGRESS_QUEUE_MAX", getattr(config, "ingress_queue_max", 2048)
            )
        )
        self.window_ms = _env_float(
            "CMTPU_INGRESS_WINDOW_MS", getattr(config, "ingress_window_ms", 2.0)
        )
        self.max_batch = int(_env_float("CMTPU_INGRESS_MAX_BATCH", 4096))
        self.lanes = LaneSet(
            lanes=self.n_lanes,
            queue_max=self.queue_max,
            sender_rps=self.sender_rps,
            now=now,
        )
        self._cmtx = threading.Lock()
        self.counters = {
            "submitted": 0,
            "admitted": 0,
            "legacy_passthrough": 0,
            "rejected_bad_envelope": 0,
            "rejected_invalid_sig": 0,
            "rejected_rate_limited": 0,
            "rejected_queue_full": 0,
            "rejected_duplicate": 0,
            "rejected_mempool_full": 0,
            "rejected_other": 0,
            "shed_total": 0,
            "preverify_batches": 0,
            "preverify_sigs": 0,
            "preverify_batch_max": 0,
        }
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tx-ingress", daemon=True
        )
        self._thread.start()

    # -- admission surface ---------------------------------------------------

    def check_tx(self, tx: bytes, callback=None, sender: str = "") -> None:
        """Admit ``tx`` asynchronously; rejections answer via ``callback``.

        Never blocks: over-rate and over-capacity submissions are shed with
        a coded ResponseCheckTx instead of waiting for queue space.
        """
        self._count("submitted")
        try:
            env = decode_envelope(tx)
        except BadEnvelope as e:
            self._count("rejected_bad_envelope")
            self._answer(callback, _reject_response(CODE_BAD_ENVELOPE, str(e)))
            return
        # Duplicate short-circuit: seen txs (gossip echo, client retry) go
        # straight to the mempool, which records the new sender and raises
        # — no bucket charge, no queue slot, no signature work.
        if self.mempool.cache.has(tx):
            try:
                self.mempool.check_tx(tx, callback=callback, sender=sender)
            except ErrTxInCache:
                self._count("rejected_duplicate")
                self._answer(
                    callback,
                    _reject_response(CODE_TX_IN_CACHE, "tx already exists in cache"),
                )
            except ErrMempoolIsFull as e:
                self._count("rejected_mempool_full")
                self._count("shed_total")
                self._answer(callback, _reject_response(CODE_MEMPOOL_FULL, str(e)))
            except Exception as e:
                self._count("rejected_other")
                self._answer(callback, _reject_response(CODE_REJECTED, str(e)))
            return
        if env is None:
            self._count("legacy_passthrough")
            item = LaneItem(tx=tx, sender="", lane=0, meta=(None, callback, sender))
        else:
            ident = env.sender
            if not self.lanes.rate_check(ident):
                self._count("rejected_rate_limited")
                self._count("shed_total")
                self._answer(
                    callback,
                    _reject_response(
                        CODE_RATE_LIMITED, f"sender {ident[:16]} over rate limit"
                    ),
                )
                return
            item = LaneItem(
                tx=tx,
                sender=ident,
                lane=self.lanes.clamp_lane(env.priority),
                meta=(env, callback, sender or ident),
            )
        try:
            self.lanes.push(item)
        except LaneFull as e:
            self._count("rejected_queue_full")
            self._count("shed_total")
            self._answer(
                callback, _reject_response(CODE_QUEUE_FULL, f"mempool full: {e}")
            )
            return
        self._wake.set()

    # -- dispatcher ----------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait()
            if self._stop.is_set():
                return
            self._wake.clear()
            # Micro-batch window measured from the first waiter, mirroring
            # the CoalescingScheduler: trade window_ms of latency for one
            # fused preverify dispatch across concurrent senders.
            if self.window_ms > 0:
                time.sleep(self.window_ms / 1000.0)
            while True:
                batch = self.lanes.drain(self.max_batch)
                if not batch:
                    break
                try:
                    self._process(batch)
                except Exception:
                    # The dispatcher thread must survive anything — a dead
                    # dispatcher would silently blackhole all admission.
                    for it in batch:
                        _, cb, _ = it.meta
                        self._answer(
                            cb, _reject_response(CODE_REJECTED, "ingress error")
                        )

    def _process(self, batch) -> None:
        signed = [it for it in batch if it.meta[0] is not None]
        bits = []
        if signed:
            verifier = ed25519.BatchVerifier()
            for it in signed:
                env = it.meta[0]
                verifier.add(
                    ed25519.PubKey(env.pubkey), env.sign_bytes(), env.signature
                )
            try:
                # Ingress-class admission into the continuous-batching
                # engine (round 14): preverify work rides the shared device
                # queue below consensus votes and blocksync, above light
                # prewarm. BatchVerifier semantics (cache filter, dedup,
                # scalar fallback on chain exhaustion) are unchanged.
                with engine.submission_class(engine.CLASS_INGRESS):
                    _, bits = verifier.verify()
            except Exception:
                # Anchor of last resort: scalar-verify each envelope so a
                # broken backend chain degrades throughput, not correctness.
                bits = [
                    ed25519.PubKey(it.meta[0].pubkey).verify_signature(
                        it.meta[0].sign_bytes(), it.meta[0].signature
                    )
                    for it in signed
                ]
            with self._cmtx:
                self.counters["preverify_batches"] += 1
                self.counters["preverify_sigs"] += len(signed)
                self.counters["preverify_batch_max"] = max(
                    self.counters["preverify_batch_max"], len(signed)
                )
        verdict = dict(zip(map(id, signed), bits))
        for it in batch:
            env, cb, sender = it.meta
            if env is not None and not verdict.get(id(it), False):
                self._count("rejected_invalid_sig")
                self._answer(
                    cb,
                    _reject_response(CODE_INVALID_SIGNATURE, "envelope signature invalid"),
                )
                continue
            try:
                self.mempool.check_tx(it.tx, callback=cb, sender=sender, lane=it.lane)
                self._count("admitted")
            except ErrTxInCache:
                self._count("rejected_duplicate")
                self._answer(
                    cb, _reject_response(CODE_TX_IN_CACHE, "tx already exists in cache")
                )
            except ErrMempoolIsFull as e:
                self._count("rejected_mempool_full")
                self._count("shed_total")
                self._answer(cb, _reject_response(CODE_MEMPOOL_FULL, str(e)))
            except Exception as e:
                self._count("rejected_other")
                self._answer(cb, _reject_response(CODE_REJECTED, str(e)))

    # -- plumbing ------------------------------------------------------------

    def _count(self, key: str, n: int = 1) -> None:
        with self._cmtx:
            self.counters[key] += n

    @staticmethod
    def _answer(callback, res: abci.ResponseCheckTx) -> None:
        if callback is not None:
            try:
                callback(res)
            except Exception:
                pass

    def stats(self) -> dict:
        with self._cmtx:
            out = dict(self.counters)
        out["lane_depths"] = self.lanes.depths()
        out["lanes"] = self.n_lanes
        out["sender_rps"] = self.sender_rps
        out["queue_max"] = self.queue_max
        return out

    def lane_depths(self):
        return self.lanes.depths()

    def register_metrics(self, registry) -> None:
        def sample(key):
            return lambda: float(self.counters[key])

        for key in (
            "admitted",
            "legacy_passthrough",
            "rejected_bad_envelope",
            "rejected_invalid_sig",
            "rejected_rate_limited",
            "rejected_queue_full",
            "rejected_duplicate",
            "rejected_mempool_full",
            "shed_total",
            "preverify_batches",
            "preverify_sigs",
            "preverify_batch_max",
        ):
            registry.gauge_func(
                "ingress", f"{key}_total" if not key.startswith("preverify") else key,
                f"ingress {key.replace('_', ' ')}", sample(key),
            )
        registry.gauge_func(
            "ingress", "queue_depth", "total queued txs across lanes",
            lambda: float(self.lanes.size()),
        )
        for i in range(self.n_lanes):
            registry.gauge_func(
                "ingress", f"lane{i}_depth", f"queued txs in lane {i}",
                (lambda i=i: float(self.lanes.depths()[i])),
            )

    def flush_queue(self, timeout: float = 5.0) -> bool:
        """Block until the lane queues are empty (tests/bench)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.lanes.size() == 0:
                return True
            self._wake.set()
            time.sleep(0.002)
        return False

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=2.0)

    def __getattr__(self, name):
        # Everything that is not admission (reap, update, size, cache,
        # txs_front, locks, ...) is the wrapped mempool's business.
        return getattr(self.mempool, name)

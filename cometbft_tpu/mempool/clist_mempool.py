"""FIFO mempool with async CheckTx validation and LRU dedup cache
(reference: mempool/clist_mempool.go + mempool/cache.go).

Ordering is insertion-FIFO (the reference's concurrent linked list —
an OrderedDict here, same iteration semantics). Survivors are re-checked
against the app after every block commit (clist_mempool.go:45-49).
"""

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass

from cometbft_tpu.abci import types as abci
from cometbft_tpu.types.tx import tx_key


@dataclass
class MempoolTx:
    """mempool/clist_mempool.go mempoolTx."""

    height: int  # height when validated
    gas_wanted: int
    tx: bytes
    senders: set
    lane: int = 0  # QoS priority lane (higher drains first at reap)
    seq: int = 0  # admission order, FIFO tiebreak within a lane


class TxCache:
    """LRU cache of seen tx keys (mempool/cache.go:120)."""

    def __init__(self, size: int):
        self._size = size
        self._map: collections.OrderedDict[bytes, None] = collections.OrderedDict()
        self._mtx = threading.Lock()

    def push(self, tx: bytes) -> bool:
        """False if already present (cache.go Push)."""
        k = tx_key(tx)
        with self._mtx:
            if k in self._map:
                self._map.move_to_end(k)
                return False
            if len(self._map) >= self._size:
                self._map.popitem(last=False)
            self._map[k] = None
            return True

    def remove(self, tx: bytes) -> None:
        with self._mtx:
            self._map.pop(tx_key(tx), None)

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()

    def has(self, tx: bytes) -> bool:
        with self._mtx:
            return tx_key(tx) in self._map


class NopTxCache:
    def push(self, tx: bytes) -> bool:
        return True

    def remove(self, tx: bytes) -> None:
        pass

    def reset(self) -> None:
        pass

    def has(self, tx: bytes) -> bool:
        return False


class ErrTxInCache(Exception):
    def __init__(self):
        super().__init__("tx already exists in cache")


class ErrMempoolIsFull(Exception):
    def __init__(self, num_txs, max_txs, txs_bytes, max_bytes):
        super().__init__(
            f"mempool is full: number of txs {num_txs} (max: {max_txs}), "
            f"total txs bytes {txs_bytes} (max: {max_bytes})"
        )


class ErrTxTooLarge(Exception):
    def __init__(self, max_size, actual):
        super().__init__(f"Tx too large. Max size is {max_size}, but got {actual}")


class ErrPreCheck(Exception):
    pass


class CListMempool:
    """mempool/clist_mempool.go:30-520."""

    def __init__(
        self,
        config,
        proxy_app_conn,
        height: int = 0,
        pre_check=None,
        post_check=None,
    ):
        self.config = config
        self.proxy_app = proxy_app_conn
        self.height = height
        self.pre_check = pre_check
        self.post_check = post_check
        self._txs: collections.OrderedDict[bytes, MempoolTx] = collections.OrderedDict()
        self._txs_bytes = 0
        self._mtx = threading.RLock()  # update lock (held during block commit)
        self.cache = (
            TxCache(config.cache_size) if config.cache_size > 0 else NopTxCache()
        )
        self.recheck_txs: list[bytes] = []
        self._notified_available = threading.Event()
        self.tx_available_callback = None
        self._admit_seq = 0

    # -- Mempool interface (mempool/mempool.go:32) ---------------------------

    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    def size(self) -> int:
        return len(self._txs)

    def size_bytes(self) -> int:
        return self._txs_bytes

    def flush_app_conn(self) -> None:
        self.proxy_app.flush()

    def flush(self) -> None:
        """Remove all txs + reset cache (clist_mempool.go Flush)."""
        with self._mtx:
            self._txs.clear()
            self._txs_bytes = 0
            self.cache.reset()

    def check_tx(self, tx: bytes, callback=None, sender: str = "", lane: int = 0) -> None:
        """clist_mempool.go:202-280 CheckTx: size/pre-check, cache dedup,
        async app CheckTx, insertion via resCbFirstTime. ``lane`` tags the
        entry's QoS priority lane (0 = legacy/lowest) for lane-aware reap."""
        with self._mtx:
            tx_size = len(tx)
            if self.size() >= self.config.size or (
                self._txs_bytes + tx_size > self.config.max_txs_bytes
            ):
                raise ErrMempoolIsFull(
                    self.size(), self.config.size, self._txs_bytes, self.config.max_txs_bytes
                )
            if tx_size > self.config.max_tx_bytes:
                raise ErrTxTooLarge(self.config.max_tx_bytes, tx_size)
            if self.pre_check:
                try:
                    self.pre_check(tx)
                except Exception as e:
                    raise ErrPreCheck(str(e)) from e
            if not self.cache.push(tx):
                # Record the sender on the existing entry (clist_mempool.go:240).
                k = tx_key(tx)
                entry = self._txs.get(k)
                if entry is not None and sender:
                    entry.senders.add(sender)
                raise ErrTxInCache()

        def on_res(res: abci.ResponseCheckTx):
            self._res_cb_first_time(tx, sender, res, lane=lane)
            if callback:
                callback(res)

        self.proxy_app.check_tx_async(abci.RequestCheckTx(tx=tx), on_res)

    def _res_cb_first_time(
        self, tx: bytes, sender: str, res: abci.ResponseCheckTx, lane: int = 0
    ):
        post_ok = True
        if self.post_check:
            try:
                self.post_check(tx, res)
            except Exception:
                post_ok = False
        if res.code == abci.CODE_TYPE_OK and post_ok:
            with self._mtx:
                # Re-check capacity at insertion time: other txs may have been
                # admitted since the pre-flight check (clist_mempool.go:386
                # resCbFirstTime re-runs isFull).
                if self.size() >= self.config.size or (
                    self._txs_bytes + len(tx) > self.config.max_txs_bytes
                ):
                    self.cache.remove(tx)
                    return
                k = tx_key(tx)
                if k not in self._txs:
                    self._admit_seq += 1
                    self._txs[k] = MempoolTx(
                        height=self.height,
                        gas_wanted=res.gas_wanted,
                        tx=tx,
                        senders={sender} if sender else set(),
                        lane=lane,
                        seq=self._admit_seq,
                    )
                    self._txs_bytes += len(tx)
            self._notify_tx_available()
        else:
            # invalid: remove from cache so it can be resubmitted (if KeepInvalid off)
            if not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(tx)

    def _notify_tx_available(self) -> None:
        """Fire once per height (clist_mempool.go notifyTxsAvailable latch)."""
        if (
            self.size() > 0
            and self.tx_available_callback
            and not self._notified_available.is_set()
        ):
            self._notified_available.set()
            self.tx_available_callback()

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        """Lane-aware ReapMaxBytesMaxGas: high-priority lanes drain first,
        FIFO (admission seq) within a lane. With no ingress wired every tx
        sits in lane 0 and this degenerates to the reference's pure FIFO."""
        with self._mtx:
            total_bytes = 0
            total_gas = 0
            out = []
            entries = list(self._txs.values())
            if any(m.lane for m in entries):
                entries.sort(key=lambda m: (-m.lane, m.seq))
            for mtx in entries:
                tx_len = len(mtx.tx) + 5  # amino/proto overhead bound
                if max_bytes > -1 and total_bytes + tx_len > max_bytes:
                    break
                if max_gas > -1 and total_gas + mtx.gas_wanted > max_gas:
                    break
                total_bytes += tx_len
                total_gas += mtx.gas_wanted
                out.append(mtx.tx)
            return out

    def reap_max_txs(self, n: int) -> list[bytes]:
        with self._mtx:
            txs = [m.tx for m in self._txs.values()]
            return txs if n < 0 else txs[:n]

    def update(
        self, height: int, txs: list[bytes], deliver_tx_responses, pre_check, post_check
    ) -> None:
        """clist_mempool.go:560-640 Update: called with the mempool lock held
        after every commit. Removes committed txs, re-checks survivors."""
        self.height = height
        self._notified_available.clear()
        if pre_check:
            self.pre_check = pre_check
        if post_check:
            self.post_check = post_check
        for i, tx in enumerate(txs):
            res = deliver_tx_responses[i]
            if res.code == abci.CODE_TYPE_OK:
                self.cache.push(tx)  # committed: keep in cache to block replays
            elif not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
            k = tx_key(tx)
            entry = self._txs.pop(k, None)
            if entry is not None:
                self._txs_bytes -= len(entry.tx)
        if self._txs and self.config.recheck:
            self._recheck_txs()

    def _recheck_txs(self) -> None:
        """Re-run CheckTx(RECHECK) on survivors; drop newly-invalid ones.

        The survivor snapshot is taken under ``_mtx`` (concurrent admission
        must not tear the iteration), and the rechecks go through the async
        proxy as one pipelined wave closed by a single flush — N txs cost
        one round trip to a socket/grpc app instead of N.
        """
        with self._mtx:
            snapshot = list(self._txs.items())
        if not snapshot:
            return
        results: list = [None] * len(snapshot)
        pending = threading.Event()
        remaining = [len(snapshot)]
        rlock = threading.Lock()

        def on_res(i: int):
            def cb(res: abci.ResponseCheckTx):
                results[i] = res
                with rlock:
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        pending.set()

            return cb

        for i, (_, entry) in enumerate(snapshot):
            self.proxy_app.check_tx_async(
                abci.RequestCheckTx(tx=entry.tx, type=abci.CHECK_TX_TYPE_RECHECK),
                on_res(i),
            )
        self.proxy_app.flush()
        pending.wait(timeout=10.0)
        for (k, entry), res in zip(snapshot, results):
            if res is None:  # transport died mid-wave; keep the tx
                continue
            post_ok = True
            if self.post_check:
                try:
                    self.post_check(entry.tx, res)
                except Exception:
                    post_ok = False
            if res.code != abci.CODE_TYPE_OK or not post_ok:
                with self._mtx:
                    gone = self._txs.pop(k, None)
                    if gone is not None:
                        self._txs_bytes -= len(gone.tx)
                if not self.config.keep_invalid_txs_in_cache:
                    self.cache.remove(entry.tx)

    def txs_front(self):
        """Iteration hook for the gossip reactor."""
        with self._mtx:
            return list(self._txs.values())

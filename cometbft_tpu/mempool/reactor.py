"""Mempool gossip reactor (reference: mempool/reactor.go, channel 0x30).

One broadcast thread per peer walks the mempool FIFO and forwards txs the
peer hasn't seen from us (reactor.go:132 broadcastTxRoutine); received txs
enter CheckTx with the sender recorded so they aren't echoed back.

``mempool`` here is the admission surface: when the node wires the QoS
ingress pipeline (mempool/ingress.py), gossiped txs flow through the same
envelope-preverify/lane/shedding path as RPC submissions — one admission
story regardless of where a tx came from.
"""

from __future__ import annotations

import threading
import time

from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.reactor import MEMPOOL_CHANNEL, Reactor
from cometbft_tpu.types.tx import tx_key
from cometbft_tpu.wire import proto as wire


def encode_txs_message(txs: list[bytes]) -> bytes:
    """tendermint.mempool.Txs{txs=1 repeated}."""
    inner = b""
    for tx in txs:
        inner += wire.field_bytes(1, tx, emit_default=True)
    return wire.field_message(1, inner, emit_empty=True)


def decode_txs_message(data: bytes) -> list[bytes]:
    f = wire.decode_fields(data)
    inner = wire.decode_fields(wire.get_bytes(f, 1))
    return wire.get_repeated_bytes(inner, 1)


class MempoolReactor(Reactor):
    def __init__(self, config, mempool, clock=None):
        from cometbft_tpu.simnet.clock import MonotonicClock

        super().__init__("MEMPOOL")
        self.config = config
        self.mempool = mempool
        self.clock = clock or MonotonicClock()
        self._running = False
        self._peer_sent: dict[str, set] = {}

    def get_channels(self):
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5, send_queue_capacity=100)]

    def start(self) -> None:
        self._running = True

    def stop(self) -> None:
        self._running = False

    def add_peer(self, peer) -> None:
        if not self.config.broadcast:
            return
        self._peer_sent[peer.id] = set()
        threading.Thread(
            target=self._broadcast_tx_routine, args=(peer,), daemon=True
        ).start()

    def remove_peer(self, peer, reason) -> None:
        self._peer_sent.pop(peer.id, None)

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        for tx in decode_txs_message(msg_bytes):
            try:
                self.mempool.check_tx(tx, sender=peer.id)
            except Exception:
                pass  # duplicates / full mempool are expected during gossip

    def _broadcast_tx_routine(self, peer) -> None:
        """mempool/reactor.go:132."""
        while self._running and peer.id in self._peer_sent:
            sent_set = self._peer_sent.get(peer.id)
            if sent_set is None:
                return
            batch, keys = [], []
            for mtx in self.mempool.txs_front():
                k = tx_key(mtx.tx)
                if k in sent_set or peer.id in mtx.senders:
                    continue
                keys.append(k)
                batch.append(mtx.tx)
            if batch and peer.try_send(MEMPOOL_CHANNEL, encode_txs_message(batch)):
                # Mark AFTER a successful enqueue: a full send queue drops
                # the message, and pre-marking would lose those txs from
                # gossip forever (same backpressure-liveness rule as the
                # consensus gossip).
                sent_set.update(keys)
            self.clock.sleep(0.05)

"""cometbft_tpu — a TPU-native BFT consensus framework.

A from-scratch rebuild of CometBFT's capability surface (Tendermint BFT
consensus + ABCI + block/state sync + light client + JSON-RPC), redesigned as a
two-tier system:

- **Host tier** (Python/asyncio): consensus state machine, encrypted p2p
  gossip, mempool, block/state stores, ABCI boundary, RPC. Control-flow heavy,
  adversarial, latency-sensitive — kept on CPU, mirroring where the reference
  spends control cycles (reference: consensus/state.go, p2p/, mempool/, ...).

- **Device tier** (JAX/Pallas): the crypto hot path — ZIP-215 Ed25519 batch
  signature verification and RFC-6962 SHA-256 Merkle hashing — as vectorized
  TPU kernels behind the same `BatchVerifier` seam the reference uses
  (reference: crypto/crypto.go:46-54), so commit verification
  (types/validation.go), blocksync replay (blocksync/reactor.go:360) and
  light-client bisection (light/verifier.go) ride the TPU.
"""

from cometbft_tpu.version import __version__  # noqa: F401

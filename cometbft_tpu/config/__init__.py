"""Node configuration tree (reference: config/config.go:67-1183 + toml.go).

Nested dataclasses mirror the reference's sections; `to_toml`/`from_toml`
render/parse the node's config file; durations are seconds (float) here,
milliseconds-suffixed strings in TOML.
"""

from cometbft_tpu.config.config import (
    BaseConfig,
    BlockSyncConfig,
    Config,
    ConsensusConfig,
    InstrumentationConfig,
    MempoolConfig,
    P2PConfig,
    RPCConfig,
    StateSyncConfig,
    StorageConfig,
    TxIndexConfig,
    default_config,
    test_config,
)

__all__ = [
    "BaseConfig",
    "BlockSyncConfig",
    "Config",
    "ConsensusConfig",
    "InstrumentationConfig",
    "MempoolConfig",
    "P2PConfig",
    "RPCConfig",
    "StateSyncConfig",
    "StorageConfig",
    "TxIndexConfig",
    "default_config",
    "test_config",
]

"""Config structs (reference: config/config.go)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field as dfield, replace


@dataclass
class BaseConfig:
    """config/config.go:187-320 BaseConfig."""

    root_dir: str = ""
    # The reference defaults to tcp://127.0.0.1:26658 (an external app);
    # here the in-process kvstore is the default so `init` + `start` work
    # standalone — set a socket address to run the app out of process.
    proxy_app: str = "kvstore"
    moniker: str = "anonymous"
    block_sync: bool = True
    db_backend: str = "sqlite"
    db_dir: str = "data"
    log_level: str = "info"
    log_format: str = "plain"
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    priv_validator_laddr: str = ""
    node_key_file: str = "config/node_key.json"
    abci: str = "socket"
    filter_peers: bool = False
    # In-process kvstore apps only (reference keeps this app-side, in the
    # e2e app's own config — test/e2e/app/app.go): take a state snapshot
    # every N heights so peers can statesync from this node.  0 = off.
    snapshot_interval: int = 0

    def genesis_path(self) -> str:
        return os.path.join(self.root_dir, self.genesis_file)

    def priv_validator_key_path(self) -> str:
        return os.path.join(self.root_dir, self.priv_validator_key_file)

    def priv_validator_state_path(self) -> str:
        return os.path.join(self.root_dir, self.priv_validator_state_file)

    def node_key_path(self) -> str:
        return os.path.join(self.root_dir, self.node_key_file)

    def db_path(self) -> str:
        return os.path.join(self.root_dir, self.db_dir)


@dataclass
class RPCConfig:
    """config/config.go:330-480."""

    laddr: str = "tcp://127.0.0.1:26657"
    cors_allowed_origins: tuple = ()
    cors_allowed_methods: tuple = ("HEAD", "GET", "POST")
    cors_allowed_headers: tuple = ("Origin", "Accept", "Content-Type", "X-Requested-With", "X-Server-Time")
    grpc_laddr: str = ""
    grpc_max_open_connections: int = 900
    unsafe: bool = False
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    experimental_subscription_buffer_size: int = 200
    timeout_broadcast_tx_commit: float = 10.0
    max_body_bytes: int = 1000000
    max_header_bytes: int = 1 << 20
    tls_cert_file: str = ""
    tls_key_file: str = ""
    pprof_laddr: str = ""


@dataclass
class P2PConfig:
    """config/config.go:490-620."""

    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    addr_book_file: str = "config/addrbook.json"
    addr_book_strict: bool = True
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    unconditional_peer_ids: str = ""
    persistent_peers_max_dial_period: float = 0.0
    flush_throttle_timeout: float = 0.1
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5120000
    recv_rate: int = 5120000
    pex: bool = True
    seed_mode: bool = False
    private_peer_ids: str = ""
    test_fuzz: bool = False  # wrap connections in FuzzedConn (p2p/fuzz.go)
    test_fuzz_mode: str = "delay"
    test_fuzz_max_delay: float = 0.2
    test_fuzz_prob_drop_rw: float = 0.2
    allow_duplicate_ip: bool = False
    handshake_timeout: float = 20.0
    dial_timeout: float = 3.0


@dataclass
class MempoolConfig:
    """config/config.go:640-720."""

    recheck: bool = True
    broadcast: bool = True
    wal_dir: str = ""
    size: int = 5000
    max_txs_bytes: int = 1073741824
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1048576
    max_batch_bytes: int = 0
    # QoS ingress (mempool/ingress.py); CMTPU_INGRESS_* env knobs override.
    ingress_enable: bool = True
    ingress_lanes: int = 4
    ingress_sender_rps: float = 0.0  # 0 = per-sender rate limit off
    ingress_queue_max: int = 2048
    ingress_window_ms: float = 2.0


@dataclass
class StateSyncConfig:
    """config/config.go:740-830."""

    enable: bool = False
    temp_dir: str = ""
    rpc_servers: tuple = ()
    trust_period: float = 168 * 3600.0
    trust_height: int = 0
    trust_hash: str = ""
    discovery_time: float = 15.0
    chunk_request_timeout: float = 10.0
    chunk_fetchers: int = 4


@dataclass
class BlockSyncConfig:
    """config/config.go:850-880 (+ the top-level BlockSyncMode toggle,
    config.go:85)."""

    enable: bool = True
    version: str = "v0"


@dataclass
class ConsensusConfig:
    """config/config.go:925-1080: all consensus timeouts (seconds)."""

    wal_file: str = "data/cs.wal/wal"
    root_dir: str = ""
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0
    peer_gossip_sleep_duration: float = 0.1
    peer_query_maj23_sleep_duration: float = 2.0
    double_sign_check_height: int = 0
    # Stall watchdog: if no round-step progress for this many multiples of
    # the current round's full escalated timeout budget, re-announce our
    # round step and re-fire maj23 queries (0 disables). CMTPU_STALL_FACTOR
    # env overrides at node start.
    stall_watchdog_factor: float = 10.0

    def propose_timeout(self, round_: int) -> float:
        return self.timeout_propose + self.timeout_propose_delta * round_

    def prevote_timeout(self, round_: int) -> float:
        return self.timeout_prevote + self.timeout_prevote_delta * round_

    def precommit_timeout(self, round_: int) -> float:
        return self.timeout_precommit + self.timeout_precommit_delta * round_

    def commit_time(self, t: float) -> float:
        return t + self.timeout_commit

    def round_timeout_budget(self, round_: int) -> float:
        """Worst-case wall time one full round can legitimately take at this
        escalation level — the stall watchdog's unit of patience."""
        return (
            self.propose_timeout(round_)
            + self.prevote_timeout(round_)
            + self.precommit_timeout(round_)
            + self.timeout_commit
        )

    def wal_path(self) -> str:
        return os.path.join(self.root_dir, self.wal_file)


@dataclass
class StorageConfig:
    discard_abci_responses: bool = False


@dataclass
class TxIndexConfig:
    indexer: str = "kv"  # "null" | "kv" | "psql"
    psql_conn: str = ""


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    max_open_connections: int = 3
    namespace: str = "cometbft"


@dataclass
class Config:
    """config/config.go:67-120 top-level."""

    base: BaseConfig = dfield(default_factory=BaseConfig)
    rpc: RPCConfig = dfield(default_factory=RPCConfig)
    p2p: P2PConfig = dfield(default_factory=P2PConfig)
    mempool: MempoolConfig = dfield(default_factory=MempoolConfig)
    statesync: StateSyncConfig = dfield(default_factory=StateSyncConfig)
    blocksync: BlockSyncConfig = dfield(default_factory=BlockSyncConfig)
    consensus: ConsensusConfig = dfield(default_factory=ConsensusConfig)
    storage: StorageConfig = dfield(default_factory=StorageConfig)
    tx_index: TxIndexConfig = dfield(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = dfield(default_factory=InstrumentationConfig)

    def set_root(self, root: str) -> "Config":
        self.base.root_dir = root
        self.consensus.root_dir = root
        return self

    def validate_basic(self) -> None:
        if self.base.db_backend not in ("sqlite", "memdb", "mem"):
            raise ValueError(f"unknown db_backend {self.base.db_backend}")
        for name, v in (
            ("timeout_propose", self.consensus.timeout_propose),
            ("timeout_prevote", self.consensus.timeout_prevote),
            ("timeout_precommit", self.consensus.timeout_precommit),
            ("timeout_commit", self.consensus.timeout_commit),
        ):
            if v < 0:
                raise ValueError(f"consensus.{name} can't be negative")
        if self.mempool.size < 0:
            raise ValueError("mempool.size can't be negative")
        if self.mempool.ingress_lanes < 1:
            raise ValueError("mempool.ingress_lanes must be >= 1")
        if self.mempool.ingress_sender_rps < 0:
            raise ValueError("mempool.ingress_sender_rps can't be negative")
        if self.mempool.ingress_queue_max < 1:
            raise ValueError("mempool.ingress_queue_max must be >= 1")


def default_config() -> Config:
    return Config()


def test_config() -> Config:
    """config/config.go TestConfig: tight timeouts for in-process testing."""
    c = Config()
    c.base.proxy_app = "kvstore"
    c.base.db_backend = "memdb"
    c.consensus = ConsensusConfig(
        timeout_propose=0.4,
        timeout_propose_delta=0.002,
        timeout_prevote=0.01,
        timeout_prevote_delta=0.002,
        timeout_precommit=0.01,
        timeout_precommit_delta=0.002,
        timeout_commit=0.01,
        skip_timeout_commit=True,
        peer_gossip_sleep_duration=0.005,
        peer_query_maj23_sleep_duration=0.25,
    )
    c.rpc.laddr = "tcp://127.0.0.1:36657"
    # No p2p listener by default: unit tests wire in-process meshes (or
    # explicitly set an ephemeral tcp://127.0.0.1:0 when they want sockets);
    # a fixed shared port would collide across the multi-node tests.
    c.p2p.laddr = ""
    return c

"""Minimal deterministic protobuf encoder/decoder.

Implements exactly the subset of proto3 wire format the canonical data
structures need (reference wire types: proto/tendermint/**). Proto3 rules
honored: default-valued scalar fields are omitted; fields are emitted in
ascending field-number order; `bytes`/`string`/sub-messages are
length-delimited; sfixed64 for canonical height/round (types/canonical.go).
"""

from __future__ import annotations

import struct

# Wire types
WT_VARINT = 0
WT_FIXED64 = 1
WT_LEN = 2
WT_FIXED32 = 5


_UVARINT_1B = [bytes((v,)) for v in range(0x80)]


def encode_uvarint(n: int) -> bytes:
    if n < 0x80:
        # Single-byte fast path: field tags and small lengths dominate call
        # volume on the hot sign-bytes/encode paths.
        if n < 0:
            raise ValueError("uvarint cannot encode negative")
        return _UVARINT_1B[n]
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_uvarint(buf: bytes, pos: int = 0) -> tuple[int, int]:
    """Max 10 bytes / 64 bits, matching Go's binary.Uvarint and protobuf."""
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        if shift >= 70:
            raise ValueError("varint too long")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            if result >= 1 << 64:
                raise ValueError("varint overflows 64 bits")
            return result, pos
        shift += 7


def encode_varint_signed(n: int) -> bytes:
    """proto `int64`: negative values use 10-byte two's complement varint."""
    if n < 0:
        n += 1 << 64
    return encode_uvarint(n)


def decode_varint_signed(buf: bytes, pos: int = 0) -> tuple[int, int]:
    v, pos = decode_uvarint(buf, pos)
    if v >= 1 << 63:
        v -= 1 << 64
    return v, pos


def encode_zigzag(n: int) -> bytes:
    """proto `sint64`."""
    return encode_uvarint((n << 1) ^ (n >> 63))


def tag(field_num: int, wire_type: int) -> bytes:
    return encode_uvarint((field_num << 3) | wire_type)


def field_varint(field_num: int, value: int, *, emit_default: bool = False) -> bytes:
    if value == 0 and not emit_default:
        return b""
    return tag(field_num, WT_VARINT) + encode_varint_signed(value)


def field_bool(field_num: int, value: bool, *, emit_default: bool = False) -> bytes:
    if not value and not emit_default:
        return b""
    return tag(field_num, WT_VARINT) + (b"\x01" if value else b"\x00")


def field_sfixed64(field_num: int, value: int, *, emit_default: bool = False) -> bytes:
    if value == 0 and not emit_default:
        return b""
    return tag(field_num, WT_FIXED64) + struct.pack("<q", value)


def field_fixed64(field_num: int, value: int, *, emit_default: bool = False) -> bytes:
    if value == 0 and not emit_default:
        return b""
    return tag(field_num, WT_FIXED64) + struct.pack("<Q", value)


def field_bytes(field_num: int, value: bytes, *, emit_default: bool = False) -> bytes:
    if not value and not emit_default:
        return b""
    return tag(field_num, WT_LEN) + encode_uvarint(len(value)) + value


def field_string(field_num: int, value: str, *, emit_default: bool = False) -> bytes:
    return field_bytes(field_num, value.encode("utf-8"), emit_default=emit_default)


def field_message(field_num: int, encoded: bytes | None, *, emit_empty: bool = False) -> bytes:
    """A sub-message field. None ⇒ absent. Empty-encoded messages are still
    emitted when emit_empty (gogoproto non-nullable semantics)."""
    if encoded is None:
        return b""
    if not encoded and not emit_empty:
        return b""
    return tag(field_num, WT_LEN) + encode_uvarint(len(encoded)) + encoded


def encode_bytes_len_prefixed(bz: bytes) -> bytes:
    """uvarint length prefix + raw bytes (reference: types/encoding_helper.go
    cdcEncode-style helpers / libs protoio delimited writing)."""
    return encode_uvarint(len(bz)) + bz


def length_delimited(encoded: bytes) -> bytes:
    """Length-delimited framing of a full message (protoio.MarshalDelimited),
    used for canonical vote/proposal sign bytes (types/vote.go VoteSignBytes)."""
    return encode_uvarint(len(encoded)) + encoded


# ---------------------------------------------------------------------------
# Decoding: a tolerant field walker. Returns {field_num: [raw values]} where a
# raw value is int (varint), bytes (len-delimited) or 8/4-byte packed.


def decode_fields(buf: bytes) -> dict[int, list]:
    fields: dict[int, list] = {}
    pos = 0
    while pos < len(buf):
        key, pos = decode_uvarint(buf, pos)
        fnum, wt = key >> 3, key & 7
        if wt == WT_VARINT:
            val, pos = decode_uvarint(buf, pos)
        elif wt == WT_FIXED64:
            if pos + 8 > len(buf):
                raise ValueError("truncated fixed64")
            val = buf[pos : pos + 8]
            pos += 8
        elif wt == WT_LEN:
            ln, pos = decode_uvarint(buf, pos)
            if pos + ln > len(buf):
                raise ValueError("truncated length-delimited field")
            val = buf[pos : pos + ln]
            pos += ln
        elif wt == WT_FIXED32:
            if pos + 4 > len(buf):
                raise ValueError("truncated fixed32")
            val = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        fields.setdefault(fnum, []).append(val)
    return fields


# Getters raise ValueError on wire-type confusion (a varint where bytes were
# expected, or vice versa): adversarial inputs must fail decode cleanly, not
# surface AttributeError/struct.error from deeper in the stack.


def get_varint(fields: dict, num: int, default: int = 0) -> int:
    vals = fields.get(num)
    if not vals:
        return default
    v = vals[-1]
    if not isinstance(v, int):
        raise ValueError(f"field {num}: expected varint, got length-delimited")
    if v >= 1 << 63:
        v -= 1 << 64
    return v


def get_uvarint(fields: dict, num: int, default: int = 0) -> int:
    vals = fields.get(num)
    if not vals:
        return default
    v = vals[-1]
    if not isinstance(v, int):
        raise ValueError(f"field {num}: expected varint, got length-delimited")
    return v


def get_bool(fields: dict, num: int) -> bool:
    return bool(get_uvarint(fields, num, 0))


def get_bytes(fields: dict, num: int, default: bytes = b"") -> bytes:
    vals = fields.get(num)
    if not vals:
        return default
    v = vals[-1]
    if not isinstance(v, bytes):
        raise ValueError(f"field {num}: expected length-delimited, got varint")
    return v


def get_string(fields: dict, num: int, default: str = "") -> str:
    vals = fields.get(num)
    if not vals:
        return default
    v = vals[-1]
    if not isinstance(v, bytes):
        raise ValueError(f"field {num}: expected length-delimited, got varint")
    try:
        return v.decode("utf-8")
    except UnicodeDecodeError:
        raise ValueError(f"field {num}: invalid utf-8 string")


def get_sfixed64(fields: dict, num: int, default: int = 0) -> int:
    vals = fields.get(num)
    if not vals:
        return default
    v = vals[-1]
    if not isinstance(v, bytes) or len(v) != 8:
        raise ValueError(f"field {num}: expected fixed64")
    return struct.unpack("<q", v)[0]


def get_repeated_bytes(fields: dict, num: int) -> list[bytes]:
    vals = fields.get(num, [])
    if any(not isinstance(v, bytes) for v in vals):
        raise ValueError(f"field {num}: expected length-delimited, got varint")
    return list(vals)


def get_repeated_uvarint(fields: dict, num: int) -> list[int]:
    """Repeated uvarint field, accepting both unpacked (one varint per tag)
    and proto3 packed (one length-delimited run of varints) encodings."""
    out: list[int] = []
    for v in fields.get(num, []):
        if isinstance(v, int):
            out.append(v)
        else:  # packed: bytes holding consecutive varints
            pos = 0
            while pos < len(v):
                val, pos = decode_uvarint(v, pos)
                out.append(val)
    return out

"""Hand-rolled codecs for the small crypto wire messages
(proto/tendermint/crypto/proof.proto)."""

from __future__ import annotations

from cometbft_tpu.wire import proto as wire


def encode_proof(p) -> bytes:
    """tendermint.crypto.Proof {total=1, index=2, leaf_hash=3, aunts=4}."""
    out = wire.field_varint(1, p.total)
    out += wire.field_varint(2, p.index)
    out += wire.field_bytes(3, p.leaf_hash)
    for aunt in p.aunts:
        out += wire.field_bytes(4, aunt, emit_default=True)
    return out


def decode_proof(data: bytes):
    from cometbft_tpu.crypto.merkle.proof import Proof

    f = wire.decode_fields(data)
    return Proof(
        total=wire.get_varint(f, 1),
        index=wire.get_varint(f, 2),
        leaf_hash=wire.get_bytes(f, 3),
        aunts=wire.get_repeated_bytes(f, 4),
    )


def encode_value_op(key: bytes, proof) -> bytes:
    """tendermint.crypto.ValueOp {key=1, proof=2}."""
    return wire.field_bytes(1, key) + wire.field_message(2, encode_proof(proof))


def decode_value_op(data: bytes):
    f = wire.decode_fields(data)
    key = wire.get_bytes(f, 1)
    proof_raw = wire.get_bytes(f, 2)
    return key, decode_proof(proof_raw)

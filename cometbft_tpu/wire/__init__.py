"""Deterministic protobuf wire encoding.

The reference's canonical byte formats (vote sign bytes, header field hashing,
part-set headers, …) are protobuf messages serialized with gogoproto
(reference: proto/tendermint/**, types/canonical.go). We hand-roll a minimal
deterministic encoder so canonical bytes are bit-exact and dependency-free.
"""

"""Shared library layer (reference: libs/)."""

"""Runtime profiling endpoints — the net/http/pprof analog
(reference: node/node.go:379-383 wiring config.RPC.PprofListenAddress,
DESIGN: SURVEY §5.1).

Python-native equivalents of the Go profiles, plus the device tier's:

  /debug/pprof/            index
  /debug/pprof/goroutine   every thread's current stack (threads are the
                           goroutine analog here)
  /debug/pprof/heap        tracemalloc top allocations (started on demand)
  /debug/pprof/profile     wall-clock sampling profile over ?seconds=N
                           (default 5): samples sys._current_frames and
                           aggregates frame stacks, text output
  /debug/jax/memory        per-device HBM stats (jax memory_stats)
  /debug/jax/trace         capture a JAX profiler trace for ?seconds=N into
                           ?dir= (default <home>/jax-trace) — loadable in
                           TensorBoard/Perfetto; the XLA-level view of the
                           verify/merkle kernels
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


def thread_stacks() -> str:
    """All live thread stacks (the goroutine dump analog)."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        name = t.name if t else f"thread-{ident}"
        daemon = " daemon" if (t and t.daemon) else ""
        out.append(f"--- {name} (ident {ident}{daemon}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


def sample_profile(seconds: float = 5.0, hz: int = 100) -> str:
    """Wall-clock sampling profiler: aggregate stack samples across all
    threads for `seconds`, report hottest stacks (pprof 'profile' analog
    without a C agent)."""
    counts: Counter = Counter()
    interval = 1.0 / hz
    deadline = time.monotonic() + seconds
    n = 0
    while time.monotonic() < deadline:
        for frame in sys._current_frames().values():
            stack = []
            f = frame
            while f is not None and len(stack) < 24:
                # co_qualname is 3.11+; co_name keeps 3.10 serving samples.
                qn = getattr(f.f_code, "co_qualname", f.f_code.co_name)
                stack.append(f"{f.f_code.co_filename}:{f.f_lineno}:{qn}")
                f = f.f_back
            counts[tuple(reversed(stack))] += 1
        n += 1
        time.sleep(interval)
    out = [f"# wall-clock samples: {n} over {seconds}s at ~{hz}Hz"]
    for stack, c in counts.most_common(40):
        out.append(f"\n{c} samples:")
        out.extend(f"  {line}" for line in stack[-12:])
    return "\n".join(out)


def heap_profile(top: int = 50) -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return (
            "tracemalloc just started — allocations are tracked from NOW; "
            "re-request this endpoint after exercising the node."
        )
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    total = sum(s.size for s in snap.statistics("filename"))
    out = [f"# tracemalloc: {total / 1e6:.1f} MB tracked"]
    out.extend(str(s) for s in stats)
    return "\n".join(out)


def jax_memory() -> str:
    try:
        import jax

        out = []
        for d in jax.local_devices():
            stats = d.memory_stats() or {}
            out.append(f"{d}: " + ", ".join(f"{k}={v}" for k, v in sorted(stats.items())))
        return "\n".join(out) or "no devices"
    except Exception as e:
        return f"jax unavailable: {e}"


def jax_trace(seconds: float, trace_dir: str) -> str:
    import jax

    jax.profiler.start_trace(trace_dir)
    time.sleep(seconds)
    jax.profiler.stop_trace()
    return f"trace written to {trace_dir} (open with TensorBoard/Perfetto)"


class PprofServer:
    """The /debug HTTP listener (config.rpc.pprof_laddr)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6060, trace_dir: str = "jax-trace"):
        self.host, self.port = host, port
        self.trace_dir = trace_dir
        self._httpd = None

    def start(self) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                u = urlparse(self.path)
                q = parse_qs(u.query)
                try:
                    if u.path in ("/debug/pprof", "/debug/pprof/"):
                        body = (
                            "profiles:\n  goroutine\n  heap\n  profile?seconds=N\n"
                            "device:\n  /debug/jax/memory\n  /debug/jax/trace?seconds=N\n"
                        )
                    elif u.path == "/debug/pprof/goroutine":
                        body = thread_stacks()
                    elif u.path == "/debug/pprof/heap":
                        body = heap_profile()
                    elif u.path == "/debug/pprof/profile":
                        secs = float(q.get("seconds", ["5"])[0])
                        body = sample_profile(min(secs, 60.0))
                    elif u.path == "/debug/jax/memory":
                        body = jax_memory()
                    elif u.path == "/debug/jax/trace":
                        secs = float(q.get("seconds", ["3"])[0])
                        tdir = q.get("dir", [server.trace_dir])[0]
                        body = jax_trace(min(secs, 60.0), tdir)
                    else:
                        self.send_response(404)
                        self.end_headers()
                        return
                except Exception as e:
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
                raw = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        if self.port == 0:
            self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

"""`tomllib` fallback for Python < 3.11.

The stdlib gained tomllib in 3.11; this container runs 3.10.  Everything
this repo reads back is TOML it wrote itself (config/toml.py render_toml,
e2e_generator.render_toml) or hand-written test manifests in the same
subset: comments, ``[section]`` / ``[dotted.section]`` headers, bare keys,
basic strings, ints, floats, booleans, and one-line arrays of those.  This
module parses exactly that subset strictly (unknown syntax raises, same
duplicate-table rules as tomllib) and defers to the real tomllib when it
exists, so behavior upgrades transparently on newer interpreters.
"""

from __future__ import annotations

try:  # pragma: no cover - exercised only on 3.11+
    from tomllib import TOMLDecodeError, load, loads  # noqa: F401
except ModuleNotFoundError:

    class TOMLDecodeError(ValueError):
        pass

    def load(fp) -> dict:
        data = fp.read()
        if isinstance(data, bytes):
            data = data.decode("utf-8")
        else:
            raise TypeError("load() expects a binary file object")
        return loads(data)

    def loads(text: str) -> dict:
        root: dict = {}
        table = root
        declared: set[tuple[str, ...]] = set()
        for ln, raw_line in enumerate(text.splitlines(), 1):
            line = raw_line.strip()
            if not line or line.startswith("#"):
                continue
            if line.startswith("["):
                if not line.endswith("]") or line.startswith("[["):
                    raise TOMLDecodeError(f"line {ln}: unsupported table header")
                parts = tuple(p.strip() for p in line[1:-1].split("."))
                if not all(_is_bare_key(p) for p in parts):
                    raise TOMLDecodeError(f"line {ln}: bad table name {line!r}")
                if parts in declared:
                    raise TOMLDecodeError(
                        f"line {ln}: cannot declare {'.'.join(parts)} twice"
                    )
                declared.add(parts)
                table = root
                for p in parts:
                    nxt = table.setdefault(p, {})
                    if not isinstance(nxt, dict):
                        raise TOMLDecodeError(
                            f"line {ln}: {p!r} is already a value"
                        )
                    table = nxt
                continue
            key, sep, rest = line.partition("=")
            key = key.strip()
            if not sep or not _is_bare_key(key):
                raise TOMLDecodeError(f"line {ln}: expected `key = value`")
            if key in table:
                raise TOMLDecodeError(f"line {ln}: duplicate key {key!r}")
            value, rest = _parse_value(rest.strip(), ln)
            rest = rest.strip()
            if rest and not rest.startswith("#"):
                raise TOMLDecodeError(f"line {ln}: trailing junk {rest!r}")
            table[key] = value
        return root

    def _is_bare_key(k: str) -> bool:
        return bool(k) and all(c.isalnum() or c in "-_" for c in k)

    def _parse_value(s: str, ln: int):
        """One value at the head of `s` -> (value, remainder)."""
        if not s:
            raise TOMLDecodeError(f"line {ln}: missing value")
        if s[0] == '"':
            out, i = [], 1
            while i < len(s):
                c = s[i]
                if c == "\\":
                    if i + 1 >= len(s):
                        break
                    esc = s[i + 1]
                    mapped = {
                        "\\": "\\", '"': '"', "n": "\n", "t": "\t",
                        "r": "\r", "b": "\b", "f": "\f",
                    }.get(esc)
                    if mapped is None:
                        raise TOMLDecodeError(
                            f"line {ln}: unsupported escape \\{esc}"
                        )
                    out.append(mapped)
                    i += 2
                elif c == '"':
                    return "".join(out), s[i + 1:]
                else:
                    out.append(c)
                    i += 1
            raise TOMLDecodeError(f"line {ln}: unterminated string")
        if s[0] == "'":  # literal string: no escapes, ends at the next '
            end = s.find("'", 1)
            if end < 0:
                raise TOMLDecodeError(f"line {ln}: unterminated string")
            return s[1:end], s[end + 1:]
        if s[0] == "[":
            items = []
            rest = s[1:].strip()
            while True:
                if not rest:
                    raise TOMLDecodeError(f"line {ln}: unterminated array")
                if rest[0] == "]":
                    return items, rest[1:]
                v, rest = _parse_value(rest, ln)
                items.append(v)
                rest = rest.strip()
                if rest.startswith(","):
                    rest = rest[1:].strip()
                elif rest and rest[0] != "]":
                    raise TOMLDecodeError(
                        f"line {ln}: expected `,` or `]` in array"
                    )
        # bool / number token: runs to the next delimiter
        i = 0
        while i < len(s) and s[i] not in ",]#":
            i += 1
        token, rest = s[:i].strip(), s[i:]
        if token == "true":
            return True, rest
        if token == "false":
            return False, rest
        try:
            if any(c in token for c in ".eE") and not token.startswith("0x"):
                return float(token), rest
            return int(token, 0), rest
        except ValueError:
            raise TOMLDecodeError(f"line {ln}: bad value {token!r}") from None

"""Pubsub server with the query DSL (reference: libs/pubsub/ +
libs/pubsub/query/query.go).

Query grammar (subset-complete vs the reference's PEG): conditions joined by
AND, each `key OP value` with OP ∈ {=, <, <=, >, >=, CONTAINS, EXISTS};
values are 'single-quoted strings', numbers, or date/time literals
(TIME/DATE prefixes accepted as plain strings). Events carry attributes as
{composite_key: [values]}; numeric comparisons apply when both sides parse
as numbers (query.go:269-347 semantics).
"""

from __future__ import annotations

import queue
import re
import threading
from dataclasses import dataclass, field as dfield
from typing import Any

_COND_RE = re.compile(
    # Quoted strings have NO escape sequences — matching the reference
    # grammar (libs/pubsub/query), where a value is '...' of non-quote
    # characters; a lone backslash-quote would otherwise parse but never
    # unescape, silently mismatching.
    r"\s*([\w.\-/]+)\s*(>=|<=|=|<|>|\bCONTAINS\b|\bEXISTS\b)\s*"
    r"('[^']*'|[\w.\-:+TZ]*)\s*",
    re.IGNORECASE,
)


@dataclass(frozen=True)
class Condition:
    key: str
    op: str
    value: str


class Query:
    """Compiled query; matches against {key: [values]} attribute maps."""

    def __init__(self, s: str):
        self._str = s.strip()
        self.conditions = self._parse(self._str)

    @staticmethod
    def _split_and(s: str) -> list[str]:
        """Split on AND outside single-quoted strings."""
        parts, buf, in_quote, i = [], [], False, 0
        while i < len(s):
            ch = s[i]
            if ch == "'":
                in_quote = not in_quote
                buf.append(ch)
                i += 1
            elif (
                not in_quote
                and s[i : i + 3].upper() == "AND"
                and (i == 0 or s[i - 1].isspace())
                and (i + 3 >= len(s) or s[i + 3].isspace())
            ):
                parts.append("".join(buf))
                buf = []
                i += 3
            else:
                buf.append(ch)
                i += 1
        parts.append("".join(buf))
        return parts

    @classmethod
    def _parse(cls, s: str) -> list[Condition]:
        if not s:
            return []
        conds = []
        for part in cls._split_and(s):
            part = part.strip()
            if not part:
                continue
            m = _COND_RE.fullmatch(part)
            if not m:
                raise ValueError(f"failed to parse query condition: {part!r}")
            key, op, raw = m.group(1), m.group(2).upper(), m.group(3)
            if op == "EXISTS":
                value = ""
            elif raw.startswith("'") and raw.endswith("'") and len(raw) >= 2:
                value = raw[1:-1]
            elif raw == "":
                # a bare `key=` has no value; only the quoted form '' means
                # the empty string (the reference grammar requires a value)
                raise ValueError(f"failed to parse query condition: {part!r}")
            else:
                value = raw
            conds.append(Condition(key, op, value))
        return conds

    def matches(self, attrs: dict[str, list]) -> bool:
        for cond in self.conditions:
            values = attrs.get(cond.key)
            if values is None:
                return False
            if cond.op == "EXISTS":
                continue
            if not any(_match_one(v, cond.op, cond.value) for v in values):
                return False
        return True

    def __str__(self) -> str:
        return self._str

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and self._str == str(other)

    def __hash__(self) -> int:
        return hash(self._str)


def _match_one(value: str, op: str, target: str) -> bool:
    value = str(value)
    if op == "=":
        return value == target
    if op == "CONTAINS":
        return target in value
    try:
        a, b = float(value), float(target)
    except ValueError:
        return False
    return {"<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b}[op]


class Message:
    __slots__ = ("data", "events")

    def __init__(self, data: Any, events: dict[str, list]):
        self.data = data
        self.events = events


class Subscription:
    """A buffered out-channel; canceled flag set on unsubscribe
    (libs/pubsub/subscription.go)."""

    def __init__(self, capacity: int = 100):
        self.out: queue.Queue[Message] = queue.Queue(maxsize=capacity)
        self.canceled = threading.Event()
        self.cancel_reason: str | None = None

    def cancel(self, reason: str) -> None:
        self.cancel_reason = reason
        self.canceled.set()


class Server:
    """libs/pubsub/pubsub.go Server: subscribe/publish with per-subscriber
    queries. Synchronous publish (the reference's PublishWithEvents blocks on
    full subscriber buffers; we drop-on-full to avoid stalling consensus —
    subscribers that fall behind are canceled, matching the bus's
    non-blocking wrapper behavior in the reference node)."""

    def __init__(self):
        self._mtx = threading.RLock()
        # subscriber -> {query -> Subscription}
        self._subs: dict[str, dict[Query, Subscription]] = {}
        self._running = False

    def start(self) -> None:
        self._running = True

    def stop(self) -> None:
        with self._mtx:
            for qs in self._subs.values():
                for sub in qs.values():
                    sub.cancel("server stopped")
            self._subs.clear()
            self._running = False

    def num_clients(self) -> int:
        with self._mtx:
            return len(self._subs)

    def num_client_subscriptions(self, subscriber: str) -> int:
        with self._mtx:
            return len(self._subs.get(subscriber, {}))

    def subscribe(self, subscriber: str, query: Query, out_capacity: int = 100) -> Subscription:
        with self._mtx:
            qs = self._subs.setdefault(subscriber, {})
            if query in qs:
                raise ValueError("already subscribed")
            sub = Subscription(out_capacity)
            qs[query] = sub
            return sub

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        with self._mtx:
            qs = self._subs.get(subscriber)
            if not qs or query not in qs:
                raise KeyError("subscription not found")
            qs.pop(query).cancel("unsubscribed")
            if not qs:
                del self._subs[subscriber]

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._mtx:
            qs = self._subs.pop(subscriber, None)
            if qs is None:
                raise KeyError("subscription not found")
            for sub in qs.values():
                sub.cancel("unsubscribed")

    def publish(self, data: Any) -> None:
        self.publish_with_events(data, {})

    def publish_with_events(self, data: Any, events: dict[str, list]) -> None:
        msg = Message(data, events)
        with self._mtx:
            targets = [
                (name, q, sub)
                for name, qs in self._subs.items()
                for q, sub in qs.items()
                if q.matches(events)
            ]
        for _, _, sub in targets:
            try:
                sub.out.put_nowait(msg)
            except queue.Full:
                sub.cancel("client is not pulling messages fast enough")

"""Thread-safe bit array for vote/part presence tracking
(reference: libs/bits/bit_array.go, gossiped between peers)."""

from __future__ import annotations

import random
import threading

from cometbft_tpu.wire import proto as wire


class BitArray:
    def __init__(self, bits: int = 0):
        self._bits = bits
        self._elems = [0] * ((bits + 63) // 64)
        self._mtx = threading.Lock()

    # -- core ---------------------------------------------------------------

    @property
    def size(self) -> int:
        return self._bits

    def get_index(self, i: int) -> bool:
        with self._mtx:
            return self._get(i)

    def _get(self, i: int) -> bool:
        if i >= self._bits or i < 0:
            return False
        return bool(self._elems[i // 64] >> (i % 64) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        with self._mtx:
            if i >= self._bits or i < 0:
                return False
            if v:
                self._elems[i // 64] |= 1 << (i % 64)
            else:
                self._elems[i // 64] &= ~(1 << (i % 64))
            return True

    def copy(self) -> "BitArray":
        with self._mtx:
            c = BitArray(self._bits)
            c._elems = list(self._elems)
            return c

    def or_with(self, other: "BitArray") -> "BitArray":
        """Union sized to the larger operand (bit_array.go Or)."""
        if other is None:
            return self.copy()
        c = BitArray(max(self._bits, other._bits))
        with self._mtx:
            a = list(self._elems)
        with other._mtx:
            b = list(other._elems)
        for i in range(len(c._elems)):
            v = 0
            if i < len(a):
                v |= a[i]
            if i < len(b):
                v |= b[i]
            c._elems[i] = v
        return c

    def and_with(self, other: "BitArray") -> "BitArray":
        """Intersection sized to the smaller operand (bit_array.go And)."""
        if other is None:
            return BitArray(0)
        c = BitArray(min(self._bits, other._bits))
        with self._mtx:
            a = list(self._elems)
        with other._mtx:
            b = list(other._elems)
        for i in range(len(c._elems)):
            c._elems[i] = a[i] & b[i]
        c._trim()
        return c

    def not_(self) -> "BitArray":
        c = BitArray(self._bits)
        with self._mtx:
            for i in range(len(self._elems)):
                c._elems[i] = ~self._elems[i] & ((1 << 64) - 1)
        c._trim()
        return c

    def sub(self, other: "BitArray") -> "BitArray":
        """self AND NOT other, sized to self (bit_array.go Sub)."""
        if other is None:
            return self.copy()
        c = self.copy()
        with other._mtx:
            b = list(other._elems)
        for i in range(min(len(c._elems), len(b))):
            c._elems[i] &= ~b[i] & ((1 << 64) - 1)
        c._trim()
        return c

    def _trim(self) -> None:
        """Mask bits beyond size in the last word."""
        if self._bits % 64 != 0 and self._elems:
            self._elems[-1] &= (1 << (self._bits % 64)) - 1

    def is_empty(self) -> bool:
        with self._mtx:
            return all(e == 0 for e in self._elems)

    def is_full(self) -> bool:
        with self._mtx:
            if self._bits == 0:
                return True
            for i in range(len(self._elems) - 1):
                if self._elems[i] != (1 << 64) - 1:
                    return False
            last_bits = self._bits % 64 or 64
            return self._elems[-1] == (1 << last_bits) - 1

    def pick_random(self) -> tuple[int, bool]:
        """A uniformly random true bit (bit_array.go PickRandom)."""
        with self._mtx:
            true_indices = [
                i for i in range(self._bits) if self._get(i)
            ]
        if not true_indices:
            return 0, False
        return random.choice(true_indices), True

    def num_true_bits(self) -> int:
        with self._mtx:
            return sum(bin(e).count("1") for e in self._elems)

    def update(self, other: "BitArray") -> None:
        """Copy other's contents into self (sizes must match semantics of Go:
        copies min overlap)."""
        if other is None:
            return
        with other._mtx:
            b = list(other._elems)
        with self._mtx:
            for i in range(min(len(self._elems), len(b))):
                self._elems[i] = b[i]
            self._trim()

    def __eq__(self, other) -> bool:
        if not isinstance(other, BitArray):
            return NotImplemented
        return self._bits == other._bits and self._elems == other._elems

    def __repr__(self) -> str:
        with self._mtx:
            s = "".join("x" if self._get(i) else "_" for i in range(self._bits))
        return f"BA{{{self._bits}:{s}}}"

    # -- wire (libs/bits proto) ---------------------------------------------

    def encode(self) -> bytes:
        out = wire.field_varint(1, self._bits)
        # repeated uint64 packed
        if any(self._elems):
            packed = b"".join(
                wire.encode_uvarint(e) for e in self._elems
            )
            out += wire.tag(2, wire.WT_LEN) + wire.encode_uvarint(len(packed)) + packed
        return out

    # Decode bound: largest legitimate wire bit array is a part-set presence
    # map (max block parts) or a vote map (max validators) — cap well above
    # both so a malicious varint can't force a giant allocation.
    MAX_DECODE_BITS = 1 << 24

    @classmethod
    def decode(cls, data: bytes) -> "BitArray":
        f = wire.decode_fields(data)
        bits = wire.get_varint(f, 1)
        if bits < 0 or bits > cls.MAX_DECODE_BITS:
            raise ValueError(f"bit array size {bits} out of bounds")
        ba = cls(bits)
        raw = wire.get_bytes(f, 2)
        elems = []
        pos = 0
        while pos < len(raw):
            v, pos = wire.decode_uvarint(raw, pos)
            elems.append(v)
        for i in range(min(len(elems), len(ba._elems))):
            ba._elems[i] = elems[i]
        ba._trim()
        return ba

"""Structured key-value logging (reference: libs/log — TMLogger/
NewTMLogger/NewFilter).

Levels debug < info < error; a logger carries bound context keys (With),
renders either the reference's terminal format
(`I[2006-01-02|15:04:05.000] message            module=consensus h=5`)
or JSON lines, and supports per-module level filtering
(log.AllowLevelWith 'module' overrides, log.go NewFilter)."""

from __future__ import annotations

import json
import sys
import threading
import time

DEBUG, INFO, ERROR, NONE = 0, 1, 2, 3
_LEVEL_NAMES = {DEBUG: "D", INFO: "I", ERROR: "E"}
_NAME_TO_LEVEL = {"debug": DEBUG, "info": INFO, "error": ERROR, "none": NONE}


def parse_level(name: str) -> int:
    try:
        return _NAME_TO_LEVEL[name.lower()]
    except KeyError:
        raise ValueError(f"unknown log level {name!r}") from None


class Logger:
    """libs/log.Logger with bound context (With)."""

    def __init__(self, sink, context: tuple = ()):
        self._sink = sink
        self._context = context

    def with_(self, **kv) -> "Logger":
        return Logger(self._sink, self._context + tuple(kv.items()))

    def debug(self, msg: str, **kv) -> None:
        self._sink.log(DEBUG, msg, self._context + tuple(kv.items()))

    def info(self, msg: str, **kv) -> None:
        self._sink.log(INFO, msg, self._context + tuple(kv.items()))

    def error(self, msg: str, **kv) -> None:
        self._sink.log(ERROR, msg, self._context + tuple(kv.items()))


class _Sink:
    """Shared formatter/filter/output (one lock per destination)."""

    def __init__(self, stream=None, fmt: str = "plain", level: int = INFO,
                 module_levels: dict | None = None):
        self.stream = stream or sys.stderr
        self.fmt = fmt
        self.level = level
        self.module_levels = {k: parse_level(v) for k, v in (module_levels or {}).items()}
        self._mtx = threading.Lock()

    def _allowed(self, level: int, kv: tuple) -> bool:
        module = next((v for k, v in kv if k == "module"), None)
        threshold = self.module_levels.get(module, self.level)
        return level >= threshold

    def log(self, level: int, msg: str, kv: tuple) -> None:
        if not self._allowed(level, kv):
            return
        now = time.time()
        if self.fmt == "json":
            rec = {"level": _LEVEL_NAMES.get(level, "?"), "ts": now, "msg": msg}
            rec.update({str(k): _jsonable(v) for k, v in kv})
            line = json.dumps(rec)
        else:
            ts = time.strftime("%Y-%m-%d|%H:%M:%S", time.localtime(now))
            ms = int((now % 1) * 1000)
            pairs = " ".join(f"{k}={_render(v)}" for k, v in kv)
            line = f"{_LEVEL_NAMES.get(level, '?')}[{ts}.{ms:03d}] {msg:<44}{(' ' + pairs) if pairs else ''}"
        with self._mtx:
            print(line, file=self.stream, flush=True)


def _render(v) -> str:
    if isinstance(v, bytes):
        return v.hex().upper()[:16]
    return str(v)


def _jsonable(v):
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def new_logger(
    stream=None,
    fmt: str = "plain",
    level: str = "info",
    module_levels: dict | None = None,
) -> Logger:
    """NewTMLogger + NewFilter in one: `module_levels` maps a module name
    (the `module=...` context key) to its own minimum level."""
    return Logger(_Sink(stream, fmt, parse_level(level), module_levels))


class NopLogger(Logger):
    def __init__(self):
        super().__init__(None)

    def with_(self, **kv):
        return self

    def debug(self, *a, **k):
        pass

    def info(self, *a, **k):
        pass

    def error(self, *a, **k):
        pass

"""Flow rate monitoring + limiting (reference: libs/flowrate/flowrate.go
Monitor — mzimmerman/flowrate as vendored by the reference).

Monitor tracks a byte stream's totals and rates (average, EMA instantaneous,
peak) and enforces a target rate by sleeping the caller — MConnection holds
one per direction for its send/recv throttling and reports Status() through
the p2p layer."""

from __future__ import annotations

import threading
import time


class Monitor:
    """flowrate.Monitor: rate accounting + blocking limiter."""

    def __init__(self, sample_period: float = 0.1):
        self.sample_period = max(sample_period, 0.01)
        self._mtx = threading.Lock()
        self.start = time.monotonic()
        self.bytes_total = 0
        self.samples = 0
        self.inst_rate = 0.0  # EMA over sample periods
        self.peak_rate = 0.0
        self._window_bytes = 0
        self._window_start = self.start
        # limiter state
        self._allowance = 0.0
        self._last_fill = self.start

    def update(self, n: int) -> int:
        """Record n transferred bytes (flowrate.go Update)."""
        now = time.monotonic()
        with self._mtx:
            self.bytes_total += n
            self._window_bytes += n
            elapsed = now - self._window_start
            if elapsed >= self.sample_period:
                rate = self._window_bytes / elapsed
                # EMA with the reference's ~0.25 new-sample weight.
                self.inst_rate = (
                    rate if self.samples == 0 else 0.75 * self.inst_rate + 0.25 * rate
                )
                self.peak_rate = max(self.peak_rate, rate)
                self.samples += 1
                self._window_bytes = 0
                self._window_start = now
        return n

    def limit(self, want: int, rate: int, block: bool = True) -> int:
        """Token-bucket admission for `want` bytes at `rate` B/s: returns the
        admitted byte count, sleeping when block=True (flowrate.go Limit)."""
        if rate <= 0:
            return want
        with self._mtx:
            now = time.monotonic()
            self._allowance = min(
                float(rate), self._allowance + (now - self._last_fill) * rate
            )
            self._last_fill = now
            self._allowance -= want
            deficit = -self._allowance
        if deficit > 0:
            if not block:
                with self._mtx:
                    self._allowance += want  # undo: caller sends nothing
                return 0
            time.sleep(deficit / rate)
            with self._mtx:
                self._allowance = min(self._allowance, 0.0)
        return want

    def status(self) -> dict:
        """flowrate.Status: totals + rates for /net_info reporting."""
        with self._mtx:
            duration = time.monotonic() - self.start
            return {
                "duration": duration,
                "bytes": self.bytes_total,
                "avg_rate": self.bytes_total / duration if duration > 0 else 0.0,
                "inst_rate": self.inst_rate,
                "peak_rate": self.peak_rate,
            }

"""Deadlock & stall detection (SURVEY §5.2 — the single-process analog of
the reference's `go test -race` + go-deadlock usage).

Three tools:

  TrackedLock   an opt-in threading.Lock wrapper that records the wait-for
                graph (thread -> lock it waits on; lock -> owning thread).
                `detect_cycles()` reports actual deadlock cycles with the
                stacks of the involved threads. Zero overhead when unused;
                tests and CMTPU_DEBUG_LOCKS=1 runs opt in.
  Watchdog      progress monitor: samples a counter (e.g. consensus height)
                and fires a callback with a full thread-stack dump when it
                stops advancing for `stall_after` seconds — the "node is
                wedged, tell me where" tool.
  dump_stacks   one-shot all-thread stack dump (also exposed via the pprof
                endpoint's /debug/pprof/goroutine).
"""

from __future__ import annotations

import threading
import time

from cometbft_tpu.libs.pprof import thread_stacks as dump_stacks

_registry_mtx = threading.Lock()
_all_locks: list = []


class TrackedLock:
    """A lock participating in deadlock detection."""

    def __init__(self, name: str = ""):
        self._lock = threading.Lock()
        self.name = name or f"lock-{id(self):x}"
        self.owner: int | None = None
        self.waiters: dict[int, float] = {}
        self._meta = threading.Lock()
        with _registry_mtx:
            _all_locks.append(self)

    def acquire(self, timeout: float = -1) -> bool:
        me = threading.get_ident()
        with self._meta:
            self.waiters[me] = time.monotonic()
        try:
            ok = self._lock.acquire(timeout=timeout)
        finally:
            with self._meta:
                self.waiters.pop(me, None)
        if ok:
            self.owner = me
        return ok

    def release(self) -> None:
        self.owner = None
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *a):
        self.release()


def detect_cycles() -> list[list[str]]:
    """Find wait-for cycles over all TrackedLocks: thread T waits on lock L
    whose owner waits on a lock owned by ... T. Returns one
    ["thread A -> lockX (held by B)", ...] chain per cycle found."""
    with _registry_mtx:
        locks = list(_all_locks)
    waits: dict[int, "TrackedLock"] = {}
    owners: dict[int, list["TrackedLock"]] = {}
    for lk in locks:
        with lk._meta:
            for tid in lk.waiters:
                waits[tid] = lk
        if lk.owner is not None:
            owners.setdefault(lk.owner, []).append(lk)
    cycles = []
    for start_tid in list(waits):
        chain, tid, seen = [], start_tid, set()
        while tid in waits:
            if tid in seen:
                if tid == start_tid:
                    cycles.append(chain)
                break
            seen.add(tid)
            lk = waits[tid]
            chain.append(f"thread {tid} -> {lk.name} (held by {lk.owner})")
            if lk.owner is None:
                break
            tid = lk.owner
    return cycles


def stuck_waiters(threshold: float = 10.0) -> list[str]:
    """Threads blocked on a TrackedLock for longer than `threshold`."""
    now = time.monotonic()
    out = []
    with _registry_mtx:
        locks = list(_all_locks)
    for lk in locks:
        with lk._meta:
            for tid, since in lk.waiters.items():
                if now - since > threshold:
                    out.append(
                        f"thread {tid} stuck {now - since:.1f}s on {lk.name} "
                        f"(held by {lk.owner})"
                    )
    return out


class Watchdog:
    """Fires when a progress counter stops moving (consensus height, pool
    height, ...) — dumps every thread's stack so the wedge is attributable."""

    def __init__(self, progress_fn, stall_after: float = 60.0, interval: float = 5.0,
                 on_stall=None, logger=None):
        self.progress_fn = progress_fn
        self.stall_after = stall_after
        self.interval = interval
        self.on_stall = on_stall
        self.logger = logger
        self._last_value = None
        self._last_change = time.monotonic()
        self._running = False
        self.stalls = 0

    def start(self) -> None:
        self._running = True
        threading.Thread(target=self._run, daemon=True, name="watchdog").start()

    def stop(self) -> None:
        self._running = False

    def _run(self) -> None:
        while self._running:
            time.sleep(self.interval)
            try:
                v = self.progress_fn()
            except Exception:
                continue
            now = time.monotonic()
            if v != self._last_value:
                self._last_value = v
                self._last_change = now
                continue
            if now - self._last_change >= self.stall_after:
                self._last_change = now  # rate-limit repeat reports
                self.stalls += 1
                report = (
                    f"watchdog: no progress for {self.stall_after}s "
                    f"(value {v!r})\n"
                    + "\n".join(stuck_waiters(self.stall_after / 2))
                    + "\n"
                    + dump_stacks()
                )
                if self.logger:
                    self.logger.error("node stalled", module="watchdog", value=v)
                if self.on_stall:
                    self.on_stall(report)

"""Rotating file group (reference: libs/autofile/group.go + autofile.go).

A Group owns a "head" file at `path` plus rotated chunks `path.000`,
`path.001`, ... Writes land in the head; when the head passes
head_size_limit it is renamed to the next index (RotateFile, group.go:220).
When the group's total size passes total_size_limit the oldest chunks are
deleted (checkTotalSizeLimit, group.go:320). GroupReader streams the chunks
oldest-first then the head — the consensus WAL's multi-file catchup scan
rides on it."""

from __future__ import annotations

import os
import re
import threading


class Group:
    """libs/autofile/group.go Group."""

    def __init__(
        self,
        head_path: str,
        head_size_limit: int = 10 * 1024 * 1024,
        total_size_limit: int = 1024 * 1024 * 1024,
    ):
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)
        self.head_path = head_path
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        self._mtx = threading.Lock()
        self._head = open(head_path, "ab")
        # Orderly-shutdown intent. Late writers racing close() are benign
        # no-ops ONLY when close() was actually called; any other closed-file
        # state (teardown-order bug, double stop) must keep crashing loudly
        # instead of silently dropping WAL frames or faking durability.
        self._closed = False

    # -- index bookkeeping -----------------------------------------------------

    def _chunk_re(self):
        return re.compile(re.escape(os.path.basename(self.head_path)) + r"\.(\d{3,})$")

    def chunk_indices(self) -> list[int]:
        d = os.path.dirname(self.head_path) or "."
        rx = self._chunk_re()
        out = []
        for name in os.listdir(d):
            m = rx.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def min_index(self) -> int:
        idx = self.chunk_indices()
        return idx[0] if idx else 0

    def max_index(self) -> int:
        idx = self.chunk_indices()
        return (idx[-1] + 1) if idx else 0  # head is one past the last chunk

    def _chunk_path(self, i: int) -> str:
        return f"{self.head_path}.{i:03d}"

    # -- writing ---------------------------------------------------------------

    def write(self, data: bytes) -> None:
        with self._mtx:
            if self._closed:
                return  # orderly shutdown: late writers are no-ops
            self._head.write(data)

    def flush_and_sync(self) -> None:
        with self._mtx:
            if self._closed:
                return  # orderly shutdown
            self._head.flush()
            os.fsync(self._head.fileno())

    def maybe_rotate(self) -> bool:
        """group.go checkHeadSizeLimit: rotate when the head is over limit.
        Called between frames so rotation never splits a record."""
        with self._mtx:
            if self.head_size_limit <= 0 or self._closed:
                return False
            if self._head.tell() < self.head_size_limit:
                return False
            self._head.flush()
            os.fsync(self._head.fileno())
            self._head.close()
            nxt = self.max_index()
            os.replace(self.head_path, self._chunk_path(nxt))
            self._head = open(self.head_path, "ab")
        self._check_total_size()
        return True

    def _check_total_size(self) -> None:
        if self.total_size_limit <= 0:
            return
        with self._mtx:
            sizes = []
            for i in self.chunk_indices():
                p = self._chunk_path(i)
                try:
                    sizes.append((i, os.path.getsize(p)))
                except OSError:
                    continue
            total = sum(sz for _, sz in sizes)
            try:
                total += os.path.getsize(self.head_path)
            except OSError:
                pass
            for i, sz in sizes:
                if total <= self.total_size_limit:
                    break
                try:
                    os.unlink(self._chunk_path(i))
                except OSError:
                    pass
                total -= sz

    def close(self) -> None:
        with self._mtx:
            self._closed = True
            try:
                self._head.flush()
                os.fsync(self._head.fileno())
            except (OSError, ValueError):
                pass
            self._head.close()

    def reopen(self) -> None:
        with self._mtx:
            try:
                self._head.close()
            except OSError:
                pass
            self._head = open(self.head_path, "ab")
            self._closed = False

    def head_size(self) -> int:
        with self._mtx:
            if self._closed:
                return 0
            return self._head.tell()

    # -- reading ---------------------------------------------------------------

    def paths_oldest_first(self) -> list[str]:
        return [self._chunk_path(i) for i in self.chunk_indices()] + [self.head_path]

    def reader(self):
        """GroupReader (group.go:480): a single byte stream across chunks."""
        return _GroupReader(self.paths_oldest_first())


class _GroupReader:
    def __init__(self, paths: list[str]):
        self._paths = paths
        self._i = 0
        self._f = None

    def read(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            if self._f is None:
                if self._i >= len(self._paths):
                    return out
                try:
                    self._f = open(self._paths[self._i], "rb")
                except FileNotFoundError:
                    self._i += 1
                    continue
            chunk = self._f.read(n - len(out))
            if not chunk:
                self._f.close()
                self._f = None
                self._i += 1
                continue
            out += chunk
        return out

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

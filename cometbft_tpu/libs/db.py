"""Key-value database abstraction (reference: cometbft-db via config/db.go:29).

Two backends: MemDB (sorted in-memory dict — the test seam from
consensus/common_test.go's dbm.NewMemDB) and SQLiteDB (stdlib sqlite3, the
persistent default replacing goleveldb; same ordered-iteration contract).
"""

from __future__ import annotations

import bisect
import os
import sqlite3
import threading


class DB:
    """Ordered KV store: Get/Set/Delete/Iterator/Batch (cometbft-db API)."""

    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def delete_sync(self, key: bytes) -> None:
        self.delete(key)

    def compact(self) -> None:
        """Reclaim space (cmd compact-db; goleveldb CompactRange in the
        reference — VACUUM for the sqlite backend, no-op in memory)."""

    def iterator(self, start: bytes | None = None, end: bytes | None = None):
        """Ascending iterator over [start, end) as (key, value) pairs."""
        raise NotImplementedError

    def reverse_iterator(self, start: bytes | None = None, end: bytes | None = None):
        raise NotImplementedError

    def new_batch(self) -> "Batch":
        return Batch(self)

    def close(self) -> None:
        pass

    def stats(self) -> dict:
        return {}


class Batch:
    """Write batch with atomic-ish apply (cometbft-db Batch)."""

    def __init__(self, db: DB):
        self._db = db
        self._ops: list[tuple[str, bytes, bytes | None]] = []

    def set(self, key: bytes, value: bytes) -> None:
        self._ops.append(("set", bytes(key), bytes(value)))

    def delete(self, key: bytes) -> None:
        self._ops.append(("del", bytes(key), None))

    def write(self) -> None:
        for op, k, v in self._ops:
            if op == "set":
                self._db.set(k, v)
            else:
                self._db.delete(k)
        self._ops.clear()

    def write_sync(self) -> None:
        self.write()

    def close(self) -> None:
        self._ops.clear()


class MemDB(DB):
    """Sorted in-memory store (cometbft-db memdb)."""

    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._keys: list[bytes] = []
        self._mtx = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            return self._data.get(bytes(key))

    def set(self, key: bytes, value: bytes) -> None:
        key, value = bytes(key), bytes(value)
        with self._mtx:
            if key not in self._data:
                bisect.insort(self._keys, key)
            self._data[key] = value

    def delete(self, key: bytes) -> None:
        key = bytes(key)
        with self._mtx:
            if key in self._data:
                del self._data[key]
                i = bisect.bisect_left(self._keys, key)
                del self._keys[i]

    def _range(self, start, end):
        lo = 0 if start is None else bisect.bisect_left(self._keys, bytes(start))
        hi = len(self._keys) if end is None else bisect.bisect_left(self._keys, bytes(end))
        return lo, hi

    def iterator(self, start=None, end=None):
        with self._mtx:
            lo, hi = self._range(start, end)
            items = [(k, self._data[k]) for k in self._keys[lo:hi]]
        yield from items

    def reverse_iterator(self, start=None, end=None):
        with self._mtx:
            lo, hi = self._range(start, end)
            items = [(k, self._data[k]) for k in reversed(self._keys[lo:hi])]
        yield from items


class SQLiteDB(DB):
    """Persistent KV on stdlib sqlite3 (WAL mode). Plays the role of the
    reference's goleveldb default backend (config/toml.go:92-110)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
        )
        self._conn.commit()
        self._mtx = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (bytes(key),)
            ).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                (bytes(key), bytes(value)),
            )
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._mtx:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (bytes(key),))
            self._conn.commit()

    def compact(self) -> None:
        with self._mtx:
            self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
            self._conn.execute("VACUUM")
            self._conn.commit()

    def iterator(self, start=None, end=None):
        q, args = "SELECT k, v FROM kv", []
        clauses = []
        if start is not None:
            clauses.append("k >= ?")
            args.append(bytes(start))
        if end is not None:
            clauses.append("k < ?")
            args.append(bytes(end))
        if clauses:
            q += " WHERE " + " AND ".join(clauses)
        q += " ORDER BY k ASC"
        with self._mtx:
            rows = self._conn.execute(q, args).fetchall()
        for k, v in rows:
            yield bytes(k), bytes(v)

    def reverse_iterator(self, start=None, end=None):
        rows = list(self.iterator(start, end))
        yield from reversed(rows)

    def new_batch(self) -> "Batch":
        return _SQLiteBatch(self)

    def close(self) -> None:
        with self._mtx:
            self._conn.close()


class _SQLiteBatch(Batch):
    def write(self) -> None:
        db = self._db
        with db._mtx:
            for op, k, v in self._ops:
                if op == "set":
                    db._conn.execute(
                        "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (k, v)
                    )
                else:
                    db._conn.execute("DELETE FROM kv WHERE k = ?", (k,))
            db._conn.commit()
        self._ops.clear()


def new_db(name: str, backend: str, db_dir: str) -> DB:
    """config/db.go DefaultDBProvider analog."""
    if backend in ("memdb", "mem"):
        return MemDB()
    return SQLiteDB(os.path.join(db_dir, f"{name}.sqlite"))

"""Deterministic kill-points for crash-recovery testing
(reference: libs/fail/fail.go:9-39).

Every `fail()` call site hit increments a process-wide counter; when the
counter reaches the integer in $FAIL_TEST_INDEX the process hard-exits
(os._exit — no cleanup, no flushing), simulating a crash at exactly that
point between the non-atomic persistence steps of finalizeCommit/ApplyBlock
(call sites mirror consensus/state.go:787,1656,1670,1693,1712,1720 and
state/execution.go:212,219,255,263).
"""

from __future__ import annotations

import os

_call_index = -1


def fail() -> None:
    global _call_index
    env = os.environ.get("FAIL_TEST_INDEX")
    if env is None:
        return
    _call_index += 1
    if _call_index == int(env):
        os._exit(99)

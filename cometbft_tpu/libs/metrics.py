"""Prometheus metrics primitives + exposition server
(reference: libs/metrics + the go-kit/prometheus providers each subsystem's
metrics.go instantiates; exposition served like node/node.go:385-387).

Self-contained (no prometheus_client in the image): Counter/Gauge/Histogram
with label support, a GaugeFunc for scrape-time sampling of live objects
(mempool size, peer count — cheaper than write-path instrumentation), and a
text-format (version 0.0.4) HTTP endpoint.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() else repr(f)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str = "", label_names: tuple = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._children: dict[tuple, object] = {}
        self._mtx = threading.Lock()

    def labels(self, **kv):
        key = tuple(str(kv[n]) for n in self.label_names)
        with self._mtx:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _default(self):
        return self.labels() if not self.label_names else None

    def _samples(self):
        """Yield (suffix, labels-dict, value) triples."""
        with self._mtx:
            items = list(self._children.items())
        for key, child in items:
            labels = dict(zip(self.label_names, key))
            yield from child._child_samples(labels)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        for suffix, labels, value in self._samples():
            lines.append(f"{self.name}{suffix}{_fmt_labels(labels)} {_fmt_value(value)}")
        return "\n".join(lines)


class _CounterChild:
    def __init__(self):
        self._v = 0.0
        self._mtx = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._mtx:
            self._v += n

    def _child_samples(self, labels):
        yield "", labels, self._v


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)


class _GaugeChild:
    def __init__(self):
        self._v = 0.0
        self._mtx = threading.Lock()

    def set(self, v: float) -> None:
        with self._mtx:
            self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._mtx:
            self._v += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def _child_samples(self, labels):
        yield "", labels, self._v


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float) -> None:
        self.labels().set(v)

    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self.labels().dec(n)


class GaugeFunc(_Metric):
    """Scrape-time gauge: samples a callable at render time."""

    kind = "gauge"

    def __init__(self, name: str, help_text: str, fn):
        super().__init__(name, help_text)
        self._fn = fn

    def _samples(self):
        try:
            v = float(self._fn())
        except Exception:
            return
        yield "", {}, v


DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class _HistogramChild:
    def __init__(self, buckets):
        self.buckets = buckets
        self._counts = [0] * (len(buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._mtx = threading.Lock()

    def observe(self, v: float) -> None:
        with self._mtx:
            self._sum += v
            self._n += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def _child_samples(self, labels):
        with self._mtx:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[i]
                yield "_bucket", {**labels, "le": _fmt_value(b)}, cum
            cum += self._counts[-1]
            yield "_bucket", {**labels, "le": "+Inf"}, cum
            yield "_sum", labels, self._sum
            yield "_count", labels, self._n


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text="", label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(buckets)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        self.labels().observe(v)


class Registry:
    def __init__(self, namespace: str = ""):
        self.namespace = namespace
        self._metrics: list[_Metric] = []
        self._mtx = threading.Lock()

    def _full_name(self, subsystem: str, name: str) -> str:
        parts = [p for p in (self.namespace, subsystem, name) if p]
        return "_".join(parts)

    def counter(self, subsystem: str, name: str, help_text: str = "", labels=()) -> Counter:
        return self._add(Counter(self._full_name(subsystem, name), help_text, labels))

    def gauge(self, subsystem: str, name: str, help_text: str = "", labels=()) -> Gauge:
        return self._add(Gauge(self._full_name(subsystem, name), help_text, labels))

    def gauge_func(self, subsystem: str, name: str, help_text: str, fn) -> GaugeFunc:
        return self._add(GaugeFunc(self._full_name(subsystem, name), help_text, fn))

    def histogram(
        self, subsystem: str, name: str, help_text: str = "", labels=(),
        buckets=DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._add(
            Histogram(self._full_name(subsystem, name), help_text, labels, buckets)
        )

    def _add(self, m: _Metric):
        with self._mtx:
            self._metrics.append(m)
        return m

    def render(self) -> str:
        with self._mtx:
            metrics = list(self._metrics)
        return "\n".join(m.render() for m in metrics) + "\n"


class MetricsServer:
    """The /metrics endpoint (node/node.go:385 startPrometheusServer)."""

    def __init__(self, registry: Registry, host: str = "127.0.0.1", port: int = 26660):
        self.registry = registry
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None

    def start(self) -> None:
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                if self.path.split("?")[0] != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr lines
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        if self.port == 0:
            self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

"""Inspect: a read-only RPC server over a STOPPED node's data directory
(reference: inspect/inspect.go:29 + rpc/core routes subset).

Used for crash forensics: no p2p, no consensus, no app — just the stores
and indexers behind the data RPC endpoints."""

from __future__ import annotations

from cometbft_tpu.config import Config
from cometbft_tpu.libs.db import new_db
from cometbft_tpu.rpc.core import Environment, routes
from cometbft_tpu.rpc.jsonrpc.server import JSONRPCServer
from cometbft_tpu.state import StateStore
from cometbft_tpu.state.txindex import KVBlockIndexer, KVTxIndexer, NullTxIndexer
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.events import EventBus
from cometbft_tpu.types.genesis import GenesisDoc

# Routes that only touch storage/indexers (inspect/rpc/rpc.go Routes).
INSPECT_ROUTES = (
    "health",
    "status",
    "genesis",
    "blockchain",
    "block",
    "block_by_hash",
    "block_results",
    "commit",
    "header",
    "header_by_hash",
    "validators",
    "consensus_params",
    "tx",
    "tx_search",
    "block_search",
)


class Inspector:
    """inspect.Inspect: stores + indexers behind a JSONRPC listener."""

    def __init__(self, config: Config):
        self.config = config
        db_dir = config.base.db_path()
        self.block_store = BlockStore(new_db("blockstore", config.base.db_backend, db_dir))
        self.state_store = StateStore(new_db("state", config.base.db_backend, db_dir))
        if config.tx_index.indexer == "kv":
            tx_indexer = KVTxIndexer(new_db("tx_index", config.base.db_backend, db_dir))
            block_indexer = KVBlockIndexer(
                new_db("block_index", config.base.db_backend, db_dir)
            )
        else:
            tx_indexer = NullTxIndexer()
            block_indexer = NullTxIndexer()
        genesis = GenesisDoc.from_file(config.base.genesis_path())
        env = Environment(
            config=config,
            state_store=self.state_store,
            block_store=self.block_store,
            consensus_state=None,
            mempool=None,
            evidence_pool=None,
            event_bus=EventBus(),
            genesis_doc=genesis,
            priv_validator_pub_key=None,
            node_info={"moniker": config.base.moniker, "network": genesis.chain_id},
            tx_indexer=tx_indexer,
            block_indexer=block_indexer,
            proxy_app_query=None,
        )
        all_routes = routes(env)
        self._routes = {k: v for k, v in all_routes.items() if k in INSPECT_ROUTES}
        host, _, port = config.rpc.laddr.split("://")[-1].rpartition(":")
        self.server = JSONRPCServer(self._routes, host or "127.0.0.1", int(port))

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    @property
    def port(self) -> int:
        return self.server.port

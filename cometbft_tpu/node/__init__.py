"""Node assembly (reference: node/node.go NewNode + OnStart)."""

from cometbft_tpu.node.node import Node, default_new_node

__all__ = ["Node", "default_new_node"]

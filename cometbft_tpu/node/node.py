"""Node: dependency assembly + lifecycle (reference: node/node.go:137 NewNode,
:371 OnStart, node/setup.go:64 DefaultNewNode).

Assembly order mirrors the reference: DBs → state → ABCI conns → handshake
replay → event bus + indexers → mempool/evidence/executor → consensus →
P2P switch + reactors → RPC.

Boot phasing (node/node.go:423-433): when statesync is enabled and the
store is empty, OnStart runs the light-client-verified snapshot restore
first, hands the bootstrapped state to blocksync (SwitchToBlockSync), and
blocksync's caught-up hook starts consensus. Without statesync, blocksync
runs from the store head unless this node is the only validator
(onlyValidatorIsUs, node/node.go:174), in which case consensus starts
immediately.
"""

from __future__ import annotations

import os
import threading
import time

from cometbft_tpu.abci.client import LocalClientCreator
from cometbft_tpu.abci.example.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import WAL
from cometbft_tpu.evidence import EvidencePool
from cometbft_tpu.libs.db import new_db
from cometbft_tpu.mempool import CListMempool
from cometbft_tpu.privval import FilePV
from cometbft_tpu.proxy import AppConns
from cometbft_tpu.rpc.core import Environment, routes
from cometbft_tpu.rpc.jsonrpc.server import JSONRPCServer
from cometbft_tpu.state import BlockExecutor, StateStore, make_genesis_state
from cometbft_tpu.state.txindex import (
    IndexerService,
    KVBlockIndexer,
    KVTxIndexer,
    NullTxIndexer,
)
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.events import EventBus
from cometbft_tpu.types.genesis import GenesisDoc


class Node:
    """node/node.go Node."""

    def __init__(
        self,
        config: Config,
        genesis_doc: GenesisDoc,
        priv_validator,
        client_creator,
        logger=None,
        custom_reactors: dict | None = None,
        transport_factory=None,
        clock=None,
    ):
        from cometbft_tpu.simnet.clock import MonotonicClock

        self.config = config
        self.genesis_doc = genesis_doc
        self.priv_validator = priv_validator
        self.logger = logger
        # Injected time source, threaded into consensus + p2p + blocksync so
        # a simulated deployment (simnet) controls every timer from one
        # virtual clock. Default: wall clock, behavior unchanged.
        self.clock = clock or MonotonicClock()
        # fn(node_info, node_key, fuzz_config) -> transport duck-typing
        # MultiplexTransport (listen/dial/close). None = real TCP transport;
        # simnet injects SimTransport here.
        self._transport_factory = transport_factory
        # node/node.go CustomReactors option: name -> Reactor, added to the
        # switch after the built-ins (same-name entries replace built-ins in
        # the reference; here extra names only — replacement would need the
        # channel table rebuilt).
        self._custom_reactors = custom_reactors or {}

        # Storage (node/node.go:147 initDBs).
        db_dir = config.base.db_path()
        self.block_store = BlockStore(new_db("blockstore", config.base.db_backend, db_dir))
        self.state_store = StateStore(
            new_db("state", config.base.db_backend, db_dir),
            discard_abci_responses=config.storage.discard_abci_responses,
        )

        # State from DB or genesis (node/node.go:156).
        state = self.state_store.load()
        if state is None:
            state = make_genesis_state(genesis_doc)
            self.state_store.save(state)

        # ABCI connections (node/node.go:164).
        self.proxy_app = AppConns(client_creator)
        self.proxy_app.start()

        # Event bus + indexers are created AND started before the handshake
        # (node/node.go:173-182 precede :210 doHandshake) so a block applied
        # during crash-recovery replay is published and indexed.
        self.event_bus = EventBus()
        if config.tx_index.indexer == "kv":
            self.tx_indexer = KVTxIndexer(new_db("tx_index", config.base.db_backend, db_dir))
            self.block_indexer = KVBlockIndexer(
                new_db("block_index", config.base.db_backend, db_dir)
            )
        elif config.tx_index.indexer == "psql":
            # SQL event sink (state/indexer/sink/psql): write-only relational
            # indexing for external SQL consumers; /tx_search et al refuse.
            from cometbft_tpu.state.sink_sql import SqlEventSink

            conn = config.tx_index.psql_conn or os.path.join(
                db_dir, "event_sink.sqlite"
            )
            self.event_sink = SqlEventSink(conn, genesis_doc.chain_id)
            self.tx_indexer = self.event_sink.tx_indexer()
            self.block_indexer = self.event_sink.block_indexer()
        else:
            self.tx_indexer = NullTxIndexer()
            self.block_indexer = NullTxIndexer()
        self.indexer_service = IndexerService(
            self.tx_indexer, self.block_indexer, self.event_bus
        )
        self.event_bus.start()
        self.indexer_service.start()

        # Handshake: full replay.go height-case analysis so consensus state,
        # block store, and app advance together (node/node.go:210).
        from cometbft_tpu.consensus.replay import Handshaker

        handshaker = Handshaker(
            self.state_store,
            state,
            self.block_store,
            genesis_doc,
            event_bus=self.event_bus,
            logger=logger,
        )
        state = handshaker.handshake(self.proxy_app)

        # Mempool + evidence + executor (node/node.go:230-248).
        self.mempool = CListMempool(config.mempool, self.proxy_app.mempool)
        # QoS ingress: admission pipeline (envelope preverify, lanes,
        # rate limits, shedding) fronting the clist mempool. RPC and the
        # gossip reactor submit through it; consensus/executor keep the
        # raw mempool (reap/update are not admission).
        self.ingress = None
        if getattr(config.mempool, "ingress_enable", True):
            from cometbft_tpu.mempool.ingress import IngressPipeline

            self.ingress = IngressPipeline(config.mempool, self.mempool)
        self.evidence_pool = EvidencePool(
            new_db("evidence", config.base.db_backend, db_dir),
            self.state_store,
            self.block_store,
            logger,
        )
        self.block_executor = BlockExecutor(
            self.state_store,
            self.proxy_app.consensus,
            self.mempool,
            self.evidence_pool,
            self.block_store,
            self.event_bus,
            logger,
        )

        # Metrics (node/node.go:385-387 + each subsystem's PrometheusMetrics).
        self.metrics_registry = None
        self.metrics_server = None
        cs_metrics = None
        if config.instrumentation.prometheus:
            from cometbft_tpu.consensus.metrics import Metrics as CsMetrics
            from cometbft_tpu.libs.metrics import MetricsServer, Registry

            reg = Registry(namespace=config.instrumentation.namespace)
            self.metrics_registry = reg
            cs_metrics = CsMetrics(reg)
            reg.gauge_func("mempool", "size", "Txs in the mempool.",
                           lambda: self.mempool.size())
            if self.ingress is not None:
                self.ingress.register_metrics(reg)
            reg.gauge_func("p2p", "peers", "Connected peers.",
                           lambda: self.switch.num_peers() if self.switch else 0)
            reg.gauge_func("blockstore", "height", "Block store tip height.",
                           lambda: self.block_store.height())
            reg.gauge_func("blockstore", "base", "Block store base height.",
                           lambda: self.block_store.base())
            self._register_backend_metrics(reg)
            self._register_engine_metrics(reg)
            self._register_recvq_metrics(reg)
            self._register_mesh_metrics(reg)
            self._register_fanout_metrics(reg)
            self._register_hotpath_metrics(reg)
            self._register_lightgw_metrics(reg)
            self._register_evidence_metrics(reg)
            addr = config.instrumentation.prometheus_listen_addr
            host, _, port = addr.rpartition(":")
            self.metrics_server = MetricsServer(
                reg, host.replace("tcp://", "") or "127.0.0.1", int(port)
            )

        # Consensus (node/node.go:256).
        wal = WAL(config.consensus.wal_path()) if config.base.root_dir else None
        self.consensus_state = ConsensusState(
            config.consensus,
            state,
            self.block_executor,
            self.block_store,
            self.mempool,
            self.evidence_pool,
            self.event_bus,
            wal=wal,
            metrics=cs_metrics,
            clock=self.clock,
        )
        if priv_validator is not None:
            self.consensus_state.set_priv_validator(priv_validator)

        # Boot mode (node/node.go:174 onlyValidatorIsUs + :423 stateSync
        # gating: statesync only ever runs into an empty store).
        self._state_sync = bool(config.statesync.enable) and state.last_block_height == 0
        self._block_sync = (
            config.base.block_sync
            and config.blocksync.enable
            and not _only_validator_is_us(state, priv_validator)
        )

        # P2P switch + reactors (node/node.go:285-345), assembled whenever a
        # p2p listen address is configured; in-process meshes (devnet) leave
        # it empty and wire consensus broadcast directly.
        self.switch = None
        self.p2p_laddr = ""
        if config.p2p.laddr:
            from cometbft_tpu.blocksync.reactor import BlocksyncReactor
            from cometbft_tpu.consensus.reactor import ConsensusReactor
            from cometbft_tpu.evidence.reactor import EvidenceReactor
            from cometbft_tpu.mempool.reactor import MempoolReactor
            from cometbft_tpu.p2p.key import NodeKey
            from cometbft_tpu.p2p.node_info import NodeInfo
            from cometbft_tpu.p2p.switch import Switch
            from cometbft_tpu.p2p.transport import MultiplexTransport
            from cometbft_tpu.statesync import StatesyncReactor

            if config.base.root_dir:
                self.node_key = NodeKey.load_or_gen(config.base.node_key_path())
            else:
                self.node_key = NodeKey()
            self.node_info = NodeInfo(
                node_id=self.node_key.id,
                network=genesis_doc.chain_id,
                moniker=config.base.moniker,
            )
            fuzz_config = None
            if config.p2p.test_fuzz:
                from cometbft_tpu.p2p.fuzz import FuzzConnConfig

                fuzz_config = FuzzConnConfig(
                    mode=config.p2p.test_fuzz_mode,
                    max_delay=config.p2p.test_fuzz_max_delay,
                    prob_drop_rw=config.p2p.test_fuzz_prob_drop_rw,
                )
            make_transport = self._transport_factory or (
                lambda ni, nk, fz: MultiplexTransport(ni, nk, fz)
            )
            self.switch = Switch(
                self.node_info,
                make_transport(self.node_info, self.node_key, fuzz_config),
                config=config.p2p,
                clock=self.clock,
            )
            self.consensus_reactor = ConsensusReactor(
                self.consensus_state,
                gossip_sleep=config.consensus.peer_gossip_sleep_duration,
            )
            # Gossiped txs enter the same admission path as RPC submissions
            # (preverify + lanes), with the peer id recorded as sender.
            self.mempool_reactor = MempoolReactor(
                config.mempool, self.ingress or self.mempool, clock=self.clock
            )
            self.evidence_reactor = EvidenceReactor(self.evidence_pool)
            self.blocksync_reactor = BlocksyncReactor(
                self.consensus_state.state,
                self.block_executor,
                self.block_store,
                block_sync=self._block_sync and not self._state_sync,
                on_caught_up=self._on_blocksync_caught_up,
                clock=self.clock,
            )
            self.statesync_reactor = StatesyncReactor(
                snapshot_conn=self.proxy_app.snapshot
            )
            self.switch.add_reactor("MEMPOOL", self.mempool_reactor)
            self.switch.add_reactor("EVIDENCE", self.evidence_reactor)
            self.switch.add_reactor("CONSENSUS", self.consensus_reactor)
            self.switch.add_reactor("BLOCKSYNC", self.blocksync_reactor)
            self.switch.add_reactor("STATESYNC", self.statesync_reactor)

            # PEX + address book (node/setup.go createPEXReactorAndAddToSwitch),
            # unless discovery is disabled (config.go PexReactor).
            self.pex_reactor = None
            if config.p2p.pex:
                from cometbft_tpu.p2p.pex import AddrBook, PexReactor

                book_path = (
                    os.path.join(config.base.root_dir, config.p2p.addr_book_file)
                    if config.base.root_dir
                    else ""
                )
                self.addr_book = AddrBook(book_path, strict=config.p2p.addr_book_strict)
                self.addr_book.add_our_address(self.node_key.id)
                self.addr_book.add_private_ids(
                    [i for i in config.p2p.private_peer_ids.split(",") if i]
                )
                self.pex_reactor = PexReactor(
                    self.addr_book,
                    seeds=[s.strip() for s in config.p2p.seeds.split(",") if s.strip()],
                    seed_mode=config.p2p.seed_mode,
                    max_outbound=config.p2p.max_num_outbound_peers,
                )
                self.switch.add_reactor("PEX", self.pex_reactor)

            for name, reactor in self._custom_reactors.items():
                self.switch.add_reactor(name, reactor)

        # RPC (node/node.go:392 startRPC).
        self.rpc_server = None
        self.grpc_server = None
        self._rpc_env = None

        # Light-client gateway (light/gateway.py): built on first
        # light_sync/light_proof RPC, never at boot — the lazy accessor is
        # what the RPC env carries and the metrics gauges deliberately
        # bypass (they read _light_gateway directly, so a scrape never
        # constructs it).
        self._light_gateway = None
        self._light_gateway_lock = threading.Lock()

        # Checkpoint-bundle origin (light/origin.py): same lazy contract —
        # built on the first light_bundle RPC / export, never at boot, and
        # the bundle gauges read _bundle_origin directly.
        self._bundle_origin = None
        self._bundle_origin_lock = threading.Lock()

    def _mmr_state_path(self) -> str:
        """One persisted accumulator state file under the node's db dir,
        shared by the gateway and the bundle origin (identical content at
        any size; writes are atomic replaces)."""
        return os.path.join(self.config.base.db_path(), "light_mmr.state")

    def light_gateway(self):
        """The node's LightGateway over its local stores; None when
        CMTPU_LIGHTGW=0 disables serving."""
        if os.environ.get("CMTPU_LIGHTGW", "1").strip().lower() in (
            "0", "false", "off",
        ):
            return None
        with self._light_gateway_lock:
            if self._light_gateway is None:
                from cometbft_tpu.light.gateway import LightGateway
                from cometbft_tpu.light.provider import BlockStoreProvider

                self._light_gateway = LightGateway(
                    self.genesis_doc.chain_id,
                    BlockStoreProvider(
                        self.genesis_doc.chain_id,
                        self.block_store,
                        self.state_store,
                    ),
                    state_path=self._mmr_state_path(),
                    logger=self.logger,
                )
            return self._light_gateway

    def bundle_origin(self, build: bool = True):
        """The node's BundleOrigin over its local stores; None when
        CMTPU_BUNDLE=0 disables the subsystem.  build=False peeks at the
        already-constructed origin (stats/metrics paths) without ever
        constructing one."""
        from cometbft_tpu.light.origin import bundles_enabled

        if not bundles_enabled():
            return None
        if not build:
            return self._bundle_origin
        with self._bundle_origin_lock:
            if self._bundle_origin is None:
                from cometbft_tpu.light.origin import BundleOrigin
                from cometbft_tpu.light.provider import BlockStoreProvider

                self._bundle_origin = BundleOrigin(
                    self.genesis_doc.chain_id,
                    BlockStoreProvider(
                        self.genesis_doc.chain_id,
                        self.block_store,
                        self.state_store,
                    ),
                    state_path=self._mmr_state_path(),
                    logger=self.logger,
                )
            return self._bundle_origin

    @staticmethod
    def _register_backend_metrics(reg) -> None:
        """backend_trips / backend_retries / backend_deadline_exceeded /
        backend_active_tier gauges plus the scheduler_* coalescer gauges,
        sampled lazily off the process-wide verification backend.  Sampling (not registering) checks for the
        supervisor so scraping never forces backend construction — under
        CMTPU_BACKEND=auto with an accelerator visible that would import
        jax at node boot instead of first verification."""
        from cometbft_tpu.sidecar import backend as backend_mod

        def sample(key):
            def fn():
                b = backend_mod._backend  # no get_backend(): never constructs
                if getattr(b, "name", "") == "coalesce":
                    b = b.inner  # supervisor gauges read the wrapped chain
                counters = getattr(b, "counters", None)
                if counters is None:
                    return 0
                c = counters()
                if key == "active_tier":
                    return b.active_tier_index
                return c.get(key, 0)

            return fn

        def sched_sample(key):
            # Lazy like sample(): zeros until the coalescing scheduler
            # exists (CMTPU_COALESCE=0 keeps them zero forever).
            def fn():
                b = backend_mod._backend
                if getattr(b, "name", "") != "coalesce":
                    return 0
                c = b.counters()
                if key == "coalesce_ratio_milli":
                    return int(1000 * c["requests"] / max(1, c["dispatches"]))
                if key == "queue_wait_p95_us":
                    return int(c["queue_wait_p95_ms"] * 1000)
                return c.get(key, 0)

            return fn

        reg.gauge_func("backend", "trips",
                       "Verification-tier circuit-breaker trips.",
                       sample("trips"))
        reg.gauge_func("backend", "retries",
                       "Verification-tier transient-error retries.",
                       sample("retries"))
        reg.gauge_func("backend", "deadline_exceeded",
                       "Verification calls past CMTPU_DEADLINE_MS.",
                       sample("deadline_exceeded"))
        reg.gauge_func("backend", "active_tier",
                       "Degradation-chain index of the serving tier "
                       "(0 = primary).",
                       sample("active_tier"))
        reg.gauge_func("scheduler", "requests",
                       "Verification requests submitted to the coalescer.",
                       sched_sample("requests"))
        reg.gauge_func("scheduler", "dispatches",
                       "Backend dispatches the coalescer issued.",
                       sched_sample("dispatches"))
        reg.gauge_func("scheduler", "batched_requests",
                       "Requests that shared a coalesced dispatch.",
                       sched_sample("batched_requests"))
        reg.gauge_func("scheduler", "fallback_splits",
                       "Coalesced dispatches split into per-request retries.",
                       sched_sample("fallback_splits"))
        reg.gauge_func("scheduler", "coalesce_ratio_milli",
                       "Requests per dispatch x1000.",
                       sched_sample("coalesce_ratio_milli"))
        reg.gauge_func("scheduler", "queue_wait_p95_us",
                       "95th-percentile coalescer queue wait, microseconds.",
                       sched_sample("queue_wait_p95_us"))

        def sidecar_sample(key):
            # Lazy like the others: zeros until a grpc tier exists (bare
            # CMTPU_BACKEND=grpc client, or the auto chain's sidecar tier,
            # possibly chaos-wrapped). Never dials or constructs.
            def fn():
                b = backend_mod._backend
                if getattr(b, "name", "") == "coalesce":
                    b = b.inner
                g = None
                if getattr(b, "name", "") == "grpc":
                    g = b
                else:
                    for t in getattr(b, "tiers", []):
                        be = t.backend
                        if getattr(be, "name", "").startswith("chaos"):
                            be = be.inner
                        if getattr(be, "name", "") == "grpc":
                            g = be
                            break
                counters = getattr(g, "counters", None)
                if counters is None:
                    return 0
                return counters().get(key, 0)

            return fn

        reg.gauge_func("sidecar", "streamed_calls",
                       "Batch verifications streamed to the sidecar in "
                       "chunks.",
                       sidecar_sample("streamed_calls"))
        reg.gauge_func("sidecar", "streamed_chunks",
                       "Chunks sent on streamed sidecar verifications.",
                       sidecar_sample("streamed_chunks"))
        reg.gauge_func("sidecar", "unary_calls",
                       "Batch verifications sent to the sidecar as one "
                       "frame.",
                       sidecar_sample("unary_calls"))
        reg.gauge_func("sidecar", "stream_retries",
                       "Streamed sidecar calls retried on a fresh "
                       "connection.",
                       sidecar_sample("stream_retries"))
        reg.gauge_func("sidecar", "remote_mesh_width",
                       "Serving pod chip count from the Ping capability "
                       "reply.",
                       sidecar_sample("remote_mesh_width"))

    @staticmethod
    def _register_engine_metrics(reg) -> None:
        """engine_* gauges: the continuous-batching verification engine's
        per-class view (consensus/blocksync/ingress/light admission counts,
        dispatched signatures, p95 admission wait, starvation promotions)
        plus its dispatch total. Lazy like the backend gauges — the sampler
        peeks `backend_mod._backend` (never get_backend()) and unwraps the
        CoalescingScheduler shim, so a scrape never constructs the chain;
        the legacy scheduler_*/vote_batch_* gauges keep reading through
        their existing registrations. Zeros under CMTPU_COALESCE=0."""
        from cometbft_tpu.sidecar import backend as backend_mod

        def _engine():
            from cometbft_tpu.sidecar.engine import engine_of

            return engine_of(backend_mod._backend)

        def eng_sample(fn0):
            def fn():
                eng = _engine()
                if eng is None:
                    return 0
                try:
                    return fn0(eng)
                except Exception:
                    return 0

            return fn

        reg.gauge_func(
            "engine", "dispatches",
            "Device dispatches the continuous-batching engine issued.",
            eng_sample(lambda e: e.counters_["dispatches"]),
        )
        from cometbft_tpu.sidecar.engine import CLASS_NAMES

        for klass, cname in enumerate(CLASS_NAMES):
            reg.gauge_func(
                "engine", f"{cname}_admitted",
                f"{cname}-class requests admitted to the engine.",
                eng_sample(
                    lambda e, k=klass: e.class_counters_[k]["admitted"]
                ),
            )
            reg.gauge_func(
                "engine", f"{cname}_dispatched_sigs",
                f"{cname}-class signatures dispatched to the device.",
                eng_sample(
                    lambda e, k=klass: e.class_counters_[k]["dispatched_sigs"]
                ),
            )
            reg.gauge_func(
                "engine", f"{cname}_p95_us",
                f"{cname}-class 95th-percentile admission wait, microseconds.",
                eng_sample(
                    lambda e, k=klass: int(e.class_wait_p95_ms(k) * 1000)
                ),
            )
            reg.gauge_func(
                "engine", f"{cname}_starvation_promotions",
                f"{cname}-class requests promoted past fresher "
                "higher-class work by the starvation hatch.",
                eng_sample(
                    lambda e, k=klass: e.class_counters_[k][
                        "starvation_promotions"
                    ]
                ),
            )

    def _register_recvq_metrics(self, reg) -> None:
        """recvq_* gauges: the prioritized p2p recv demux, aggregated across
        every live peer connection plus retired-peer totals (per-channel
        queue depth, per-class deliveries, sheds, starvation promotions,
        max queue delay).  Lazy like the backend gauges — the sampler reads
        `self.switch` via getattr (registration runs before __init__ builds
        it) and the switch only walks already-built MConnections, so a
        scrape never constructs anything.  Empty/zero under CMTPU_RECVQ=0."""

        def _stats():
            sw = getattr(self, "switch", None)
            if sw is None:
                return None
            try:
                return sw.recvq_stats()
            except Exception:
                return None

        def rq(key):
            def fn():
                st = _stats()
                return int(st.get(key, 0)) if st else 0

            return fn

        reg.gauge_func("recvq", "depth",
                       "Messages queued in recv demux queues (all peers).",
                       rq("depth"))
        reg.gauge_func("recvq", "delivered_total",
                       "Messages the recv demux delivered to reactors.",
                       rq("delivered_total"))
        reg.gauge_func("recvq", "shed_total",
                       "Sheddable-class messages dropped on queue overflow.",
                       rq("shed_total"))
        reg.gauge_func("recvq", "promoted_total",
                       "Messages promoted past higher-class backlog by the "
                       "starvation hatch.",
                       rq("promoted_total"))
        reg.gauge_func("recvq", "backpressure_waits",
                       "Framer waits on a full consensus/blocksync queue "
                       "(TCP backpressure engaged).",
                       rq("backpressure_waits"))
        reg.gauge_func("recvq", "max_delay_us",
                       "Worst observed recv queue delay, microseconds.",
                       rq("max_delay_us"))
        from cometbft_tpu.p2p.conn.recvq import CLASS_NAMES as _RQ_CLASSES

        for cname in _RQ_CLASSES:
            reg.gauge_func(
                "recvq", f"{cname}_delivered",
                f"{cname}-class messages delivered by the recv demux.",
                rq(f"{cname}_delivered"),
            )
        # Per-channel depth over the reserved global channel ids
        # (p2p/reactor.py); unknown future channels still show up in the
        # recvq_stats RPC's `channels` map.
        from cometbft_tpu.p2p import reactor as _reactor_mod

        for chan in (
            _reactor_mod.PEX_CHANNEL,
            _reactor_mod.CONSENSUS_STATE_CHANNEL,
            _reactor_mod.CONSENSUS_DATA_CHANNEL,
            _reactor_mod.CONSENSUS_VOTE_CHANNEL,
            _reactor_mod.CONSENSUS_VOTE_SET_BITS_CHANNEL,
            _reactor_mod.MEMPOOL_CHANNEL,
            _reactor_mod.EVIDENCE_CHANNEL,
            _reactor_mod.BLOCKSYNC_CHANNEL,
            _reactor_mod.SNAPSHOT_CHANNEL,
            _reactor_mod.CHUNK_CHANNEL,
        ):
            def chan_depth(c=chan):
                st = _stats()
                if not st:
                    return 0
                return int(st.get("channels", {}).get(f"{c:#04x}", 0))

            reg.gauge_func("recvq", f"depth_ch{chan:02x}",
                           f"Recv demux queue depth on channel {chan:#04x}.",
                           chan_depth)

    def _register_evidence_metrics(self, reg) -> None:
        """evidence_* gauges: the misbehavior-accountability pipeline
        (pending pool size, lifetime reported/added/committed/expired).
        Lazy like the other families — the sampler reads
        `self.evidence_pool` via getattr, and `pending` walks only the
        pool's own DB prefix, so a scrape never constructs anything."""

        def ev(key):
            def fn():
                pool = getattr(self, "evidence_pool", None)
                if pool is None:
                    return 0
                try:
                    return int(pool.stats_snapshot().get(key, 0))
                except Exception:
                    return 0

            return fn

        reg.gauge_func("evidence", "pending",
                       "Evidence pieces pending inclusion in a block.",
                       ev("pending"))
        reg.gauge_func("evidence", "reported_total",
                       "Conflicting-vote reports received from consensus.",
                       ev("reported_total"))
        reg.gauge_func("evidence", "added_total",
                       "Evidence pieces accepted into the pending pool.",
                       ev("added_total"))
        reg.gauge_func("evidence", "committed_total",
                       "Evidence pieces committed in blocks.",
                       ev("committed_total"))
        reg.gauge_func("evidence", "expired_total",
                       "Pending evidence pruned past max-age.",
                       ev("expired_total"))

    @staticmethod
    def _register_mesh_metrics(reg) -> None:
        """mesh_* gauges: pod-scale sharding of the device verify tier
        (device count, sharded dispatches, bucket-padding lanes, sharded
        merkle roots).  Strictly passive — the sampler reads the ed25519
        kernel module only if something else already imported it, and the
        device count only if something already probed it, so a scrape never
        imports jax or touches a possibly-wedged device tunnel."""
        import sys as _sys

        def mesh_sample(key):
            def fn():
                ek = _sys.modules.get("cometbft_tpu.ops.ed25519_kernel")
                if ek is None:
                    return 0
                return ek.mesh_counters().get(key, 0)

            return fn

        reg.gauge_func("mesh", "devices",
                       "Process-local chips one verify dispatch shards "
                       "across (0 until the device tier probes).",
                       mesh_sample("devices"))
        reg.gauge_func("mesh", "sharded_dispatches",
                       "Verify dispatches routed to the multi-chip program.",
                       mesh_sample("sharded_dispatches"))
        reg.gauge_func("mesh", "padded_lanes",
                       "Bucket-padding lanes shipped on sharded dispatches.",
                       mesh_sample("padded_lanes"))
        reg.gauge_func("mesh", "merkle_sharded_dispatches",
                       "Fused merkle roots served by the subtree-parallel "
                       "mesh program.",
                       mesh_sample("merkle_sharded_dispatches"))

    @staticmethod
    def _register_fanout_metrics(reg) -> None:
        """fanout_* gauges: the multi-host verification fleet (shard count,
        combined width, dispatches, redistributions, shards cooling down).
        Lazy like the backend gauges — the sampler walks the ALREADY-BUILT
        chain under `backend_mod._backend` for a tier named `fanout` (never
        get_backend(), never a dial), so a scrape with no fleet configured
        costs a few getattr probes and reads zero."""
        from cometbft_tpu.sidecar import backend as backend_mod

        def _fanout():
            stack, seen = [backend_mod._backend], set()
            while stack:
                b = stack.pop()
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                if getattr(b, "name", "") == "fanout":
                    return b
                stack.append(getattr(b, "inner", None))
                for t in getattr(b, "tiers", ()) or ():
                    stack.append(getattr(t, "backend", None))
            return None

        def fan_sample(fn0):
            def fn():
                fan = _fanout()
                if fan is None:
                    return 0
                try:
                    return fn0(fan)
                except Exception:
                    return 0

            return fn

        import time as _time

        reg.gauge_func("fanout", "shards",
                       "Shards in the verification fleet (0 = no fleet).",
                       fan_sample(lambda f: len(f.shards)))
        reg.gauge_func("fanout", "width",
                       "Combined fleet width (sum of shard mesh widths).",
                       fan_sample(lambda f: f.mesh_width()))
        reg.gauge_func("fanout", "dispatches",
                       "Batches the fleet fanned out across its shards.",
                       fan_sample(lambda f: f.counters_["dispatches"]))
        reg.gauge_func("fanout", "shard_failures",
                       "Per-shard slice failures (error or deadline).",
                       fan_sample(lambda f: f.counters_["shard_failures"]))
        reg.gauge_func("fanout", "redistributions",
                       "Retry rounds that re-split dead shards' slices "
                       "across survivors.",
                       fan_sample(lambda f: f.counters_["redistributions"]))
        reg.gauge_func("fanout", "redistributed_sigs",
                       "Signatures re-dispatched by redistribution rounds.",
                       fan_sample(lambda f: f.counters_["redistributed_sigs"]))
        reg.gauge_func("fanout", "shards_down",
                       "Shards currently sitting out a failure cooldown.",
                       fan_sample(lambda f: sum(
                           1 for s in f.shards
                           if not s.healthy(_time.monotonic())
                       )))

    def _register_hotpath_metrics(self, reg) -> None:
        """Consensus hot-path gauges: the vote-admission micro-batcher, WAL
        group commit, and the blocksync verify/apply pipeline. Lazy like the
        backend gauges — `sigbatch.counters()` never constructs a batcher,
        and the WAL/blocksync reads are getattr probes on objects built
        later in __init__, so a scrape is always side-effect free."""
        from cometbft_tpu.crypto import sigbatch

        def vb(key):
            return lambda: sigbatch.counters().get(key, 0)

        def vb_ratio():
            c = sigbatch.counters()
            return int(1000 * c["requests"] / max(1, c["dispatches"]))

        reg.gauge_func("vote_batch", "requests",
                       "Signature-verify requests to the vote micro-batcher.",
                       vb("requests"))
        reg.gauge_func("vote_batch", "dispatches",
                       "Columnar dispatches the vote micro-batcher issued.",
                       vb("dispatches"))
        reg.gauge_func("vote_batch", "coalesce_ratio_milli",
                       "Vote-batch requests per dispatch x1000.",
                       vb_ratio)
        reg.gauge_func("vote_batch", "cache_hits",
                       "Vote admissions answered by the verified-triple cache.",
                       vb("cache_hits"))
        reg.gauge_func("wal", "group_commits_total",
                       "WAL fsyncs that covered more than one write_sync caller.",
                       lambda: getattr(
                           getattr(getattr(self, "consensus_state", None),
                                   "wal", None),
                           "group_commits", 0) or 0)
        reg.gauge_func("blocksync", "pipeline_overlap_ms",
                       "Accumulated verify/apply overlap in blocksync, ms.",
                       lambda: int(getattr(
                           getattr(self, "blocksync_reactor", None),
                           "pipeline_overlap_ms", 0) or 0))

    def _register_lightgw_metrics(self, reg) -> None:
        """Light-client gateway gauges. Strictly passive: they read the
        `_light_gateway` attribute (getattr-guarded — registration runs
        before __init__ assigns it) and never call the light_gateway()
        accessor, so a metrics scrape can never construct the gateway."""

        def gw(key):
            def fn():
                g = getattr(self, "_light_gateway", None)
                if g is None:
                    return 0
                return int(g.stats().get(key, 0))
            return fn

        def gw_share_milli():
            g = getattr(self, "_light_gateway", None)
            if g is None:
                return 0
            return int(1000 * g.stats()["plan_share_ratio"])

        reg.gauge_func("lightgw", "sessions_total",
                       "Light-gateway sync sessions admitted.",
                       gw("sessions_total"))
        reg.gauge_func("lightgw", "sessions_active",
                       "Light-gateway sync sessions currently in flight.",
                       gw("sessions_active"))
        reg.gauge_func("lightgw", "sessions_rejected",
                       "Light-gateway sessions shed at the concurrency cap.",
                       gw("sessions_rejected"))
        reg.gauge_func("lightgw", "plan_cache_hits",
                       "Descent plans answered from the memoized plan cache.",
                       gw("plan_hits"))
        reg.gauge_func("lightgw", "proofs_served",
                       "MMR cold-sync inclusion proofs served.",
                       gw("proofs_served"))
        reg.gauge_func("lightgw", "plan_share_ratio_milli",
                       "Plans served per plan computed x1000.",
                       gw_share_milli)
        reg.gauge_func("lightgw", "proof_bytes_served",
                       "Total wire bytes of MMR cold-sync proofs served.",
                       gw("proof_bytes_served"))

        # Bundle-origin gauges: same passive contract against
        # _bundle_origin — a scrape never constructs the origin.
        def bo(key):
            def fn():
                o = getattr(self, "_bundle_origin", None)
                if o is None:
                    return 0
                return int(o.stats().get(key, 0))
            return fn

        reg.gauge_func("lightgw", "bundles_built",
                       "Checkpoint bundles frozen by the origin.",
                       bo("bundles_built"))
        reg.gauge_func("lightgw", "bundle_hits",
                       "Checkpoint bundle serves (RPC/export/in-process).",
                       bo("bundle_hits"))
        reg.gauge_func("lightgw", "bundle_fallbacks",
                       "Bundle requests refused (no checkpoint/pruned/"
                       "mismatch) — the client fell back interactively.",
                       bo("bundle_fallbacks"))
        reg.gauge_func("lightgw", "bundle_bytes_served",
                       "Total wire bytes of checkpoint bundles served.",
                       bo("bundle_bytes_served"))

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        """node/node.go:371 OnStart (event bus/indexer already run from
        __init__, as in NewNode): p2p listen + dial, then the statesync →
        blocksync → consensus phase chain."""
        if self.switch is not None:
            host, port = _parse_laddr(self.config.p2p.laddr)
            self.p2p_laddr = self.switch.start(f"{host}:{port}")
            if self.logger:
                self.logger.info(
                    "p2p listening", module="p2p", addr=self.p2p_laddr,
                    node_id=self.node_key.id,
                )
            # Persistent peers ride the switch's backoff redial loop
            # (switch.go reconnectToPeer): peers that aren't up yet — the
            # normal case when a testnet launches in parallel — must not
            # fail OnStart.
            self.switch.add_persistent_peers(
                [a.strip() for a in self.config.p2p.persistent_peers.split(",") if a.strip()]
            )
            self.switch.dial_persistent_peers()

        if self.metrics_server is not None:
            self.metrics_server.start()
        if self.config.rpc.pprof_laddr:
            from cometbft_tpu.libs.pprof import PprofServer

            host, _, port = self.config.rpc.pprof_laddr.split("://")[-1].rpartition(":")
            self.pprof_server = PprofServer(
                host or "127.0.0.1",
                int(port),
                trace_dir=os.path.join(self.config.base.root_dir or ".", "jax-trace"),
            )
            self.pprof_server.start()
        if os.environ.get("CMTPU_WATCHDOG"):
            from cometbft_tpu.libs.deadlock import Watchdog

            self.watchdog = Watchdog(
                lambda: self.consensus_state.rs.height,
                stall_after=float(os.environ["CMTPU_WATCHDOG"]),
                logger=self.logger,
                on_stall=lambda report: print(report),
            )
            self.watchdog.start()

        if self._state_sync and self.switch is not None:
            threading.Thread(
                target=self._statesync_routine, daemon=True, name="statesync"
            ).start()
        elif self._block_sync and self.switch is not None:
            pass  # blocksync reactor's pool routine runs; caught-up hook
            # starts consensus (_on_blocksync_caught_up)
        else:
            self.consensus_state.start()
        rpc_laddr = self.config.rpc.laddr
        if rpc_laddr:
            host, port = _parse_laddr(rpc_laddr)
            pub = None
            if self.priv_validator is not None:
                pub = self.priv_validator.get_pub_key()
            env = Environment(
                config=self.config,
                state_store=self.state_store,
                block_store=self.block_store,
                consensus_state=self.consensus_state,
                consensus_reactor=getattr(self, "consensus_reactor", None),
                mempool=self.ingress or self.mempool,
                ingress=self.ingress,
                evidence_pool=self.evidence_pool,
                event_bus=self.event_bus,
                genesis_doc=self.genesis_doc,
                priv_validator_pub_key=pub,
                node_info={"moniker": self.config.base.moniker, "network": self.genesis_doc.chain_id},
                tx_indexer=self.tx_indexer,
                block_indexer=self.block_indexer,
                proxy_app_query=self.proxy_app.query,
                p2p_peers=self.switch,
                light_gateway=self.light_gateway,
                bundle_origin=self.bundle_origin,
            )
            self._rpc_env = env
            routes_map = routes(env)
            self.rpc_server = JSONRPCServer(routes_map, host, port)
            self.rpc_server.start()
            if self.config.rpc.grpc_laddr:
                # node/node.go startRPC grpcListener branch: the minimal
                # BroadcastAPI (Ping/BroadcastTx) on its own port.
                from cometbft_tpu.rpc.grpc_server import GrpcBroadcastServer

                self.grpc_server = GrpcBroadcastServer(
                    routes_map, self.config.rpc.grpc_laddr
                )
                self.grpc_server.start()

    def stop(self) -> None:
        self.consensus_state.stop()
        if self.ingress is not None:
            self.ingress.close()
        if getattr(self, "pprof_server", None) is not None:
            self.pprof_server.stop()
        if getattr(self, "watchdog", None) is not None:
            self.watchdog.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        if self.switch is not None:
            self.switch.stop()
        self.indexer_service.stop()
        self.event_bus.stop()
        if getattr(self, "event_sink", None) is not None:
            self.event_sink.stop()
        if self.rpc_server:
            self.rpc_server.stop()
        if self.grpc_server is not None:
            self.grpc_server.stop()
        # last: RPC handlers reach ABCI through these clients — close them
        # only after no request can arrive
        self.proxy_app.stop()

    @property
    def rpc_port(self) -> int:
        return self.rpc_server.port if self.rpc_server else 0

    # -- boot phases (node/node.go:423-433) -----------------------------------

    def _on_blocksync_caught_up(self, state) -> None:
        """blocksync's SwitchToConsensus hook (blocksync/reactor.go:392)."""
        self.consensus_state.update_to_state(state)
        self.consensus_state.start()

    def _make_state_provider(self):
        """node/setup.go-style light StateProvider over the configured RPC
        servers (config.go StateSyncConfig.RPCServers)."""
        from cometbft_tpu.light.provider import HTTPProvider
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.statesync import LightClientStateProvider
        from cometbft_tpu.types import cmttime

        cfg = self.config.statesync
        if not cfg.rpc_servers:
            raise ValueError("statesync.rpc_servers must be set when statesync is enabled")
        providers = [
            HTTPProvider(self.genesis_doc.chain_id, HTTPClient(s))
            for s in cfg.rpc_servers
        ]
        return LightClientStateProvider(
            self.genesis_doc.chain_id,
            providers[0],
            providers[1:],
            trust_height=cfg.trust_height,
            trust_hash=bytes.fromhex(cfg.trust_hash),
            trust_period_ns=int(cfg.trust_period * 10**9),
            consensus_params=self.consensus_state.state.consensus_params,
            now=cmttime.now,
        )

    def _statesync_routine(self) -> None:
        """node/node.go:423-433 startStateSync: snapshot restore verified by
        the light client, store bootstrap, then SwitchToBlockSync — whose
        caught-up hook starts consensus."""
        from cometbft_tpu.statesync import Syncer

        cfg = self.config.statesync
        try:
            provider = self._make_state_provider()
            syncer = Syncer(
                self.proxy_app.snapshot,
                self.proxy_app.query,
                provider,
                self.statesync_reactor.request_chunk,
                chunk_timeout=cfg.chunk_request_timeout,
                chunk_fetchers=cfg.chunk_fetchers,
            )
            self.statesync_reactor.set_syncer(syncer)
            if self.logger:
                self.logger.info("starting statesync", module="statesync")
            state, commit = syncer.sync_any(
                discovery_time=cfg.discovery_time, timeout=600
            )
            if self.logger:
                self.logger.info(
                    "snapshot restored; switching to blocksync",
                    module="statesync", height=state.last_block_height,
                )
            self.state_store.bootstrap(state)
            self.block_store.save_seen_commit(state.last_block_height, commit)
            self.blocksync_reactor.switch_to_block_sync(state, self.block_executor)
        except Exception as e:
            # Fall back to blocksync-from-genesis rather than leaving a
            # zombie node (consensus only starts via blocksync's caught-up
            # hook, and the reactor was built with block_sync=False while
            # statesync was armed).
            if self.logger:
                self.logger.error(
                    "statesync failed; falling back to blocksync",
                    module="statesync", err=str(e),
                )
            else:
                print(f"statesync failed ({e}); falling back to blocksync")
            self.blocksync_reactor.switch_to_block_sync(
                self.consensus_state.state, self.block_executor
            )


def _only_validator_is_us(state, priv_validator) -> bool:
    """node/node.go:174: a 1-validator net that IS us must not wait for
    blocksync peers before producing blocks."""
    if priv_validator is None:
        return False
    if state.validators.size() != 1:
        return False
    return state.validators.validators[0].address == priv_validator.get_pub_key().address()


def _parse_laddr(laddr: str) -> tuple[str, int]:
    addr = laddr.split("://", 1)[-1]
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def default_new_node(config: Config, logger=None, app=None) -> Node:
    """node/setup.go:64 DefaultNewNode: files from config; the app comes
    from proxy_app — "kvstore"/"noop" in-process, otherwise a socket address
    served by an external ABCI app (proxy/client.go DefaultClientCreator);
    a remote signer when priv_validator_laddr is set (node/node.go:181
    createAndStartPrivValidator SocketVal branch)."""
    if logger is None:
        from cometbft_tpu.libs.log import new_logger

        logger = new_logger(level=config.base.log_level, fmt=config.base.log_format)
    genesis = GenesisDoc.from_file(config.base.genesis_path())
    if config.base.priv_validator_laddr:
        from cometbft_tpu.privval.signer import (
            RetrySignerClient,
            SignerClient,
            SignerListenerEndpoint,
        )

        endpoint = SignerListenerEndpoint(config.base.priv_validator_laddr)
        pv = RetrySignerClient(SignerClient(endpoint, genesis.chain_id))
    else:
        pv = FilePV.load_or_generate(
            config.base.priv_validator_key_path(),
            config.base.priv_validator_state_path(),
        )
    if app is not None:
        creator = LocalClientCreator(app)
    elif config.base.proxy_app == "kvstore":
        creator = LocalClientCreator(
            KVStoreApplication(snapshot_interval=config.base.snapshot_interval)
        )
    elif config.base.proxy_app == "persistent_kvstore":
        from cometbft_tpu.abci.example.kvstore import PersistentKVStoreApplication

        creator = LocalClientCreator(
            PersistentKVStoreApplication(
                snapshot_interval=config.base.snapshot_interval
            )
        )
    elif config.base.proxy_app == "noop":
        from cometbft_tpu.abci import types as abci_types

        creator = LocalClientCreator(abci_types.Application())
    elif config.base.proxy_app.startswith("grpc://"):
        from cometbft_tpu.abci.grpc import GrpcClientCreator

        creator = GrpcClientCreator(config.base.proxy_app)
    else:
        from cometbft_tpu.abci.client import SocketClientCreator

        creator = SocketClientCreator(config.base.proxy_app)
    return Node(config, genesis, pv, creator, logger)

"""Node: dependency assembly + lifecycle (reference: node/node.go:137 NewNode,
:371 OnStart, node/setup.go:64 DefaultNewNode).

Assembly order mirrors the reference: DBs → state → ABCI conns → handshake
replay → event bus + indexers → mempool/evidence/executor → consensus → RPC.
"""

from __future__ import annotations

import os

from cometbft_tpu.abci.client import LocalClientCreator
from cometbft_tpu.abci.example.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import WAL
from cometbft_tpu.evidence import EvidencePool
from cometbft_tpu.libs.db import new_db
from cometbft_tpu.mempool import CListMempool
from cometbft_tpu.privval import FilePV
from cometbft_tpu.proxy import AppConns
from cometbft_tpu.rpc.core import Environment, routes
from cometbft_tpu.rpc.jsonrpc.server import JSONRPCServer
from cometbft_tpu.state import BlockExecutor, StateStore, make_genesis_state
from cometbft_tpu.state.txindex import (
    IndexerService,
    KVBlockIndexer,
    KVTxIndexer,
    NullTxIndexer,
)
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.events import EventBus
from cometbft_tpu.types.genesis import GenesisDoc


class Node:
    """node/node.go Node."""

    def __init__(
        self,
        config: Config,
        genesis_doc: GenesisDoc,
        priv_validator,
        client_creator,
        logger=None,
    ):
        self.config = config
        self.genesis_doc = genesis_doc
        self.priv_validator = priv_validator
        self.logger = logger

        # Storage (node/node.go:147 initDBs).
        db_dir = config.base.db_path()
        self.block_store = BlockStore(new_db("blockstore", config.base.db_backend, db_dir))
        self.state_store = StateStore(new_db("state", config.base.db_backend, db_dir))

        # State from DB or genesis (node/node.go:156).
        state = self.state_store.load()
        if state is None:
            state = make_genesis_state(genesis_doc)
            self.state_store.save(state)

        # ABCI connections (node/node.go:164).
        self.proxy_app = AppConns(client_creator)
        self.proxy_app.start()

        # Event bus + indexers are created AND started before the handshake
        # (node/node.go:173-182 precede :210 doHandshake) so a block applied
        # during crash-recovery replay is published and indexed.
        self.event_bus = EventBus()
        if config.tx_index.indexer == "kv":
            self.tx_indexer = KVTxIndexer(new_db("tx_index", config.base.db_backend, db_dir))
            self.block_indexer = KVBlockIndexer(
                new_db("block_index", config.base.db_backend, db_dir)
            )
        else:
            self.tx_indexer = NullTxIndexer()
            self.block_indexer = NullTxIndexer()
        self.indexer_service = IndexerService(
            self.tx_indexer, self.block_indexer, self.event_bus
        )
        self.event_bus.start()
        self.indexer_service.start()

        # Handshake: full replay.go height-case analysis so consensus state,
        # block store, and app advance together (node/node.go:210).
        from cometbft_tpu.consensus.replay import Handshaker

        handshaker = Handshaker(
            self.state_store,
            state,
            self.block_store,
            genesis_doc,
            event_bus=self.event_bus,
            logger=logger,
        )
        state = handshaker.handshake(self.proxy_app)

        # Mempool + evidence + executor (node/node.go:230-248).
        self.mempool = CListMempool(config.mempool, self.proxy_app.mempool)
        self.evidence_pool = EvidencePool(
            new_db("evidence", config.base.db_backend, db_dir),
            self.state_store,
            self.block_store,
            logger,
        )
        self.block_executor = BlockExecutor(
            self.state_store,
            self.proxy_app.consensus,
            self.mempool,
            self.evidence_pool,
            self.block_store,
            self.event_bus,
            logger,
        )

        # Consensus (node/node.go:256).
        wal = WAL(config.consensus.wal_path()) if config.base.root_dir else None
        self.consensus_state = ConsensusState(
            config.consensus,
            state,
            self.block_executor,
            self.block_store,
            self.mempool,
            self.evidence_pool,
            self.event_bus,
            wal=wal,
        )
        if priv_validator is not None:
            self.consensus_state.set_priv_validator(priv_validator)

        # RPC (node/node.go:392 startRPC).
        self.rpc_server = None
        self._rpc_env = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        """node/node.go:371 OnStart (event bus/indexer already run from
        __init__, as in NewNode)."""
        self.consensus_state.start()
        rpc_laddr = self.config.rpc.laddr
        if rpc_laddr:
            host, port = _parse_laddr(rpc_laddr)
            pub = None
            if self.priv_validator is not None:
                pub = self.priv_validator.get_pub_key()
            env = Environment(
                config=self.config,
                state_store=self.state_store,
                block_store=self.block_store,
                consensus_state=self.consensus_state,
                mempool=self.mempool,
                evidence_pool=self.evidence_pool,
                event_bus=self.event_bus,
                genesis_doc=self.genesis_doc,
                priv_validator_pub_key=pub,
                node_info={"moniker": self.config.base.moniker, "network": self.genesis_doc.chain_id},
                tx_indexer=self.tx_indexer,
                block_indexer=self.block_indexer,
                proxy_app_query=self.proxy_app.query,
            )
            self._rpc_env = env
            self.rpc_server = JSONRPCServer(routes(env), host, port)
            self.rpc_server.start()

    def stop(self) -> None:
        self.consensus_state.stop()
        self.indexer_service.stop()
        self.event_bus.stop()
        if self.rpc_server:
            self.rpc_server.stop()

    @property
    def rpc_port(self) -> int:
        return self.rpc_server.port if self.rpc_server else 0


def _parse_laddr(laddr: str) -> tuple[str, int]:
    addr = laddr.split("://", 1)[-1]
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def default_new_node(config: Config, logger=None, app=None) -> Node:
    """node/setup.go:64 DefaultNewNode: files from config, kvstore app when
    none supplied (proxy_app == "kvstore")."""
    genesis = GenesisDoc.from_file(config.base.genesis_path())
    pv = FilePV.load_or_generate(
        config.base.priv_validator_key_path(),
        config.base.priv_validator_state_path(),
    )
    if app is None:
        app = KVStoreApplication()
    return Node(config, genesis, pv, LocalClientCreator(app), logger)

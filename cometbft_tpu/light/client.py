"""Light client with skipping (bisection) verification
(reference: light/client.go).

The client keeps a trusted store of verified LightBlocks. To verify a new
header it first tries one non-adjacent jump from the latest trusted block —
if fewer than 1/3 of the trusted validators persist (ErrNewValSetCantBeTrusted),
it bisects: fetch the midpoint header, verify trusted→pivot, then
pivot→target (light/client.go:706 verifySkipping). Every hop's commit is
batch-verified on the device tier. Witness cross-checking (detector.py) runs
after primary verification."""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from cometbft_tpu.light import verifier
from cometbft_tpu.sidecar import engine
from cometbft_tpu.light.provider import (
    ErrLightBlockNotFound,
    ErrNoResponse,
    Provider,
)
from cometbft_tpu.light.store import LightStore
from cometbft_tpu.types import cmttime
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.light_block import LightBlock
from cometbft_tpu.types.validation import Fraction

DEFAULT_PRUNING_SIZE = 1000
DEFAULT_MAX_CLOCK_DRIFT_NS = 10 * 10**9
DEFAULT_MAX_RETRY_ATTEMPTS = 10


@dataclass
class TrustOptions:
    """light/client.go TrustOptions: root of trust from a social checkpoint."""

    period_ns: int
    height: int
    hash: bytes

    def validate_basic(self) -> None:
        if self.period_ns <= 0:
            raise ValueError("negative or zero trusting period")
        if self.height <= 0:
            raise ValueError("negative or zero height")
        if len(self.hash) != 32:
            raise ValueError(f"expected hash size to be 32 bytes, got {len(self.hash)}")


class ErrNoWitnesses(Exception):
    pass


class Client:
    """light/client.go Client."""

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: list[Provider],
        store: LightStore,
        trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL,
        max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
        skip_verification: str = "skipping",  # or "sequential"
        gateway=None,  # LightGateway / RemoteGateway: untrusted accelerator
        gateway_proofs: bool | None = None,  # try the MMR proof path first
        bundle_source=None,  # checkpoint-bundle source (light/bundle.py)
        logger=None,
    ):
        verifier.validate_trust_level(trust_level)
        trust_options.validate_basic()
        self.chain_id = chain_id
        self.trusting_period_ns = trust_options.period_ns
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.primary = primary
        self.witnesses = list(witnesses)
        self.had_witnesses = bool(witnesses)
        self.store = store
        self.pruning_size = pruning_size
        self.mode = skip_verification
        self.gateway = gateway
        if gateway_proofs is None:
            from cometbft_tpu.light.gateway import proof_mode

            gateway_proofs = proof_mode() == "mmr"
        self.gateway_proofs = gateway_proofs
        self.bundle_source = bundle_source
        # p2p re-serving: the raw bytes of the last bundle THIS client
        # verified — handed onward unchanged via self.bundle().
        self._held_bundle: bytes | None = None
        self.logger = logger
        # Speculative-bisection counters (bench/e2e observability).
        self.speculation = {"descents": 0, "prewarmed_sigs": 0}
        # Gateway-assisted sync counters: which path served each forward
        # verification, and what a rejected/unavailable gateway cost.
        self.gateway_stats = {
            "plan_syncs": 0,
            "proof_syncs": 0,
            "proof_rejects": 0,
            "fallbacks": 0,
            "proof_bytes": 0,
            "bundle_syncs": 0,
            "bundle_rejects": 0,
            "bundle_bytes": 0,
        }
        self._init_trust(trust_options)

    # -- initialization (client.go:266-360) -----------------------------------

    def _init_trust(self, opts: TrustOptions) -> None:
        existing = self.store.light_block(opts.height)
        if existing is not None:
            if existing.hash() != opts.hash:
                raise ValueError(
                    f"stored header hash {existing.hash().hex()} does not match "
                    f"trust option hash {opts.hash.hex()} at height {opts.height}"
                )
            return
        lb = self.primary.light_block(opts.height)
        if lb.hash() != opts.hash:
            raise ValueError(
                f"primary's header hash {lb.hash().hex()} does not match trust "
                f"option hash {opts.hash.hex()} at height {opts.height}"
            )
        lb.validate_basic(self.chain_id)
        self.store.save_light_block(lb)

    # -- public API -----------------------------------------------------------

    def trusted_light_block(self, height: int) -> LightBlock | None:
        """client.go TrustedLightBlock: from the store only."""
        if height == 0:
            h = self.store.last_light_block_height()
            if h < 0:
                return None
            height = h
        return self.store.light_block(height)

    def latest_trusted(self) -> LightBlock | None:
        h = self.store.last_light_block_height()
        return self.store.light_block(h) if h >= 0 else None

    def update(self, now: Time | None = None) -> LightBlock | None:
        """client.go Update: verify the primary's latest header."""
        now = now or cmttime.now()
        latest = self.primary.light_block(0)
        trusted = self.latest_trusted()
        if trusted is not None and latest.height <= trusted.height:
            return None
        return self.verify_light_block_at_height(latest.height, now, _latest=latest)

    def verify_light_block_at_height(
        self, height: int, now: Time | None = None, _latest: LightBlock | None = None
    ) -> LightBlock:
        """client.go VerifyLightBlockAtHeight: fetch + verify + cross-check."""
        if height <= 0:
            raise ValueError("height must be positive")
        now = now or cmttime.now()
        existing = self.store.light_block(height)
        if existing is not None:
            return existing
        target = _latest if _latest is not None and _latest.height == height else (
            self.primary.light_block(height)
        )
        target.validate_basic(self.chain_id)
        self.verify_header(target, now)
        return target

    def verify_header(self, new_lb: LightBlock, now: Time) -> None:
        """client.go:525 VerifyHeader (with the provided validator set)."""
        trusted = self.latest_trusted()
        if trusted is None:
            raise RuntimeError("no trusted state to verify from")
        if new_lb.height > trusted.height:
            if self.mode == "sequential":
                trace = self._verify_sequential(trusted, new_lb, now)
            else:
                # Cold-sync ladder: checkpoint bundle (zero interactivity,
                # tried before any CMTPU_LIGHTGW_PROOF mode) -> gateway
                # proof/plan -> local bisection.  Every rung re-derives
                # the same trust check, so a refusal only costs the next
                # rung, never the decision.
                trace = None
                if self.bundle_source is not None:
                    trace = self._try_verify_bundle(trusted, new_lb, now)
                if trace is None:
                    if self.gateway is not None:
                        trace = self._verify_with_gateway(trusted, new_lb, now)
                    else:
                        trace = self._verify_skipping(trusted, new_lb, now)
            for lb in trace:
                self.store.save_light_block(lb)
        elif new_lb.height < self.store.first_light_block_height():
            self._verify_backwards(new_lb)
            self.store.save_light_block(new_lb)
        else:
            # Height within the trusted range but not stored: verify forward
            # from the closest lower trusted block.
            base = self.store.light_block_before(new_lb.height)
            if base is None:
                raise RuntimeError(f"no trusted block below {new_lb.height}")
            trace = self._verify_skipping(base, new_lb, now)
            for lb in trace:
                self.store.save_light_block(lb)
        self._detect_divergence(new_lb, now)
        self.store.prune(self.pruning_size)

    # -- verification strategies ----------------------------------------------

    def _verify_sequential(self, trusted: LightBlock, target: LightBlock, now: Time):
        """client.go:613 verifySequential: every height in order."""
        trace = []
        current = trusted
        for h in range(trusted.height + 1, target.height + 1):
            lb = target if h == target.height else self.primary.light_block(h)
            lb.validate_basic(self.chain_id)
            verifier.verify_adjacent(
                current.signed_header,
                lb.signed_header,
                lb.validator_set,
                self.trusting_period_ns,
                now,
                self.max_clock_drift_ns,
            )
            current = lb
            trace.append(lb)
        return trace

    def _verify_skipping(self, trusted: LightBlock, target: LightBlock, now: Time):
        """client.go:706 verifySkipping: bisection on ErrNewValSetCantBeTrusted.

        With speculative bisection: after each pivot fetch, the commits the
        descent will verify if the optimistic path holds (pivot, then every
        block still on the stack) are batch-prewarmed through the backend in
        one dispatch (`_speculate_descent`), so the sequential hop checks
        below run as verified-triple cache hits.  The decision logic is
        untouched — speculation only ever inserts VALID triples into the
        cache, so the trace is bit-identical to the unspeculated walk."""
        trace = []
        current = trusted
        stack = [target]
        fetches = 0
        while stack:
            candidate = stack[-1]
            try:
                verifier.verify(
                    current.signed_header,
                    current.validator_set,
                    candidate.signed_header,
                    candidate.validator_set,
                    self.trusting_period_ns,
                    now,
                    self.max_clock_drift_ns,
                    self.trust_level,
                )
            except verifier.ErrNewValSetCantBeTrusted:
                pivot = (current.height + candidate.height) // 2
                if pivot in (current.height, candidate.height):
                    raise
                fetches += 1
                if fetches > DEFAULT_MAX_RETRY_ATTEMPTS * 4:
                    raise RuntimeError("bisection: too many pivot fetches")
                lb = self.primary.light_block(pivot)
                lb.validate_basic(self.chain_id)
                stack.append(lb)
                self._speculate_descent(current, stack)
                continue
            current = candidate
            stack.pop()
            trace.append(candidate)
        return trace

    def _speculate_descent(self, current: LightBlock, stack: list) -> None:
        """Prewarm the verified-triple cache for the descent's optimistic
        hop chain: (current -> stack[-1]), (stack[-1] -> stack[-2]), ...,
        (stack[1] -> stack[0]).  One BatchVerifier call carries every hop's
        union prefix — when the process backend is the coalescing scheduler
        this also merges with other clients' concurrent descents.  Errors
        are swallowed: speculation is an accelerator, never an arbiter (the
        sequential checks in _verify_skipping re-derive every verdict)."""
        try:
            from cometbft_tpu.crypto import ed25519
            from cometbft_tpu.types import validation

            triples: list[tuple] = []
            lower = current
            for upper in reversed(stack):
                adjacent = upper.height == lower.height + 1
                triples.extend(
                    validation.speculative_verify_triples(
                        self.chain_id,
                        lower.validator_set,
                        upper.validator_set,
                        upper.signed_header.commit,
                        None if adjacent else self.trust_level,
                    )
                )
                lower = upper
            if not triples:
                return
            bv = ed25519.BatchVerifier()
            for pub, msg, sig in triples:
                try:
                    bv.add(pub, msg, sig)
                except (TypeError, ValueError):
                    continue  # non-ed25519 or malformed entry: engine's call
            if len(bv):
                self.speculation["descents"] += 1
                self.speculation["prewarmed_sigs"] += len(bv)
                # Light-class engine admission: speculative descent is
                # opportunistic prewarm, lowest on the priority ladder.
                with engine.submission_class(engine.CLASS_LIGHT):
                    bv.verify()  # cache-filters, dedups, populates _verified
        except Exception:
            pass

    # -- checkpoint-bundle cold sync (light/bundle.py; static artifact) -------

    def _try_verify_bundle(self, trusted: LightBlock, target: LightBlock,
                           now: Time):
        """Zero-interactivity cold sync off a checkpoint bundle; returns a
        trace or None (refusal -> the caller falls through to the gateway
        or bisection — a forged/stale bundle can never cause a wrong
        accept, only this fallback).

        Acceptance is Bundle.verify: our OWN trust anchor must be a
        ladder rung with our OWN stored hash, every rung must prove into
        the root the shipped peaks bag to, and the anchor light block
        must pass the standard trusting-overlap + commit check — the
        exact interactive-path predicate, so decisions stay
        bit-identical.  When the checkpoint sits below the target the
        verified anchor becomes the new trusted base and the remaining
        span rides the normal paths."""
        from cometbft_tpu.light.bundle import Bundle

        try:
            raw = self.bundle_source.bundle(target.height)
            if raw is None:
                raise ValueError("no bundle available")
            bundle = raw if isinstance(raw, Bundle) else Bundle.decode(raw)
            data = bundle.encode() if isinstance(raw, Bundle) else raw
            if bundle.anchor.height > target.height:
                raise ValueError(
                    f"bundle checkpoint {bundle.anchor.height} above "
                    f"target {target.height}"
                )
            anchor = bundle.verify(
                self.chain_id, trusted, now, self.trusting_period_ns,
                self.max_clock_drift_ns, self.trust_level,
            )
            if anchor.height == target.height and \
                    anchor.hash() != target.hash():
                # The artifact verified but names a different header than
                # our primary at the same height — a conflict the bundle
                # path must not arbitrate.  Refuse; the interactive walk
                # (and the detector) handles it against the primary.
                raise ValueError("bundle anchor disagrees with primary")
        except Exception as e:
            self.gateway_stats["bundle_rejects"] += 1
            if self.logger:
                self.logger.info(
                    "checkpoint bundle rejected; falling back",
                    module="light", err=repr(e),
                )
            return None
        self.gateway_stats["bundle_syncs"] += 1
        self.gateway_stats["bundle_bytes"] += len(data)
        self._held_bundle = data
        if anchor.height == target.height:
            # Keep OUR target object as the decision object (hash-equal).
            return [target]
        trace = [anchor]
        if self.gateway is not None:
            trace.extend(self._verify_with_gateway(anchor, target, now))
        else:
            trace.extend(self._verify_skipping(anchor, target, now))
        return trace

    def bundle(self, height: int = 0) -> bytes | None:
        """BundleSource duck type: peer-to-peer re-serving.  A synced
        client hands the exact bytes it verified onward — the next client
        re-derives everything, so relaying costs no trust."""
        if self._held_bundle is None:
            return None
        if height:
            from cometbft_tpu.light.bundle import Bundle

            if Bundle.decode(self._held_bundle).anchor.height > height:
                return None
        return self._held_bundle

    # -- gateway-assisted sync (light/gateway.py; untrusted accelerator) ------

    def _verify_with_gateway(self, trusted: LightBlock, target: LightBlock,
                             now: Time):
        """Gateway-assisted forward verification with guaranteed fallback.

        Proof mode first (when enabled): O(log n) MMR inclusion proofs
        binding the gateway's history to both our trust anchor and the
        target, plus the standard one-hop trust check of the target
        against OUR trusted validator set — rejected proofs NEVER degrade
        the decision, they only cost the fallback.
        Plan mode next: the gateway's memoized descent plan prefetches the
        pivots and prewarms the shared verified-triple cache, then the
        bit-identical local _verify_skipping walk re-verifies every hop
        (a poisoned plan block fails that walk and we fall back to the
        real primary).  Any gateway failure -> plain local bisection."""
        if self.gateway_proofs:
            try:
                return self._verify_gateway_proof(trusted, target, now)
            except Exception as e:
                self.gateway_stats["proof_rejects"] += 1
                if self.logger:
                    self.logger.info(
                        "gateway proof rejected; falling back",
                        module="light", err=repr(e),
                    )
        try:
            plan = self.gateway.sync_plan(trusted.height, target.height, now)
            by_height = {}
            for lb in plan:
                lb.validate_basic(self.chain_id)
                by_height[lb.height] = lb
            # The gateway's copy of the target must BE our target — the
            # decision object stays the one our primary handed us.
            if target.height in by_height and \
                    by_height[target.height].hash() != target.hash():
                raise ValueError("gateway plan disagrees on target header")
            old_primary = self.primary
            self.primary = _PlanProvider(self.chain_id, by_height, old_primary)
            try:
                trace = self._verify_skipping(trusted, target, now)
            finally:
                self.primary = old_primary
            self.gateway_stats["plan_syncs"] += 1
            return trace
        except Exception as e:
            self.gateway_stats["fallbacks"] += 1
            if self.logger:
                self.logger.info(
                    "gateway sync failed; local bisection",
                    module="light", err=repr(e),
                )
            return self._verify_skipping(trusted, target, now)

    def _verify_gateway_proof(self, trusted: LightBlock, target: LightBlock,
                              now: Time):
        """Cold-sync acceptance = the standard one-hop verification
        (verifier.verify: trusting-overlap against OUR trusted validator
        set, then the target's own +2/3 commit) PLUS accumulator
        membership: both our trust anchor and the target must prove into
        ONE gateway root.  Inclusion under a gateway-supplied root is
        history-binding, never trust — it can only narrow acceptance, so
        a gateway forging a self-signed history proves inclusion of
        garbage and still dies on the trusted-set overlap.  Everything is
        re-derived client-side from the response; any failure (including
        ErrNewValSetCantBeTrusted when rotation diluted the anchor's
        overlap) raises and the caller falls back to plan mode, whose
        walk bisects."""
        from cometbft_tpu.light.mmr import verify_inclusion

        if verifier.header_expired(trusted.signed_header,
                                   self.trusting_period_ns, now):
            raise verifier.ErrOldHeaderExpired(
                trusted.signed_header.header.time.add_nanos(
                    self.trusting_period_ns
                ),
                now,
            )
        resp = self.gateway.prove(target.height, anchor_height=trusted.height)
        size, root = int(resp["size"]), resp["root"]
        anchor = resp.get("anchor")
        if anchor is None:
            raise ValueError("gateway proof lacks the trust-anchor branch")
        if int(resp["target"]["index"]) != target.height - 1 or \
                int(anchor["index"]) != trusted.height - 1:
            raise ValueError("gateway proof indexes do not match heights")
        verify_inclusion(root, size, trusted.height - 1, anchor["aunts"],
                         trusted.hash())
        verify_inclusion(root, size, target.height - 1,
                         resp["target"]["aunts"], target.hash())
        verifier.verify(
            trusted.signed_header,
            trusted.validator_set,
            target.signed_header,
            target.validator_set,
            self.trusting_period_ns,
            now,
            self.max_clock_drift_ns,
            self.trust_level,
        )
        self.gateway_stats["proof_syncs"] += 1
        self.gateway_stats["proof_bytes"] += int(resp.get("bytes", 0))
        return [target]

    def _verify_backwards(self, target: LightBlock) -> None:
        """client.go backwards: hash-chain from the earliest trusted header."""
        first_h = self.store.first_light_block_height()
        current = self.store.light_block(first_h)
        for h in range(first_h - 1, target.height - 1, -1):
            lb = target if h == target.height else self.primary.light_block(h)
            lb.validate_basic(self.chain_id)
            verifier.verify_backwards(lb.header, current.header)
            current = lb

    # -- witness cross-check (detector.go) ------------------------------------

    def _detect_divergence(self, new_lb: LightBlock, now: Time) -> None:
        from cometbft_tpu.light.detector import ErrNoWitnesses, detect_divergence

        if not self.witnesses:
            if self.had_witnesses:
                # client.go errNoWitnesses: a client that HAD witnesses but
                # lost them all must not silently trust the primary forever.
                raise ErrNoWitnesses(
                    "all witnesses removed; reset the light client"
                )
            return
        detect_divergence(self, new_lb, now)

    def remove_witness(self, witness: Provider) -> None:
        self.witnesses = [w for w in self.witnesses if w is not witness]


class _PlanProvider(Provider):
    """Primary wrapper for one gateway-assisted descent: pivots named by
    the plan are served from memory, anything else (a plan that guessed
    wrong, latest-height probes) falls through to the real primary — so a
    stale or partial plan degrades to extra fetches, never to a different
    verification outcome."""

    def __init__(self, chain_id: str, blocks: dict[int, LightBlock], primary):
        self._chain_id = chain_id
        self._blocks = blocks
        self._primary = primary

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        lb = self._blocks.get(height) if height else None
        return lb if lb is not None else self._primary.light_block(height)

    def report_evidence(self, ev) -> None:
        self._primary.report_evidence(ev)


def random_witness_order(n: int) -> list[int]:
    order = list(range(n))
    for i in range(n - 1, 0, -1):
        j = secrets.randbelow(i + 1)
        order[i], order[j] = order[j], order[i]
    return order

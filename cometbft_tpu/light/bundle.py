"""Checkpoint bundles: static, cacheable light-client cold sync.

A bundle is a deterministic, self-contained byte artifact — the anchor
light block (header + commit + validator set), the MMR peaks at the
anchor, and inclusion paths for a geometric ladder of intermediate
heights (anchor, anchor/2, anchor/4, ..., 1) — built at checkpoint
intervals by light/origin.py.  "Practical Light Clients for
Committee-Based Blockchains" (arXiv:2410.03347) is the grounding: cold
sync becomes a replicable artifact rather than a conversation.

Trust model: a bundle is **history-binding, never trust**.  Acceptance
is re-derived entirely client-side — the client's OWN trust anchor must
prove into the bundle's root at a ladder height with the client's OWN
stored hash, every ladder hop must prove into that same root, and the
anchor light block must pass the standard trusting-overlap check
(`verifier.verify`: overlap against the client's trusted validator set,
then the anchor's own +2/3 commit).  A forged, stale, or truncated
bundle can only fail one of those checks and cost a fallback; it can
never move a trust decision.

Content addressing: a bundle's name IS the hex of its SHA-256.  An
artifact that cannot change without changing its name is safe to
replicate through any dumb HTTP cache, file sync, or peer — there is no
freshness or authenticity state for an intermediary to corrupt, which is
what lets the origin scale to millions of clients without answering
them.

Wire format (proto-shaped, canonical field order, see types/light_block
for the idiom):

    Bundle:    1 chain_id (string)   2 anchor (LightBlock message)
               3 mmr_size (uvarint)  4 peaks (repeated 32-byte)
               5 ladder (repeated LadderHop message)
    LadderHop: 1 height (uvarint)    2 header_hash (32-byte)
               3 aunts (repeated 32-byte)
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from dataclasses import field as dfield

from cometbft_tpu.light import verifier
from cometbft_tpu.light.mmr import bag_peaks, verify_inclusion
from cometbft_tpu.types.light_block import LightBlock
from cometbft_tpu.wire import proto as wire


class BundleError(Exception):
    """Bundle malformed / unverifiable / unavailable; clients treat any
    of these as 'fall back to the interactive paths'."""


def ladder_heights(anchor_height: int) -> list[int]:
    """The geometric ladder frozen into a bundle at `anchor_height`:
    descending halvings down to height 1 (anchor included).  O(log n)
    hops keep the witness cost bounded as history grows, and height 1 —
    the canonical social-checkpoint anchor — is always a rung."""
    if anchor_height < 1:
        raise BundleError(f"bad anchor height {anchor_height}")
    out, h = [], anchor_height
    while h >= 1:
        out.append(h)
        if h == 1:
            break
        h //= 2
    return out


@dataclass
class LadderHop:
    """One rung: header hash at `height` plus its inclusion path under
    the bundle root (leaf index = height - 1)."""

    height: int
    header_hash: bytes
    aunts: list[bytes]

    def encode(self) -> bytes:
        return (
            wire.field_varint(1, self.height, emit_default=True)
            + wire.field_bytes(2, self.header_hash)
            + b"".join(wire.field_bytes(3, a, emit_default=True)
                       for a in self.aunts)
        )

    @classmethod
    def decode(cls, data: bytes) -> "LadderHop":
        f = wire.decode_fields(data)
        return cls(
            height=wire.get_uvarint(f, 1),
            header_hash=wire.get_bytes(f, 2),
            aunts=wire.get_repeated_bytes(f, 3),
        )


@dataclass
class Bundle:
    """The checkpoint artifact; see module docstring for the format and
    trust model."""

    chain_id: str
    anchor: LightBlock
    mmr_size: int
    peaks: list[bytes]
    ladder: list[LadderHop]
    # Encode memo (immutable-after-construction, same contract as
    # LightBlock._enc): the origin re-serves one artifact thousands of
    # times and its name is a hash of these exact bytes.
    _enc: bytes | None = dfield(default=None, compare=False, repr=False)

    def encode(self) -> bytes:
        if self._enc is None:
            self._enc = (
                wire.field_string(1, self.chain_id)
                + wire.field_message(2, self.anchor.encode(), emit_empty=True)
                + wire.field_varint(3, self.mmr_size, emit_default=True)
                + b"".join(wire.field_bytes(4, p, emit_default=True)
                           for p in self.peaks)
                + b"".join(wire.field_message(5, hop.encode())
                           for hop in self.ladder)
            )
        return self._enc

    @classmethod
    def decode(cls, data: bytes) -> "Bundle":
        try:
            f = wire.decode_fields(data)
            b = cls(
                chain_id=wire.get_string(f, 1),
                anchor=LightBlock.decode(wire.get_bytes(f, 2)),
                mmr_size=wire.get_uvarint(f, 3),
                peaks=wire.get_repeated_bytes(f, 4),
                ladder=[LadderHop.decode(h)
                        for h in wire.get_repeated_bytes(f, 5)],
            )
        except Exception as e:
            raise BundleError(f"bundle undecodable: {e}") from e
        # No encode-memo from the wire input: a peer's non-canonical field
        # order must not survive as this bundle's canonical bytes (the
        # content address below would then lie about what was hashed).
        return b

    def bundle_hash(self) -> bytes:
        return hashlib.sha256(self.encode()).digest()

    @property
    def name(self) -> str:
        """Content address: the artifact's immutable, cache-safe name."""
        return self.bundle_hash().hex()

    def root(self) -> bytes:
        """The claimed history root, recomputed from the shipped peaks —
        never taken from a separate wire field."""
        return bag_peaks(list(self.peaks))

    # -- verification ------------------------------------------------------

    def self_check(self, chain_id: str | None = None) -> None:
        """Structural + internal-consistency checks that need no client
        state: anchor validity (including its own +2/3 commit via
        validate_basic's commit wiring at verify time), ladder shape, and
        every hop proving into the root the peaks bag to.  Raises
        BundleError.  Trust is NOT established here — see verify()."""
        if chain_id is not None and self.chain_id != chain_id:
            raise BundleError(
                f"bundle chain {self.chain_id!r}, want {chain_id!r}"
            )
        try:
            self.anchor.validate_basic(self.chain_id)
        except Exception as e:
            raise BundleError(f"bundle anchor invalid: {e}") from e
        if self.mmr_size != self.anchor.height:
            raise BundleError(
                f"bundle size {self.mmr_size} != anchor height "
                f"{self.anchor.height}"
            )
        if len(self.peaks) != bin(self.mmr_size).count("1") or any(
            len(p) != 32 for p in self.peaks
        ):
            raise BundleError("bundle peaks do not decompose the size")
        want = ladder_heights(self.anchor.height)
        got = [hop.height for hop in self.ladder]
        if got != want:
            raise BundleError(
                f"bundle ladder heights {got} != geometric ladder {want}"
            )
        if self.ladder[0].header_hash != self.anchor.hash():
            raise BundleError("bundle ladder top is not the anchor header")
        root = self.root()
        for hop in self.ladder:
            try:
                verify_inclusion(root, self.mmr_size, hop.height - 1,
                                 list(hop.aunts), hop.header_hash)
            except Exception as e:
                raise BundleError(
                    f"ladder hop {hop.height} fails inclusion: {e}"
                ) from e

    def ladder_hash(self, height: int) -> bytes | None:
        for hop in self.ladder:
            if hop.height == height:
                return hop.header_hash
        return None

    def verify(self, chain_id: str, trusted: LightBlock, now,
               trusting_period_ns: int, max_clock_drift_ns: int,
               trust_level) -> LightBlock:
        """Full client-side acceptance; returns the (now-trustable) anchor
        light block or raises (BundleError / verifier errors) — callers
        treat ANY raise as 'refuse the bundle, fall back'.

        Order matters: structural self-check first (cheap, no signatures),
        then the client's own anchor must appear on the ladder with the
        client's OWN stored hash (history binding), then expiry, then the
        standard trusting-overlap + commit verification — the exact check
        interactive sync runs, so decisions stay bit-identical."""
        self.self_check(chain_id)
        if self.anchor.height <= trusted.height:
            raise BundleError(
                f"bundle anchor {self.anchor.height} not above trusted "
                f"height {trusted.height}"
            )
        bound = self.ladder_hash(trusted.height)
        if bound is None:
            raise BundleError(
                f"trusted height {trusted.height} is not a ladder rung"
            )
        if bound != trusted.hash():
            raise BundleError(
                "bundle history does not contain our trust anchor"
            )
        if verifier.header_expired(trusted.signed_header,
                                   trusting_period_ns, now):
            raise verifier.ErrOldHeaderExpired(
                trusted.signed_header.header.time.add_nanos(
                    trusting_period_ns
                ),
                now,
            )
        verifier.verify(
            trusted.signed_header,
            trusted.validator_set,
            self.anchor.signed_header,
            self.anchor.validator_set,
            trusting_period_ns,
            now,
            max_clock_drift_ns,
            trust_level,
        )
        return self.anchor


def check_name(name: str, data: bytes) -> None:
    """Content-address check: `data` must hash to `name`.  Every consumer
    of a cached/replicated bundle runs this BEFORE decoding — a flipped
    bit anywhere in transit renames the artifact."""
    got = hashlib.sha256(data).hexdigest()
    if got != name:
        raise BundleError(
            f"bundle content address mismatch: named {name[:16]}…, "
            f"hashes to {got[:16]}…"
        )


# -- sources (where a client gets bundle bytes) -----------------------------


class DirBundleSource:
    """Flat-directory source: the layout `bundle export` writes and any
    dumb HTTP cache or file sync can replicate — `<name>.bundle` blobs
    plus an `index.json` mapping checkpoint heights to names."""

    def __init__(self, path: str):
        self.path = path

    def _index(self) -> dict:
        import json
        import os

        try:
            with open(os.path.join(self.path, "index.json")) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise BundleError(f"bundle index unreadable: {e}") from e

    def bundle(self, height: int = 0) -> bytes | None:
        """Bytes of the best checkpoint at or below `height` (0 = latest),
        content-address-checked.  None when the directory has nothing
        usable (the client falls back)."""
        import os

        idx = self._index()
        by_height = {int(h): n for h, n in idx.get("bundles", {}).items()}
        if not by_height:
            return None
        eligible = [h for h in by_height if height == 0 or h <= height]
        if not eligible:
            return None
        name = by_height[max(eligible)]
        try:
            with open(os.path.join(self.path, f"{name}.bundle"), "rb") as f:
                data = f.read()
        except OSError as e:
            raise BundleError(f"bundle blob unreadable: {e}") from e
        check_name(name, data)
        return data


class RemoteBundleSource:
    """Source over a node's `light_bundle` RPC route."""

    def __init__(self, rpc_client):
        self.client = rpc_client

    def bundle(self, height: int = 0) -> bytes | None:
        import base64

        res = self.client.call("light_bundle", height=str(height))
        if not res.get("enabled", False) or not res.get("bundle"):
            return None
        data = base64.b64decode(res["bundle"])
        check_name(res["name"], data)
        return data


class MemoryBundleSource:
    """In-memory source — peer-to-peer re-serving: a synced client holds
    the raw bytes it verified and hands them onward unchanged (the next
    client re-derives everything, so relaying costs no trust)."""

    def __init__(self, data: bytes | None = None):
        self._data = data

    def put(self, data: bytes) -> None:
        self._data = data

    def bundle(self, height: int = 0) -> bytes | None:
        if self._data is None:
            return None
        if height:
            b = Bundle.decode(self._data)
            if b.anchor.height > height:
                return None
        return self._data

"""BundleOrigin: the node side of checkpoint-bundle serving.

The MMR light gateway (light/gateway.py) shares verification work but
still answers every client interactively.  The origin instead FREEZES
the accumulator at checkpoint intervals (`CMTPU_BUNDLE_INTERVAL`,
default 1000 heights) into immutable, content-addressed artifacts
(light/bundle.py) that any dumb cache, file sync, or peer replicates —
the node becomes an origin, not a server.

The origin and the gateway share one chain accumulator discipline: lazy
resume from the persisted MMR state file (mmr.resume_or_new — refuses
loudly when the state disagrees with the block store), chunked
append-only catch-up (mmr.catch_up), atomic re-save.  Historical
checkpoint roots come from the SAME live accumulator via
peaks_at/prove_at — append-only means old nodes persist, so no second
tree is ever built.

Serving is bounded: the encoded-bundle store keeps the newest
`CMTPU_BUNDLE_KEEP` checkpoints (older ones are expected to live in
exported directories/caches — that is the point), and decoded Bundle
objects sit behind a small refresh-on-reput LRU (`CMTPU_BUNDLE_CACHE`).
`CMTPU_BUNDLE=0` disables the subsystem (the lazy Node accessor returns
None and the RPC route answers enabled=false).
"""

from __future__ import annotations

import json
import os
import threading

from cometbft_tpu.light import mmr as mmr_mod
from cometbft_tpu.light.bundle import (
    Bundle,
    BundleError,
    LadderHop,
    ladder_heights,
)
from cometbft_tpu.light.provider import Provider
from cometbft_tpu.types.light_block import LightBlock

_MMR_CATCHUP_CHUNK = 256


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def bundles_enabled() -> bool:
    return os.environ.get("CMTPU_BUNDLE", "1").strip().lower() not in (
        "0", "false", "off",
    )


def bundle_interval() -> int:
    return max(1, _env_int("CMTPU_BUNDLE_INTERVAL", 1000))


class BundleOrigin:
    """Builds and re-serves checkpoint bundles over a block-store-backed
    provider; see module docstring."""

    def __init__(
        self,
        chain_id: str,
        source: Provider,
        interval: int | None = None,
        keep: int | None = None,
        state_path: str | None = None,
        logger=None,
    ):
        self.chain_id = chain_id
        self.source = source
        self.interval = max(1, interval if interval is not None
                            else bundle_interval())
        self.keep = max(1, keep if keep is not None
                        else _env_int("CMTPU_BUNDLE_KEEP", 8))
        self.decoded_cache_max = max(1, _env_int("CMTPU_BUNDLE_CACHE", 4))
        self.state_path = state_path
        self.logger = logger
        self._mmr: mmr_mod.MMR | None = None
        self._mmr_lock = threading.Lock()
        # checkpoint height -> (name, encoded bytes); bounded to the
        # newest `keep` checkpoints (evict lowest height).
        self._encoded: dict[int, tuple[str, bytes]] = {}
        # checkpoint height -> decoded Bundle; insertion-ordered LRU,
        # refresh-on-reput (the verified-triple cache idiom).
        self._decoded: dict[int, Bundle] = {}
        self._store_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._stats = {
            "bundles_built": 0,
            "bundle_hits": 0,
            "bundle_fallbacks": 0,
            "bundle_bytes_served": 0,
        }

    def _bump(self, key: str, by: int = 1) -> None:
        with self._stats_lock:
            self._stats[key] += by

    # -- accumulator (shared discipline with LightGateway) -----------------

    def _fetch(self, height: int) -> LightBlock:
        try:
            lb = self.source.light_block(height)
        except Exception as e:
            raise BundleError(
                f"source has no light block {height}: {e}"
            ) from e
        lb.validate_basic(self.chain_id)
        return lb

    def _header_hash(self, height: int) -> bytes:
        fast = getattr(self.source, "header_hash", None)
        if fast is not None:
            h = fast(height)
            if h is not None:
                return h
        return self._fetch(height).hash()

    def _safe_header_hash(self, height: int) -> bytes | None:
        try:
            return self._header_hash(height)
        except Exception:
            return None

    def _ensure_mmr(self) -> int:
        """Resume/extend the accumulator to the source tip; returns the
        tip height.  Raises BundleError (pruned source, unusable state
        file — refuse loudly, never rebuild over a mismatch)."""
        base_fn = getattr(self.source, "base_height", None)
        if base_fn is not None:
            base = int(base_fn() or 1)
            if base > 1:
                raise BundleError(
                    f"source history pruned below height {base}; bundles "
                    "need the full chain from height 1"
                )
        try:
            latest = self.source.light_block(0).height
        except Exception as e:
            raise BundleError(f"source tip unavailable: {e}") from e
        with self._mmr_lock:
            if self._mmr is None:
                try:
                    self._mmr = mmr_mod.resume_or_new(
                        self.state_path, self._safe_header_hash
                    )
                except mmr_mod.MMRStateError as e:
                    raise BundleError(str(e)) from e
        grew = mmr_mod.catch_up(
            self._mmr, self._mmr_lock, latest, self._header_hash,
            chunk=_MMR_CATCHUP_CHUNK,
        )
        if grew and self.state_path:
            with self._mmr_lock:
                mmr_mod.save_state(self._mmr, self.state_path)
        return latest

    # -- checkpoints -------------------------------------------------------

    def checkpoint_height(self, tip: int, at: int = 0) -> int:
        """Largest interval boundary <= min(tip, at or tip); 0 = none."""
        ceiling = min(tip, at) if at else tip
        return (ceiling // self.interval) * self.interval

    def _build(self, boundary: int) -> tuple[str, bytes]:
        """Freeze the accumulator at `boundary` into one artifact.  Caller
        holds _store_lock (builds are per-interval-rare; serialize them)."""
        anchor = self._fetch(boundary)
        with self._mmr_lock:
            peaks = [p for _, p in self._mmr.peaks_at(boundary)]
            proofs = {
                h: self._mmr.prove_at(h - 1, boundary)
                for h in ladder_heights(boundary)
            }
        ladder = []
        for h, proof in proofs.items():
            digest = anchor.hash() if h == boundary else self._header_hash(h)
            ladder.append(LadderHop(height=h, header_hash=digest,
                                    aunts=list(proof.aunts)))
        bundle = Bundle(
            chain_id=self.chain_id,
            anchor=anchor,
            mmr_size=boundary,
            peaks=peaks,
            ladder=ladder,
        )
        data = bundle.encode()
        self._encoded[boundary] = (bundle.name, data)
        while len(self._encoded) > self.keep:
            self._encoded.pop(min(self._encoded))
        self._bump("bundles_built")
        if self.logger:
            self.logger.info(
                "checkpoint bundle built", module="light",
                height=boundary, name=bundle.name[:16],
                bytes=len(data),
            )
        return bundle.name, data

    def get_encoded(self, height: int = 0) -> tuple[str, bytes, int]:
        """(name, bytes, checkpoint_height) of the best checkpoint at or
        below `height` (0 = latest).  Raises BundleError when no
        checkpoint exists yet — callers count that as a fallback."""
        try:
            tip = self._ensure_mmr()
            boundary = self.checkpoint_height(tip, height)
            if boundary < 1:
                raise BundleError(
                    f"no checkpoint at or below height {height or tip} "
                    f"(tip {tip}, interval {self.interval})"
                )
            with self._store_lock:
                ent = self._encoded.get(boundary)
                if ent is None:
                    ent = self._build(boundary)
            name, data = ent
        except BundleError:
            self._bump("bundle_fallbacks")
            raise
        self._bump("bundle_hits")
        self._bump("bundle_bytes_served", len(data))
        return name, data, boundary

    def get(self, height: int = 0) -> Bundle:
        """Decoded-bundle LRU over get_encoded."""
        name, data, boundary = self.get_encoded(height)
        with self._store_lock:
            b = self._decoded.pop(boundary, None)
            if b is None:
                b = Bundle.decode(data)
            while len(self._decoded) >= self.decoded_cache_max:
                self._decoded.pop(next(iter(self._decoded)))
            self._decoded[boundary] = b  # refresh-on-reput
        return b

    def bundle(self, height: int = 0) -> bytes | None:
        """BundleSource duck type (light/bundle.py) — an in-process client
        syncs straight off its node's origin."""
        try:
            return self.get_encoded(height)[1]
        except BundleError:
            return None

    # -- flat-directory export (the CDN shape) -----------------------------

    def export(self, out_dir: str, at: int = 0) -> dict:
        """Write every retained checkpoint as `<name>.bundle` plus an
        `index.json` into `out_dir` — the exact layout DirBundleSource
        reads and any dumb HTTP cache replicates.  Returns the index."""
        tip = self._ensure_mmr()
        top = self.checkpoint_height(tip, at)
        if top < 1:
            raise BundleError(
                f"nothing to export: tip {tip} below interval {self.interval}"
            )
        boundaries = list(range(self.interval, top + 1, self.interval))
        boundaries = boundaries[-self.keep:]
        os.makedirs(out_dir, exist_ok=True)
        index: dict = {
            "chain_id": self.chain_id,
            "interval": self.interval,
            "bundles": {},
        }
        for b in boundaries:
            with self._store_lock:
                ent = self._encoded.get(b)
                if ent is None:
                    ent = self._build(b)
            name, data = ent
            path = os.path.join(out_dir, f"{name}.bundle")
            if not os.path.exists(path):
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
            index["bundles"][str(b)] = name
        index["latest"] = index["bundles"][str(boundaries[-1])]
        tmp = os.path.join(out_dir, f"index.json.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(index, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(out_dir, "index.json"))
        return index

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        with self._store_lock:
            out["bundles_stored"] = len(self._encoded)
        with self._mmr_lock:
            out["mmr_size"] = self._mmr.size if self._mmr is not None else 0
        out["interval"] = self.interval
        out["keep"] = self.keep
        return out

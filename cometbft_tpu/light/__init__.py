"""Light client subsystem (reference: light/ — 4,290 LoC Go).

- verifier: pure VerifyAdjacent / VerifyNonAdjacent / VerifyBackwards
- client:   bisection Client with trusted store + witness cross-check
- detector: divergence detection + LightClientAttackEvidence
- provider: Provider interface (mock / http)
- store:    DB-backed trusted LightBlock store
- proxy:    verified RPC proxy (`cometbft light` daemon)
"""

from cometbft_tpu.light.client import Client, TrustOptions
from cometbft_tpu.light.gateway import (
    GatewayError,
    LightGateway,
    RemoteGateway,
)
from cometbft_tpu.light.mmr import MMR
from cometbft_tpu.light.provider import (
    BlockStoreProvider,
    ErrLightBlockNotFound,
    ErrNoResponse,
    HTTPProvider,
    MockProvider,
    Provider,
)
from cometbft_tpu.light.store import LightStore
from cometbft_tpu.light import verifier

__all__ = [
    "Client",
    "TrustOptions",
    "Provider",
    "MockProvider",
    "HTTPProvider",
    "BlockStoreProvider",
    "LightStore",
    "LightGateway",
    "RemoteGateway",
    "GatewayError",
    "MMR",
    "verifier",
    "ErrLightBlockNotFound",
    "ErrNoResponse",
]

"""Light client subsystem (reference: light/ — 4,290 LoC Go).

- verifier: pure VerifyAdjacent / VerifyNonAdjacent / VerifyBackwards
- client:   bisection Client with trusted store + witness cross-check
- detector: divergence detection + LightClientAttackEvidence
- provider: Provider interface (mock / http)
- store:    DB-backed trusted LightBlock store
- proxy:    verified RPC proxy (`cometbft light` daemon)
- mmr:      append-only RFC-6962 accumulator over committed headers
- gateway:  node-side shared-verification sync service (interactive)
- bundle:   content-addressed checkpoint artifacts (static cold sync)
- origin:   node-side bundle builder/exporter — the CDN origin
"""

from cometbft_tpu.light.bundle import (
    Bundle,
    BundleError,
    DirBundleSource,
    MemoryBundleSource,
    RemoteBundleSource,
)
from cometbft_tpu.light.client import Client, TrustOptions
from cometbft_tpu.light.gateway import (
    GatewayError,
    LightGateway,
    RemoteGateway,
)
from cometbft_tpu.light.mmr import MMR
from cometbft_tpu.light.origin import BundleOrigin
from cometbft_tpu.light.provider import (
    BlockStoreProvider,
    ErrLightBlockNotFound,
    ErrNoResponse,
    HTTPProvider,
    MockProvider,
    Provider,
)
from cometbft_tpu.light.store import LightStore
from cometbft_tpu.light import verifier

__all__ = [
    "Client",
    "TrustOptions",
    "Provider",
    "MockProvider",
    "HTTPProvider",
    "BlockStoreProvider",
    "LightStore",
    "LightGateway",
    "RemoteGateway",
    "GatewayError",
    "Bundle",
    "BundleError",
    "BundleOrigin",
    "DirBundleSource",
    "MemoryBundleSource",
    "RemoteBundleSource",
    "MMR",
    "verifier",
    "ErrLightBlockNotFound",
    "ErrNoResponse",
]

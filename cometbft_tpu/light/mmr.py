"""Merkle Mountain Range over committed headers (light-client gateway).

Append-only accumulator in the style of "The Merkle Mountain Belt"
(arXiv:2511.13582): leaves are appended one committed header hash at a
time and the structure keeps every perfect-subtree node, so an inclusion
proof for any past leaf under the latest peak set is produced in
O(log^2 n) hashes WITHOUT rehashing the history.

RFC-6962 compatibility is exact, not "in spirit": leaves and inner nodes
use crypto/merkle's domain-separated `leaf_hash` / `inner_hash`, peaks
are bagged right-to-left, and — because bagging the binary-decomposition
peaks right-to-left is literally the `get_split_point` recursion of
crypto/merkle/tree.py — `MMR.root()` equals `hash_from_byte_slices(leaves)`
and `MMR.prove(i)` emits a standard `crypto.merkle.proof.Proof` whose
aunts are identical to `proofs_from_byte_slices(leaves)[1][i]`.  A cold
light client therefore verifies a gateway proof with the existing Proof
machinery; nothing new to trust in the verifier.
"""

from __future__ import annotations

import os
import struct

from cometbft_tpu.crypto.merkle.hash import empty_hash, inner_hash, leaf_hash
from cometbft_tpu.crypto.merkle.proof import Proof
from cometbft_tpu.crypto.merkle.tree import get_split_point

_STATE_MAGIC = b"CMTPU-MMR-v1\n"


class MMRStateError(Exception):
    """Persisted MMR state is unreadable or inconsistent with its own
    peaks or with the chain it claims to accumulate.  Callers must treat
    this as fatal for the state file — refuse loudly, never guess."""


class MMR:
    """Append-only RFC-6962 Merkle tree with O(1) amortized append.

    `_levels[k][j]` is the root of the perfect subtree over leaves
    [j * 2^k, (j+1) * 2^k) — only complete pairs are merged, so level k
    holds floor(n / 2^k) nodes and the peaks of the range are the
    right-most node of each level where the binary digit of n is set.
    """

    def __init__(self) -> None:
        self._levels: list[list[bytes]] = [[]]

    def __len__(self) -> int:
        return len(self._levels[0])

    @property
    def size(self) -> int:
        return len(self._levels[0])

    def append(self, data: bytes) -> int:
        """Append one leaf (raw bytes, e.g. a 32-byte header hash); returns
        its 0-based leaf index."""
        idx = len(self._levels[0])
        self._levels[0].append(leaf_hash(data))
        k = 0
        # Merge complete pairs upward: after appending leaf idx, level k
        # gains a node whenever 2^(k+1) divides into the filled prefix.
        while len(self._levels[k]) % 2 == 0 and len(self._levels[k]) > 0:
            if len(self._levels) == k + 1:
                self._levels.append([])
            lvl = self._levels[k]
            self._levels[k + 1].append(inner_hash(lvl[-2], lvl[-1]))
            k += 1
        return idx

    def peaks(self) -> list[tuple[int, bytes]]:
        """[(subtree_size, peak_hash)] left-to-right — the binary
        decomposition of `size`, largest peak first."""
        return self.peaks_at(self.size)

    def peaks_at(self, size: int) -> list[tuple[int, bytes]]:
        """Peaks of the PREFIX of the first `size` leaves.  Every node over
        leaves [0, size) was created when those leaves were appended and is
        never mutated afterward, so any historical peak set is still
        addressable — this is what lets one live accumulator serve
        checkpoint artifacts frozen at past sizes."""
        if not 0 <= size <= self.size:
            raise IndexError(f"prefix size {size} not in MMR of size {self.size}")
        out: list[tuple[int, bytes]] = []
        consumed = 0
        for k in range(size.bit_length() - 1, -1, -1):
            if size & (1 << k):
                out.append((1 << k, self._levels[k][consumed >> k]))
                consumed += 1 << k
        return out

    def root(self) -> bytes:
        """Peaks bagged right-to-left == RFC-6962 root of the leaf list."""
        return bag_peaks([p for _, p in self.peaks()])

    def root_at(self, size: int) -> bytes:
        """RFC-6962 root of the first `size` leaves (historical root)."""
        return bag_peaks([p for _, p in self.peaks_at(size)])

    def _range_root(self, start: int, count: int) -> bytes:
        """Root of leaves [start, start+count).  A stored node when the
        range is an aligned perfect subtree; otherwise the split-point
        recursion over stored nodes (only the right spine is imperfect,
        so this is O(log n) hashes)."""
        if count & (count - 1) == 0 and start % count == 0:
            k = count.bit_length() - 1
            return self._levels[k][start >> k]
        k = get_split_point(count)
        return inner_hash(
            self._range_root(start, k), self._range_root(start + k, count - k)
        )

    def prove(self, index: int) -> Proof:
        """Inclusion proof for leaf `index` under the current root —
        bit-identical to proofs_from_byte_slices' audit path."""
        return self.prove_at(index, self.size)

    def prove_at(self, index: int, size: int) -> Proof:
        """Inclusion proof for leaf `index` under the HISTORICAL root of
        the first `size` leaves — identical to what prove() returned when
        the accumulator was that size (append-only: old nodes persist)."""
        n = size
        if not 0 < n <= self.size:
            raise IndexError(f"prefix size {n} not in MMR of size {self.size}")
        if not 0 <= index < n:
            raise IndexError(f"leaf {index} not in MMR prefix of size {n}")
        spans: list[tuple[int, int]] = []
        start, count, i = 0, n, index
        while count > 1:
            k = get_split_point(count)
            if i < k:
                spans.append((start + k, count - k))
                count = k
            else:
                spans.append((start, k))
                start += k
                i -= k
                count -= k
        # Aunts are ordered leaf-sibling first (proof.go contract); the
        # walk above collected them root-side first.
        resolved = [self._range_root(s, c) for s, c in reversed(spans)]
        return Proof(
            total=n, index=index, leaf_hash=self._levels[0][index], aunts=resolved
        )


def bag_peaks(peaks: list[bytes]) -> bytes:
    """Bag a left-to-right peak list right-to-left into the RFC-6962 root
    of the underlying leaf list.  Pure function so wire-decoded peak sets
    (checkpoint bundles) recompute their claimed root client-side."""
    if not peaks:
        return empty_hash()
    h = peaks[-1]
    for p in reversed(peaks[:-1]):
        h = inner_hash(p, h)
    return h


# -- persistence (shared by the light gateway and the bundle origin) --------
#
# State file layout: magic, uvarint-free fixed header (size as u64), the
# peak hashes of the full prefix (the integrity anchor named by the round-20
# design), then every level-0 leaf hash.  Upper levels are NOT stored: they
# are pure hashing over level 0 (no block-store refetch), so load() rebuilds
# them and then REFUSES loudly if the rebuilt peaks disagree with the stored
# ones — a truncated/garbled file or one from a different chain can only
# fail closed.


def save_state(mmr: MMR, path: str) -> None:
    """Atomically persist (size, peaks, leaf hashes) to `path`."""
    n = mmr.size
    peaks = [p for _, p in mmr.peaks()]
    blob = (
        _STATE_MAGIC
        + struct.pack(">QB", n, len(peaks))
        + b"".join(peaks)
        + b"".join(mmr._levels[0])
    )
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_state(path: str) -> MMR:
    """Rebuild an MMR from a state file written by save_state — raises
    MMRStateError on any structural or peak mismatch (refuse loudly; the
    caller decides whether a fresh rebuild from the block store is safe)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise MMRStateError(f"mmr state unreadable: {e}") from e
    if not blob.startswith(_STATE_MAGIC):
        raise MMRStateError("mmr state: bad magic")
    off = len(_STATE_MAGIC)
    if len(blob) < off + 9:
        raise MMRStateError("mmr state: truncated header")
    n, n_peaks = struct.unpack_from(">QB", blob, off)
    off += 9
    expect = off + 32 * n_peaks + 32 * n
    if len(blob) != expect or n_peaks != bin(n).count("1"):
        raise MMRStateError(
            f"mmr state: truncated/garbled (size {n}, {n_peaks} peaks, "
            f"{len(blob)} bytes, want {expect})"
        )
    peaks = [blob[off + 32 * i: off + 32 * (i + 1)] for i in range(n_peaks)]
    off += 32 * n_peaks
    mmr = MMR()
    mmr._levels = [[blob[off + 32 * i: off + 32 * (i + 1)] for i in range(n)]]
    # Rebuild upper levels from the stored leaf hashes (pure hashing).
    k = 0
    while len(mmr._levels[k]) > 1:
        lvl = mmr._levels[k]
        mmr._levels.append(
            [inner_hash(lvl[2 * j], lvl[2 * j + 1]) for j in range(len(lvl) // 2)]
        )
        k += 1
    got = [p for _, p in mmr.peaks()]
    if got != peaks:
        raise MMRStateError("mmr state: stored peaks do not match leaf hashes")
    return mmr


def resume_or_new(path: str | None, last_leaf_hash) -> MMR:
    """Load persisted state when `path` exists, cross-checking the LAST
    persisted leaf against the live chain via `last_leaf_hash(height) ->
    32-byte header hash | None` — a state file that disagrees with the
    block store it claims to accumulate raises MMRStateError instead of
    serving proofs for someone else's history.  No file -> fresh MMR."""
    if not path or not os.path.exists(path):
        return MMR()
    mmr = load_state(path)
    if mmr.size:
        h = last_leaf_hash(mmr.size)
        if h is None:
            raise MMRStateError(
                f"mmr state has {mmr.size} leaves but the source has no "
                f"header at height {mmr.size}"
            )
        if leaf_hash(h) != mmr._levels[0][mmr.size - 1]:
            raise MMRStateError(
                f"mmr state leaf {mmr.size - 1} does not match the source "
                f"header hash at height {mmr.size}"
            )
    return mmr


def catch_up(mmr: MMR, lock, tip: int, header_hash, chunk: int = 256) -> bool:
    """Append committed header hashes (heights mmr.size+1 .. tip) through
    `header_hash(height) -> bytes`.  Fetches run in bounded chunks OUTSIDE
    the lock — a tall-chain catch-up must not stall concurrent proof
    sessions — and each append re-checks the size under the lock, so
    concurrent catch-ups (hashes are deterministic per height) never
    double-append.  Returns True when leaves were added.  Shared by the
    light gateway and the bundle origin."""
    grew = False
    while True:
        with lock:
            next_h = mmr.size + 1
        if next_h > tip:
            return grew
        hi = min(tip, next_h + chunk - 1)
        hashes = [(h, header_hash(h)) for h in range(next_h, hi + 1)]
        with lock:
            for h, digest in hashes:
                if h == mmr.size + 1:
                    mmr.append(digest)
                    grew = True


def verify_inclusion(root: bytes, total: int, index: int, aunts: list[bytes],
                     data: bytes) -> None:
    """Check that `data` is the leaf at `index` of the `total`-leaf tree
    with `root` — raises ValueError otherwise.  Pure function over the
    existing Proof verifier, for callers holding a wire-decoded proof."""
    Proof(total=total, index=index, leaf_hash=leaf_hash(data),
          aunts=list(aunts)).verify(root, data)

"""Merkle Mountain Range over committed headers (light-client gateway).

Append-only accumulator in the style of "The Merkle Mountain Belt"
(arXiv:2511.13582): leaves are appended one committed header hash at a
time and the structure keeps every perfect-subtree node, so an inclusion
proof for any past leaf under the latest peak set is produced in
O(log^2 n) hashes WITHOUT rehashing the history.

RFC-6962 compatibility is exact, not "in spirit": leaves and inner nodes
use crypto/merkle's domain-separated `leaf_hash` / `inner_hash`, peaks
are bagged right-to-left, and — because bagging the binary-decomposition
peaks right-to-left is literally the `get_split_point` recursion of
crypto/merkle/tree.py — `MMR.root()` equals `hash_from_byte_slices(leaves)`
and `MMR.prove(i)` emits a standard `crypto.merkle.proof.Proof` whose
aunts are identical to `proofs_from_byte_slices(leaves)[1][i]`.  A cold
light client therefore verifies a gateway proof with the existing Proof
machinery; nothing new to trust in the verifier.
"""

from __future__ import annotations

from cometbft_tpu.crypto.merkle.hash import empty_hash, inner_hash, leaf_hash
from cometbft_tpu.crypto.merkle.proof import Proof
from cometbft_tpu.crypto.merkle.tree import get_split_point


class MMR:
    """Append-only RFC-6962 Merkle tree with O(1) amortized append.

    `_levels[k][j]` is the root of the perfect subtree over leaves
    [j * 2^k, (j+1) * 2^k) — only complete pairs are merged, so level k
    holds floor(n / 2^k) nodes and the peaks of the range are the
    right-most node of each level where the binary digit of n is set.
    """

    def __init__(self) -> None:
        self._levels: list[list[bytes]] = [[]]

    def __len__(self) -> int:
        return len(self._levels[0])

    @property
    def size(self) -> int:
        return len(self._levels[0])

    def append(self, data: bytes) -> int:
        """Append one leaf (raw bytes, e.g. a 32-byte header hash); returns
        its 0-based leaf index."""
        idx = len(self._levels[0])
        self._levels[0].append(leaf_hash(data))
        k = 0
        # Merge complete pairs upward: after appending leaf idx, level k
        # gains a node whenever 2^(k+1) divides into the filled prefix.
        while len(self._levels[k]) % 2 == 0 and len(self._levels[k]) > 0:
            if len(self._levels) == k + 1:
                self._levels.append([])
            lvl = self._levels[k]
            self._levels[k + 1].append(inner_hash(lvl[-2], lvl[-1]))
            k += 1
        return idx

    def peaks(self) -> list[tuple[int, bytes]]:
        """[(subtree_size, peak_hash)] left-to-right — the binary
        decomposition of `size`, largest peak first."""
        n = self.size
        out: list[tuple[int, bytes]] = []
        consumed = 0
        for k in range(n.bit_length() - 1, -1, -1):
            if n & (1 << k):
                out.append((1 << k, self._levels[k][consumed >> k]))
                consumed += 1 << k
        return out

    def root(self) -> bytes:
        """Peaks bagged right-to-left == RFC-6962 root of the leaf list."""
        pk = self.peaks()
        if not pk:
            return empty_hash()
        h = pk[-1][1]
        for _, p in reversed(pk[:-1]):
            h = inner_hash(p, h)
        return h

    def _range_root(self, start: int, count: int) -> bytes:
        """Root of leaves [start, start+count).  A stored node when the
        range is an aligned perfect subtree; otherwise the split-point
        recursion over stored nodes (only the right spine is imperfect,
        so this is O(log n) hashes)."""
        if count & (count - 1) == 0 and start % count == 0:
            k = count.bit_length() - 1
            return self._levels[k][start >> k]
        k = get_split_point(count)
        return inner_hash(
            self._range_root(start, k), self._range_root(start + k, count - k)
        )

    def prove(self, index: int) -> Proof:
        """Inclusion proof for leaf `index` under the current root —
        bit-identical to proofs_from_byte_slices' audit path."""
        n = self.size
        if not 0 <= index < n:
            raise IndexError(f"leaf {index} not in MMR of size {n}")
        spans: list[tuple[int, int]] = []
        start, count, i = 0, n, index
        while count > 1:
            k = get_split_point(count)
            if i < k:
                spans.append((start + k, count - k))
                count = k
            else:
                spans.append((start, k))
                start += k
                i -= k
                count -= k
        # Aunts are ordered leaf-sibling first (proof.go contract); the
        # walk above collected them root-side first.
        resolved = [self._range_root(s, c) for s, c in reversed(spans)]
        return Proof(
            total=n, index=index, leaf_hash=self._levels[0][index], aunts=resolved
        )


def verify_inclusion(root: bytes, total: int, index: int, aunts: list[bytes],
                     data: bytes) -> None:
    """Check that `data` is the leaf at `index` of the `total`-leaf tree
    with `root` — raises ValueError otherwise.  Pure function over the
    existing Proof verifier, for callers holding a wire-decoded proof."""
    Proof(total=total, index=index, leaf_hash=leaf_hash(data),
          aunts=list(aunts)).verify(root, data)

"""Light block providers (reference: light/provider/provider.go, mock, http).

A Provider serves LightBlocks for a chain and accepts evidence reports. The
HTTP provider rides the JSON-RPC client (rpc/client/http.py) against a full
node's /commit + /validators routes."""

from __future__ import annotations

from cometbft_tpu.types.block import SignedHeader
from cometbft_tpu.types.light_block import LightBlock
from cometbft_tpu.types.validator_set import ValidatorSet


class ErrLightBlockNotFound(Exception):
    """provider.ErrLightBlockNotFound: requested height unavailable."""


class ErrNoResponse(Exception):
    """provider.ErrNoResponse: provider unreachable/misbehaving."""


class Provider:
    """light/provider/provider.go Provider interface."""

    def chain_id(self) -> str:
        raise NotImplementedError

    def light_block(self, height: int) -> LightBlock:
        """Height 0 means latest. Raises ErrLightBlockNotFound/ErrNoResponse."""
        raise NotImplementedError

    def report_evidence(self, ev) -> None:
        raise NotImplementedError


class MockProvider(Provider):
    """light/provider/mock/mock.go: canned LightBlocks by height."""

    def __init__(self, chain_id: str, light_blocks: dict[int, LightBlock]):
        self._chain_id = chain_id
        self.light_blocks = dict(light_blocks)
        self.evidences = []

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            if not self.light_blocks:
                raise ErrLightBlockNotFound("no blocks")
            height = max(self.light_blocks)
        lb = self.light_blocks.get(height)
        if lb is None:
            raise ErrLightBlockNotFound(f"no light block at height {height}")
        return lb

    def report_evidence(self, ev) -> None:
        self.evidences.append(ev)


class BlockStoreProvider(Provider):
    """Node-local provider over the block + state stores — the light
    gateway's source when it runs inside a node (no RPC round trip, no
    JSON re-encode).  Commit selection mirrors the /commit route: the tip
    serves its seen commit, history serves the canonical block commit."""

    def __init__(self, chain_id: str, block_store, state_store):
        self._chain_id = chain_id
        self._block_store = block_store
        self._state_store = state_store

    def chain_id(self) -> str:
        return self._chain_id

    def base_height(self) -> int:
        """Lowest retained height — the gateway refuses MMR proof serving
        when the store is pruned above 1 (leaf index = height - 1)."""
        return self._block_store.base()

    def header_hash(self, height: int) -> bytes | None:
        """Header hash without materializing the validator set (the MMR
        append path touches every height once)."""
        meta = self._block_store.load_block_meta(height)
        return meta.header.hash() if meta is not None else None

    def light_block(self, height: int) -> LightBlock:
        tip = self._block_store.height()
        h = height if height > 0 else tip
        meta = self._block_store.load_block_meta(h)
        if meta is None:
            raise ErrLightBlockNotFound(f"no block meta at height {h}")
        if h == tip:
            commit = self._block_store.load_seen_commit(h)
        else:
            commit = self._block_store.load_block_commit(h)
        if commit is None:
            raise ErrLightBlockNotFound(f"no commit at height {h}")
        vals = self._state_store.load_validators(h)
        if vals is None:
            raise ErrLightBlockNotFound(f"no validators at height {h}")
        return LightBlock(
            signed_header=SignedHeader(meta.header, commit), validator_set=vals
        )

    def report_evidence(self, ev) -> None:
        pass  # a node-local source has nowhere meaningful to forward this


class HTTPProvider(Provider):
    """light/provider/http/http.go: LightBlocks from a node's RPC."""

    def __init__(self, chain_id: str, rpc_client):
        self._chain_id = chain_id
        self.client = rpc_client

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        h = height if height > 0 else None
        try:
            commit_res = self.client.commit(h)
            actual_h = int(commit_res["signed_header"]["header"]["height"])
            vals = self._validators_all(actual_h)
        except (ErrLightBlockNotFound, ErrNoResponse):
            raise
        except Exception as e:
            raise ErrNoResponse(str(e)) from e
        sh = _signed_header_from_json(commit_res["signed_header"])
        lb = LightBlock(signed_header=sh, validator_set=vals)
        lb.validate_basic(self._chain_id)
        return lb

    def _validators_all(self, height: int) -> ValidatorSet:
        """Page through /validators (http.go:165)."""
        from cometbft_tpu.types.validator import Validator

        vals = []
        page = 1
        while True:
            res = self.client.validators(height, page=page, per_page=100)
            for v in res["validators"]:
                vals.append(_validator_from_json(v))
            total = int(res["total"])
            if len(vals) >= total or not res["validators"]:
                break
            page += 1
        if not vals:
            raise ErrLightBlockNotFound(f"no validators at height {height}")
        return ValidatorSet(vals)

    def report_evidence(self, ev) -> None:
        self.client.broadcast_evidence(ev)


def _validator_from_json(v: dict):
    import base64

    from cometbft_tpu.crypto.encoding import pub_key_from_type_and_bytes
    from cometbft_tpu.types.validator import Validator

    pk = v["pub_key"]
    pub = pub_key_from_type_and_bytes(pk["type"], base64.b64decode(pk["value"]))
    val = Validator.new(pub, int(v["voting_power"]))
    val.proposer_priority = int(v.get("proposer_priority", 0))
    return val


def _signed_header_from_json(d: dict) -> SignedHeader:
    from cometbft_tpu.rpc.json_codec import signed_header_from_json

    return signed_header_from_json(d)

"""Light-client gateway: shared-verification sync service (node-side).

One node serves thousands of concurrently-syncing light clients. Three
sharing layers turn N identical bisections into ~1x the work:

- **Plan cache + single-flight.** A descent plan — the pivot-height set a
  skipping verification from trusted height T to target height H will
  fetch — depends only on (T, H) and the chain, so it is memoized in an
  LRU (refresh-on-reput, same semantics as the verified-triple cache).
  Concurrent misses on the same key coalesce behind one computation.
- **Shared verified-triple cache.** The gateway verifies each plan's hop
  commits once while computing it (speculatively prefetched, exactly like
  light/client.py's descent); every client's mandatory local re-verify of
  the same hops then hits `crypto/ed25519._verified` instead of the
  device.
- **Coalescing scheduler underneath.** The gateway's own verification
  dispatches go through the process backend — the CoalescingScheduler →
  ResilientBackend chain under CMTPU_BACKEND=auto — so plan computations
  for *different* keys merge into columnar dispatches with everything
  else in flight.

Cold clients skip bisection entirely: the gateway maintains an
append-only RFC-6962 Merkle Mountain Range over committed header hashes
(light/mmr.py) and serves "header h is in the history that also contains
your trust anchor" as two O(log n) inclusion proofs under one root, plus
the target light block. The client checks both proofs AND runs the
standard one-hop trust check itself.

Trust model (detector model): the gateway is an **untrusted
accelerator**. Plan mode ships blocks the client re-validates and
re-verifies hop by hop — a poisoned plan fails the client's own
verification and the client falls back to its primary, bit-identically.
Proof mode is accepted only when the anchor inclusion, target inclusion,
and the standard one-hop verification (trusting overlap against the
client's OWN trusted validator set, then the target's +2/3 commit) all
check out client-side — inclusion under a gateway-chosen root is
history-binding, never trust, so a forged self-signed history still dies
on the overlap check, and rotation that dilutes the anchor's overlap
makes the proof path refuse (falling back to plan mode, whose walk
bisects). Any failure falls back toward full local bisection. Witness
cross-checking (detector.py) runs unchanged either way — a lying gateway
can waste a client's time, never change its decision.

Knobs: CMTPU_LIGHTGW (enable, default on), CMTPU_LIGHTGW_SESSIONS (max
concurrent sessions, default 64), CMTPU_LIGHTGW_PLAN_CACHE (plan LRU cap,
default 256), CMTPU_LIGHTGW_PROOF (mmr | plan — whether clients try the
MMR proof path first, default mmr).
"""

from __future__ import annotations

import base64
import os
import threading

from cometbft_tpu.light import verifier
from cometbft_tpu.sidecar import engine
from cometbft_tpu.light import mmr as mmr_mod
from cometbft_tpu.light.provider import Provider
from cometbft_tpu.types.light_block import LightBlock
from cometbft_tpu.types.validation import Fraction

# Generous simulation horizon: the gateway's descent simulation must not
# enforce trust expiry (that is the client's job on re-verify) — it only
# discovers which pivots the client's own walk will fetch.
_SIM_PERIOD_NS = 10 * 365 * 24 * 3600 * 10**9
_MAX_PLAN_FETCHES = 64
_MMR_CATCHUP_CHUNK = 256


class GatewayError(Exception):
    """Gateway unavailable / overloaded / asked for the impossible; clients
    treat any of these as 'fall back to local bisection'."""


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def proof_mode() -> str:
    """mmr (clients try the accumulator proof first) | plan."""
    mode = os.environ.get("CMTPU_LIGHTGW_PROOF", "mmr").strip().lower()
    return mode if mode in ("mmr", "plan") else "mmr"


class LightGateway:
    """Node-side fan-in service; see module docstring for the design."""

    def __init__(
        self,
        chain_id: str,
        source: Provider,
        max_sessions: int | None = None,
        plan_cache: int | None = None,
        trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL,
        state_path: str | None = None,
        logger=None,
    ):
        self.chain_id = chain_id
        self.source = source
        self.trust_level = trust_level
        self.state_path = state_path
        self.logger = logger
        self.max_sessions = max_sessions if max_sessions is not None else max(
            1, _env_int("CMTPU_LIGHTGW_SESSIONS", 64)
        )
        self.plan_cache_max = plan_cache if plan_cache is not None else max(
            1, _env_int("CMTPU_LIGHTGW_PLAN_CACHE", 256)
        )
        # Lazy: resumed from the persisted state file (if any) on first
        # proof — see _ensure_mmr.
        self._mmr: mmr_mod.MMR | None = None
        self._mmr_lock = threading.Lock()
        # (trusted_height, target_height) -> tuple of plan heights (sorted,
        # target included). Insertion-ordered dict as LRU, refresh-on-reput.
        self._plans: dict[tuple[int, int], tuple[int, ...]] = {}
        self._plan_lock = threading.Lock()
        # Single-flight: key -> Event the computing session sets when done.
        self._inflight: dict[tuple[int, int], threading.Event] = {}
        self._sessions = threading.Semaphore(self.max_sessions)
        self._stats_lock = threading.Lock()
        self._stats = {
            "sessions_total": 0,
            "sessions_active": 0,
            "sessions_peak": 0,
            "sessions_rejected": 0,
            "plan_hits": 0,
            "plan_misses": 0,
            "plan_waits": 0,  # single-flight riders on someone else's miss
            "proofs_served": 0,
            "proof_bytes": 0,
            "prewarmed_sigs": 0,
        }

    # -- session accounting ------------------------------------------------

    def _enter(self) -> None:
        if not self._sessions.acquire(blocking=False):
            with self._stats_lock:
                self._stats["sessions_rejected"] += 1
            raise GatewayError(
                f"gateway at max concurrent sessions ({self.max_sessions})"
            )
        with self._stats_lock:
            self._stats["sessions_total"] += 1
            self._stats["sessions_active"] += 1
            self._stats["sessions_peak"] = max(
                self._stats["sessions_peak"], self._stats["sessions_active"]
            )

    def _exit(self) -> None:
        with self._stats_lock:
            self._stats["sessions_active"] -= 1
        self._sessions.release()

    def _bump(self, key: str, by: int = 1) -> None:
        with self._stats_lock:
            self._stats[key] += by

    # -- descent plans -----------------------------------------------------

    def sync_plan(
        self, trusted_height: int, target_height: int, now=None
    ) -> list[LightBlock]:
        """Blocks the client's skipping walk from trusted_height to
        target_height will fetch (pivots + target), plan-cache/
        single-flight shared across sessions.  The gateway verified the
        hop commits while computing the plan, so the caller's mandatory
        re-verification runs against a warm verified-triple cache."""
        if not 0 < trusted_height < target_height:
            raise GatewayError(
                f"bad plan range {trusted_height} -> {target_height}"
            )
        self._enter()
        try:
            key = (trusted_height, target_height)
            heights = self._cached_plan(key)
            if heights is None:
                cached, mine, evt = self._claim(key)
                if cached is not None:
                    # Lost the race to a computation that finished between
                    # our cache miss and the claim — that IS a hit.
                    heights = cached
                    self._bump("plan_hits")
                elif mine:
                    try:
                        heights = self._compute_plan(
                            trusted_height, target_height, now
                        )
                        with self._plan_lock:
                            self._plan_put(key, heights)
                    finally:
                        with self._plan_lock:
                            self._inflight.pop(key, None)
                        evt.set()
                    self._bump("plan_misses")
                else:
                    evt.wait(timeout=120.0)
                    heights = self._cached_plan(key, count_hit=False)
                    if heights is None:  # computing session failed
                        heights = self._compute_plan(
                            trusted_height, target_height, now
                        )
                        with self._plan_lock:
                            self._plan_put(key, heights)
                        self._bump("plan_misses")
                    else:
                        self._bump("plan_waits")
            return [self._fetch(h) for h in heights]
        finally:
            self._exit()

    def _claim(self, key) -> tuple:
        """(cached_heights | None, owns_computation, event | None) — the
        plan cache is re-checked under the SAME lock that creates the
        inflight event, so a session whose computing peer finished between
        its cache miss and the claim rides the fresh cache entry instead
        of claiming ownership and recomputing the plan."""
        with self._plan_lock:
            heights = self._plans.get(key)
            if heights is not None:
                self._plan_put(key, heights)  # refresh-on-reput
                return heights, False, None
            evt = self._inflight.get(key)
            if evt is not None:
                return None, False, evt
            evt = threading.Event()
            self._inflight[key] = evt
            return None, True, evt

    def _cached_plan(self, key, count_hit: bool = True):
        with self._plan_lock:
            heights = self._plans.get(key)
            if heights is not None:
                self._plan_put(key, heights)  # refresh-on-reput
        if heights is not None and count_hit:
            self._bump("plan_hits")
        return heights

    def _plan_put(self, key, heights) -> None:
        # Caller holds _plan_lock. Same shape as ed25519._verified_put_many:
        # delete + reinsert moves the key to the young end; evict oldest
        # past the cap.
        self._plans.pop(key, None)
        while len(self._plans) >= self.plan_cache_max:
            self._plans.pop(next(iter(self._plans)))
        self._plans[key] = heights

    def _compute_plan(self, trusted_height, target_height, now) -> tuple:
        """Mirror of light/client.py _verify_skipping, recording the fetch
        set instead of a trust decision.  Runs under the simulation horizon
        (_SIM_PERIOD_NS): expiry/drift enforcement stays with the client —
        the plan only has to name the pivots the client's walk needs."""
        trusted = self._fetch(trusted_height)
        target = self._fetch(target_height)
        if now is None:
            now = target.signed_header.header.time.add_nanos(10**9)
        heights = {target_height}
        current, stack, fetches = trusted, [target], 0
        while stack:
            candidate = stack[-1]
            try:
                verifier.verify(
                    current.signed_header,
                    current.validator_set,
                    candidate.signed_header,
                    candidate.validator_set,
                    _SIM_PERIOD_NS,
                    now,
                    _SIM_PERIOD_NS,
                    self.trust_level,
                )
            except verifier.ErrNewValSetCantBeTrusted:
                pivot = (current.height + candidate.height) // 2
                if pivot in (current.height, candidate.height):
                    raise GatewayError("bisection cannot make progress")
                fetches += 1
                if fetches > _MAX_PLAN_FETCHES:
                    raise GatewayError("plan: too many pivot fetches")
                lb = self._fetch(pivot)
                heights.add(pivot)
                stack.append(lb)
                self._speculate(current, stack)
                continue
            except Exception as e:
                raise GatewayError(f"plan simulation failed: {e}") from e
            current = candidate
            stack.pop()
        return tuple(sorted(heights))

    def _speculate(self, current: LightBlock, stack: list) -> None:
        """Union-prefix prewarm of the descent's remaining hop commits in
        one BatchVerifier dispatch (identical to the client's
        _speculate_descent) — this is where concurrent sessions' work
        merges in the coalescing scheduler."""
        try:
            from cometbft_tpu.crypto import ed25519
            from cometbft_tpu.types import validation

            triples: list[tuple] = []
            lower = current
            for upper in reversed(stack):
                adjacent = upper.height == lower.height + 1
                triples.extend(
                    validation.speculative_verify_triples(
                        self.chain_id,
                        lower.validator_set,
                        upper.validator_set,
                        upper.signed_header.commit,
                        None if adjacent else self.trust_level,
                    )
                )
                lower = upper
            bv = ed25519.BatchVerifier()
            for pub, msg, sig in triples:
                try:
                    bv.add(pub, msg, sig)
                except (TypeError, ValueError):
                    continue
            if len(bv):
                self._bump("prewarmed_sigs", len(bv))
                # Light-class (lowest) admission into the continuous-
                # batching engine: prewarm rides spare device capacity and
                # relies on the starvation hatch for eventual service.
                with engine.submission_class(engine.CLASS_LIGHT):
                    bv.verify()
        except Exception:
            pass  # accelerator, never an arbiter

    def _fetch(self, height: int) -> LightBlock:
        try:
            lb = self.source.light_block(height)
        except Exception as e:
            raise GatewayError(f"source has no light block {height}: {e}") from e
        lb.validate_basic(self.chain_id)
        return lb

    # -- MMR proofs --------------------------------------------------------

    def _header_hash(self, height: int) -> bytes:
        fast = getattr(self.source, "header_hash", None)
        if fast is not None:
            h = fast(height)
            if h is not None:
                return h
        return self._fetch(height).hash()

    def _safe_header_hash(self, height: int) -> bytes | None:
        try:
            return self._header_hash(height)
        except Exception:
            return None

    def _ensure_mmr(self) -> None:
        """Resume the accumulator from the persisted state file (if any)
        and append committed header hashes up to the source's tip. Header
        hashes are immutable once committed, so append-only is safe; a
        state file that disagrees with its own peaks or with the block
        store refuses loudly (mmr.resume_or_new), it is never papered
        over with a silent rebuild.

        Leaf index = height - 1, so proof serving needs the full history
        from height 1: a pruned store (base > 1) is refused loudly up
        front instead of letting every cold client pay a doomed per-block
        fetch.  Catch-up (mmr.catch_up, shared with the bundle origin)
        fetches in bounded chunks outside the lock so a tall-chain first
        prove() never stalls concurrent proof sessions."""
        base_fn = getattr(self.source, "base_height", None)
        if base_fn is not None:
            base = int(base_fn() or 1)
            if base > 1:
                raise GatewayError(
                    f"source history pruned below height {base}; MMR proof "
                    "serving needs the full chain from height 1"
                )
        try:
            latest = self.source.light_block(0).height
        except Exception as e:
            raise GatewayError(f"source tip unavailable: {e}") from e
        with self._mmr_lock:
            if self._mmr is None:
                try:
                    self._mmr = mmr_mod.resume_or_new(
                        self.state_path, self._safe_header_hash
                    )
                except mmr_mod.MMRStateError as e:
                    raise GatewayError(str(e)) from e
        grew = mmr_mod.catch_up(
            self._mmr, self._mmr_lock, latest, self._header_hash,
            chunk=_MMR_CATCHUP_CHUNK,
        )
        if grew and self.state_path:
            with self._mmr_lock:
                mmr_mod.save_state(self._mmr, self.state_path)

    def prove(self, height: int, anchor_height: int = 0) -> dict:
        """Target light block + inclusion proofs for the target header and
        the caller's trust anchor under one MMR root.  The caller verifies
        both proofs and the target's commit itself; `bytes` is the honest
        wire size of what a cold client must transfer on this path."""
        self._enter()
        try:
            self._ensure_mmr()
            with self._mmr_lock:
                n = self._mmr.size
                if not 1 <= height <= n:
                    raise GatewayError(f"height {height} not in MMR (size {n})")
                if anchor_height and not 1 <= anchor_height <= n:
                    raise GatewayError(
                        f"anchor {anchor_height} not in MMR (size {n})"
                    )
                root = self._mmr.root()
                target_proof = self._mmr.prove(height - 1)
                anchor_proof = (
                    self._mmr.prove(anchor_height - 1) if anchor_height else None
                )
            lb = self._fetch(height)
            out = {
                "size": n,
                "root": root,
                "light_block": lb,
                "target": {
                    "index": target_proof.index,
                    "aunts": list(target_proof.aunts),
                },
            }
            if anchor_proof is not None:
                out["anchor"] = {
                    "index": anchor_proof.index,
                    "aunts": list(anchor_proof.aunts),
                }
            n_aunts = len(target_proof.aunts) + (
                len(anchor_proof.aunts) if anchor_proof else 0
            )
            out["bytes"] = len(lb.encode()) + 32 * (n_aunts + 1) + 16
            self._bump("proofs_served")
            self._bump("proof_bytes", out["bytes"])
            return out
        finally:
            self._exit()

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        with self._plan_lock:
            out["plans_cached"] = len(self._plans)
        with self._mmr_lock:
            out["mmr_size"] = self._mmr.size if self._mmr is not None else 0
        # Stable external name for the proof wire-bytes counter (the
        # internal key predates it and keeps feeding existing readers).
        out["proof_bytes_served"] = out["proof_bytes"]
        shared = out["plan_hits"] + out["plan_waits"]
        out["plan_share_ratio"] = round(
            (shared + out["plan_misses"]) / max(1, out["plan_misses"]), 3
        )
        out["max_sessions"] = self.max_sessions
        out["proof_mode"] = proof_mode()
        return out


class RemoteGateway:
    """Client-side handle over a node's gateway RPC routes (light_sync /
    light_proof / light_gateway_stats) — same duck type as LightGateway,
    so light/client.py takes either."""

    def __init__(self, rpc_client):
        self.client = rpc_client

    def sync_plan(self, trusted_height, target_height, now=None):
        res = self.client.call(
            "light_sync",
            trusted_height=str(trusted_height),
            target_height=str(target_height),
        )
        return [
            LightBlock.decode(base64.b64decode(b)) for b in res["blocks"]
        ]

    def prove(self, height, anchor_height=0):
        res = self.client.call(
            "light_proof",
            height=str(height),
            anchor_height=str(anchor_height),
        )
        out = {
            "size": int(res["size"]),
            "root": bytes.fromhex(res["root"]),
            "light_block": LightBlock.decode(
                base64.b64decode(res["light_block"])
            ),
            "target": {
                "index": int(res["target"]["index"]),
                "aunts": [bytes.fromhex(a) for a in res["target"]["aunts"]],
            },
            "bytes": int(res["proof_bytes"]),
        }
        if res.get("anchor"):
            out["anchor"] = {
                "index": int(res["anchor"]["index"]),
                "aunts": [bytes.fromhex(a) for a in res["anchor"]["aunts"]],
            }
        return out

    def stats(self) -> dict:
        return self.client.call("light_gateway_stats")

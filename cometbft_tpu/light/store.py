"""Light client trusted store (reference: light/store/db/db.go).

DB-backed store of verified LightBlocks keyed by height, with first/last
height queries and pruning."""

from __future__ import annotations

import struct

from cometbft_tpu.libs.db import DB
from cometbft_tpu.types.light_block import LightBlock

_PREFIX = b"lb/"
_SIZE_KEY = b"lb_size"


def _key(height: int) -> bytes:
    return _PREFIX + struct.pack(">q", height)


class LightStore:
    """light/store/store.go Store interface + db implementation."""

    def __init__(self, db: DB):
        self._db = db

    def save_light_block(self, lb: LightBlock) -> None:
        if lb.height <= 0:
            raise ValueError("1 <= height required")
        self._db.set(_key(lb.height), lb.encode())

    def delete_light_block(self, height: int) -> None:
        self._db.delete(_key(height))

    def light_block(self, height: int) -> LightBlock | None:
        raw = self._db.get(_key(height))
        if raw is None:
            return None
        return LightBlock.decode(raw)

    def _heights(self) -> list[int]:
        out = []
        for k, _ in self._db.iterator(_PREFIX, _PREFIX + b"\xff"):
            out.append(struct.unpack(">q", k[len(_PREFIX):])[0])
        return sorted(out)

    def last_light_block_height(self) -> int:
        hs = self._heights()
        return hs[-1] if hs else -1

    def first_light_block_height(self) -> int:
        hs = self._heights()
        return hs[0] if hs else -1

    def light_block_before(self, height: int) -> LightBlock | None:
        """Largest stored height strictly below `height` (db.go:141)."""
        best = None
        for h in self._heights():
            if h < height:
                best = h
            else:
                break
        return self.light_block(best) if best is not None else None

    def size(self) -> int:
        return len(self._heights())

    def prune(self, size: int) -> None:
        """Remove oldest blocks down to `size` entries (db.go Prune)."""
        hs = self._heights()
        excess = len(hs) - size
        for h in hs[:max(excess, 0)]:
            self.delete_light_block(h)

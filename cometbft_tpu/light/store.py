"""Light client trusted store (reference: light/store/db/db.go).

DB-backed store of verified LightBlocks keyed by height, with first/last
height queries and pruning."""

from __future__ import annotations

import os
import struct

from cometbft_tpu.libs.db import DB
from cometbft_tpu.types.light_block import LightBlock

_PREFIX = b"lb/"
_SIZE_KEY = b"lb_size"
DEFAULT_CACHE_BLOCKS = 16


def _key(height: int) -> bytes:
    return _PREFIX + struct.pack(">q", height)


class LightStore:
    """light/store/store.go Store interface + db implementation."""

    # Decoded blocks the store hands back repeatedly (latest_trusted on
    # every verify, bisection re-reads). Decoding a 4k-validator block is
    # ~100 ms of pure-python proto work, so a small write-through object
    # cache in front of the DB pays for itself on the first hit. The DB
    # stays the source of truth; the cache only ever mirrors it.  The cap
    # is an LRU with refresh-on-reput (the CMTPU_VERIFY_CACHE_MAX
    # semantics): CMTPU_LIGHT_STORE_CACHE or the cache_blocks kwarg —
    # gateway-fronted stores serving many clients want it above the
    # default 16.

    def __init__(self, db: DB, cache_blocks: int | None = None):
        if cache_blocks is None:
            try:
                cache_blocks = int(
                    os.environ.get(
                        "CMTPU_LIGHT_STORE_CACHE", str(DEFAULT_CACHE_BLOCKS)
                    )
                )
            except ValueError:
                cache_blocks = DEFAULT_CACHE_BLOCKS
        self._cache_blocks = max(1, cache_blocks)
        self._db = db
        self._cache: dict[int, LightBlock] = {}

    def _cache_put(self, lb: LightBlock) -> None:
        # Delete + reinsert moves the height to the young end; evict from
        # the old end past the cap (insertion-ordered dict as LRU).
        self._cache.pop(lb.height, None)
        while len(self._cache) >= self._cache_blocks:
            self._cache.pop(next(iter(self._cache)))
        self._cache[lb.height] = lb

    def save_light_block(self, lb: LightBlock) -> None:
        if lb.height <= 0:
            raise ValueError("1 <= height required")
        self._db.set(_key(lb.height), lb.encode())
        self._cache_put(lb)

    def delete_light_block(self, height: int) -> None:
        self._db.delete(_key(height))
        self._cache.pop(height, None)

    def light_block(self, height: int) -> LightBlock | None:
        lb = self._cache.get(height)
        if lb is not None:
            return lb
        raw = self._db.get(_key(height))
        if raw is None:
            return None
        lb = LightBlock.decode(raw)
        self._cache_put(lb)
        return lb

    def _heights(self) -> list[int]:
        out = []
        for k, _ in self._db.iterator(_PREFIX, _PREFIX + b"\xff"):
            out.append(struct.unpack(">q", k[len(_PREFIX):])[0])
        return sorted(out)

    def last_light_block_height(self) -> int:
        hs = self._heights()
        return hs[-1] if hs else -1

    def first_light_block_height(self) -> int:
        hs = self._heights()
        return hs[0] if hs else -1

    def light_block_before(self, height: int) -> LightBlock | None:
        """Largest stored height strictly below `height` (db.go:141)."""
        best = None
        for h in self._heights():
            if h < height:
                best = h
            else:
                break
        return self.light_block(best) if best is not None else None

    def size(self) -> int:
        return len(self._heights())

    def prune(self, size: int) -> None:
        """Remove oldest blocks down to `size` entries (db.go Prune)."""
        hs = self._heights()
        excess = len(hs) - size
        for h in hs[:max(excess, 0)]:
            self.delete_light_block(h)

"""Witness cross-checking + light-client attack evidence
(reference: light/detector.go).

After the primary's header verifies, every witness is asked for the same
height. A witness returning a DIFFERENT header for a verified height is
evidence of an attack on one of the two: the detector builds
LightClientAttackEvidence against the conflicting chain and reports it to
both sides, then fails verification so the caller can react."""

from __future__ import annotations

from cometbft_tpu.light import verifier
from cometbft_tpu.light.provider import ErrLightBlockNotFound, ErrNoResponse
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.evidence import LightClientAttackEvidence
from cometbft_tpu.types.light_block import LightBlock


class ErrConflictingHeaders(Exception):
    """detector.go errConflictingHeaders."""

    def __init__(self, witness_index: int, block: LightBlock):
        self.witness_index = witness_index
        self.block = block
        super().__init__(
            f"witness #{witness_index} has a different header at height "
            f"{block.height}: {block.hash().hex()}"
        )


class ErrLightClientAttack(Exception):
    pass


class ErrNoWitnesses(Exception):
    """client.go errNoWitnesses: every witness has been removed — the client
    can no longer cross-check the primary and must be reset."""


def detect_divergence(client, new_lb: LightBlock, now: Time) -> None:
    """detector.go:48 detectDivergence: compare primary header with every
    witness; on conflict, build + report evidence and raise."""
    conflicts = []
    drop = []
    for i, witness in enumerate(list(client.witnesses)):
        try:
            w_lb = witness.light_block(new_lb.height)
        except (ErrLightBlockNotFound, ErrNoResponse):
            # Unresponsive/behind witnesses are dropped (detector.go:92-100).
            drop.append(witness)
            continue
        if w_lb.hash() != new_lb.hash():
            conflicts.append((i, witness, w_lb))
    for w in drop:
        client.remove_witness(w)
    if not conflicts:
        return
    reported = 0
    for i, witness, w_lb in conflicts:
        reported += _examine_and_report(client, new_lb, witness, w_lb, now)
    if reported == 0:
        # Every conflicting witness failed verification from the common
        # trusted header — they are simply bad witnesses (already removed),
        # not proof of an attack on the primary (detector.go:105-112). But a
        # client that has lost its whole witness set can no longer detect
        # anything: surface that instead of silently trusting the primary.
        if client.had_witnesses and not client.witnesses:
            raise ErrNoWitnesses(
                "all witnesses removed; no cross-checking possible — reset "
                "the light client with fresh witnesses"
            )
        return
    raise ErrLightClientAttack(
        f"{reported} witness(es) returned verifiable conflicting headers at "
        f"height {new_lb.height}; evidence reported"
    )


def _examine_and_report(client, primary_lb, witness, witness_lb, now: Time) -> int:
    """detector.go:120-210 compareNewHeaderWithWitness + evidence build: find
    the common trusted header, VERIFY the witness's conflicting chain from it
    (examineConflictingHeaderAgainstTrace), and only then attach the
    conflicting block and report against both providers.

    Returns 1 if evidence was reported (genuine divergence), 0 if the witness
    was merely bad (its header does not verify from the common header — it is
    removed without accusing the primary)."""
    common = _find_common_block(client, witness, primary_lb.height)
    if common is not None and not _witness_chain_verifies(
        client, common, witness, witness_lb, now
    ):
        # One faulty/malicious witness must not DoS the client or file bogus
        # evidence against an honest primary: drop it and carry on.
        client.remove_witness(witness)
        return 0
    ev_against_primary = make_attack_evidence(primary_lb, common)
    ev_against_witness = make_attack_evidence(witness_lb, common)
    # The witness believes its own chain: send it evidence of the primary's
    # block, and vice versa (detector.go gatherEvidence).
    try:
        witness.report_evidence(ev_against_primary)
    except Exception:
        pass
    try:
        client.primary.report_evidence(ev_against_witness)
    except Exception:
        pass
    client.remove_witness(witness)
    return 1


def _find_common_block(client, witness, below_height: int):
    """detector.go examineConflictingHeaderAgainstTrace step 1: the latest
    block in the client's verified trace (the trusted store) that the witness
    reports with the SAME hash — the point the two chains last agreed."""
    heights = sorted(
        (h for h in client.store._heights() if h < below_height), reverse=True
    )
    for h in heights:
        trusted = client.store.light_block(h)
        if trusted is None:
            continue
        try:
            w_lb = witness.light_block(h)
        except Exception:
            continue
        if w_lb.hash() == trusted.hash():
            return trusted
    return None


def _witness_chain_verifies(client, common, witness, witness_lb, now: Time) -> bool:
    """detector.go examineConflictingHeaderAgainstTrace step 2: light-verify
    the witness's conflicting block from the common header, bisecting through
    the WITNESS's own chain when validator rotation breaks one-shot trust —
    a genuine fork signed by rotating validators must still be attributable."""
    if witness_lb.height <= common.height:
        return False
    trusted = common
    pending = [witness_lb]
    for _ in range(64):  # bisection depth bound (client.go maxVerifyIterations)
        if not pending:
            return True
        block = pending[-1]
        try:
            verifier.verify(
                trusted.signed_header,
                trusted.validator_set,
                block.signed_header,
                block.validator_set,
                client.trusting_period_ns,
                now,
                client.max_clock_drift_ns,
                client.trust_level,
            )
            trusted = block
            pending.pop()
        except verifier.ErrNewValSetCantBeTrusted:
            pivot = (trusted.height + block.height) // 2
            if pivot in (trusted.height, block.height):
                return False
            try:
                pending.append(witness.light_block(pivot))
            except Exception:
                return False
        except Exception:
            return False
    return False


def make_attack_evidence(conflicting: LightBlock, common: LightBlock | None):
    """types/evidence.go LightClientAttackEvidence from a conflicting block.
    Byzantine validators = signers of the conflicting commit that were in the
    common (trusted) validator set (types/evidence.go GetByzantineValidators,
    lunatic case)."""
    byzantine = []
    total_power = 0
    if common is not None:
        total_power = common.validator_set.total_voting_power()
        commit = conflicting.signed_header.commit
        for cs in commit.signatures:
            if not cs.for_block_flag():
                continue
            _, val = common.validator_set.get_by_address(cs.validator_address)
            if val is not None:
                byzantine.append(val)
    # Timestamp/total power anchor to the COMMON (trusted) block: the pool's
    # verifier compares them against ITS chain at evidence.Height() ==
    # common_height (evidence/verify.go:46), not the attacker's header.
    anchor = common if common is not None else conflicting
    return LightClientAttackEvidence(
        conflicting_block=conflicting,
        common_height=anchor.height,
        byzantine_validators=byzantine,
        total_voting_power=total_power,
        timestamp=anchor.signed_header.header.time,
    )

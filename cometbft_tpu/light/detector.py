"""Witness cross-checking + light-client attack evidence
(reference: light/detector.go).

After the primary's header verifies, every witness is asked for the same
height. A witness returning a DIFFERENT header for a verified height is
evidence of an attack on one of the two: the detector builds
LightClientAttackEvidence against the conflicting chain and reports it to
both sides, then fails verification so the caller can react."""

from __future__ import annotations

from cometbft_tpu.light import verifier
from cometbft_tpu.light.provider import ErrLightBlockNotFound, ErrNoResponse
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.evidence import LightClientAttackEvidence
from cometbft_tpu.types.light_block import LightBlock


class ErrConflictingHeaders(Exception):
    """detector.go errConflictingHeaders."""

    def __init__(self, witness_index: int, block: LightBlock):
        self.witness_index = witness_index
        self.block = block
        super().__init__(
            f"witness #{witness_index} has a different header at height "
            f"{block.height}: {block.hash().hex()}"
        )


class ErrLightClientAttack(Exception):
    pass


def detect_divergence(client, new_lb: LightBlock, now: Time) -> None:
    """detector.go:48 detectDivergence: compare primary header with every
    witness; on conflict, build + report evidence and raise."""
    conflicts = []
    drop = []
    for i, witness in enumerate(list(client.witnesses)):
        try:
            w_lb = witness.light_block(new_lb.height)
        except (ErrLightBlockNotFound, ErrNoResponse):
            # Unresponsive/behind witnesses are dropped (detector.go:92-100).
            drop.append(witness)
            continue
        if w_lb.hash() != new_lb.hash():
            conflicts.append((i, witness, w_lb))
    for w in drop:
        client.remove_witness(w)
    if not conflicts:
        return
    for i, witness, w_lb in conflicts:
        _examine_and_report(client, new_lb, witness, w_lb, now)
    raise ErrLightClientAttack(
        f"{len(conflicts)} witness(es) returned conflicting headers at height "
        f"{new_lb.height}; evidence reported"
    )


def _examine_and_report(client, primary_lb, witness, witness_lb, now: Time) -> None:
    """detector.go:120-210 compareNewHeaderWithWitness + evidence build: find
    the common trusted header, attach the conflicting block, and report
    against both providers."""
    common = client.store.light_block_before(primary_lb.height)
    if common is None:
        common = client.latest_trusted()
    ev_against_primary = make_attack_evidence(primary_lb, common)
    ev_against_witness = make_attack_evidence(witness_lb, common)
    # The witness believes its own chain: send it evidence of the primary's
    # block, and vice versa (detector.go gatherEvidence).
    try:
        witness.report_evidence(ev_against_primary)
    except Exception:
        pass
    try:
        client.primary.report_evidence(ev_against_witness)
    except Exception:
        pass
    client.remove_witness(witness)


def make_attack_evidence(conflicting: LightBlock, common: LightBlock | None):
    """types/evidence.go LightClientAttackEvidence from a conflicting block.
    Byzantine validators = signers of the conflicting commit that were in the
    common (trusted) validator set (types/evidence.go GetByzantineValidators,
    lunatic case)."""
    byzantine = []
    total_power = 0
    if common is not None:
        total_power = common.validator_set.total_voting_power()
        commit = conflicting.signed_header.commit
        for cs in commit.signatures:
            if not cs.for_block_flag():
                continue
            val = common.validator_set.get_by_address(cs.validator_address)
            if val is not None:
                byzantine.append(val)
    return LightClientAttackEvidence(
        conflicting_block=conflicting,
        common_height=common.height if common is not None else conflicting.height,
        byzantine_validators=byzantine,
        total_voting_power=total_power,
        timestamp=conflicting.signed_header.header.time,
    )

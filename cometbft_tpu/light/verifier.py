"""Pure stateless light-client verification (reference: light/verifier.go).

VerifyNonAdjacent is the skipping-verification core: ≥1/3 (trust level) of
the LAST trusted validator set must have signed the new header
(VerifyCommitLightTrusting), plus ≥2/3 of the new header's own validator set
(VerifyCommitLight) — both batch-verified on the device tier when the set is
large (types/validation.py routes through the ed25519 kernel)."""

from __future__ import annotations

from cometbft_tpu.types import validation
from cometbft_tpu.types.block import SignedHeader
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.validation import ErrNotEnoughVotingPowerSigned, Fraction

DEFAULT_TRUST_LEVEL = Fraction(1, 3)


class ErrOldHeaderExpired(Exception):
    def __init__(self, expired_at: Time, now: Time):
        self.expired_at = expired_at
        self.now = now
        super().__init__(f"old header has expired at {expired_at} (now: {now})")


class ErrInvalidHeader(Exception):
    pass


class ErrNewValSetCantBeTrusted(Exception):
    """< trustLevel of the trusted set signed the new header — the caller
    should bisect (light/errors.go)."""


def validate_trust_level(lvl: Fraction) -> None:
    """light/verifier.go:196-204: must be within [1/3, 1]."""
    if lvl.numerator * 3 < lvl.denominator or lvl.numerator > lvl.denominator or (
        lvl.denominator == 0
    ):
        raise ValueError(f"trustLevel must be within [1/3, 1], given {lvl}")


def header_expired(h: SignedHeader, trusting_period_ns: int, now: Time) -> bool:
    """light/verifier.go:207-210."""
    expiration = h.header.time.add_nanos(trusting_period_ns)
    return not expiration.after(now)


def _verify_new_header_and_vals(
    untrusted: SignedHeader, untrusted_vals, trusted: SignedHeader, now: Time,
    max_clock_drift_ns: int,
) -> None:
    """light/verifier.go:153-192."""
    try:
        untrusted.validate_basic(trusted.header.chain_id)
    except ValueError as e:
        raise ErrInvalidHeader(f"untrusted.ValidateBasic failed: {e}") from e
    if untrusted.header.height <= trusted.header.height:
        raise ErrInvalidHeader(
            f"expected new header height {untrusted.header.height} to be greater "
            f"than old header height {trusted.header.height}"
        )
    if not untrusted.header.time.after(trusted.header.time):
        raise ErrInvalidHeader(
            f"expected new header time {untrusted.header.time} after old header "
            f"time {trusted.header.time}"
        )
    if not untrusted.header.time.before(now.add_nanos(max_clock_drift_ns)):
        raise ErrInvalidHeader(
            f"new header has a time from the future {untrusted.header.time} "
            f"(now: {now}, drift: {max_clock_drift_ns}ns)"
        )
    if untrusted.header.validators_hash != untrusted_vals.hash():
        raise ErrInvalidHeader(
            f"expected new header validators ({untrusted.header.validators_hash.hex()}) "
            f"to match supplied set ({untrusted_vals.hash().hex()})"
        )


def verify_non_adjacent(
    trusted: SignedHeader,
    trusted_vals,
    untrusted: SignedHeader,
    untrusted_vals,
    trusting_period_ns: int,
    now: Time,
    max_clock_drift_ns: int,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """light/verifier.go:32-80 VerifyNonAdjacent."""
    if untrusted.header.height == trusted.header.height + 1:
        raise ValueError("headers must be non adjacent in height")
    if header_expired(trusted, trusting_period_ns, now):
        raise ErrOldHeaderExpired(
            trusted.header.time.add_nanos(trusting_period_ns), now
        )
    _verify_new_header_and_vals(untrusted, untrusted_vals, trusted, now, max_clock_drift_ns)
    try:
        validation.verify_commit_light_trusting(
            trusted.header.chain_id, trusted_vals, untrusted.commit, trust_level
        )
    except ErrNotEnoughVotingPowerSigned as e:
        raise ErrNewValSetCantBeTrusted(str(e)) from e
    # Always last: untrustedVals can be made huge to DoS the light client.
    try:
        validation.verify_commit_light(
            trusted.header.chain_id,
            untrusted_vals,
            untrusted.commit.block_id,
            untrusted.header.height,
            untrusted.commit,
        )
    except Exception as e:
        raise ErrInvalidHeader(str(e)) from e


def verify_adjacent(
    trusted: SignedHeader,
    untrusted: SignedHeader,
    untrusted_vals,
    trusting_period_ns: int,
    now: Time,
    max_clock_drift_ns: int,
) -> None:
    """light/verifier.go:93-133 VerifyAdjacent."""
    if untrusted.header.height != trusted.header.height + 1:
        raise ValueError("headers must be adjacent in height")
    if header_expired(trusted, trusting_period_ns, now):
        raise ErrOldHeaderExpired(
            trusted.header.time.add_nanos(trusting_period_ns), now
        )
    _verify_new_header_and_vals(untrusted, untrusted_vals, trusted, now, max_clock_drift_ns)
    if untrusted.header.validators_hash != trusted.header.next_validators_hash:
        raise ErrInvalidHeader(
            f"expected old header next validators "
            f"({trusted.header.next_validators_hash.hex()}) to match new header "
            f"validators ({untrusted.header.validators_hash.hex()})"
        )
    try:
        validation.verify_commit_light(
            trusted.header.chain_id,
            untrusted_vals,
            untrusted.commit.block_id,
            untrusted.header.height,
            untrusted.commit,
        )
    except Exception as e:
        raise ErrInvalidHeader(str(e)) from e


def verify(
    trusted: SignedHeader,
    trusted_vals,
    untrusted: SignedHeader,
    untrusted_vals,
    trusting_period_ns: int,
    now: Time,
    max_clock_drift_ns: int,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """light/verifier.go:136-151 Verify: adjacent or skipping."""
    if untrusted.header.height != trusted.header.height + 1:
        verify_non_adjacent(
            trusted, trusted_vals, untrusted, untrusted_vals,
            trusting_period_ns, now, max_clock_drift_ns, trust_level,
        )
    else:
        verify_adjacent(
            trusted, untrusted, untrusted_vals, trusting_period_ns, now,
            max_clock_drift_ns,
        )


def verify_backwards(untrusted_header, trusted_header) -> None:
    """light/verifier.go:213-245 VerifyBackwards: hash-chain one height down."""
    try:
        untrusted_header.validate_basic()
    except ValueError as e:
        raise ErrInvalidHeader(str(e)) from e
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise ErrInvalidHeader("header belongs to another chain")
    if not untrusted_header.time.before(trusted_header.time):
        raise ErrInvalidHeader(
            f"expected older header time {untrusted_header.time} to be before "
            f"newer header time {trusted_header.time}"
        )
    if trusted_header.last_block_id.hash != untrusted_header.hash():
        raise ErrInvalidHeader(
            f"older header hash {untrusted_header.hash().hex()} does not match "
            f"trusted header's last block "
            f"{trusted_header.last_block_id.hash.hex()}"
        )

"""Light-client RPC proxy (reference: light/proxy/proxy.go + routes.go).

Serves a subset of the node RPC, where every piece of returned data is
verified through the light client before being handed to the caller: headers
and commits come from the verified store, ABCI query results are checked
against the verified app hash chain (merkle proof checking is the app's
ProofOps contract)."""

from __future__ import annotations

from cometbft_tpu.rpc.jsonrpc.server import JSONRPCServer, RPCError


def _hexu(b: bytes) -> str:
    return b.hex().upper()


def proxy_routes(client, rpc_client) -> dict:
    """light/proxy/routes.go: verified subset + passthrough."""

    def status():
        latest = client.latest_trusted()
        return {
            "node_info": {"network": client.chain_id},
            "sync_info": {
                "latest_block_height": str(latest.height) if latest else "0",
                "latest_block_hash": _hexu(latest.hash()) if latest else "",
                "latest_app_hash": (
                    _hexu(latest.header.app_hash) if latest else ""
                ),
            },
            "light_client": True,
        }

    def header(height=None):
        lb = _verified(height)
        from cometbft_tpu.rpc.core import _header_json

        return {"header": _header_json(lb.header)}

    def commit(height=None):
        lb = _verified(height)
        from cometbft_tpu.rpc.core import _commit_json, _header_json

        return {
            "signed_header": {
                "header": _header_json(lb.header),
                "commit": _commit_json(lb.signed_header.commit),
            },
            "canonical": True,
        }

    def validators(height=None, page="1", per_page="30"):
        lb = _verified(height)
        from cometbft_tpu.rpc.core import _validator_json

        vals = lb.validator_set
        page_i, per_page_i = max(1, int(page)), min(100, max(1, int(per_page)))
        start = (page_i - 1) * per_page_i
        sel = vals.validators[start : start + per_page_i]
        return {
            "block_height": str(lb.height),
            "validators": [_validator_json(v) for v in sel],
            "count": str(len(sel)),
            "total": str(vals.size()),
        }

    def abci_query(path="", data="", height=None, prove=True):
        """Passthrough with height pinned to a verified header (proxy
        guarantees the response's height is verifiable; full merkle proof
        checking requires the app's proof ops)."""
        res = rpc_client.call(
            "abci_query", path=path, data=data, height=height or "0", prove=True
        )
        resp_height = int(res["response"].get("height", 0))
        if resp_height > 0:
            _verified(resp_height + 1)  # app hash for H is in header H+1
        return res

    def broadcast_tx_commit(tx=""):
        return rpc_client.call("broadcast_tx_commit", tx=tx)

    def broadcast_tx_sync(tx=""):
        return rpc_client.call("broadcast_tx_sync", tx=tx)

    def broadcast_tx_async(tx=""):
        return rpc_client.call("broadcast_tx_async", tx=tx)

    def _verified(height):
        h = int(height) if height not in (None, "") else 0
        if h == 0:
            lb = client.update()
            if lb is None:
                lb = client.latest_trusted()
        else:
            lb = client.verify_light_block_at_height(h)
        if lb is None:
            raise RPCError(-32603, f"no verified header at height {height}", None)
        return lb

    return {
        "status": status,
        "header": header,
        "commit": commit,
        "validators": validators,
        "abci_query": abci_query,
        "broadcast_tx_commit": broadcast_tx_commit,
        "broadcast_tx_sync": broadcast_tx_sync,
        "broadcast_tx_async": broadcast_tx_async,
        "health": lambda: {},
    }


class LightProxy:
    """light/proxy/proxy.go Proxy: light client + RPC server."""

    def __init__(self, client, rpc_client, host: str = "127.0.0.1", port: int = 8888):
        self.client = client
        self.rpc_client = rpc_client
        self.server = JSONRPCServer(proxy_routes(client, rpc_client), host, port)

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    @property
    def port(self) -> int:
        return self.server.port

"""Light-client RPC proxy (reference: light/proxy/proxy.go + routes.go +
light/rpc/client.go for the verifying wrappers).

Serves a subset of the node RPC, where every piece of returned data is
verified through the light client before being handed to the caller: headers
and commits come from the verified store; ABCI query results must carry
merkle ProofOps, which are checked against the app hash of the verified
header at height+1 (light/rpc/client.go:132-190)."""

from __future__ import annotations

import base64
import urllib.parse

from cometbft_tpu.rpc.jsonrpc.server import JSONRPCServer, RPCError


def _hexu(b: bytes) -> str:
    return b.hex().upper()


def default_merkle_key_path_fn(path: str, key: bytes) -> str:
    """light/rpc/client.go:72 DefaultMerkleKeyPathFn for cosmos-style
    '/store/<name>/key' paths, falling back to a single-segment key path for
    flat single-store apps (the provable kvstore)."""
    from cometbft_tpu.crypto.merkle.proof_key_path import KeyEncoding, KeyPath

    kp = KeyPath()
    parts = path.split("/")
    if len(parts) >= 3 and parts[1] == "store" and parts[-1] == "key":
        kp = kp.append_key("/".join(parts[2:-1]).encode(), KeyEncoding.URL)
    return str(kp.append_key(key, KeyEncoding.HEX))


def proxy_routes(client, rpc_client, key_path_fn=default_merkle_key_path_fn) -> dict:
    """light/proxy/routes.go: verified subset + passthrough."""

    def status():
        latest = client.latest_trusted()
        return {
            "node_info": {"network": client.chain_id},
            "sync_info": {
                "latest_block_height": str(latest.height) if latest else "0",
                "latest_block_hash": _hexu(latest.hash()) if latest else "",
                "latest_app_hash": (
                    _hexu(latest.header.app_hash) if latest else ""
                ),
            },
            "light_client": True,
        }

    def header(height=None):
        lb = _verified(height)
        from cometbft_tpu.rpc.core import _header_json

        return {"header": _header_json(lb.header)}

    def commit(height=None):
        lb = _verified(height)
        from cometbft_tpu.rpc.core import _commit_json, _header_json

        return {
            "signed_header": {
                "header": _header_json(lb.header),
                "commit": _commit_json(lb.signed_header.commit),
            },
            "canonical": True,
        }

    def validators(height=None, page="1", per_page="30"):
        lb = _verified(height)
        from cometbft_tpu.rpc.core import _validator_json

        vals = lb.validator_set
        page_i, per_page_i = max(1, int(page)), min(100, max(1, int(per_page)))
        start = (page_i - 1) * per_page_i
        sel = vals.validators[start : start + per_page_i]
        return {
            "block_height": str(lb.height),
            "validators": [_validator_json(v) for v in sel],
            "count": str(len(sel)),
            "total": str(vals.size()),
        }

    def abci_query(path="", data="", height=None, prove=True):
        """light/rpc/client.go:132 ABCIQueryWithOptions: force prove,
        require proof ops, and verify the value (or absence) proof against
        the app hash of the verified header at resp.height + 1."""
        res = rpc_client.call(
            "abci_query", path=path, data=data, height=height or "0", prove=True
        )
        resp = res.get("response", {})
        if int(resp.get("code", 0)) != 0:
            raise RPCError(-32603, f"err response code: {resp.get('code')}", None)
        key = base64.b64decode(resp.get("key") or "")
        if not key:
            raise RPCError(-32603, "empty key", None)
        ops_json = (resp.get("proofOps") or {}).get("ops") or []
        if not ops_json:
            # Also the shape of a verified-absence gap: SimpleMap value ops
            # cannot prove non-membership (the reference's DefaultProofRuntime
            # has the same limit — absence needs range/IAVL ops), so an
            # absent key and a proof-stripping node are indistinguishable
            # here and both must be rejected.
            raise RPCError(
                -32603,
                "no proof ops (value-op apps cannot prove absence; query an "
                "existing key or use an app with range proofs)",
                None,
            )
        resp_height = int(resp.get("height", 0))
        if resp_height <= 0:
            raise RPCError(-32603, "negative or zero height", None)
        # App hash for H is in header H+1, which on a live chain lands one
        # block interval after the query's height: retry briefly
        # (light/rpc/client.go's updateLightClientIfNeededTo equivalent).
        import time as _time

        lb = None
        deadline = _time.monotonic() + 5.0
        while True:
            try:
                lb = _verified(resp_height + 1)
                break
            except Exception:
                if _time.monotonic() >= deadline:
                    raise
                _time.sleep(0.1)

        from cometbft_tpu.crypto.merkle import default_proof_runtime
        from cometbft_tpu.crypto.merkle.proof_op import ProofOp, ProofOps

        ops = ProofOps(
            ops=[
                ProofOp(
                    type=o["type"],
                    key=base64.b64decode(o.get("key") or ""),
                    data=base64.b64decode(o.get("data") or ""),
                )
                for o in ops_json
            ]
        )
        value = base64.b64decode(resp.get("value") or "")
        prt = default_proof_runtime()
        try:
            if value:
                prt.verify_value(
                    ops, lb.header.app_hash, key_path_fn(path, key), value
                )
            else:
                prt.verify_absence(ops, lb.header.app_hash, key_path_fn(path, key))
        except Exception as e:
            raise RPCError(-32603, f"proof verification failed: {e}", None)
        return res

    def broadcast_tx_commit(tx=""):
        return rpc_client.call("broadcast_tx_commit", tx=tx)

    def broadcast_tx_sync(tx=""):
        return rpc_client.call("broadcast_tx_sync", tx=tx)

    def broadcast_tx_async(tx=""):
        return rpc_client.call("broadcast_tx_async", tx=tx)

    def _verified(height):
        h = int(height) if height not in (None, "") else 0
        if h == 0:
            lb = client.update()
            if lb is None:
                lb = client.latest_trusted()
        else:
            lb = client.verify_light_block_at_height(h)
        if lb is None:
            raise RPCError(-32603, f"no verified header at height {height}", None)
        return lb

    return {
        "status": status,
        "header": header,
        "commit": commit,
        "validators": validators,
        "abci_query": abci_query,
        "broadcast_tx_commit": broadcast_tx_commit,
        "broadcast_tx_sync": broadcast_tx_sync,
        "broadcast_tx_async": broadcast_tx_async,
        "health": lambda: {},
    }


class LightProxy:
    """light/proxy/proxy.go Proxy: light client + RPC server."""

    def __init__(self, client, rpc_client, host: str = "127.0.0.1", port: int = 8888):
        self.client = client
        self.rpc_client = rpc_client
        self.server = JSONRPCServer(proxy_routes(client, rpc_client), host, port)

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    @property
    def port(self) -> int:
        return self.server.port

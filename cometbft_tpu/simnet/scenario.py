"""Deterministic 50–200 node consensus scenarios on one SimClock.

The harness runs N full ``ConsensusState`` machines (real executor, real
ABCI kvstore app, real mempool, real crypto — only the WAL is nil and the
wire is virtual) **single-threaded**: nothing calls ``cs.start()``.
Instead of receive/tock/watchdog threads, every external stimulus is a
SimClock event —

* a :class:`SimTicker` turns ``schedule_timeout`` into a clock event whose
  callback enqueues the tock and synchronously drains that node's queue;
* each node's ``set_broadcast`` fan-out schedules per-peer deliveries at
  ``now + latency(zone_i, zone_j) + jitter`` (seeded), subject to drop and
  the scripted partition state;
* partitions, heals, churn, tx load, and the per-node stall-watchdog
  check are themselves clock events scheduled from the spec.

Because the driver pops events in ``(due, seq)`` order from one heap and
``cmttime.now()`` is virtualized onto the same clock, two runs of the
same spec produce *bit-identical* blocks — same timestamps, same votes,
same hashes — while wall time is only the Python/crypto work, typically
an order of magnitude less than the simulated chain time.

Vote-batch modeling: with ``vote_window_ms`` set, vote deliveries are
quantized up to window boundaries and delivered per (node, window)
bucket, pre-verified in one ``_prebatch_vote_signatures`` dispatch —
the sim-side analogue of ``CMTPU_VOTE_BATCH_WINDOW_MS``.

Non-goals (see ops/DESIGN.md round 13): no device-call simulation —
verification backends run for real; no blocksync in-harness, so churned
nodes that miss blocks are reported as stragglers rather than caught up.
"""

from __future__ import annotations

import math
import queue
import random
import time as _time

from cometbft_tpu.consensus.ticker import TimeoutTicker
from cometbft_tpu.simnet.clock import SimClock

GENESIS_SECONDS = 1_700_000_000


class SimTicker(TimeoutTicker):
    """Single-pending-timeout ticker whose tocks go straight to a sink
    callback (no tock queue, no pump thread)."""

    def __init__(self, clock: SimClock, sink):
        super().__init__(clock=clock)
        self._sink = sink

    def _fire(self, ti) -> None:
        self._sink(ti)


def default_spec(**overrides) -> dict:
    """Baseline WAN scenario; every field overridable (generator/manifest)."""
    spec = {
        "seed": 0,
        "validators": 50,
        "blocks": 10,  # target committed height
        "zones": 4,
        "zone_latency_ms": None,  # NxN (zones); synthesized from seed if None
        "jitter_ms": 10.0,
        "drop_p": 0.0,
        "vote_window_ms": 0.0,
        # WAN-ish consensus timeouts (seconds, simulated).
        "timeout_propose": 3.0,
        "timeout_propose_delta": 0.5,
        "timeout_prevote": 1.0,
        "timeout_prevote_delta": 0.5,
        "timeout_precommit": 1.0,
        "timeout_precommit_delta": 0.5,
        # WAN-realistic commit dwell (Cosmos Hub mainnet ships 5s). Sim
        # dead time costs no wall time — the clock jumps it — so a
        # realistic dwell is free and keeps block cadence honest.
        "timeout_commit": 5.0,
        "partitions": [],  # [{"at_s", "heal_s", "fraction"}]
        "churn": [],  # [{"at_s", "down_s", "nodes"}] nodes = count, never node 0
        "tx_interval_s": 0.0,  # 0 = no load
        "txs_per_interval": 1,
        # Byzantine actor windows (simnet/byzantine.py): [{"role":
        # equivocator|withholder|flooder, "node", "from_s", "until_s",
        # ...role knobs}]. Node 0 is never byzantine (hash reference).
        "byzantine": [],
        # In-sim blocksync late-joins: [{"node", "at_s"}] — the node is a
        # genesis validator that stays dark until at_s, then catches up
        # through real blocksync wire frames over the sim links and
        # switches into consensus. Never node 0.
        "joins": [],
        # Background vote/evidence gossip tick (the reactor
        # gossipVotesRoutine / evidence broadcast analogue): each tick a
        # node relays to one rotating peer the votes that peer provably
        # lacks at its own current round, plus any pending evidence.
        # 0 disables (pre-round-19 behavior).
        "gossip_interval_s": 1.0,
        "max_sim_s": 600.0,
        "watchdog_poll_s": 2.0,
        # Lower than the production default (10): sim recovery from a
        # heal should take round-budgets, not minutes of sim time.
        "stall_factor": 4.0,
    }
    unknown = set(overrides) - set(spec)
    if unknown:
        raise ValueError(f"unknown simnet spec keys {sorted(unknown)}")
    spec.update(overrides)
    return spec


def _synth_zone_latency(rng: random.Random, zones: int) -> list[list[float]]:
    """Symmetric zone-pair base latency (ms): LAN-ish intra, WAN inter."""
    m = [[0.0] * zones for _ in range(zones)]
    for a in range(zones):
        m[a][a] = rng.uniform(2.0, 15.0)
        for b in range(a + 1, zones):
            m[a][b] = m[b][a] = rng.uniform(40.0, 150.0)
    return m


class _SimNode:
    __slots__ = (
        "index", "name", "cs", "mempool", "app", "online",
        # Round 19: handles the blocksync late-join path needs to rebuild
        # a caught-up ConsensusState, plus the node's evidence pool.
        "cfg", "pv", "evpool", "executor", "block_store", "state_store",
    )

    def __init__(self, index, name, cs, mempool, app):
        self.index = index
        self.name = name
        self.cs = cs
        self.mempool = mempool
        self.app = app
        self.online = True
        self.cfg = None
        self.pv = None
        self.evpool = None
        self.executor = None
        self.block_store = None
        self.state_store = None


class Scenario:
    """One seeded run. Build with a spec dict (see default_spec), then
    :meth:`run` to completion; ``report`` holds the result + full schedule."""

    def __init__(self, spec: dict):
        self.spec = dict(spec)
        self.seed = int(spec["seed"])
        self.rng = random.Random(f"simnet:{self.seed}")
        self.clock = SimClock()
        self.n = int(spec["validators"])
        self.nodes: list[_SimNode] = []
        self._groups: list[set[int]] | None = None
        self._vote_buckets: dict[tuple[int, int], list] = {}
        # FIFO clamp per directed link (i, j): jitter may stretch a
        # stream, never reorder it — parts must not overtake their
        # proposal (a part arriving first is dropped, as in state.go).
        self._fifo: dict[tuple[int, int], float] = {}
        self._tx_counter = 0
        self.counters = {
            "deliveries": 0,
            "dropped": 0,
            "partitioned": 0,
            "offline_skips": 0,
            "vote_dispatches": 0,
            "stall_fires": 0,
            "catchups": 0,
            "conflicts_reported": 0,
            "gossip_votes": 0,
            "gossip_evidence": 0,
            "evidence_rejects": 0,
            "joins": 0,
            "join_completions": 0,
            "blocksync_served": 0,
        }
        self.schedule = {}  # realized schedule, filled by _build/_script
        self.byz_actors: list = []
        self._evidence_detections: list[dict] = []
        self._commit_times: list[list] = []  # [height, sim_s] at node 0
        # Gossip relay bookkeeping: per (i, j) the (height, sent-keys set)
        # of votes already relayed, and a per-node rotor for peer choice.
        self._gossip_sent: dict[tuple[int, int], tuple[int, set]] = {}
        self._gossip_rotor: dict[int, int] = {}
        # Blocksync late-join state per joining node index.
        self._join_nodes: set[int] = {
            int(j["node"]) for j in spec.get("joins", [])
        }
        self._join_state: dict[int, dict] = {}
        self._join_reports: list[dict] = []

    # -- assembly -------------------------------------------------------------

    def _build(self) -> None:
        from cometbft_tpu.abci.example.kvstore import KVStoreApplication
        from cometbft_tpu.config import test_config
        from cometbft_tpu.consensus.state import ConsensusState
        from cometbft_tpu.crypto import ed25519
        from cometbft_tpu.evidence.pool import EvidencePool
        from cometbft_tpu.mempool import CListMempool
        from cometbft_tpu.proxy import AppConns, local_client_creator
        from cometbft_tpu.state import BlockExecutor, StateStore, make_genesis_state
        from cometbft_tpu.store import BlockStore
        from cometbft_tpu.libs.db import MemDB
        from cometbft_tpu.types import GenesisDoc, GenesisValidator, Time
        from cometbft_tpu.types.priv_validator import MockPV

        spec = self.spec
        pvs = [
            MockPV(
                priv_key=ed25519.gen_priv_key_from_secret(
                    f"simnet:{self.seed}:val{i}".encode()
                )
            )
            for i in range(self.n)
        ]
        gen_vals = [
            GenesisValidator(pv.address(), pv.get_pub_key(), 10, f"sim{i}")
            for i, pv in enumerate(pvs)
        ]
        gen = GenesisDoc(
            chain_id=f"simnet-{self.seed}",
            genesis_time=Time(GENESIS_SECONDS, 0),
            validators=gen_vals,
        )
        gen.validate_and_complete()

        zones = int(spec["zones"])
        self.zone_of = [i % zones for i in range(self.n)]
        zl = spec["zone_latency_ms"] or _synth_zone_latency(self.rng, zones)
        self.zone_latency_ms = [[float(x) for x in row] for row in zl]
        self.jitter_s = float(spec["jitter_ms"]) / 1000.0
        self.drop_p = float(spec["drop_p"])
        self.vote_window_s = float(spec["vote_window_ms"]) / 1000.0

        for i, pv in enumerate(pvs):
            state = make_genesis_state(gen)
            app = KVStoreApplication()
            conns = AppConns(local_client_creator(app))
            conns.start()
            cfg = test_config()
            for k in (
                "timeout_propose", "timeout_propose_delta",
                "timeout_prevote", "timeout_prevote_delta",
                "timeout_precommit", "timeout_precommit_delta",
                "timeout_commit",
            ):
                setattr(cfg.consensus, k, float(spec[k]))
            cfg.consensus.skip_timeout_commit = False
            mempool = CListMempool(cfg.mempool, conns.mempool)
            state_store = StateStore(MemDB())
            block_store = BlockStore(MemDB())
            state_store.save(state)
            evpool = EvidencePool(MemDB(), state_store, block_store)
            executor = BlockExecutor(
                state_store, conns.consensus, mempool, evpool, block_store
            )
            sink = self._make_tock_sink(i)
            ticker = SimTicker(self.clock, sink)
            cs = ConsensusState(
                cfg.consensus,
                state,
                executor,
                block_store,
                mempool,
                evpool=evpool,
                wal=None,
                ticker=ticker,
                clock=self.clock,
                name=f"sim{i}",
            )
            cs.set_priv_validator(pv)
            cs._stall_factor = float(spec["stall_factor"])
            cs.set_broadcast(self._make_broadcast(i))
            node = _SimNode(i, f"sim{i}", cs, mempool, app)
            node.cfg = cfg
            node.pv = pv
            node.evpool = evpool
            node.executor = executor
            node.block_store = block_store
            node.state_store = state_store
            self._tap_conflict_reports(i, evpool)
            cs.set_on_stall(self._make_on_stall(node))
            self.nodes.append(node)

        # Byzantine actors wrap the node's OWN broadcast (same send
        # surface, no consensus-code forks — see simnet/byzantine.py).
        from cometbft_tpu.simnet.byzantine import make_actor

        for entry in spec["byzantine"]:
            actor = make_actor(self, entry)
            if actor.node_index in self._join_nodes:
                raise ValueError(
                    "a byzantine node cannot also be a late-joiner"
                )
            bnode = self.nodes[actor.node_index]
            bnode.cs.set_broadcast(actor.wrap(bnode.cs._broadcast))
            self.byz_actors.append(actor)
        for j in sorted(self._join_nodes):
            if not (1 <= j < self.n):
                raise ValueError(
                    f"join node must be in 1..{self.n - 1} "
                    "(node 0 is the hash-reference node)"
                )
            self.nodes[j].online = False

        self.schedule = {
            "seed": self.seed,
            "validators": self.n,
            "zones": zones,
            "zone_of": list(self.zone_of),
            "zone_latency_ms": self.zone_latency_ms,
            "jitter_ms": float(spec["jitter_ms"]),
            "drop_p": self.drop_p,
            "vote_window_ms": float(spec["vote_window_ms"]),
            "timeouts": {
                k: float(spec[k])
                for k in (
                    "timeout_propose", "timeout_propose_delta",
                    "timeout_prevote", "timeout_prevote_delta",
                    "timeout_precommit", "timeout_precommit_delta",
                    "timeout_commit",
                )
            },
            "partitions": [],
            "churn": [],
            "byzantine": [a.resolved() for a in self.byz_actors],
            "joins": [
                {"node": int(j["node"]), "at_s": float(j["at_s"])}
                for j in spec["joins"]
            ],
            "gossip_interval_s": float(spec["gossip_interval_s"]),
        }

    def _tap_conflict_reports(self, i: int, evpool) -> None:
        """Timestamp every conflicting-vote report (the evidence DETECTION
        moment) so the report can bound detection→commitment latency."""
        orig = evpool.report_conflicting_votes

        def report(vote_a, vote_b):
            self.counters["conflicts_reported"] += 1
            self._evidence_detections.append({
                "node": i,
                "height": vote_a.height,
                "round": vote_a.round,
                "validator_index": vote_a.validator_index,
                "sim_s": round(self.clock.now(), 6),
            })
            orig(vote_a, vote_b)

        evpool.report_conflicting_votes = report

    # -- event plumbing -------------------------------------------------------

    def _make_tock_sink(self, i: int):
        def sink(ti):
            node = self.nodes[i]
            node.cs._queue.put(("timeout", ti, ""))
            self._drain(node)
        return sink

    def _make_on_stall(self, node: _SimNode):
        """Reactor-gossip analogue: a stalled node re-announces its OWN
        contribution to the current round — proposal + parts (if it holds
        the complete block) and its own votes. A quorum-wide stall thus
        re-announces the whole vote set exactly once collectively (each
        voter re-sends itself), instead of every node flooding everything
        it knows; cross-height gaps are the catchup path's job. Everything
        is idempotent at the receivers, mirroring the real reactor's
        NewRoundStep/maj23 stall re-broadcast."""
        from cometbft_tpu.consensus.messages import (
            BlockPartMessage, ProposalMessage, VoteMessage,
        )

        def on_stall():
            self.counters["stall_fires"] += 1
            cs = node.cs
            rs = cs.rs
            bc = cs._broadcast
            if bc is None or not node.online:
                return
            if rs.proposal is not None:
                bc(ProposalMessage(rs.proposal))
            parts = rs.proposal_block_parts
            if parts is not None and parts.is_complete():
                for k in range(parts.total):
                    bc(BlockPartMessage(rs.height, rs.round, parts.get_part(k)))
            addr = cs.priv_validator_pub_key.address() if cs.priv_validator_pub_key else None
            if addr is None:
                return
            for vs in (rs.votes.prevotes(rs.round), rs.votes.precommits(rs.round)):
                if vs is None:
                    continue
                own = vs.get_by_address(addr)
                if own is not None:
                    bc(VoteMessage(own))
        return on_stall

    # Heights served per catchup fire: one height per fire cannot close a
    # growing gap (the chain advances ~1 height per commit dwell while the
    # watchdog fires every poll × stall budget) — a blocksync late-joiner
    # handed off one block behind tip would trail forever. A span bounds
    # the burst while converging in O(gap / span) fires.
    _CATCHUP_SPAN = 20

    def _catchup(self, node: _SimNode) -> None:
        """Consensus-reactor catchup-gossip analogue: a peer that already
        committed this node's current height re-sends, for a span of the
        node's missing heights, each height's precommits (from its seen
        commit) and block parts. The link FIFO keeps the span ordered, so
        the node commits height h between the h and h+1 deliveries —
        exactly the lagging-peer flow of reactor.go, span-batched."""
        from cometbft_tpu.consensus.messages import BlockPartMessage, VoteMessage
        from cometbft_tpu.types.vote import PRECOMMIT_TYPE, Vote

        h = node.cs.rs.height
        donor = next(
            (d for d in self.nodes
             if d.online and d.index != node.index and d.cs.rs.height > h
             and self._reachable(d.index, node.index)),
            None,
        )
        if donor is None:
            return
        served = False
        for hh in range(h, min(donor.cs.rs.height, h + self._CATCHUP_SPAN)):
            seen = donor.cs.block_store.load_seen_commit(hh)
            block = donor.cs.block_store.load_block(hh)
            if seen is None or block is None:
                break
            served = True
            msgs = []
            for idx, sig in enumerate(seen.signatures):
                if sig.is_absent():
                    continue
                msgs.append(VoteMessage(Vote(
                    type=PRECOMMIT_TYPE,
                    height=seen.height,
                    round=seen.round,
                    block_id=sig.block_id(seen.block_id),
                    timestamp=sig.timestamp,
                    validator_address=sig.validator_address,
                    validator_index=idx,
                    signature=sig.signature,
                )))
            parts = block.make_part_set()
            for k in range(parts.total):
                msgs.append(BlockPartMessage(hh, seen.round, parts.get_part(k)))
            for msg in msgs:
                self._send_direct(donor.index, node.index, msg)
        if served:
            self.counters["catchups"] += 1

    def _send_direct(self, i: int, j: int, msg) -> None:
        due = max(
            self.clock.now() + self._link_delay(i, j),
            self._fifo.get((i, j), 0.0),
        )
        self._fifo[(i, j)] = due
        self.clock.timer(due - self.clock.now(), self._deliver, j, msg, f"sim{i}")

    def _drain(self, node: _SimNode) -> None:
        """Synchronous stand-in for _receive_routine: process everything
        queued on this node (own internal messages re-enter mid-drain)."""
        cs = node.cs
        while True:
            try:
                kind, payload, peer_id = cs._queue.get_nowait()
            except queue.Empty:
                return
            try:
                with cs._mtx:
                    if kind == "timeout":
                        cs._handle_timeout(payload)
                    else:
                        cs._handle_msg(payload, peer_id)
            except Exception:
                import traceback
                print(f"[{node.name}] sim drain failure: {traceback.format_exc()}")

    def _reachable(self, a: int, b: int) -> bool:
        if self._groups is None:
            return True
        ga = next((g for g in self._groups if a in g), None)
        gb = next((g for g in self._groups if b in g), None)
        if ga is None or gb is None:
            return True
        return ga is gb

    def _link_delay(self, a: int, b: int) -> float:
        d = self.zone_latency_ms[self.zone_of[a]][self.zone_of[b]] / 1000.0
        if self.jitter_s > 0:
            d += self.rng.random() * self.jitter_s
        return d

    def _make_broadcast(self, i: int):
        from cometbft_tpu.consensus.messages import VoteMessage

        def broadcast(msg):
            if not self.nodes[i].online:
                self.counters["offline_skips"] += 1
                return
            is_vote = self.vote_window_s > 0 and isinstance(msg, VoteMessage)
            peer_id = f"sim{i}"
            for j in range(self.n):
                if j == i:
                    continue
                if not self._reachable(i, j):
                    self.counters["partitioned"] += 1
                    continue
                if self.drop_p > 0 and self.rng.random() < self.drop_p:
                    self.counters["dropped"] += 1
                    continue
                due = max(
                    self.clock.now() + self._link_delay(i, j),
                    self._fifo.get((i, j), 0.0),
                )
                if is_vote:
                    self._bucket_vote(i, j, due, msg, peer_id)
                else:
                    self._fifo[(i, j)] = due
                    self.clock.timer(
                        due - self.clock.now(), self._deliver, j, msg, peer_id
                    )
        return broadcast

    def _deliver(self, j: int, msg, peer_id: str) -> None:
        node = self.nodes[j]
        if not node.online:
            self.counters["offline_skips"] += 1
            return
        self.counters["deliveries"] += 1
        node.cs._queue.put(("peer", msg, peer_id))
        self._drain(node)

    # Vote-window quantization: deliveries round UP to the next window
    # boundary and land as one per-(node, window) bucket, pre-verified in a
    # single batch dispatch — deterministic, and the dispatch count drops
    # by ~the bucket fill factor (the sim analogue of the vote-batch knob).
    def _bucket_vote(self, i: int, j: int, due: float, msg, peer_id: str) -> None:
        w = self.vote_window_s
        slot = int(math.floor(due / w)) + 1
        self._fifo[(i, j)] = slot * w
        key = (j, slot)
        bucket = self._vote_buckets.get(key)
        if bucket is None:
            self._vote_buckets[key] = [(msg, peer_id)]
            self.clock.timer(slot * w - self.clock.now(), self._flush_votes, key)
        else:
            bucket.append((msg, peer_id))

    def _flush_votes(self, key) -> None:
        j, _slot = key
        bucket = self._vote_buckets.pop(key, [])
        node = self.nodes[j]
        if not bucket or not node.online:
            self.counters["offline_skips"] += 0 if not bucket else len(bucket)
            return
        items = [("peer", m, pid) for m, pid in bucket]
        self.counters["vote_dispatches"] += 1
        self.counters["deliveries"] += len(items)
        if len(items) >= 8:
            node.cs._prebatch_vote_signatures(items)
        for item in items:
            node.cs._queue.put(item)
        self._drain(node)

    # -- scripted schedule ----------------------------------------------------

    def _script(self) -> None:
        spec = self.spec
        for p in spec["partitions"]:
            at = float(p["at_s"])
            heal = float(p["heal_s"])
            frac = float(p.get("fraction", 0.5))
            k = max(1, min(self.n - 1, int(round(self.n * frac))))
            groups = [set(range(k)), set(range(k, self.n))]
            self.clock.timer(at, self._set_partition, groups)
            self.clock.timer(heal, self._set_partition, None)
            self.schedule["partitions"].append(
                {"at_s": at, "heal_s": heal, "fraction": frac,
                 "group_sizes": [k, self.n - k]}
            )
        # Node 0 is the reference node for hashes: never churn it. Join
        # nodes are dark until their at_s — churning one would double-book
        # its online flag. With no joins this is identical sampling.
        churnable = [i for i in range(1, self.n) if i not in self._join_nodes]
        for c in spec["churn"]:
            at = float(c["at_s"])
            down = float(c["down_s"])
            count = min(int(c.get("nodes", 1)), max(self.n // 3 - 1, 0),
                        len(churnable))
            picked = self.rng.sample(churnable, count) if count else []
            for idx in picked:
                self.clock.timer(at, self._set_online, idx, False)
                self.clock.timer(at + down, self._set_online, idx, True)
            self.schedule["churn"].append(
                {"at_s": at, "down_s": down, "nodes": sorted(picked)}
            )
        if float(spec["tx_interval_s"]) > 0:
            self.clock.timer(float(spec["tx_interval_s"]), self._inject_txs)
        poll = float(spec["watchdog_poll_s"])
        if poll > 0:
            for i in range(self.n):
                self.clock.timer(poll, self._watchdog_tick, i)
        gossip = float(spec["gossip_interval_s"])
        if gossip > 0:
            for i in range(self.n):
                # Staggered first ticks: node i's gossip phase is offset so
                # N nodes do not all relay on the same clock instant.
                self.clock.timer(gossip * (1.0 + i / self.n), self._gossip_tick, i)
        for j in spec["joins"]:
            self.clock.timer(float(j["at_s"]), self._begin_join, int(j["node"]))
        for actor in self.byz_actors:
            actor.start()

    def _set_partition(self, groups) -> None:
        self._groups = groups

    def _set_online(self, idx: int, online: bool) -> None:
        node = self.nodes[idx]
        node.online = online
        if online:
            # Back from the dead: rearm whatever timer the current step
            # needs and reset the stall baseline.
            cs = node.cs
            cs._last_progress = self.clock.now()
            with cs._mtx:
                cs._rearm_step_timeout()

    def _inject_txs(self) -> None:
        spec = self.spec
        for _ in range(int(spec["txs_per_interval"])):
            target = self.nodes[self._tx_counter % self.n]
            if target.online:
                tx = f"sim{self.seed}-tx{self._tx_counter}=v".encode()
                try:
                    target.mempool.check_tx(tx)
                except Exception:
                    pass  # full mempool under load is expected
                self._drain(target)
            self._tx_counter += 1
        self.clock.timer(float(spec["tx_interval_s"]), self._inject_txs)

    def _watchdog_tick(self, i: int) -> None:
        node = self.nodes[i]
        if node.online:
            cs = node.cs
            cs._stall_check()
            # Height straggler (missed a commit to drops/partition/churn):
            # after one round-0 budget of idleness, a caught-up peer
            # re-serves that height (reactor catchup-gossip analogue).
            idle = self.clock.now() - cs._last_progress
            if idle > cs.config.round_timeout_budget(0):
                self._catchup(node)
            self._drain(node)
        self.clock.timer(float(self.spec["watchdog_poll_s"]), self._watchdog_tick, i)

    # -- background gossip (votes + evidence) ---------------------------------
    #
    # The reactor-analogue the byzantine layer leans on: the per-signer
    # broadcast alone never places two CONFLICTING copies of a vote in one
    # honest node's VoteSet (each camp only ever saw its own copy), so
    # equivocation would go undetected and pending evidence would only
    # commit when the detecting node itself proposes. Each gossip tick a
    # node picks one rotating same-height peer and relays (a) the votes it
    # holds at that peer's CURRENT round which the peer provably lacks or
    # holds a DIFFERENT copy of — the HasVote-bitmap logic of
    # gossipVotesRoutine, with harness omniscience standing in for the
    # tracked peer state — and (b) its pending evidence as real
    # evidence-reactor wire bytes. In a healthy full mesh every vote is
    # already at every peer, so (a) relays almost nothing; after a heal or
    # under equivocation it converges the split knowledge within ticks.

    def _gossip_tick(self, i: int) -> None:
        node = self.nodes[i]
        if node.online and node.cs is not None:
            self._relay_votes(i)
            self._relay_evidence(i)
        self.clock.timer(float(self.spec["gossip_interval_s"]), self._gossip_tick, i)

    def _gossip_peer(self, i: int) -> int | None:
        h = self.nodes[i].cs.rs.height
        candidates = [
            j for j in range(self.n)
            if j != i and self.nodes[j].online and self.nodes[j].cs is not None
            and self.nodes[j].cs.rs.height == h and self._reachable(i, j)
        ]
        if not candidates:
            return None
        rotor = self._gossip_rotor.get(i, 0)
        self._gossip_rotor[i] = rotor + 1
        return candidates[rotor % len(candidates)]

    def _relay_votes(self, i: int, cap: int = 16) -> None:
        from cometbft_tpu.consensus.messages import VoteMessage

        j = self._gossip_peer(i)
        if j is None:
            return
        cs_i, cs_j = self.nodes[i].cs, self.nodes[j].cs
        h, r_j = cs_i.rs.height, cs_j.rs.round
        sent_h, sent = self._gossip_sent.get((i, j), (None, None))
        if sent_h != h:
            sent = set()
            self._gossip_sent[(i, j)] = (h, sent)
        relayed = 0
        for vs_i, vs_j in (
            (cs_i.rs.votes.prevotes(r_j), cs_j.rs.votes.prevotes(r_j)),
            (cs_i.rs.votes.precommits(r_j), cs_j.rs.votes.precommits(r_j)),
        ):
            if vs_i is None:
                continue
            for idx, vote in enumerate(vs_i.votes):
                if vote is None or relayed >= cap:
                    continue
                key = (r_j, vote.type, idx)
                if key in sent:
                    continue
                theirs = vs_j.votes[idx] if vs_j is not None else None
                if theirs is not None and theirs.block_id == vote.block_id:
                    continue  # peer already holds this copy (HasVote)
                sent.add(key)
                relayed += 1
                self.counters["gossip_votes"] += 1
                self._send_direct(i, j, VoteMessage(vote))

    def _relay_evidence(self, i: int, cap: int = 4) -> None:
        from cometbft_tpu.evidence.reactor import encode_evidence_list_msg

        j = self._gossip_peer(i)
        if j is None:
            return
        evpool = self.nodes[i].evpool
        if evpool is None:
            return
        pending, _ = evpool.pending_evidence(-1)
        if not pending:
            return
        raw = encode_evidence_list_msg(pending[:cap])
        self.counters["gossip_evidence"] += 1
        self.clock.timer(self._link_delay(i, j), self._deliver_evidence, j, raw)

    def _deliver_evidence(self, j: int, raw: bytes) -> None:
        from cometbft_tpu.evidence.reactor import decode_evidence_list_msg

        node = self.nodes[j]
        if not node.online or node.evpool is None:
            self.counters["offline_skips"] += 1
            return
        for ev in decode_evidence_list_msg(raw):
            try:
                node.evpool.add_evidence(ev)
            except Exception:
                # Peers that have not yet committed the evidence height
                # reject it (evidence/reactor.go swallows the same way);
                # the sender keeps re-offering while it stays pending.
                self.counters["evidence_rejects"] += 1

    # -- in-sim blocksync late-join -------------------------------------------
    #
    # A join node is a genesis validator that stays dark until ``at_s``,
    # then catches up by driving REAL blocksync wire frames
    # (encode_block_request/encode_block_response + the
    # verify_commit_light-then-apply flow of blocksync/reactor.py
    # _try_sync_one) over the sim link model, and finally constructs a
    # fresh ConsensusState from the synced state — the same boot sequence
    # a wall-clock node performs, minus the thread-driven reactor shell
    # that would break single-threaded determinism.

    _JOIN_WINDOW = 8  # request pipeline depth (blocksync pool analogue)
    _JOIN_POLL_S = 0.5

    def _begin_join(self, j: int) -> None:
        self.counters["joins"] += 1
        self._join_state[j] = {
            "blocks": {},
            "requested": set(),
            "state": self.nodes[j].state_store.load(),
            "synced": 0,
            "started_s": round(self.clock.now(), 6),
            "done": False,
        }
        self._blocksync_tick(j)

    def _pick_donor(self, j: int):
        best = None
        for d in self.nodes:
            if (
                d.index == j or not d.online or d.cs is None
                or not self._reachable(d.index, j)
            ):
                continue
            h = d.block_store.height()
            if h > 0 and (best is None or h > best.block_store.height()):
                best = d
        return best

    def _blocksync_tick(self, j: int) -> None:
        from cometbft_tpu.blocksync.reactor import encode_block_request

        js = self._join_state.get(j)
        if js is None or js["done"]:
            return
        node = self.nodes[j]
        donor = self._pick_donor(j)
        if donor is not None:
            tip = donor.block_store.height()
            my_h = node.block_store.height()
            if my_h >= tip - 1:
                # Within one block of the donor tip: the pair rule cannot
                # certify the tip block, so switch to consensus — the
                # watchdog catchup path serves the remainder, exactly the
                # reactor's is_caught_up handoff.
                self._complete_join(j, js)
                return
            for h in range(my_h + 1, min(my_h + 1 + self._JOIN_WINDOW, tip + 1)):
                if h in js["blocks"] or h in js["requested"]:
                    continue
                js["requested"].add(h)
                raw = encode_block_request(h)
                self.clock.timer(
                    self._link_delay(j, donor.index),
                    self._bs_serve, donor.index, j, raw,
                )
        self.clock.timer(self._JOIN_POLL_S, self._blocksync_tick, j)

    def _bs_serve(self, d: int, j: int, raw: bytes) -> None:
        from cometbft_tpu.blocksync.reactor import (
            decode_message,
            encode_block_response,
        )

        donor = self.nodes[d]
        if not donor.online:
            return  # request lost: the joiner's next tick re-picks a donor
        kind, height = decode_message(raw)
        assert kind == "block_request"
        block = donor.block_store.load_block(height)
        if block is None:
            return
        self.counters["blocksync_served"] += 1
        self.clock.timer(
            self._link_delay(d, j), self._bs_receive, j,
            encode_block_response(block),
        )

    def _bs_receive(self, j: int, raw: bytes) -> None:
        from cometbft_tpu.blocksync.reactor import decode_message

        js = self._join_state.get(j)
        if js is None or js["done"]:
            return
        kind, block = decode_message(raw)
        assert kind == "block_response"
        h = block.header.height
        js["blocks"][h] = block
        js["requested"].discard(h)
        self._bs_apply(j, js)

    def _bs_apply(self, j: int, js: dict) -> None:
        """reactor.py _try_sync_one verbatim: verify `first` with
        `second.last_commit` (verify_commit_light — the TPU-batched call),
        validate, save with the certifying commit, apply."""
        from cometbft_tpu.types.block import BlockID

        node = self.nodes[j]
        while True:
            h = node.block_store.height() + 1
            first, second = js["blocks"].get(h), js["blocks"].get(h + 1)
            if first is None or second is None:
                return
            first_parts = first.make_part_set()
            first_id = BlockID(first.hash(), first_parts.header())
            state = js["state"]
            state.validators.verify_commit_light(
                state.chain_id, first_id, h, second.last_commit
            )
            node.executor.validate_block(state, first)
            node.block_store.save_block(first, first_parts, second.last_commit)
            js["state"], _ = node.executor.apply_block(state, first_id, first)
            del js["blocks"][h]
            js["synced"] += 1

    def _complete_join(self, j: int, js: dict) -> None:
        from cometbft_tpu.consensus.state import ConsensusState

        node = self.nodes[j]
        js["done"] = True
        sink = self._make_tock_sink(j)
        cs = ConsensusState(
            node.cfg.consensus,
            js["state"],
            node.executor,
            node.block_store,
            node.mempool,
            evpool=node.evpool,
            wal=None,
            ticker=SimTicker(self.clock, sink),
            clock=self.clock,
            name=node.name,
        )
        cs.set_priv_validator(node.pv)
        cs._stall_factor = float(self.spec["stall_factor"])
        cs.set_broadcast(self._make_broadcast(j))
        node.cs = cs
        cs.set_on_stall(self._make_on_stall(node))
        node.online = True
        cs.ticker.start()
        cs._schedule_round0()
        self.counters["join_completions"] += 1
        self._join_reports.append({
            "node": j,
            "started_s": js["started_s"],
            "joined_s": round(self.clock.now(), 6),
            "synced_blocks": js["synced"],
            "height_at_join": node.block_store.height(),
        })

    # -- run ------------------------------------------------------------------

    def run(self) -> dict:
        import gc
        import os

        from cometbft_tpu.crypto import sigbatch
        from cometbft_tpu.types import cmttime
        from cometbft_tpu.types.cmttime import Time

        target_height = int(self.spec["blocks"]) + 1
        horizon = float(self.spec["max_sim_s"])
        wall_start = _time.monotonic()

        def sim_now() -> Time:
            ns = GENESIS_SECONDS * 10**9 + int(self.clock.now() * 1e9)
            return Time(ns // 10**9, ns % 10**9)

        cmttime.set_now_source(sim_now)
        # The wall-clock vote-admission micro-batch would make every scalar
        # verify wait out a real window with no concurrent producers to
        # share it (the harness is single-threaded) — the sim models vote
        # batching virtually instead (vote_window_ms).
        prev_window = os.environ.get("CMTPU_VOTE_BATCH_WINDOW_MS")
        os.environ["CMTPU_VOTE_BATCH_WINDOW_MS"] = "0"
        sigbatch.reset()
        # The drive loop allocates millions of short-lived objects against
        # a large persistent heap (N nodes × stores × caches): generational
        # GC passes dominate wall time and grow with heap size, making
        # back-to-back runs progressively slower. The harness has no
        # reference cycles it needs collected mid-run.
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            self._build()
            self._script()
            for node in self.nodes:
                if node.index in self._join_nodes:
                    continue  # dark until its join event fires
                node.cs.ticker.start()
                node.cs._schedule_round0()
            cs0 = self.nodes[0].cs
            last_h = cs0.rs.height
            while (
                cs0.rs.height < target_height
                and self.clock.now() < horizon
                and self.clock.step()
            ):
                if cs0.rs.height != last_h:
                    t = round(self.clock.now(), 6)
                    for hh in range(last_h, cs0.rs.height):
                        self._commit_times.append([hh, t])
                    last_h = cs0.rs.height
            if cs0.rs.height != last_h:  # the commit that ended the loop
                t = round(self.clock.now(), 6)
                for hh in range(last_h, cs0.rs.height):
                    self._commit_times.append([hh, t])
        finally:
            cmttime.set_now_source(None)
            if prev_window is None:
                os.environ.pop("CMTPU_VOTE_BATCH_WINDOW_MS", None)
            else:
                os.environ["CMTPU_VOTE_BATCH_WINDOW_MS"] = prev_window
            sigbatch.reset()
            for node in self.nodes:
                node.cs.ticker.stop()

        wall = _time.monotonic() - wall_start
        if gc_was_enabled:
            gc.enable()
            gc.collect()
        sim_time = self.clock.now()
        heights = [n.cs.rs.height for n in self.nodes]
        committed = min(cs0.rs.height - 1, int(self.spec["blocks"]))
        hashes = {}
        for h in range(1, committed + 1):
            blk = self.nodes[0].cs.block_store.load_block(h)
            hashes[h] = blk.hash().hex() if blk is not None else None
        reached = cs0.rs.height >= target_height
        # Hash agreement (the e2e runner's invariant, in-process form):
        # every node that committed the highest common height must hold the
        # bit-identical block there. Stragglers below it are exempt — they
        # are reported, not silently passed.
        common = 0
        agreed_hash = None
        agreement = True
        if committed >= 1:
            common = min(
                [committed]
                + [h - 1 for h in heights if h - 1 >= 1 and h >= cs0.rs.height - 1]
            )
            agreed_hash = hashes.get(common)
            for node in self.nodes:
                if node.cs.rs.height - 1 < common:
                    continue
                blk = node.cs.block_store.load_block(common)
                if blk is None or blk.hash().hex() != agreed_hash:
                    agreement = False
        safety_ok, conflicting = self._check_safety(committed)
        return {
            "ok": reached and agreement and safety_ok,
            "seed": self.seed,
            "validators": self.n,
            "blocks_target": int(self.spec["blocks"]),
            "height_node0": cs0.rs.height,
            "heights_min": min(heights),
            "heights_max": max(heights),
            "stragglers": [
                i for i, h in enumerate(heights) if h < cs0.rs.height - 1
            ],
            "block_hashes": hashes,
            "agreed_height": common,
            "agreed_hash": agreed_hash,
            "hash_agreement": agreement,
            "safety_ok": safety_ok,
            "conflicting_heights": conflicting,
            "evidence": self._evidence_report(committed),
            "recovery": self._recovery_report(),
            "joins": list(self._join_reports),
            "commit_times": [list(x) for x in self._commit_times],
            "sim_time_s": round(sim_time, 6),
            "wall_time_s": round(wall, 6),
            "accel": round(sim_time / wall, 3) if wall > 0 else None,
            "events": self.clock.events_run,
            "counters": dict(self.counters),
            "schedule": self.schedule,
        }

    # -- report helpers -------------------------------------------------------

    def _check_safety(self, committed: int) -> tuple[bool, list[int]]:
        """The BFT safety contract: no two HONEST nodes hold different
        blocks at any committed height (byzantine nodes' own stores are
        not part of the claim). Distinct from hash_agreement, which only
        checks the highest common height."""
        byz = {a.node_index for a in self.byz_actors}
        conflicting = []
        for h in range(1, committed + 1):
            seen = None
            for node in self.nodes:
                if node.index in byz:
                    continue
                meta = node.cs.block_store.load_block_meta(h)
                if meta is None:
                    continue
                bh = meta.block_id.hash
                if seen is None:
                    seen = bh
                elif bh != seen:
                    conflicting.append(h)
                    break
        return not conflicting, conflicting

    def _evidence_report(self, committed: int) -> dict:
        """Detection → pending → committed accounting, from node 0's chain
        (every honest chain is bit-identical when safety holds)."""
        committed_heights = []
        committed_count = 0
        for h in range(1, committed + 1):
            blk = self.nodes[0].cs.block_store.load_block(h)
            if blk is not None and blk.evidence:
                committed_heights.append(h)
                committed_count += len(blk.evidence)
        byz = {a.node_index for a in self.byz_actors}
        pending_honest = 0
        pool_stats: dict[str, int] = {}
        for node in self.nodes:
            if node.index in byz or node.evpool is None:
                continue
            snap = node.evpool.stats_snapshot()
            pending_honest = max(pending_honest, snap["pending"])
            for k, v in snap.items():
                pool_stats[k] = pool_stats.get(k, 0) + v
        first = self._evidence_detections[0] if self._evidence_detections else None
        commit_s = None
        if committed_heights:
            at = dict((hh, t) for hh, t in self._commit_times)
            commit_s = at.get(committed_heights[0])
        return {
            "detections": len(self._evidence_detections),
            "first_detection": first,
            "committed_heights": committed_heights,
            "committed_count": committed_count,
            "first_commit_sim_s": commit_s,
            "detect_to_commit_s": (
                round(commit_s - first["sim_s"], 6)
                if commit_s is not None and first is not None else None
            ),
            "max_pending_honest": pending_honest,
            "pool_stats": pool_stats,
        }

    def _recovery_report(self) -> dict:
        """Block-rate recovery after the last byzantine/partition window:
        baseline = median commit interval during clean time before the
        first window; recovered when a post-window commit interval is
        back within 2x baseline."""
        disturb_from = [float(a.from_s) for a in self.byz_actors]
        disturb_until = [float(a.until_s) for a in self.byz_actors]
        for p in self.schedule.get("partitions", []):
            disturb_from.append(float(p["at_s"]))
            disturb_until.append(float(p["heal_s"]))
        if not disturb_from or len(self._commit_times) < 3:
            return {"applicable": False}
        t_from, t_until = min(disturb_from), max(disturb_until)
        ct = self._commit_times
        intervals = [
            (ct[k][1], ct[k][1] - ct[k - 1][1]) for k in range(1, len(ct))
        ]
        base = sorted(dt for t, dt in intervals if t <= t_from)
        source = "pre_window"
        if not base:
            # Nothing committed before the window opened (early
            # disturbance): take the run's steady-state tail instead —
            # the last quartile of intervals — as the honest baseline.
            tail = [dt for _, dt in intervals[-max(2, len(intervals) // 4):]]
            base = sorted(tail)
            source = "tail"
        baseline = base[len(base) // 2] if base else None
        recovered_at = None
        if baseline:
            for t, dt in intervals:
                if t > t_until and dt <= 2.0 * baseline:
                    recovered_at = t
                    break
        return {
            "applicable": True,
            "baseline_source": source,
            "baseline_interval_s": round(baseline, 6) if baseline else None,
            "window": [t_from, t_until],
            "recovered_at_s": recovered_at,
            "recovery_lag_s": (
                round(recovered_at - t_until, 6)
                if recovered_at is not None else None
            ),
        }


def run_scenario(spec: dict | None = None, **overrides) -> dict:
    """Build + run one seeded scenario; returns the report dict (the
    ``schedule`` key is sufficient to replay the run bit-identically)."""
    full = default_spec(**{**(spec or {}), **overrides})
    return Scenario(full).run()

"""Clock abstraction: real monotonic time vs an event-heap virtual clock.

Production code paths (consensus state, ticker, switch redial, blocksync
poll loops) take an injected ``Clock`` and default to ``MonotonicClock``,
whose three methods are literally ``time.monotonic`` / ``time.sleep`` /
``threading.Timer`` — zero behavior change when nothing is injected.

``SimClock`` is a discrete-event virtual clock.  Virtual time never
passes on its own: it jumps to the next scheduled event's due time, and
only when every *registered actor* is blocked (sleeping or waiting on
the clock).  A simulation therefore runs exactly as fast as the host can
drain the event heap — a 100-second simulated chain that contains two
seconds of actual work completes in two wall seconds — while every
timer/sleep interleaving stays deterministic given a deterministic event
set.

Two driving modes:

* **Single-threaded** (the scenario harness): nobody registers actors;
  the driver pops events itself via :meth:`SimClock.step` /
  :meth:`SimClock.run` and timer callbacks execute inline on the driver
  thread.  Fully deterministic — the heap is ordered by
  ``(due, sequence)`` and the sequence counter is allocated in program
  order.
* **Threaded** (clock-driven unit tests, SimTransport under real
  threads): threads ``register_actor()`` themselves; any thread blocked
  in :meth:`sleep`/:meth:`wait_until` advances time itself once ALL
  registered actors are blocked, firing due timer callbacks from
  whichever thread performed the advance.  Timer callbacks must
  therefore stay short and non-blocking (queue puts, event sets) — the
  convention every in-repo user follows.
"""

from __future__ import annotations

import heapq
import threading
import time as _time


class TimerHandle:
    """Cancelable one-shot timer, returned by ``Clock.timer``."""

    def cancel(self) -> None:  # pragma: no cover - interface default
        pass


class Clock:
    """now()/sleep()/timer() — the only time surface consensus uses."""

    def now(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def timer(self, delay: float, fn, *args) -> TimerHandle:
        """Schedule ``fn(*args)`` after ``delay`` seconds; returns a handle
        whose ``cancel()`` is a no-op once the callback started."""
        raise NotImplementedError


class _RealTimerHandle(TimerHandle):
    def __init__(self, t: threading.Timer):
        self._t = t

    def cancel(self) -> None:
        self._t.cancel()


class MonotonicClock(Clock):
    """Wall-clock implementation: the pre-simnet behavior, verbatim."""

    def now(self) -> float:
        return _time.monotonic()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)

    def timer(self, delay: float, fn, *args) -> TimerHandle:
        t = threading.Timer(delay, fn, args=args)
        t.daemon = True
        t.start()
        return _RealTimerHandle(t)


class _SimTimerEntry(TimerHandle):
    __slots__ = ("due", "seq", "fn", "args", "cancelled")

    def __init__(self, due: float, seq: int, fn, args):
        self.due = due
        self.seq = seq
        self.fn = fn
        self.args = args
        self.cancelled = False

    def cancel(self) -> None:
        # Flag only: the entry stays heap-resident and is skipped on pop,
        # so cancellation never needs a heap rebuild.
        self.cancelled = True

    def __lt__(self, other: "_SimTimerEntry") -> bool:
        return (self.due, self.seq) < (other.due, other.seq)


class SimClock(Clock):
    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._heap: list[_SimTimerEntry] = []
        self._seq = 0
        self._cond = threading.Condition()
        # thread ident -> actor name, for threads whose runnable state
        # gates time advancement.
        self._actors: dict[int, str] = {}
        # thread idents currently blocked inside sleep()/wait_until().
        self._blocked: set[int] = set()
        self.events_run = 0

    # -- Clock surface ------------------------------------------------------

    def now(self) -> float:
        return self._now

    def timer(self, delay: float, fn, *args) -> TimerHandle:
        with self._cond:
            entry = _SimTimerEntry(
                self._now + max(float(delay), 0.0), self._seq, fn, args
            )
            self._seq += 1
            heapq.heappush(self._heap, entry)
            self._cond.notify_all()
        return entry

    def sleep(self, seconds: float) -> None:
        self.wait_until(self._now + max(float(seconds), 0.0))

    def wait_until(self, due: float) -> None:
        """Block the calling thread until virtual time reaches ``due``.

        The sleeper schedules a wake event so the advance logic has a
        target, marks itself blocked, and — if it finds every registered
        actor blocked — performs the advance itself.  The 50 ms real
        ``Condition.wait`` is only a lost-wakeup backstop; advancement is
        driven by notifications, not by that timeout.
        """
        ident = threading.get_ident()
        with self._cond:
            if due <= self._now:
                return
            wake = _SimTimerEntry(due, self._seq, None, ())
            self._seq += 1
            heapq.heappush(self._heap, wake)
            self._blocked.add(ident)
            self._cond.notify_all()
            try:
                while self._now < due:
                    fired = self._advance_locked_if_all_blocked()
                    if fired:
                        self._run_entries(fired)
                        continue
                    if self._now >= due:
                        break
                    self._cond.wait(0.05)
            finally:
                self._blocked.discard(ident)
                wake.cancelled = True
                self._cond.notify_all()

    # -- actors -------------------------------------------------------------

    def register_actor(self, name: str = "") -> None:
        """Declare the calling thread an actor: virtual time may only
        advance while this thread is blocked in sleep()/wait_until()."""
        with self._cond:
            self._actors[threading.get_ident()] = name or "actor"

    def unregister_actor(self) -> None:
        with self._cond:
            self._actors.pop(threading.get_ident(), None)
            self._cond.notify_all()

    # -- driving ------------------------------------------------------------

    def pending(self) -> int:
        with self._cond:
            return sum(1 for e in self._heap if not e.cancelled)

    def next_due(self) -> float | None:
        with self._cond:
            for e in sorted(self._heap):
                if not e.cancelled:
                    return e.due
            return None

    def step(self) -> bool:
        """Single-threaded driver: pop the earliest live event, advance to
        its due time, run its callback inline.  False when the heap is
        drained."""
        with self._cond:
            entry = self._pop_live_locked()
            if entry is None:
                return False
            self._now = entry.due
            self._cond.notify_all()
        self._run_entries([entry])
        return True

    def run(self, until: float | None = None, max_events: int | None = None) -> int:
        """Drain events (single-threaded mode) until the heap empties, the
        next event lies past ``until``, or ``max_events`` ran. Returns the
        number of events executed."""
        ran = 0
        while max_events is None or ran < max_events:
            with self._cond:
                entry = self._pop_live_locked(peek_limit=until)
                if entry is None:
                    break
                self._now = entry.due
                self._cond.notify_all()
            self._run_entries([entry])
            ran += 1
        if until is not None and self._now < until and self.next_due() is None:
            # No events left before the horizon: time simply passes.
            with self._cond:
                self._now = until
                self._cond.notify_all()
        return ran

    # -- internals ----------------------------------------------------------

    def _pop_live_locked(self, peek_limit: float | None = None):
        while self._heap:
            if peek_limit is not None and self._heap[0].due > peek_limit:
                return None
            entry = heapq.heappop(self._heap)
            if not entry.cancelled:
                return entry
        return None

    def _advance_locked_if_all_blocked(self) -> list[_SimTimerEntry]:
        """If every registered actor is blocked, jump to the earliest due
        time and collect everything due there. Caller holds the lock and
        runs the returned callbacks outside it."""
        if any(i not in self._blocked for i in self._actors):
            return []
        entry = self._pop_live_locked()
        if entry is None:
            return []
        self._now = entry.due
        fired = [entry]
        while self._heap and self._heap[0].due <= self._now:
            nxt = heapq.heappop(self._heap)
            if not nxt.cancelled:
                fired.append(nxt)
        self._cond.notify_all()
        return fired

    def _run_entries(self, entries) -> None:
        for e in entries:
            self.events_run += 1
            if e.fn is not None and not e.cancelled:
                e.fn(*e.args)

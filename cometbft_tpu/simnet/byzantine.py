"""Scripted byzantine adversaries for the simnet scenario harness.

Every adversary taps the node's OWN send surface — the broadcast hook
the scenario installs with ``cs.set_broadcast`` — and never forks
consensus code: the byzantine node runs the same ``ConsensusState``
machine as every honest peer, and the actor merely rewrites, splits,
delays, or replays what leaves it. That mirrors how a real byzantine
operator would act (patch the gossip layer, not rebuild Tendermint) and
guarantees the honest nodes under test exercise their production
decision paths against well-formed, correctly signed adversarial bytes.

Roles (attach via the scenario spec's ``byzantine`` list):

* ``equivocator`` — for each own prevote/precommit, signs a second
  conflicting vote (a fabricated, seed-derived block id at the same
  (height, round, type)) and splits delivery: one camp of peers gets the
  honest vote, the other camp gets the conflicting one. With
  ``only_partitioned`` the split only happens while a partition is
  active, with the camps equal to the partition sides — the classic
  "invisible" equivocation that no honest node can witness until the
  heal merges vote knowledge. The adversary's own links straddle the
  partition (it reaches both sides): the scripted partition models
  correlated *honest* link failure, and an adversary that lost one side
  too would simply be a crashed node, not a byzantine one.
* ``withholder`` — while active, the node's own ProposalMessage and
  BlockPartMessage broadcasts are dropped (``delay_s = 0``) or delayed
  by ``delay_s`` simulated seconds, forcing honest peers through
  ``timeout_propose`` nil-prevote rounds whenever its proposer turn
  comes up.
* ``flooder`` — replays its own recently broadcast votes (stale rounds,
  duplicates) to a seeded sample of peers at ``rate_hz``, griefing the
  vote-admission/dedup path without ever producing invalid signatures.

Determinism: each actor draws from its own ``random.Random`` stream
seeded from (scenario seed, node, role), and every action is either a
synchronous rewrite inside a broadcast call or a SimClock event — two
runs of the same spec replay bit-identically, adversaries included.
"""

from __future__ import annotations

import hashlib
import random

ROLES = ("equivocator", "withholder", "flooder")

_COMMON_KEYS = {"role", "node", "from_s", "until_s"}
_ROLE_KEYS = {
    "equivocator": {"only_partitioned"},
    "withholder": {"delay_s"},
    "flooder": {"rate_hz", "burst", "fanout"},
}


def make_actor(scenario, entry: dict):
    """Validate one ``byzantine`` spec entry and build its actor."""
    role = entry.get("role")
    if role not in ROLES:
        raise ValueError(f"unknown byzantine role {role!r} (want one of {ROLES})")
    unknown = set(entry) - _COMMON_KEYS - _ROLE_KEYS[role]
    if unknown:
        raise ValueError(f"unknown byzantine keys {sorted(unknown)} for {role}")
    node = int(entry.get("node", -1))
    if not (1 <= node < scenario.n):
        raise ValueError(
            f"byzantine node must be in 1..{scenario.n - 1} "
            "(node 0 is the hash-reference node)"
        )
    cls = {"equivocator": Equivocator, "withholder": Withholder,
           "flooder": Flooder}[role]
    return cls(scenario, entry, node)


class _ActorBase:
    role = ""

    def __init__(self, scenario, entry: dict, node: int):
        self.scen = scenario
        self.node_index = node
        self.from_s = float(entry.get("from_s", 0.0))
        until = entry.get("until_s")
        self.until_s = (
            float(until) if until is not None else float(scenario.spec["max_sim_s"])
        )
        self.rng = random.Random(
            f"simnet-byz:{scenario.seed}:{node}:{self.role}"
        )

    def active(self) -> bool:
        t = self.scen.clock.now()
        return self.from_s <= t < self.until_s

    def wrap(self, base):
        """Return the broadcast fn to install in place of ``base``."""
        return base

    def start(self) -> None:
        """Schedule any clock-driven loops (called once, before the run)."""

    def resolved(self) -> dict:
        """The realized schedule entry (embedded in report/repro.json)."""
        return {
            "role": self.role,
            "node": self.node_index,
            "from_s": self.from_s,
            "until_s": self.until_s,
        }

    def _count(self, key: str, n: int = 1) -> None:
        self.scen.counters[key] = self.scen.counters.get(key, 0) + n


class Equivocator(_ActorBase):
    role = "equivocator"

    def __init__(self, scenario, entry, node):
        super().__init__(scenario, entry, node)
        self.only_partitioned = bool(entry.get("only_partitioned", False))
        # Static camps for the un-partitioned mode: a seeded half/half
        # split of the peer set (under a partition the camps ARE the
        # partition sides instead).
        peers = [j for j in range(scenario.n) if j != node]
        self.rng.shuffle(peers)
        self._camp_b = set(peers[len(peers) // 2:])
        self.first_equivocation_s: float | None = None

    def resolved(self) -> dict:
        out = super().resolved()
        out["only_partitioned"] = self.only_partitioned
        return out

    def wrap(self, base):
        from cometbft_tpu.consensus.messages import VoteMessage

        def broadcast(msg):
            if not isinstance(msg, VoteMessage) or not self.active():
                base(msg)
                return
            scen = self.scen
            if self.only_partitioned and scen._groups is None:
                base(msg)
                return
            node = scen.nodes[self.node_index]
            pub = node.cs.priv_validator_pub_key
            vote = msg.vote
            if pub is None or vote.validator_address != pub.address():
                base(msg)  # not our own vote (relay etc.) — pass through
                return
            alt = self._conflicting_vote(node, vote)
            if alt is None:
                base(msg)
                return
            if self.first_equivocation_s is None:
                self.first_equivocation_s = round(scen.clock.now(), 6)
            self._count("byz_equivocations")
            self._split_deliver(msg, VoteMessage(alt))

        return broadcast

    def _conflicting_vote(self, node, vote):
        """A correctly signed vote at the same (h, r, type) for a
        fabricated, seed-derived block id — differs from the honest vote
        whether that one was nil or a real block."""
        from cometbft_tpu.types import BlockID, Vote
        from cometbft_tpu.types.part_set import PartSetHeader

        scen = self.scen
        mark = hashlib.sha256(
            f"simnet-equivocation:{scen.seed}:{self.node_index}:"
            f"{vote.height}:{vote.round}:{vote.type}".encode()
        ).digest()
        alt = Vote(
            type=vote.type,
            height=vote.height,
            round=vote.round,
            block_id=BlockID(mark, PartSetHeader(1, mark)),
            timestamp=vote.timestamp,
            validator_address=vote.validator_address,
            validator_index=vote.validator_index,
        )
        try:
            return node.cs.priv_validator.sign_vote(node.cs.state.chain_id, alt)
        except Exception:
            return None

    def _split_deliver(self, honest_msg, alt_msg) -> None:
        """Camp A gets the honest vote, camp B the conflicting one.
        Adversary links ignore the partition (see module docstring) and
        the drop model — the adversary makes sure its words arrive."""
        scen = self.scen
        i = self.node_index
        if scen._groups is not None:
            own = next((g for g in scen._groups if i in g), None)
            for j in range(scen.n):
                if j == i:
                    continue
                other_side = own is not None and j not in own
                scen._send_direct(i, j, alt_msg if other_side else honest_msg)
        else:
            for j in range(scen.n):
                if j == i:
                    continue
                scen._send_direct(
                    i, j, alt_msg if j in self._camp_b else honest_msg
                )


class Withholder(_ActorBase):
    role = "withholder"

    def __init__(self, scenario, entry, node):
        super().__init__(scenario, entry, node)
        self.delay_s = float(entry.get("delay_s", 0.0))

    def resolved(self) -> dict:
        out = super().resolved()
        out["delay_s"] = self.delay_s
        return out

    def wrap(self, base):
        from cometbft_tpu.consensus.messages import (
            BlockPartMessage,
            ProposalMessage,
        )

        def broadcast(msg):
            if self.active() and isinstance(
                msg, (ProposalMessage, BlockPartMessage)
            ):
                self._count("byz_withheld")
                if self.delay_s > 0:
                    # Late release: peers decide whether it is still
                    # relevant (stale-round proposals are ignored).
                    self.scen.clock.timer(self.delay_s, base, msg)
                return
            base(msg)

        return broadcast


class Flooder(_ActorBase):
    role = "flooder"

    def __init__(self, scenario, entry, node):
        super().__init__(scenario, entry, node)
        self.rate_hz = float(entry.get("rate_hz", 5.0))
        self.burst = int(entry.get("burst", 4))
        self.fanout = int(entry.get("fanout", 8))
        self._ring: list = []  # own recently broadcast VoteMessages

    def resolved(self) -> dict:
        out = super().resolved()
        out.update(rate_hz=self.rate_hz, burst=self.burst, fanout=self.fanout)
        return out

    def wrap(self, base):
        from cometbft_tpu.consensus.messages import VoteMessage

        def broadcast(msg):
            if isinstance(msg, VoteMessage):
                self._ring.append(msg)
                if len(self._ring) > 64:
                    del self._ring[0]
            base(msg)

        return broadcast

    def start(self) -> None:
        if self.rate_hz > 0:
            self.scen.clock.timer(max(self.from_s, 1e-9), self._tick)

    def _tick(self) -> None:
        scen = self.scen
        if scen.clock.now() >= self.until_s:
            return
        node = scen.nodes[self.node_index]
        if self.active() and node.online and self._ring:
            replay = [
                self._ring[self.rng.randrange(len(self._ring))]
                for _ in range(self.burst)
            ]
            peers = [
                j for j in range(scen.n)
                if j != self.node_index and scen._reachable(self.node_index, j)
            ]
            if len(peers) > self.fanout:
                peers = self.rng.sample(peers, self.fanout)
            for j in peers:
                for m in replay:
                    scen._send_direct(self.node_index, j, m)
                    self._count("byz_flooded")
        self.scen.clock.timer(1.0 / self.rate_hz, self._tick)

"""In-memory transport with a seeded WAN link model.

``SimTransport`` duck-types the ``MultiplexTransport`` surface
(``listen(addr, accept_cb)`` / ``dial(addr, expected_id)`` / ``close()``)
and hands out the real :class:`cometbft_tpu.p2p.transport.UpgradedConn`
wrapper, so a production ``Switch`` (and the ``MConnection`` threads it
spawns) runs over simulated links unchanged — ``Node`` accepts it through
its ``transport_factory`` hook.

``SimNetwork`` owns the link model: per-pair base latency + seeded
jitter, optional bandwidth (serialization delay + a busy-until point per
directed link), and per-write drop.  Drops are whole-``write()`` calls —
``MConnection`` writes exactly one framed packet per call, so a dropped
write is a cleanly lost packet, never a desynced stream.  Partitions are
runtime-scriptable: ``partition(groups)`` silently discards traffic (and
refuses dials) across group boundaries until ``heal()``.

Delivery happens through ``clock.timer`` — a real ``MonotonicClock``
delivers on wall-time ``threading.Timer``s; a ``SimClock`` delivers when
the driver (or the blocked-actor advance) reaches the due time.  Per
directed link, delivery times are clamped monotonic so jitter can delay
but never reorder a byte stream.
"""

from __future__ import annotations

import random
import threading

from cometbft_tpu.p2p.transport import TransportError, UpgradedConn
from cometbft_tpu.simnet.clock import MonotonicClock


def _host_port(addr: str) -> str:
    """'proto://id@host:port' -> 'host:port' (mirrors transport._split_addr)."""
    if "://" in addr:
        addr = addr.split("://", 1)[1]
    if "@" in addr:
        addr = addr.split("@", 1)[1]
    return addr


class SimConn:
    """One endpoint of an in-memory duplex byte pipe.

    Surface = what ``MConnection`` and ``UpgradedConn`` need from a
    ``SecretConnection``: ``write``/``sendall``, ``read_exact``/``recv``,
    ``close``, and ``rem_pub_key`` (the peer-id source).
    """

    def __init__(self, network: "SimNetwork", local_id: str, remote_id: str, rem_pub_key):
        self.network = network
        self.local_id = local_id
        self.remote_id = remote_id
        self.rem_pub_key = rem_pub_key
        self.peer: "SimConn | None" = None  # set by the pairing dial
        self._buf = bytearray()
        self._cond = threading.Condition()
        self._closed = False
        self._eof = False  # peer closed: drain the buffer, then EOF

    # -- sending ------------------------------------------------------------

    def write(self, data: bytes) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        self.network._transmit(self, bytes(data))

    sendall = write

    # -- receiving ----------------------------------------------------------

    def _deliver(self, data: bytes) -> None:
        with self._cond:
            if self._closed:
                return
            self._buf += data
            self._cond.notify_all()

    def _signal_eof(self) -> None:
        with self._cond:
            self._eof = True
            self._cond.notify_all()

    def read_exact(self, n: int) -> bytes:
        with self._cond:
            while len(self._buf) < n:
                if self._closed or self._eof:
                    raise ConnectionError("connection closed")
                # Real-time poll as a lost-wakeup backstop; deliveries
                # notify, so the common path never waits the full tick.
                self._cond.wait(0.1)
            out = bytes(self._buf[:n])
            del self._buf[:n]
            return out

    def recv(self, n: int) -> bytes:
        with self._cond:
            while not self._buf:
                if self._closed or self._eof:
                    return b""  # socket-style EOF
                self._cond.wait(0.1)
            out = bytes(self._buf[:n])
            del self._buf[: len(out)]
            return out

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self.peer is not None:
            self.peer._signal_eof()


class SimNetwork:
    """Shared medium: listener registry + seeded per-link WAN model."""

    def __init__(
        self,
        clock=None,
        seed: int = 0,
        latency_s: float = 0.0,
        jitter_s: float = 0.0,
        bandwidth_bps: float = 0.0,  # 0 = infinite
        drop_p: float = 0.0,
    ):
        self.clock = clock or MonotonicClock()
        self._rng = random.Random(seed)
        self._mtx = threading.RLock()
        self._listeners: dict[str, SimTransport] = {}
        self._defaults = {
            "latency_s": latency_s,
            "jitter_s": jitter_s,
            "bandwidth_bps": bandwidth_bps,
            "drop_p": drop_p,
        }
        self._link_overrides: dict[frozenset, dict] = {}
        self._groups: list[set[str]] | None = None  # active partition
        # Per directed link: when the link frees up (bandwidth) and the
        # last scheduled delivery time (FIFO clamp under jitter).
        self._busy_until: dict[tuple[str, str], float] = {}
        self._last_delivery: dict[tuple[str, str], float] = {}
        # Per-source adversarial send taps (see set_send_tap).
        self._send_taps: dict[str, object] = {}
        self.stats = {"delivered": 0, "dropped": 0, "partitioned": 0, "tapped": 0}

    # -- topology scripting --------------------------------------------------

    def set_link(self, a_id: str, b_id: str, **params) -> None:
        """Override latency_s/jitter_s/bandwidth_bps/drop_p for one pair."""
        bad = set(params) - set(self._defaults)
        if bad:
            raise ValueError(f"unknown link params {sorted(bad)}")
        with self._mtx:
            self._link_overrides.setdefault(frozenset((a_id, b_id)), {}).update(params)

    def partition(self, groups) -> None:
        """Split the net: traffic (and dials) crossing group boundaries is
        silently discarded. Nodes in no group keep full connectivity."""
        with self._mtx:
            self._groups = [set(g) for g in groups]

    def heal(self) -> None:
        with self._mtx:
            self._groups = None

    def reachable(self, a_id: str, b_id: str) -> bool:
        with self._mtx:
            if self._groups is None:
                return True
            ga = next((g for g in self._groups if a_id in g), None)
            gb = next((g for g in self._groups if b_id in g), None)
            if ga is None or gb is None:
                return True
            return ga is gb

    def link_params(self, a_id: str, b_id: str) -> dict:
        with self._mtx:
            p = dict(self._defaults)
            p.update(self._link_overrides.get(frozenset((a_id, b_id)), {}))
            return p

    def set_send_tap(self, node_id: str, fn) -> None:
        """Install an adversarial tap on every write ``node_id`` makes.

        ``fn(dst_id, data)`` returns ``None`` to pass the write through
        untouched, or a list of ``(extra_delay_s, payload)`` replacements:
        ``[]`` drops the write, one entry delays/rewrites it, several
        duplicate it. Taps operate on whole ``write()`` calls — one framed
        MConnection packet — so a byzantine tap can reorder/replay/withhold
        *packets* without ever desyncing a stream (same granularity as the
        link drop model). ``fn=None`` removes the tap.
        """
        with self._mtx:
            if fn is None:
                self._send_taps.pop(node_id, None)
            else:
                self._send_taps[node_id] = fn

    # -- wire ----------------------------------------------------------------

    def _transmit(self, src: SimConn, data: bytes) -> None:
        dst = src.peer
        if dst is None:
            raise ConnectionError("unpaired conn")
        tap = self._send_taps.get(src.local_id)
        if tap is not None:
            plan = tap(src.remote_id, data)
            if plan is not None:
                self.stats["tapped"] += 1
                for extra_delay, payload in plan:
                    self._schedule(src, dst, bytes(payload), float(extra_delay))
                return
        self._schedule(src, dst, data, 0.0)

    def _schedule(self, src: SimConn, dst: SimConn, data: bytes, extra_delay: float) -> None:
        with self._mtx:
            if not self.reachable(src.local_id, src.remote_id):
                self.stats["partitioned"] += 1
                return
            p = self.link_params(src.local_id, src.remote_id)
            if p["drop_p"] > 0 and self._rng.random() < p["drop_p"]:
                self.stats["dropped"] += 1
                return
            now = self.clock.now()
            key = (src.local_id, src.remote_id)
            delay = p["latency_s"] + extra_delay
            if p["jitter_s"] > 0:
                delay += self._rng.uniform(0.0, p["jitter_s"])
            if p["bandwidth_bps"] > 0:
                tx = len(data) * 8.0 / p["bandwidth_bps"]
                start = max(now, self._busy_until.get(key, 0.0))
                self._busy_until[key] = start + tx
                deliver_at = start + tx + delay
            else:
                deliver_at = now + delay
            # FIFO per directed link: jitter may stretch, never reorder.
            deliver_at = max(deliver_at, self._last_delivery.get(key, 0.0))
            self._last_delivery[key] = deliver_at
            self.stats["delivered"] += 1
        self.clock.timer(max(deliver_at - now, 0.0), dst._deliver, data)

    # -- listeners ------------------------------------------------------------

    def _register(self, hp: str, transport: "SimTransport") -> str:
        with self._mtx:
            if hp in self._listeners:
                raise TransportError(f"sim address {hp} already bound")
            self._listeners[hp] = transport
            return hp

    def _unregister(self, transport: "SimTransport") -> None:
        with self._mtx:
            for hp, t in list(self._listeners.items()):
                if t is transport:
                    del self._listeners[hp]

    def _lookup(self, hp: str) -> "SimTransport | None":
        with self._mtx:
            return self._listeners.get(hp)


class SimTransport:
    """transport.MultiplexTransport duck-type over a SimNetwork."""

    def __init__(self, node_info, node_key, network: SimNetwork, fuzz_config=None):
        # fuzz_config accepted for factory-signature parity; the link model
        # subsumes it (latency/drop live in SimNetwork, seeded).
        self.node_info = node_info
        self.node_key = node_key
        self.network = network
        self._accept_cb = None
        self._closed = False

    def listen(self, addr: str, accept_cb) -> str:
        actual = self.network._register(_host_port(addr), self)
        self._accept_cb = accept_cb
        if not self.node_info.listen_addr:
            self.node_info.listen_addr = actual
        return actual

    def dial(self, addr: str, expected_id: str = "") -> UpgradedConn:
        if self._closed:
            raise TransportError("transport closed")
        hp = _host_port(addr)
        remote = self.network._lookup(hp)
        if remote is None or remote._closed or remote._accept_cb is None:
            raise TransportError(f"sim dial {hp}: no listener")
        if not self.network.reachable(self.node_key.id, remote.node_key.id):
            raise TransportError(f"sim dial {hp}: partitioned")
        if expected_id and remote.node_key.id != expected_id:
            raise TransportError(
                f"dialed {expected_id} but got {remote.node_key.id}"
            )
        try:
            self.node_info.compatible_with(remote.node_info)
        except Exception as e:
            raise TransportError(f"incompatible peer: {e}") from None
        out = SimConn(
            self.network, self.node_key.id, remote.node_key.id,
            remote.node_key.pub_key(),
        )
        inb = SimConn(
            self.network, remote.node_key.id, self.node_key.id,
            self.node_key.pub_key(),
        )
        out.peer, inb.peer = inb, out
        up_out = UpgradedConn(out, remote.node_info, outbound=True, remote_addr=hp)
        up_in = UpgradedConn(
            inb, self.node_info, outbound=False,
            remote_addr=self.node_info.listen_addr or f"{self.node_key.id[:8]}:0",
        )
        # In-process accept: the listener learns of the conn synchronously
        # (the real transport hands it to the accept thread's callback).
        remote._accept_cb(up_in)
        return up_out

    def close(self) -> None:
        self._closed = True
        self.network._unregister(self)

"""simnet: deterministic virtual-clock network simulation (round 13).

Three layers:

* :mod:`cometbft_tpu.simnet.clock` — the ``Clock`` abstraction every
  consensus/p2p timer now goes through: ``MonotonicClock`` (wall time,
  the production default — behavior identical to the pre-simnet code)
  and ``SimClock`` (an event-heap virtual clock that advances only when
  every registered actor is blocked, so simulated seconds cost only the
  host time needed to drain the events they contain).
* :mod:`cometbft_tpu.simnet.transport` — ``SimTransport``/``SimConn``:
  the ``MultiplexTransport``/``UpgradedConn`` surface over in-memory
  pipes with a seeded per-link latency/jitter/bandwidth/drop model and
  runtime-scriptable partitions and heals.
* :mod:`cometbft_tpu.simnet.scenario` — the deterministic scenario
  harness: 50-200 in-process validators on ONE ``SimClock``, WAN latency
  matrices, partition/churn schedules, replayable bit-identically from
  the seed (``network = "sim"`` e2e manifests route here).
"""

from cometbft_tpu.simnet.clock import Clock, MonotonicClock, SimClock

__all__ = [
    "Clock",
    "MonotonicClock",
    "SimClock",
    "SimNetwork",
    "SimTransport",
    "run_scenario",
]


def __getattr__(name):
    # Lazy: scenario/transport import consensus+p2p, which themselves import
    # simnet.clock — an eager import here would be circular.
    if name in ("SimNetwork", "SimTransport"):
        from cometbft_tpu.simnet import transport

        return getattr(transport, name)
    if name == "run_scenario":
        from cometbft_tpu.simnet.scenario import run_scenario

        return run_scenario
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

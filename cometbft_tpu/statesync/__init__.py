"""Statesync: bootstrap a fresh node from application snapshots instead of
replaying the whole chain (reference: statesync/ — syncer.go, reactor.go,
stateprovider.go, chunks.go).

Flow: discover snapshots from peers (channel 0x60) → offer the best one to
the local app (ABCI OfferSnapshot) → fetch + apply chunks in parallel
(channel 0x61, ABCI ApplySnapshotChunk) → verify the restored app against
the light-client-trusted app hash → bootstrap state/block stores → hand off
to blocksync, then consensus.
"""

from cometbft_tpu.statesync.reactor import StatesyncReactor
from cometbft_tpu.statesync.stateprovider import LightClientStateProvider, StateProvider
from cometbft_tpu.statesync.syncer import (
    ErrAbort,
    ErrNoSnapshots,
    ErrRejectSnapshot,
    Syncer,
)

__all__ = [
    "StatesyncReactor",
    "Syncer",
    "StateProvider",
    "LightClientStateProvider",
    "ErrAbort",
    "ErrNoSnapshots",
    "ErrRejectSnapshot",
]

"""Statesync wire messages (reference: proto/tendermint/statesync/types.proto,
statesync/reactor.go:19-22 channels 0x60/0x61).

Envelope: oneof-style outer message, one tag per variant — the same codec
shape as blocksync (cometbft_tpu/blocksync/reactor.py)."""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.wire import proto

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61

_TAG_SNAPSHOTS_REQUEST = 1
_TAG_SNAPSHOTS_RESPONSE = 2
_TAG_CHUNK_REQUEST = 3
_TAG_CHUNK_RESPONSE = 4


@dataclass
class SnapshotsRequest:
    pass


@dataclass
class SnapshotsResponse:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""

    def key(self) -> tuple:
        """Identity of a snapshot across peers (statesync/snapshots.go)."""
        return (self.height, self.format, self.chunks, self.hash)


@dataclass
class ChunkRequest:
    height: int = 0
    format: int = 0
    index: int = 0


@dataclass
class ChunkResponse:
    height: int = 0
    format: int = 0
    index: int = 0
    chunk: bytes = b""
    missing: bool = False


def encode(msg) -> bytes:
    if isinstance(msg, SnapshotsRequest):
        return proto.field_message(_TAG_SNAPSHOTS_REQUEST, b"", emit_empty=True)
    if isinstance(msg, SnapshotsResponse):
        inner = (
            proto.field_varint(1, msg.height)
            + proto.field_varint(2, msg.format)
            + proto.field_varint(3, msg.chunks)
            + proto.field_bytes(4, msg.hash)
            + proto.field_bytes(5, msg.metadata)
        )
        return proto.field_message(_TAG_SNAPSHOTS_RESPONSE, inner, emit_empty=True)
    if isinstance(msg, ChunkRequest):
        inner = (
            proto.field_varint(1, msg.height)
            + proto.field_varint(2, msg.format)
            + proto.field_varint(3, msg.index)
        )
        return proto.field_message(_TAG_CHUNK_REQUEST, inner, emit_empty=True)
    if isinstance(msg, ChunkResponse):
        inner = (
            proto.field_varint(1, msg.height)
            + proto.field_varint(2, msg.format)
            + proto.field_varint(3, msg.index)
            + proto.field_bytes(4, msg.chunk)
            + proto.field_bool(5, msg.missing)
        )
        return proto.field_message(_TAG_CHUNK_RESPONSE, inner, emit_empty=True)
    raise TypeError(f"unknown statesync message {type(msg)}")


def decode(data: bytes):
    fields = proto.decode_fields(data)
    if _TAG_SNAPSHOTS_REQUEST in fields:
        return SnapshotsRequest()
    if _TAG_SNAPSHOTS_RESPONSE in fields:
        f = proto.decode_fields(fields[_TAG_SNAPSHOTS_RESPONSE][-1])
        return SnapshotsResponse(
            height=proto.get_uvarint(f, 1),
            format=proto.get_uvarint(f, 2),
            chunks=proto.get_uvarint(f, 3),
            hash=proto.get_bytes(f, 4),
            metadata=proto.get_bytes(f, 5),
        )
    if _TAG_CHUNK_REQUEST in fields:
        f = proto.decode_fields(fields[_TAG_CHUNK_REQUEST][-1])
        return ChunkRequest(
            height=proto.get_uvarint(f, 1),
            format=proto.get_uvarint(f, 2),
            index=proto.get_uvarint(f, 3),
        )
    if _TAG_CHUNK_RESPONSE in fields:
        f = proto.decode_fields(fields[_TAG_CHUNK_RESPONSE][-1])
        return ChunkResponse(
            height=proto.get_uvarint(f, 1),
            format=proto.get_uvarint(f, 2),
            index=proto.get_uvarint(f, 3),
            chunk=proto.get_bytes(f, 4),
            missing=proto.get_bool(f, 5),
        )
    raise ValueError("unknown statesync message")

"""Trusted state for statesync via the light client
(reference: statesync/stateprovider.go:27-48).

The syncer must not trust peers about what the restored app SHOULD hash to —
the app hash, validator sets, and commit all come from light-client-verified
headers. A snapshot at height H restored the app state AFTER block H, so its
hash appears in header H+1 (stateprovider.go AppHash), and rebuilding
sm.State needs the validator sets at H, H+1, and H+2."""

from __future__ import annotations

from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.light.client import Client, TrustOptions
from cometbft_tpu.light.store import LightStore
from cometbft_tpu.state.state import State
from cometbft_tpu.types.cmttime import now as time_now
from cometbft_tpu.types.params import ConsensusParams


class StateProvider:
    """stateprovider.go StateProvider interface."""

    def app_hash(self, height: int) -> bytes:
        raise NotImplementedError

    def commit(self, height: int):
        raise NotImplementedError

    def state(self, height: int) -> State:
        raise NotImplementedError


class LightClientStateProvider(StateProvider):
    """stateprovider.go:51-90 lightClientStateProvider: wraps a light.Client
    over one or more providers (RPC in production, mocks in tests)."""

    def __init__(
        self,
        chain_id: str,
        primary,
        witnesses: list,
        trust_height: int,
        trust_hash: bytes,
        trust_period_ns: int = 168 * 3600 * 10**9,
        initial_height: int = 1,
        consensus_params: ConsensusParams | None = None,
        now=None,
    ):
        self.chain_id = chain_id
        self.initial_height = initial_height
        self._params = consensus_params or ConsensusParams()
        self._now = now or time_now
        self._client = Client(
            chain_id,
            TrustOptions(
                period_ns=trust_period_ns, height=trust_height, hash=trust_hash
            ),
            primary,
            witnesses,
            LightStore(MemDB()),
        )

    def _verified(self, height: int):
        return self._client.verify_light_block_at_height(height, self._now())

    def app_hash(self, height: int) -> bytes:
        """stateprovider.go AppHash: header H+1 carries the app hash of the
        state after block H."""
        return self._verified(height + 1).signed_header.header.app_hash

    def commit(self, height: int):
        """The verified commit FOR block `height` (saved as the seen commit
        so consensus can build on it)."""
        return self._verified(height).signed_header.commit

    def state(self, height: int) -> State:
        """stateprovider.go State: rebuild sm.State for last_block_height =
        `height` from verified headers at H, H+1, H+2."""
        lb_last = self._verified(height)
        lb_cur = self._verified(height + 1)
        lb_next = self._verified(height + 2)
        header_cur = lb_cur.signed_header.header
        return State(
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            last_block_height=height,
            last_block_id=header_cur.last_block_id,
            last_block_time=lb_last.signed_header.header.time,
            last_validators=lb_last.validator_set,
            validators=lb_cur.validator_set,
            next_validators=lb_next.validator_set,
            last_height_validators_changed=height + 1,
            consensus_params=self._params,
            last_height_consensus_params_changed=self.initial_height,
            last_results_hash=header_cur.last_results_hash,
            app_hash=header_cur.app_hash,
        )

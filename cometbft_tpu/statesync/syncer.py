"""Statesync syncer: pick a snapshot, restore the app from chunks, verify
against light-client truth (reference: statesync/syncer.go — SyncAny :145,
offerSnapshot :322, fetchChunks/applyChunks :358-470, verifyApp :485;
chunk bookkeeping from statesync/chunks.go, candidate ranking from
statesync/snapshots.go).

Host-tier design: the syncer is driven by one thread (the node's statesync
phase); chunk fetch requests go out through the reactor, responses arrive on
the reactor's receive path and land in a condition-guarded chunk table. A
small pool of request threads keeps `chunk_fetchers` requests in flight —
the same pipeline the reference builds with goroutines."""

from __future__ import annotations

import threading
import time

from cometbft_tpu.abci import types as abci_types
from cometbft_tpu.statesync.messages import SnapshotsResponse

# DoS bound on peer-supplied snapshot metadata: chunk tables are allocated
# up-front, so an unvalidated `chunks` would let one malicious
# SnapshotsResponse OOM the node (reference bounds via chunkMsgSize etc.).
MAX_SNAPSHOT_CHUNKS = 16384


class ErrNoSnapshots(Exception):
    """syncer.go errNoSnapshots: no viable snapshot (left)."""


class ErrRejectSnapshot(Exception):
    """App rejected this snapshot; try another."""


class ErrAbort(Exception):
    """App aborted statesync entirely (syncer.go errAbort)."""


class ErrVerifyFailed(Exception):
    """Restored app does not match the trusted app hash."""


class _Candidate:
    def __init__(self, snapshot: SnapshotsResponse):
        self.snapshot = snapshot
        self.peers: set[str] = set()
        self.rejected = False


class Syncer:
    """statesync/syncer.go syncer."""

    def __init__(
        self,
        snapshot_conn,
        query_conn,
        state_provider,
        request_chunk,
        chunk_timeout: float = 10.0,
        chunk_fetchers: int = 4,
        logger=None,
    ):
        self.snapshot_conn = snapshot_conn
        self.query_conn = query_conn
        self.state_provider = state_provider
        self.request_chunk = request_chunk  # (peer_id, height, format, index)
        self.chunk_timeout = chunk_timeout
        self.chunk_fetchers = chunk_fetchers
        self.logger = logger
        self._lock = threading.Condition()
        self._candidates: dict[tuple, _Candidate] = {}
        self._chunks: dict[int, bytes] = {}
        self._current: SnapshotsResponse | None = None
        self._banned_peers: set[str] = set()

    # -- inputs from the reactor ---------------------------------------------

    def add_snapshot(self, peer_id: str, snapshot: SnapshotsResponse) -> None:
        """syncer.go AddSnapshot: register a peer's snapshot offer."""
        if (
            snapshot.height <= 0
            or not 1 <= snapshot.chunks <= MAX_SNAPSHOT_CHUNKS
        ):
            return
        with self._lock:
            cand = self._candidates.setdefault(snapshot.key(), _Candidate(snapshot))
            cand.peers.add(peer_id)
            self._lock.notify_all()

    def add_chunk(self, height: int, fmt: int, index: int, chunk: bytes) -> None:
        """syncer.go AddChunk via chunks.go: accept only chunks for the
        snapshot currently being restored."""
        with self._lock:
            cur = self._current
            if cur is None or height != cur.height or fmt != cur.format:
                return
            if index not in self._chunks:
                self._chunks[index] = chunk
                self._lock.notify_all()

    # -- the sync loop --------------------------------------------------------

    def sync_any(self, discovery_time: float = 2.0, timeout: float = 120.0):
        """syncer.go:145 SyncAny: wait for discovery, then try candidates
        best-first until one restores. Returns (state, commit)."""
        deadline = time.time() + timeout
        time.sleep(discovery_time)
        while time.time() < deadline:
            cand = self._best_candidate()
            if cand is None:
                with self._lock:
                    self._lock.wait(1.0)
                continue
            try:
                return self._sync_one(cand, deadline)
            except (ErrRejectSnapshot, ErrVerifyFailed) as e:
                cand.rejected = True
                self._log(
                    f"snapshot {cand.snapshot.height} unusable ({e}); trying next"
                )
            except ErrAbort:
                raise
            except Exception as e:
                # Provider hiccup (e.g. the light provider can't serve H+2
                # for a tip snapshot yet): keep the candidate, retry shortly
                # — syncer.go SyncAny's retry loop. Bounded by `deadline`.
                self._log(f"snapshot {cand.snapshot.height} retry later: {e}")
                with self._lock:
                    self._lock.wait(1.0)
        raise ErrNoSnapshots("statesync timed out without a restorable snapshot")

    def _best_candidate(self) -> _Candidate | None:
        """snapshots.go Best(): highest height, then newest format, then most
        peers."""
        with self._lock:
            viable = [
                c
                for c in self._candidates.values()
                if not c.rejected and c.peers - self._banned_peers
            ]
        if not viable:
            return None
        return max(
            viable,
            key=lambda c: (c.snapshot.height, c.snapshot.format, len(c.peers)),
        )

    def _sync_one(self, cand: _Candidate, deadline: float):
        snapshot = cand.snapshot
        trusted_app_hash = self.state_provider.app_hash(snapshot.height)
        self._offer(snapshot, trusted_app_hash)
        with self._lock:
            self._current = snapshot
            self._chunks = {}
        try:
            self._fetch_and_apply(cand, deadline)
        finally:
            with self._lock:
                self._current = None
        state = self.state_provider.state(snapshot.height)
        commit = self.state_provider.commit(snapshot.height)
        self._verify_app(snapshot, state)
        return state, commit

    def _offer(self, snapshot: SnapshotsResponse, app_hash: bytes) -> None:
        """syncer.go:322 offerSnapshot."""
        res = self.snapshot_conn.offer_snapshot(
            abci_types.RequestOfferSnapshot(
                snapshot=abci_types.Snapshot(
                    height=snapshot.height,
                    format=snapshot.format,
                    chunks=snapshot.chunks,
                    hash=snapshot.hash,
                    metadata=snapshot.metadata,
                ),
                app_hash=app_hash,
            )
        )
        if res.result == abci_types.OFFER_SNAPSHOT_ACCEPT:
            return
        if res.result == abci_types.OFFER_SNAPSHOT_ABORT:
            raise ErrAbort("app aborted statesync on snapshot offer")
        raise ErrRejectSnapshot(f"offer result {res.result}")

    def _fetch_and_apply(self, cand: _Candidate, deadline: float) -> None:
        """syncer.go:358-470: pipelined fetch (chunk_fetchers in flight) +
        strictly in-order apply, with refetch rollback."""
        snapshot = cand.snapshot
        next_apply = 0
        requested_at: dict[int, float] = {}
        rr = 0
        while next_apply < snapshot.chunks:
            if time.time() > deadline:
                raise ErrNoSnapshots("chunk fetch timed out")
            peers = sorted(cand.peers - self._banned_peers)
            if not peers:
                raise ErrRejectSnapshot("no peers left serving this snapshot")
            now = time.time()
            with self._lock:
                outstanding = [
                    i
                    for i in range(next_apply, snapshot.chunks)
                    if i not in self._chunks
                ]
                in_flight = sum(
                    1
                    for i in outstanding
                    if now - requested_at.get(i, -1e18) <= self.chunk_timeout
                )
                to_request = [
                    i
                    for i in outstanding
                    if now - requested_at.get(i, -1e18) > self.chunk_timeout
                ][: max(0, self.chunk_fetchers - in_flight)]
            for i in to_request:
                peer = peers[rr % len(peers)]
                rr += 1
                requested_at[i] = now
                self.request_chunk(peer, snapshot.height, snapshot.format, i)
            with self._lock:
                if next_apply not in self._chunks:
                    self._lock.wait(0.05)
                    continue
                chunk = self._chunks[next_apply]
            res = self.snapshot_conn.apply_snapshot_chunk(
                abci_types.RequestApplySnapshotChunk(index=next_apply, chunk=chunk)
            )
            if res.result == abci_types.APPLY_CHUNK_ABORT:
                raise ErrAbort("app aborted statesync on chunk apply")
            if res.result == abci_types.APPLY_CHUNK_REJECT_SNAPSHOT:
                raise ErrRejectSnapshot("app rejected snapshot on chunk apply")
            for peer in res.reject_senders:
                self._banned_peers.add(peer)
            if res.result == abci_types.APPLY_CHUNK_RETRY_SNAPSHOT:
                refetch = set(range(snapshot.chunks))
            elif res.refetch_chunks or res.result == abci_types.APPLY_CHUNK_RETRY:
                refetch = set(res.refetch_chunks) or {next_apply}
            else:
                refetch = None
            if refetch is not None:
                # Roll back the apply cursor to the earliest refetched chunk:
                # already-applied chunks the app dropped must be re-applied
                # (chunks.go Retry/RetryAll semantics).
                with self._lock:
                    for i in refetch:
                        self._chunks.pop(i, None)
                        requested_at.pop(i, None)
                next_apply = min(next_apply, min(refetch))
                continue
            if res.result != abci_types.APPLY_CHUNK_ACCEPT:
                raise ErrRejectSnapshot(f"chunk apply result {res.result}")
            next_apply += 1

    def _verify_app(self, snapshot: SnapshotsResponse, state) -> None:
        """syncer.go:485 verifyApp: the restored app must sit exactly at the
        snapshot height with the trusted app hash."""
        info = self.query_conn.info(abci_types.RequestInfo())
        if info.last_block_height != snapshot.height:
            raise ErrVerifyFailed(
                f"app height {info.last_block_height} != snapshot height "
                f"{snapshot.height}"
            )
        if info.last_block_app_hash != state.app_hash:
            raise ErrVerifyFailed(
                f"app hash {info.last_block_app_hash.hex()} != trusted "
                f"{state.app_hash.hex()}"
            )

    def _log(self, msg: str) -> None:
        if self.logger:
            self.logger.info(msg)

"""Statesync p2p reactor (reference: statesync/reactor.go — channels
0x60/0x61, snapshot/chunk serving from the local app, response routing into
the syncer)."""

from __future__ import annotations

from cometbft_tpu.abci import types as abci_types
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.reactor import Reactor
from cometbft_tpu.statesync import messages as m

# reactor.go: recentSnapshots served per request.
RECENT_SNAPSHOTS = 10


class StatesyncReactor(Reactor):
    """statesync/reactor.go Reactor. Serving side always on; the syncing side
    activates when a Syncer is attached (node boot phase)."""

    def __init__(self, snapshot_conn=None, syncer=None):
        super().__init__("STATESYNC")
        self.snapshot_conn = snapshot_conn  # local app's snapshot connection
        self.syncer = syncer

    def set_syncer(self, syncer) -> None:
        self.syncer = syncer

    def get_channels(self):
        return [
            ChannelDescriptor(
                m.SNAPSHOT_CHANNEL,
                priority=5,
                send_queue_capacity=10,
                recv_message_capacity=4 * 1024 * 1024,
            ),
            ChannelDescriptor(
                m.CHUNK_CHANNEL,
                priority=3,
                send_queue_capacity=4,
                recv_message_capacity=20 * 1024 * 1024,
            ),
        ]

    def add_peer(self, peer) -> None:
        """reactor.go AddPeer: a syncing node asks every new peer for its
        snapshots."""
        if self.syncer is not None:
            peer.try_send(m.SNAPSHOT_CHANNEL, m.encode(m.SnapshotsRequest()))

    def request_snapshots(self) -> None:
        """Broadcast discovery (syncer.go SyncAny's periodic re-discovery)."""
        if self.switch:
            self.switch.broadcast(m.SNAPSHOT_CHANNEL, m.encode(m.SnapshotsRequest()))

    def request_chunk(self, peer_id: str, height: int, fmt: int, index: int) -> None:
        peer = self.switch.get_peer(peer_id) if self.switch else None
        if peer is not None:
            peer.try_send(
                m.CHUNK_CHANNEL,
                m.encode(m.ChunkRequest(height=height, format=fmt, index=index)),
            )

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        msg = m.decode(msg_bytes)
        if isinstance(msg, m.SnapshotsRequest):
            for snap in self._local_snapshots():
                peer.try_send(
                    m.SNAPSHOT_CHANNEL,
                    m.encode(
                        m.SnapshotsResponse(
                            height=snap.height,
                            format=snap.format,
                            chunks=snap.chunks,
                            hash=snap.hash,
                            metadata=snap.metadata,
                        )
                    ),
                )
        elif isinstance(msg, m.SnapshotsResponse):
            if self.syncer is not None:
                self.syncer.add_snapshot(peer.id, msg)
        elif isinstance(msg, m.ChunkRequest):
            chunk = b""
            if self.snapshot_conn is not None:
                res = self.snapshot_conn.load_snapshot_chunk(
                    abci_types.RequestLoadSnapshotChunk(
                        height=msg.height, format=msg.format, chunk=msg.index
                    )
                )
                chunk = res.chunk
            peer.try_send(
                m.CHUNK_CHANNEL,
                m.encode(
                    m.ChunkResponse(
                        height=msg.height,
                        format=msg.format,
                        index=msg.index,
                        chunk=chunk,
                        missing=not chunk,
                    )
                ),
            )
        elif isinstance(msg, m.ChunkResponse):
            if self.syncer is not None and not msg.missing:
                self.syncer.add_chunk(msg.height, msg.format, msg.index, msg.chunk)

    def _local_snapshots(self):
        """reactor.go recentSnapshots: newest first, capped."""
        if self.snapshot_conn is None:
            return []
        res = self.snapshot_conn.list_snapshots(abci_types.RequestListSnapshots())
        snaps = sorted(res.snapshots, key=lambda s: (s.height, s.format), reverse=True)
        return snaps[:RECENT_SNAPSHOTS]

"""Manifest-driven e2e testnet runner (reference: test/e2e/pkg/manifest.go +
test/e2e/runner).

The reference drives docker-compose testnets from a TOML manifest: node
topology, per-node perturbation schedules (kill / pause / disconnect /
restart), transaction load, then a liveness + hash-agreement check and an
optional benchmark report.  This is that runner over OS processes on
loopback (the deployment substrate this framework's e2e tier uses —
tests/test_e2e_processes.py holds the individual perturbations to their
semantics; this module sequences them from a manifest).

Manifest subset (same field names as the reference where they apply):

    initial_height = 1
    load_tx_rate = 100          # tx/s sustained against node 0
    target_blocks = 12          # blocks every node must reach post-perturb
    [node.validator01]
    [node.validator02]
    perturb = ["pause", "kill"]
    [node.validator03]
    perturb = ["disconnect"]

Run: ``python -m cometbft_tpu.cmd e2e --manifest m.toml`` or
``E2ERunner(manifest_path).run()``.
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import tomllib
from dataclasses import dataclass, field


@dataclass
class ManifestNode:
    name: str
    perturb: list[str] = field(default_factory=list)


@dataclass
class Manifest:
    initial_height: int = 1
    load_tx_rate: int = 50
    target_blocks: int = 8
    nodes: list[ManifestNode] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Manifest":
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        nodes = [
            ManifestNode(name=name, perturb=list(spec.get("perturb", [])))
            for name, spec in raw.get("node", {}).items()
        ]
        if not nodes:
            raise ValueError("manifest has no [node.*] entries")
        known = {"kill", "pause", "disconnect", "restart"}
        for n in nodes:
            bad = set(n.perturb) - known
            if bad:
                raise ValueError(f"{n.name}: unknown perturbations {sorted(bad)}")
        return cls(
            initial_height=int(raw.get("initial_height", 1)),
            load_tx_rate=int(raw.get("load_tx_rate", 50)),
            target_blocks=int(raw.get("target_blocks", 8)),
            nodes=nodes,
        )


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class E2ERunner:
    def __init__(self, manifest_path: str, home: str, log=print):
        self.manifest = Manifest.load(manifest_path)
        self.home = home
        self.log = log
        self.procs: dict[str, subprocess.Popen] = {}
        self.rpc_ports: dict[str, int] = {}
        self.p2p_ports: dict[str, int] = {}

    # -- setup ------------------------------------------------------------

    def setup(self) -> None:
        """testnet homes + config.toml per node (runner/setup.go shape)."""
        from cometbft_tpu.cmd.__main__ import main as cli
        from cometbft_tpu.config import default_config
        from cometbft_tpu.config.toml import write_config_file
        from cometbft_tpu.p2p.key import NodeKey

        names = [n.name for n in self.manifest.nodes]
        assert cli(
            ["testnet", "--validators", str(len(names)),
             "--output-dir", self.home, "--chain-id", "e2e-manifest"]
        ) == 0
        p2p = _free_ports(len(names))
        rpc = _free_ports(len(names))
        node_ids = [
            NodeKey.load(
                os.path.join(self.home, f"node{i}", "config", "node_key.json")
            ).id
            for i in range(len(names))
        ]
        peers = [
            f"{node_ids[i]}@127.0.0.1:{p2p[i]}" for i in range(len(names))
        ]
        for i, name in enumerate(names):
            home = os.path.join(self.home, f"node{i}")
            cfg = default_config()
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc[i]}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p[i]}"
            cfg.p2p.persistent_peers = ",".join(
                p for j, p in enumerate(peers) if j != i
            )
            cfg.p2p.addr_book_strict = False
            cfg.consensus.timeout_commit = 0.2
            cfg.consensus.skip_timeout_commit = False
            write_config_file(os.path.join(home, "config", "config.toml"), cfg)
            self.rpc_ports[name] = rpc[i]
            self.p2p_ports[name] = p2p[i]

    def _launch(self, idx: int) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "cometbft_tpu.cmd", "--home",
             os.path.join(self.home, f"node{idx}"), "start"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )

    def start(self) -> None:
        for i, node in enumerate(self.manifest.nodes):
            self.procs[node.name] = self._launch(i)
        self.log(f"started {len(self.procs)} nodes")

    # -- RPC helpers ------------------------------------------------------

    def _height(self, name: str) -> int:
        from cometbft_tpu.rpc.client import HTTPClient

        st = HTTPClient(
            f"http://127.0.0.1:{self.rpc_ports[name]}", timeout=3
        ).status()
        return int(st["sync_info"]["latest_block_height"])

    def wait_height(self, name: str, target: int, timeout: float = 240) -> int:
        deadline = time.time() + timeout
        last = -1
        while time.time() < deadline:
            try:
                last = self._height(name)
                if last >= target:
                    return last
            except Exception:
                pass
            time.sleep(0.3)
        raise TimeoutError(f"{name}: height {target} not reached (last {last})")

    # -- perturbations (runner/perturb.go) --------------------------------

    def perturb(self, node: ManifestNode, kind: str) -> None:
        name = node.name
        idx = [n.name for n in self.manifest.nodes].index(name)
        proc = self.procs[name]
        self.log(f"perturb {name}: {kind}")
        if kind == "kill" or kind == "restart":
            proc.send_signal(signal.SIGKILL)
            proc.wait()
            time.sleep(1.0)
            self.procs[name] = self._launch(idx)
        elif kind == "pause":
            proc.send_signal(signal.SIGSTOP)
            time.sleep(3.0)
            proc.send_signal(signal.SIGCONT)
        elif kind == "disconnect":
            pid = proc.pid
            t_end = time.time() + 4.0
            while time.time() < t_end:
                out = subprocess.run(
                    ["ss", "-tnp", "state", "established"],
                    capture_output=True, text=True,
                ).stdout
                for line in out.splitlines():
                    if f"pid={pid}," not in line:
                        continue
                    m = re.search(
                        r"(\d+\.\d+\.\d+\.\d+):(\d+)\s+"
                        r"(\d+\.\d+\.\d+\.\d+):(\d+)", line)
                    if not m:
                        continue
                    lip, lport, rip, rport = m.groups()
                    if int(lport) == self.rpc_ports[name] or \
                       int(rport) == self.rpc_ports[name]:
                        continue
                    subprocess.run(
                        ["ss", "-K", "src", lip, "sport", "=", lport,
                         "dst", rip, "dport", "=", rport],
                        capture_output=True,
                    )
                time.sleep(0.2)
        else:
            raise ValueError(kind)
        # After every perturbation the node must make progress again.  The
        # heal window is generous: a stall grows consensus round timeouts
        # (the reference's per-round timeout deltas), so the first
        # post-heal commit can take minutes after a partition.
        h = self.wait_height(self.manifest.nodes[0].name, 1)
        self.wait_height(name, h + 1, timeout=420)
        self.log(f"perturb {name}: {kind} healed")

    # -- load (loadtime payloads over RPC) --------------------------------

    def _load_pump(self, stop: threading.Event) -> None:
        from cometbft_tpu.loadtime import make_payload
        from cometbft_tpu.rpc.client import HTTPClient

        rate = max(1, self.manifest.load_tx_rate)
        target = self.manifest.nodes[0].name
        k = 0
        next_t = time.monotonic()
        while not stop.is_set():
            try:
                cli = HTTPClient(
                    f"http://127.0.0.1:{self.rpc_ports[target]}", timeout=3
                )
                tx = make_payload(k, time.time_ns())
                cli.call("broadcast_tx_async", tx="0x" + tx.hex())
                k += 1
            except Exception:
                pass
            next_t += 1.0 / rate
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    # -- the run ----------------------------------------------------------

    def run(self) -> dict:
        self.setup()
        self.start()
        stop = threading.Event()
        pump = threading.Thread(target=self._load_pump, args=(stop,), daemon=True)
        try:
            first = self.manifest.nodes[0].name
            h0 = self.wait_height(first, self.manifest.initial_height + 2)
            pump.start()
            for node in self.manifest.nodes:
                for kind in node.perturb:
                    self.perturb(node, kind)
            target = h0 + self.manifest.target_blocks
            heights = {
                n.name: self.wait_height(n.name, target, timeout=420)
                for n in self.manifest.nodes
            }
            # hash agreement at a common committed height (runner/test.go)
            from cometbft_tpu.rpc.client import HTTPClient

            common = min(heights.values())
            hashes = {
                n.name: HTTPClient(
                    f"http://127.0.0.1:{self.rpc_ports[n.name]}", timeout=5
                ).block(common)["block_id"]["hash"]
                for n in self.manifest.nodes
            }
            if len(set(hashes.values())) != 1:
                raise AssertionError(f"hash disagreement at {common}: {hashes}")
            report = {
                "nodes": len(self.manifest.nodes),
                "perturbations": sum(len(n.perturb) for n in self.manifest.nodes),
                "final_heights": heights,
                "agreed_height": common,
                "agreed_hash": next(iter(hashes.values())),
            }
            self.log(json.dumps(report))
            return report
        finally:
            stop.set()
            for proc in self.procs.values():
                if proc.poll() is None:
                    proc.send_signal(signal.SIGKILL)
                    proc.wait()

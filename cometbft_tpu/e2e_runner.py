"""Manifest-driven e2e testnet runner (reference: test/e2e/pkg/manifest.go +
test/e2e/runner).

The reference drives docker-compose testnets from a TOML manifest: node
topology, per-node perturbation schedules (kill / pause / disconnect /
restart — plus this framework's own ``backend_faults``, which restarts a
node with a chaos-injected supervised verification chain, and
``vote_batch``, which does that with a widened vote-admission micro-batch
window and asserts the validator's precommit still lands), transaction
load, then a liveness + hash-agreement check and an optional benchmark
report.  This is that runner over OS processes on
loopback (the deployment substrate this framework's e2e tier uses —
tests/test_e2e_processes.py holds the individual perturbations to their
semantics; this module sequences them from a manifest).

Manifest subset (same field names as the reference where they apply):

    initial_height = 1
    load_tx_rate = 100          # tx/s sustained against node 0
    target_blocks = 12          # blocks every node must reach post-perturb
    abci_protocol = "builtin"   # informational default; per-node overrides
    backend = "cpu"             # CMTPU_BACKEND for every node (cpu | hybrid)
    app = "kvstore"             # kvstore | persistent_kvstore
    snapshot_interval = 3       # app-side snapshots on genesis nodes
    validator_churn = true      # add+remove a validator via val: txs mid-run
    light_client = true         # sequentially verify the agreed height
    [node.validator01]
    [node.validator02]
    perturb = ["pause", "kill"]
    [node.validator03]
    key_type = "secp256k1"      # consensus key: ed25519 default
    abci = "socket"             # local | socket | grpc app boundary
    [node.full01]
    mode = "full"
    start_at = 5                # late join once the net reaches this height
    state_sync = true           # join via verified snapshot restore

Ordering contract (the generator enforces, load() validates): genesis
validators come first — node 0 is the height reference, load target and
statesync trust source, so it must be a genesis validator.

Run: ``python -m cometbft_tpu.cmd e2e --manifest m.toml`` or
``E2ERunner(manifest_path).run()``.
"""

from __future__ import annotations

import base64
import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

from cometbft_tpu.libs import tomlcompat as tomllib

MODES = ("validator", "full", "seed")
ABCI_MODES = ("local", "socket", "grpc")
PERTURBATIONS = (
    "kill", "pause", "disconnect", "restart", "backend_faults",
    "concurrent_light_clients", "tx_flood", "vote_batch",
    "light_gateway", "mixed_load", "recv_flood", "bundle_cold_sync",
)
BACKENDS = ("cpu", "hybrid")
APPS = ("kvstore", "persistent_kvstore")


@dataclass
class ManifestNode:
    name: str
    mode: str = "validator"  # validator | full | seed
    key_type: str = "ed25519"  # consensus key type (validators)
    start_at: int = 0  # 0 = genesis; >0 = join at that net height
    state_sync: bool = False  # late join via snapshot restore
    abci: str = "local"  # local | socket | grpc app boundary
    perturb: list[str] = field(default_factory=list)

    def is_validator(self) -> bool:
        return self.mode == "validator"


@dataclass
class Manifest:
    initial_height: int = 1
    load_tx_rate: int = 50
    target_blocks: int = 8
    backend: str = "cpu"  # CMTPU_BACKEND handed to every node
    app: str = "kvstore"  # ABCI app all nodes run
    snapshot_interval: int = 0  # app snapshots on genesis nodes
    validator_churn: bool = False  # val: tx add/remove mid-run
    light_client: bool = False  # verify the agreed height
    seed: int = -1  # generator seed (informational; -1 = hand-written)
    network: str = "real"  # real = OS processes; sim = virtual-clock simnet
    sim: dict = field(default_factory=dict)  # scenario spec (network = "sim")
    nodes: list[ManifestNode] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Manifest":
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        network = str(raw.get("network", "real"))
        if network == "sim":
            return cls._load_sim(raw)
        if network != "real":
            raise ValueError(f"unknown network {network!r} (want real | sim)")
        from cometbft_tpu.privval.file import KEY_TYPES

        nodes = [
            ManifestNode(
                name=name,
                mode=str(spec.get("mode", "validator")),
                key_type=str(spec.get("key_type", "ed25519")),
                start_at=int(spec.get("start_at", 0)),
                state_sync=bool(spec.get("state_sync", False)),
                abci=str(spec.get("abci", "local")),
                perturb=list(spec.get("perturb", [])),
            )
            for name, spec in raw.get("node", {}).items()
        ]
        if not nodes:
            raise ValueError("manifest has no [node.*] entries")
        m = cls(
            initial_height=int(raw.get("initial_height", 1)),
            load_tx_rate=int(raw.get("load_tx_rate", 50)),
            target_blocks=int(raw.get("target_blocks", 8)),
            backend=str(raw.get("backend", "cpu")),
            app=str(raw.get("app", "kvstore")),
            snapshot_interval=int(raw.get("snapshot_interval", 0)),
            validator_churn=bool(raw.get("validator_churn", False)),
            light_client=bool(raw.get("light_client", False)),
            seed=int(raw.get("seed", -1)),
            nodes=nodes,
        )
        for n in nodes:
            bad = set(n.perturb) - set(PERTURBATIONS)
            if bad:
                raise ValueError(f"{n.name}: unknown perturbations {sorted(bad)}")
            if n.mode not in MODES:
                raise ValueError(f"{n.name}: unknown mode {n.mode!r}")
            if n.key_type not in KEY_TYPES:
                raise ValueError(f"{n.name}: unknown key_type {n.key_type!r}")
            if n.abci not in ABCI_MODES:
                raise ValueError(f"{n.name}: unknown abci mode {n.abci!r}")
            if n.state_sync and n.start_at <= 0:
                raise ValueError(f"{n.name}: state_sync requires start_at > 0")
        if m.backend not in BACKENDS:
            raise ValueError(f"unknown backend {m.backend!r}")
        if m.app not in APPS:
            raise ValueError(f"unknown app {m.app!r}")
        if m.validator_churn and m.app != "persistent_kvstore":
            raise ValueError("validator_churn requires app = 'persistent_kvstore'")
        if any(n.state_sync for n in nodes) and m.snapshot_interval <= 0:
            raise ValueError("state_sync nodes need snapshot_interval > 0")
        first = nodes[0]
        if not (first.is_validator() and first.start_at == 0):
            raise ValueError(
                "node 0 must be a genesis validator (height reference + "
                "load target + statesync trust source)"
            )
        if not any(n.is_validator() and n.start_at == 0 for n in nodes):
            raise ValueError("manifest needs at least one genesis validator")
        # Equal-power quorum: the genesis validators that start at t0 must
        # alone hold > 2/3 of the validator power, or the chain never moves.
        v_total = sum(1 for n in nodes if n.is_validator())
        v_late = sum(1 for n in nodes if n.is_validator() and n.start_at > 0)
        if v_late and 3 * (v_total - v_late) <= 2 * v_total:
            raise ValueError(
                f"{v_late} late-join validators of {v_total} break quorum "
                "at genesis"
            )
        return m

    @classmethod
    def _load_sim(cls, raw: dict) -> "Manifest":
        """network = "sim": the [sim] table IS the scenario spec.

        Partition/churn schedules arrive as parallel flat arrays
        (``partition_at_s``/``partition_heal_s``/``partition_fraction``,
        ``churn_at_s``/``churn_down_s``/``churn_nodes``) — the TOML subset
        this repo parses has no inline tables — and are zipped back into
        the list-of-dicts form ``simnet.scenario.default_spec`` takes.
        No [node.*] sections: every simulated node is an equal validator.
        """
        from cometbft_tpu.simnet.scenario import default_spec

        sim_raw = dict(raw.get("sim", {}))
        parts = [
            {"at_s": a, "heal_s": h, "fraction": f}
            for a, h, f in zip(
                sim_raw.pop("partition_at_s", []),
                sim_raw.pop("partition_heal_s", []),
                sim_raw.pop("partition_fraction", []),
            )
        ]
        churn = [
            {"at_s": a, "down_s": d, "nodes": n}
            for a, d, n in zip(
                sim_raw.pop("churn_at_s", []),
                sim_raw.pop("churn_down_s", []),
                sim_raw.pop("churn_nodes", []),
            )
        ]
        byz = [
            {"role": r, "node": n, "from_s": f, "until_s": u}
            for r, n, f, u in zip(
                sim_raw.pop("byz_role", []),
                sim_raw.pop("byz_node", []),
                sim_raw.pop("byz_from_s", []),
                sim_raw.pop("byz_until_s", []),
            )
        ]
        # only_partitioned is an equivocator-only knob; the aligned array
        # carries false placeholders for other roles (make_actor rejects
        # the key elsewhere).
        for entry, op in zip(byz, sim_raw.pop("byz_only_partitioned", [])):
            if entry["role"] == "equivocator":
                entry["only_partitioned"] = bool(op)
        joins = [
            {"node": n, "at_s": a}
            for n, a in zip(
                sim_raw.pop("join_node", []),
                sim_raw.pop("join_at_s", []),
            )
        ]
        if parts:
            sim_raw["partitions"] = parts
        if churn:
            sim_raw["churn"] = churn
        if byz:
            sim_raw["byzantine"] = byz
        if joins:
            sim_raw["joins"] = joins
        sim = default_spec(**sim_raw)  # validates: unknown keys raise
        return cls(
            network="sim",
            sim=sim,
            seed=int(raw.get("seed", sim["seed"])),
            target_blocks=int(sim["blocks"]),
        )

    def validators(self) -> list[ManifestNode]:
        return [n for n in self.nodes if n.is_validator()]


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class E2ERunner:
    def __init__(self, manifest_path: str, home: str, log=print):
        self.manifest = Manifest.load(manifest_path)
        self.home = home
        self.log = log
        self.procs: dict[str, subprocess.Popen] = {}
        self.app_procs: dict[str, subprocess.Popen] = {}
        self.rpc_ports: dict[str, int] = {}
        self.p2p_ports: dict[str, int] = {}
        self._log_files: list = []
        # Nodes whose verification backend runs fault-injected (the
        # backend_faults perturbation arms this before relaunch).
        self._fault_armed: set[str] = set()
        # Per-node results of the concurrent_light_clients perturbation
        # (swarm agreement + the runner-process coalesce counter deltas).
        self._light_swarms: dict[str, dict] = {}
        # Per-node results of the light_gateway perturbation (cold-sync
        # swarm against the node's MMR proof path).
        self._light_gateways: dict[str, dict] = {}
        # Nodes relaunched with per-sender ingress rate limiting armed, and
        # the per-node results of the tx_flood perturbation.
        self._flood_armed: set[str] = set()
        self._tx_floods: dict[str, dict] = {}
        # Nodes relaunched with a widened vote-admission micro-batch window
        # on top of the faulted chain, and the per-node results of the
        # vote_batch perturbation's zero-valid-vote-loss probe.
        self._votebatch_armed: set[str] = set()
        self._vote_batches: dict[str, dict] = {}
        # Per-node results of the mixed_load perturbation (tx flood + light
        # swarm driven CONCURRENTLY: all engine classes contend at once).
        self._mixed_loads: dict[str, dict] = {}
        # Per-node results of the recv_flood perturbation (gossip-side
        # mempool flood pressuring the target's prioritized recv demux).
        self._recv_floods: dict[str, dict] = {}
        # Per-node results of the bundle_cold_sync perturbation (checkpoint
        # bundle exported live, swarm syncs from the flat dir with the
        # origin node DOWN, then the node relaunches).
        self._bundle_syncs: dict[str, dict] = {}
        # Stall forensics: every node's consensus round-state, captured at
        # the moment a wait_height deadline expires (the nodes are SIGKILLed
        # during teardown, so this is the only window to collect it).
        self.last_round_states: dict | None = None
        # network = "sim": the scenario's full resolved schedule (latency
        # matrix, partition/churn timeline, seeds) — repro.json embeds it so
        # a failing run replays bit-identically from the artifact alone.
        self.sim_schedule: dict | None = None

    # -- setup ------------------------------------------------------------

    def setup(self) -> None:
        """testnet homes + config.toml per node (runner/setup.go shape).

        The testnet CLI lays down homes validators-first (matching the
        manifest's ordering contract); per-node config then specializes
        the proxy_app boundary, statesync arming, and snapshot cadence."""
        from cometbft_tpu.cmd.__main__ import main as cli
        from cometbft_tpu.config import default_config
        from cometbft_tpu.config.toml import write_config_file
        from cometbft_tpu.p2p.key import NodeKey

        nodes = self.manifest.nodes
        n_validators = len(self.manifest.validators())
        key_types = ",".join(n.key_type for n in nodes)
        assert cli(
            ["testnet", "--validators", str(n_validators),
             "--non-validators", str(len(nodes) - n_validators),
             "--key-types", key_types,
             "--output-dir", self.home, "--chain-id", "e2e-manifest"]
        ) == 0
        p2p = _free_ports(len(nodes))
        rpc = _free_ports(len(nodes))
        node_ids = [
            NodeKey.load(
                os.path.join(self.home, f"node{i}", "config", "node_key.json")
            ).id
            for i in range(len(nodes))
        ]
        peers = [
            f"{node_ids[i]}@127.0.0.1:{p2p[i]}" for i in range(len(nodes))
        ]
        # Every node dials the genesis cohort; late joiners are dial-only
        # (nobody lists a peer that isn't up yet — the switch would retry
        # forever, which is allowed but noisy).
        genesis_idx = [i for i, n in enumerate(nodes) if n.start_at == 0]
        for i, node in enumerate(nodes):
            home = os.path.join(self.home, f"node{i}")
            cfg = default_config()
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc[i]}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p[i]}"
            cfg.p2p.persistent_peers = ",".join(
                peers[j] for j in genesis_idx if j != i
            )
            cfg.p2p.addr_book_strict = False
            cfg.p2p.allow_duplicate_ip = True
            cfg.p2p.seed_mode = node.mode == "seed"
            cfg.consensus.timeout_commit = 0.2
            cfg.consensus.skip_timeout_commit = False
            cfg.base.proxy_app = self._proxy_app_addr(i, node)
            if node.start_at == 0:
                # Only genesis nodes serve snapshots — a restoring node
                # re-offering its own half-built snapshot is the reference's
                # self-serve footgun.
                cfg.base.snapshot_interval = self.manifest.snapshot_interval
            if node.state_sync:
                cfg.statesync.enable = True
                # Trust basis (height + hash) is only knowable at launch
                # time; _launch_late rewrites this file then.
                rpc_servers = [
                    f"http://127.0.0.1:{rpc[j]}" for j in genesis_idx[:2]
                ]
                if len(rpc_servers) == 1:
                    rpc_servers *= 2  # primary + witness may be the same
                cfg.statesync.rpc_servers = tuple(rpc_servers)
                cfg.statesync.trust_height = 1
                cfg.statesync.discovery_time = 2.0
            write_config_file(os.path.join(home, "config", "config.toml"), cfg)
            self.rpc_ports[node.name] = rpc[i]
            self.p2p_ports[node.name] = p2p[i]

    def _proxy_app_addr(self, idx: int, node: ManifestNode) -> str:
        """local -> in-process app name; socket/grpc -> a unix socket under
        the node home served by an external app process."""
        if node.abci == "local":
            return self.manifest.app
        sock = os.path.join(self.home, f"node{idx}", "app.sock")
        return f"grpc://{sock}" if node.abci == "grpc" else f"unix://{sock}"

    # -- process management ----------------------------------------------

    def _node_env(self) -> dict:
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "CMTPU_BACKEND": self.manifest.backend,
        }
        if self.manifest.backend == "cpu":
            # A cpu-pinned net must never dial the axon relay from every
            # node process (sitecustomize does, whenever this is set).
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        return env

    def _open_log(self, idx: int, suffix: str = "node"):
        path = os.path.join(self.home, f"node{idx}", f"{suffix}.log")
        f = open(path, "ab")
        self._log_files.append(f)
        return f

    def _launch_app(self, idx: int, node: ManifestNode) -> None:
        """External ABCI app process for socket/grpc nodes (the reference
        runs the e2e app in its own container entrypoint)."""
        if node.abci == "local":
            return
        sock = os.path.join(self.home, f"node{idx}", "app.sock")
        if os.path.exists(sock):
            os.unlink(sock)
        addr = f"grpc://{sock}" if node.abci == "grpc" else f"unix://{sock}"
        logf = self._open_log(idx, suffix="app")
        snapshot = (
            self.manifest.snapshot_interval if node.start_at == 0 else 0
        )
        self.app_procs[node.name] = subprocess.Popen(
            [sys.executable, "-m", "cometbft_tpu.abci.server",
             self.manifest.app, "--addr", addr,
             "--transport", "grpc" if node.abci == "grpc" else "socket",
             "--snapshot-interval", str(snapshot)],
            stdout=logf, stderr=logf, env=self._node_env(),
        )
        deadline = time.time() + 15
        while not os.path.exists(sock):
            if self.app_procs[node.name].poll() is not None:
                raise RuntimeError(f"{node.name}: ABCI app process died at start")
            if time.time() > deadline:
                raise TimeoutError(f"{node.name}: ABCI app socket never appeared")
            time.sleep(0.05)

    def _fault_env(self, idx: int) -> dict:
        """The backend_faults environment: a supervised (CMTPU_BACKEND=auto)
        chain whose primary tier injects deterministic latency + errors
        (sidecar/chaos.py), seeded from the manifest seed + node index so a
        failing seed reproduces its exact fault sequence.  Probabilities
        stay moderate — the point is degrading THROUGH faults, not a dead
        node — and the anchor tier is always clean."""
        seed = max(self.manifest.seed, 0) * 1000 + idx
        return {
            "CMTPU_BACKEND": "auto",
            "CMTPU_FAULTS": "latency:0.2:25,error:0.25",
            "CMTPU_FAULTS_SEED": str(seed),
            "CMTPU_DEADLINE_MS": "2000",
            "CMTPU_BACKOFF_MS": "10",
            "CMTPU_BREAKER_COOLDOWN_MS": "2000",
        }

    def _launch(self, idx: int) -> subprocess.Popen:
        node = self.manifest.nodes[idx]
        if node.name not in self.app_procs or \
           self.app_procs[node.name].poll() is not None:
            self._launch_app(idx, node)
        logf = self._open_log(idx)
        env = self._node_env()
        if node.name in self._fault_armed:
            env.update(self._fault_env(idx))
        if node.name in self._votebatch_armed:
            # vote_batch: widen the admission micro-batch window (5x the
            # default, so concurrent peer admissions really share windows)
            # and keep the chaos-faulted supervised chain underneath it.
            env.update(self._fault_env(idx))
            env["CMTPU_VOTE_BATCH_WINDOW_MS"] = "10"
        if node.name in self._flood_armed:
            # tx_flood arms a finite per-sender admission rate so the
            # hostile signer gets shed instead of squatting the mempool.
            # The rate must sit well under what the spammer can push
            # through one HTTP connection on a slow host (~20/s observed
            # single-core) and well over the honest cadence (~1 tx/s).
            env["CMTPU_INGRESS_SENDER_RPS"] = "4"
        if "bundle_cold_sync" in node.perturb:
            # Checkpoint every 2 blocks so a short e2e run crosses several
            # boundaries — the default 1000 would never checkpoint here.
            # Armed from genesis (the perturb list is known up front).
            env["CMTPU_BUNDLE_INTERVAL"] = "2"
        return subprocess.Popen(
            [sys.executable, "-m", "cometbft_tpu.cmd", "--home",
             os.path.join(self.home, f"node{idx}"), "start"],
            stdout=logf, stderr=logf,
            env=env,
        )

    def start(self) -> None:
        """Launch the genesis cohort; late joiners wait for their height."""
        started = 0
        for i, node in enumerate(self.manifest.nodes):
            if node.start_at == 0:
                self.procs[node.name] = self._launch(i)
                started += 1
        late = len(self.manifest.nodes) - started
        self.log(f"started {started} nodes" + (f" ({late} join late)" if late else ""))

    def _launch_late(self, idx: int, node: ManifestNode) -> None:
        """runner/start.go second wave: wait for the net to reach the node's
        start_at height, arm the statesync trust basis from live chain data,
        then launch."""
        first = self.manifest.nodes[0].name
        self.wait_height(first, node.start_at)
        if node.state_sync:
            from cometbft_tpu.config import default_config
            from cometbft_tpu.config.toml import load_toml, write_config_file
            from cometbft_tpu.rpc.client import HTTPClient

            blk = HTTPClient(
                f"http://127.0.0.1:{self.rpc_ports[first]}", timeout=5
            ).block(1)
            toml_path = os.path.join(
                self.home, f"node{idx}", "config", "config.toml"
            )
            cfg = load_toml(toml_path, default_config())
            cfg.statesync.trust_height = 1
            cfg.statesync.trust_hash = blk["block_id"]["hash"]
            write_config_file(toml_path, cfg)
        self.log(f"late join {node.name} at height {node.start_at}"
                 + (" (statesync)" if node.state_sync else " (blocksync)"))
        self.procs[node.name] = self._launch(idx)

    # -- RPC helpers ------------------------------------------------------

    def _height(self, name: str) -> int:
        from cometbft_tpu.rpc.client import HTTPClient

        st = HTTPClient(
            f"http://127.0.0.1:{self.rpc_ports[name]}", timeout=3
        ).status()
        return int(st["sync_info"]["latest_block_height"])

    def wait_height(self, name: str, target: int, timeout: float = 240) -> int:
        deadline = time.time() + timeout
        last = -1
        while time.time() < deadline:
            try:
                last = self._height(name)
                if last >= target:
                    return last
            except Exception:
                pass
            time.sleep(0.3)
        self.last_round_states = self.dump_round_states()
        raise TimeoutError(f"{name}: height {target} not reached (last {last})")

    def dump_round_states(self) -> dict:
        """Every live node's dump_consensus_state — height/round/step,
        per-round vote bitmaps, and peer round views. A round-livelock is
        diagnosable from this alone: who is stuck at which round, holding
        whose votes."""
        from cometbft_tpu.rpc.client import HTTPClient

        out: dict = {}
        for node in self.manifest.nodes:
            port = self.rpc_ports.get(node.name)
            if port is None:
                continue
            try:
                dump = HTTPClient(
                    f"http://127.0.0.1:{port}", timeout=3
                ).dump_consensus_state()
            except Exception as e:
                dump = {"unreachable": repr(e)}
            out[node.name] = dump
        return out

    # -- perturbations (runner/perturb.go) --------------------------------

    def perturb(self, node: ManifestNode, kind: str) -> None:
        name = node.name
        idx = [n.name for n in self.manifest.nodes].index(name)
        proc = self.procs[name]
        self.log(f"perturb {name}: {kind}")
        if kind == "kill" or kind == "restart":
            proc.send_signal(signal.SIGKILL)
            proc.wait()
            time.sleep(1.0)
            self.procs[name] = self._launch(idx)
        elif kind == "backend_faults":
            # Relaunch with a fault-injected supervised verification chain
            # (stays armed for the rest of the run): the heal check below
            # proves the node keeps committing while its primary tier
            # throws injected errors and latency.
            self._fault_armed.add(name)
            proc.send_signal(signal.SIGKILL)
            proc.wait()
            time.sleep(1.0)
            self.procs[name] = self._launch(idx)
        elif kind == "pause":
            proc.send_signal(signal.SIGSTOP)
            time.sleep(3.0)
            proc.send_signal(signal.SIGCONT)
        elif kind == "tx_flood":
            # Relaunch with per-sender rate limiting armed, wait for the
            # node to rejoin, then run the flood: one hostile signer
            # saturating admission while well-behaved signers keep
            # submitting.  QoS holds if the honest txs still commit within
            # bound and the spammer's excess is shed (counter delta).
            self._flood_armed.add(name)
            proc.send_signal(signal.SIGKILL)
            proc.wait()
            time.sleep(1.0)
            self.procs[name] = self._launch(idx)
            h0 = self.wait_height(self.manifest.nodes[0].name, 1)
            self.wait_height(name, h0 + 1, timeout=420)
            self._tx_floods[name] = self._tx_flood(node)
        elif kind == "vote_batch":
            # Relaunch with a widened vote-admission micro-batch window AND
            # the chaos-faulted supervised chain armed (_launch reads
            # _votebatch_armed), then demand the armed validator's precommit
            # lands in a commit minted AFTER the restart: micro-batched
            # admission under injected backend faults must degrade, never
            # drop, valid votes.
            self._votebatch_armed.add(name)
            h0 = self._height(self.manifest.nodes[0].name)
            proc.send_signal(signal.SIGKILL)
            proc.wait()
            time.sleep(1.0)
            self.procs[name] = self._launch(idx)
            self._vote_batches[name] = self._vote_batch_check(name, h0)
        elif kind == "mixed_load":
            # All verification classes at once: relaunch with per-sender
            # rate limiting armed (the tx_flood arming), then drive the
            # hostile-signer flood AND a light-client bisection swarm
            # against the same node CONCURRENTLY.  Ingress preverify,
            # light-client commit verification and the node's own consensus
            # votes now contend for the one engine queue — QoS holds if the
            # flood is shed, every honest tx commits within bound, the
            # swarm agrees, and honest blocks keep landing (heal check
            # below).
            self._flood_armed.add(name)
            proc.send_signal(signal.SIGKILL)
            proc.wait()
            time.sleep(1.0)
            self.procs[name] = self._launch(idx)
            h0 = self.wait_height(self.manifest.nodes[0].name, 1)
            self.wait_height(name, h0 + 1, timeout=420)
            results: dict[str, dict] = {}
            errors: list[BaseException] = []

            def _arm(key: str, fn) -> None:
                try:
                    results[key] = fn(node)
                except BaseException as e:  # re-raised on the main thread
                    errors.append(e)

            flood_t = threading.Thread(
                target=_arm, args=("tx_flood", self._tx_flood)
            )
            swarm_t = threading.Thread(
                target=_arm, args=("light_swarm", self._light_client_swarm)
            )
            flood_t.start()
            swarm_t.start()
            flood_t.join(timeout=600)
            swarm_t.join(timeout=600)
            if errors:
                raise errors[0]
            if flood_t.is_alive() or swarm_t.is_alive():
                raise AssertionError(f"{name}: mixed_load arm never finished")
            self._mixed_loads[name] = results
        elif kind == "recv_flood":
            # No process disruption: the flooded BYTES are the perturbation.
            # Other nodes' mempools gossip a sustained tx stream into the
            # target's recv path; with the old serialized recv loop this is
            # exactly the seeds-2/3/9 stall shape (block parts queued behind
            # tx bytes past timeout_propose).  The prioritized demux must
            # keep consensus committing through the flood.
            self._recv_floods[name] = self._recv_flood(node)
        elif kind == "concurrent_light_clients":
            # No process disruption: the stress IS the perturbation.  N
            # light clients bisect against this node simultaneously; their
            # commit verifications land in the runner-process coalescing
            # scheduler, which must merge them into shared dispatches while
            # every swarm member still converges on the same hash.
            self._light_swarms[name] = self._light_client_swarm(node)
        elif kind == "light_gateway":
            # Cold-sync swarm against the node's MMR proof path: every
            # client starts from a genesis-adjacent trust anchor and syncs
            # to the tip through light_proof instead of bisecting, then the
            # result hash must agree with a plain local bisection.  No
            # process disruption here either.
            self._light_gateways[name] = self._light_gateway_swarm(node)
        elif kind == "bundle_cold_sync":
            # Export a checkpoint bundle from the live node, KILL the node,
            # cold-sync a swarm from the static flat-dir artifact with zero
            # origin interactivity, then relaunch — the heal check proves
            # the origin was never needed during the syncs.
            self._bundle_syncs[name] = self._bundle_cold_sync(node, idx)
        elif kind == "disconnect":
            pid = proc.pid
            t_end = time.time() + 4.0
            while time.time() < t_end:
                out = subprocess.run(
                    ["ss", "-tnp", "state", "established"],
                    capture_output=True, text=True,
                ).stdout
                for line in out.splitlines():
                    if f"pid={pid}," not in line:
                        continue
                    m = re.search(
                        r"(\d+\.\d+\.\d+\.\d+):(\d+)\s+"
                        r"(\d+\.\d+\.\d+\.\d+):(\d+)", line)
                    if not m:
                        continue
                    lip, lport, rip, rport = m.groups()
                    if int(lport) == self.rpc_ports[name] or \
                       int(rport) == self.rpc_ports[name]:
                        continue
                    subprocess.run(
                        ["ss", "-K", "src", lip, "sport", "=", lport,
                         "dst", rip, "dport", "=", rport],
                        capture_output=True,
                    )
                time.sleep(0.2)
        else:
            raise ValueError(kind)
        # After every perturbation the node must make progress again.  The
        # heal window is generous: a stall grows consensus round timeouts
        # (the reference's per-round timeout deltas), so the first
        # post-heal commit can take minutes after a partition.  Node 0 (a
        # genesis validator by the ordering contract) is the reference.
        h = self.wait_height(self.manifest.nodes[0].name, 1)
        self.wait_height(name, h + 1, timeout=420)
        self.log(f"perturb {name}: {kind} healed")

    # -- load (loadtime payloads over RPC) --------------------------------

    def _load_pump(self, stop: threading.Event) -> None:
        from cometbft_tpu.loadtime import make_payload
        from cometbft_tpu.rpc.client import HTTPClient

        rate = max(1, self.manifest.load_tx_rate)
        target = self.manifest.nodes[0].name
        k = 0
        next_t = time.monotonic()
        while not stop.is_set():
            try:
                cli = HTTPClient(
                    f"http://127.0.0.1:{self.rpc_ports[target]}", timeout=3
                )
                tx = make_payload(k, time.time_ns())
                cli.call("broadcast_tx_async", tx="0x" + tx.hex())
                k += 1
            except Exception:
                pass
            next_t += 1.0 / rate
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    # -- validator churn (test/e2e persistent_kvstore val: txs) -----------

    def churn_validators(self) -> dict:
        """Add a fresh ed25519 validator (power 1), wait for it to enter the
        set, then vote it back out (power 0).  The extra validator never
        runs a node — with equal powers the running cohort keeps quorum."""
        from cometbft_tpu.crypto import ed25519
        from cometbft_tpu.rpc.client import HTTPClient

        first = self.manifest.nodes[0].name
        cli = HTTPClient(
            f"http://127.0.0.1:{self.rpc_ports[first]}", timeout=5
        )
        pub = ed25519.gen_priv_key().pub_key()
        b64 = base64.b64encode(pub.bytes()).decode()

        def set_size() -> int:
            return len(cli.call("validators")["validators"])

        def tx_and_settle(power: int, want_size: int) -> None:
            """Broadcast the update and poll the validator set until it
            reflects it.  Waiting a fixed two heights is NOT enough: under
            a concurrent tx flood the churn tx can land several blocks
            after broadcast, so a height-anchored query races the update
            (observed as "4 -> 5" when the add activates only after the
            post-add query, between the two reads)."""
            tx = f"val:{b64}!{power}".encode()
            res = cli.call("broadcast_tx_sync", tx="0x" + tx.hex())
            if int(res.get("code", 0)) != 0:
                raise AssertionError(f"churn tx rejected: {res}")
            deadline = time.time() + 60
            n = set_size()
            while n != want_size and time.time() < deadline:
                time.sleep(0.25)
                n = set_size()
            if n != want_size:
                raise AssertionError(
                    f"validator set stuck at {n} (wanted {want_size}) after "
                    f"power={power} update"
                )

        base = set_size()
        self.log(f"churn: adding validator {pub.address().hex()[:12]}…")
        tx_and_settle(1, base + 1)
        self.log("churn: removing it again")
        tx_and_settle(0, base)
        return {"added_then_removed": b64, "set_size": base}

    # -- light client (runner/test.go + light package) --------------------

    def verify_light_client(self, height: int) -> dict:
        """Sequentially verify node 0's chain up to the agreed height with
        the light client — the reference's evidence/light e2e leg."""
        from cometbft_tpu.libs.db import MemDB
        from cometbft_tpu.light.client import Client, TrustOptions
        from cometbft_tpu.light.provider import HTTPProvider
        from cometbft_tpu.light.store import LightStore
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.types import cmttime

        first = self.manifest.nodes[0].name
        url = f"http://127.0.0.1:{self.rpc_ports[first]}"
        blk = HTTPClient(url, timeout=5).block(1)
        trust = TrustOptions(
            period_ns=int(3600 * 10**9),
            height=1,
            hash=bytes.fromhex(blk["block_id"]["hash"]),
        )
        primary = HTTPProvider("e2e-manifest", HTTPClient(url, timeout=5))
        client = Client(
            "e2e-manifest", trust, primary, [], LightStore(MemDB()),
            skip_verification="sequential",
        )
        lb = client.verify_light_block_at_height(height, cmttime.now())
        return {"height": lb.height, "hash": lb.hash().hex().upper()}

    def _coalesce_counters(self) -> dict | None:
        """Runner-process scheduler counter snapshot (integer counts only).

        None when verification isn't routed through the coalescing
        scheduler — backend not yet built, or CMTPU_COALESCE=0."""
        from cometbft_tpu.sidecar import backend as backend_mod

        b = backend_mod._backend
        if b is None or getattr(b, "name", "") != "coalesce":
            return None
        return {k: v for k, v in b.counters().items() if isinstance(v, int)}

    def _gateway_stats(self, url: str) -> dict | None:
        """The node's light_gateway_stats counters, or None when the
        gateway is disabled on that node (CMTPU_LIGHTGW=0)."""
        from cometbft_tpu.rpc.client import HTTPClient

        try:
            st = HTTPClient(url, timeout=5).call("light_gateway_stats")
        except Exception:
            return None
        if not st.get("enabled"):
            return None
        return {k: v for k, v in st.items() if isinstance(v, (int, float))}

    def _light_client_swarm(self, node: ManifestNode, n_clients: int = 4) -> dict:
        """N skipping-mode light clients bisect against `node` at once.

        The swarm's commit verifications all land in this (runner)
        process's verification backend, so concurrent bisections should
        coalesce into shared dispatches.  When the node serves the light
        gateway the clients sync gateway-assisted (plan mode: the shared
        descent plan is fetched once and re-verified locally by everyone)
        and the node-side gateway counter deltas ride the report.  Every
        member must converge on the same hash; the returned dict carries
        the swarm result plus the scheduler counter deltas attributable to
        the swarm."""
        from cometbft_tpu.libs.db import MemDB
        from cometbft_tpu.light.client import Client, TrustOptions
        from cometbft_tpu.light.gateway import RemoteGateway
        from cometbft_tpu.light.provider import HTTPProvider
        from cometbft_tpu.light.store import LightStore
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.types import cmttime

        name = node.name
        url = f"http://127.0.0.1:{self.rpc_ports[name]}"
        target = max(2, self._height(name))
        blk = HTTPClient(url, timeout=5).block(1)
        trust = TrustOptions(
            period_ns=int(3600 * 10**9),
            height=1,
            hash=bytes.fromhex(blk["block_id"]["hash"]),
        )
        before = self._coalesce_counters() or {}
        gw_before = self._gateway_stats(url)
        results: list = [None] * n_clients
        barrier = threading.Barrier(n_clients)

        def bisect(i: int) -> None:
            try:
                barrier.wait(timeout=30)
                gateway = None
                if gw_before is not None:
                    gateway = RemoteGateway(HTTPClient(url, timeout=5))
                client = Client(
                    "e2e-manifest", trust,
                    HTTPProvider("e2e-manifest", HTTPClient(url, timeout=5)),
                    [], LightStore(MemDB()),
                    gateway=gateway, gateway_proofs=False,
                )
                lb = client.verify_light_block_at_height(target, cmttime.now())
                results[i] = ("ok", lb.hash().hex().upper(),
                              dict(client.gateway_stats))
            except Exception as exc:  # surfaced by the agreement check
                results[i] = ("error", repr(exc))

        threads = [
            threading.Thread(target=bisect, args=(i,), daemon=True)
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        bad = [r for r in results if r is None or r[0] != "ok"]
        if bad:
            raise AssertionError(f"{name}: light swarm failures: {bad}")
        hashes = {r[1] for r in results}
        if len(hashes) != 1:
            raise AssertionError(
                f"{name}: light swarm hash disagreement: {hashes}"
            )
        out = {"clients": n_clients, "height": target, "hash": hashes.pop()}
        after = self._coalesce_counters()
        if after is not None:
            delta = {k: v - before.get(k, 0) for k, v in after.items()}
            disp = delta.get("dispatches", 0)
            delta["coalesce_ratio"] = (
                round(delta.get("requests", 0) / disp, 3) if disp else 0.0
            )
            out["coalesce"] = delta
        if gw_before is not None:
            gw_after = self._gateway_stats(url) or {}
            out["gateway"] = {
                k: round(v - gw_before.get(k, 0), 3)
                for k, v in gw_after.items()
                if k in ("sessions_total", "plan_hits", "plan_misses",
                         "plan_waits", "prewarmed_sigs")
            }
            out["gateway"]["plan_syncs"] = sum(
                r[2]["plan_syncs"] for r in results
            )
            out["gateway"]["fallbacks"] = sum(
                r[2]["fallbacks"] for r in results
            )
            if out["gateway"]["plan_syncs"] == 0:
                # Hash agreement alone would pass even if every client
                # fell back to a plain bisection — the perturbation exists
                # to exercise the gateway path, so never-took-it fails.
                raise AssertionError(
                    f"{name}: gateway armed but no client synced via the "
                    f"plan path: {out['gateway']}"
                )
        return out

    def _light_gateway_swarm(self, node: ManifestNode, n_clients: int = 4) -> dict:
        """Cold-sync swarm against `node`'s MMR proof path: every client
        trusts height 1 and jumps straight to the tip via light_proof
        (O(log n) accumulator proof + one commit verification), and the
        resulting hash must agree with a plain local bisection run after
        the swarm.  A gateway-disabled node fails loudly — this
        perturbation only appears in manifests that arm the gateway."""
        from cometbft_tpu.libs.db import MemDB
        from cometbft_tpu.light.client import Client, TrustOptions
        from cometbft_tpu.light.gateway import RemoteGateway
        from cometbft_tpu.light.provider import HTTPProvider
        from cometbft_tpu.light.store import LightStore
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.types import cmttime

        name = node.name
        url = f"http://127.0.0.1:{self.rpc_ports[name]}"
        gw_before = self._gateway_stats(url)
        if gw_before is None:
            raise AssertionError(
                f"{name}: light_gateway perturbation but gateway disabled"
            )
        target = max(2, self._height(name))
        blk = HTTPClient(url, timeout=5).block(1)
        trust = TrustOptions(
            period_ns=int(3600 * 10**9),
            height=1,
            hash=bytes.fromhex(blk["block_id"]["hash"]),
        )
        results: list = [None] * n_clients
        barrier = threading.Barrier(n_clients)

        def cold_sync(i: int) -> None:
            try:
                barrier.wait(timeout=30)
                client = Client(
                    "e2e-manifest", trust,
                    HTTPProvider("e2e-manifest", HTTPClient(url, timeout=5)),
                    [], LightStore(MemDB()),
                    gateway=RemoteGateway(HTTPClient(url, timeout=5)),
                    gateway_proofs=True,
                )
                lb = client.verify_light_block_at_height(target, cmttime.now())
                results[i] = ("ok", lb.hash().hex().upper(),
                              dict(client.gateway_stats))
            except Exception as exc:
                results[i] = ("error", repr(exc))

        threads = [
            threading.Thread(target=cold_sync, args=(i,), daemon=True)
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        bad = [r for r in results if r is None or r[0] != "ok"]
        if bad:
            raise AssertionError(f"{name}: gateway cold-sync failures: {bad}")
        hashes = {r[1] for r in results}
        if len(hashes) != 1:
            raise AssertionError(
                f"{name}: gateway cold-sync hash disagreement: {hashes}"
            )
        # Reference arm: the same sync, gateway-less — the MMR shortcut
        # must land on the bit-identical header.
        local = Client(
            "e2e-manifest", trust,
            HTTPProvider("e2e-manifest", HTTPClient(url, timeout=5)),
            [], LightStore(MemDB()),
        )
        local_hash = local.verify_light_block_at_height(
            target, cmttime.now()
        ).hash().hex().upper()
        agreed = hashes.pop()
        if local_hash != agreed:
            raise AssertionError(
                f"{name}: gateway vs local hash mismatch at {target}: "
                f"{agreed} vs {local_hash}"
            )
        gw_after = self._gateway_stats(url) or {}
        out = {
            "clients": n_clients,
            "height": target,
            "hash": agreed,
            "proof_syncs": sum(r[2]["proof_syncs"] for r in results),
            "proof_rejects": sum(r[2]["proof_rejects"] for r in results),
            "fallbacks": sum(r[2]["fallbacks"] for r in results),
            "proof_bytes": sum(r[2]["proof_bytes"] for r in results),
            "gateway": {
                k: round(v - gw_before.get(k, 0), 3)
                for k, v in gw_after.items()
                if k in ("sessions_total", "proofs_served", "proof_bytes",
                         "mmr_size")
            },
        }
        if out["proof_syncs"] == 0:
            raise AssertionError(
                f"{name}: cold-sync swarm never took the proof path: {out}"
            )
        return out

    def _bundle_cold_sync(
        self, node: ManifestNode, idx: int, n_clients: int = 4
    ) -> dict:
        """Static cold sync off a checkpoint bundle: fetch the latest
        bundle over light_bundle while the node is live (plus the trust
        anchor and expected checkpoint hash), write it to a flat directory
        the way `cmd bundle export` would, SIGKILL the node, and have N
        clients sync to the checkpoint from the directory alone — the
        origin is down for the entire swarm, so any interactivity beyond
        the bundle fails loudly.  Every client must take the bundle path
        (no rejects, no fallbacks) and land on the hash the node itself
        reported before it went down.  The node is relaunched afterwards;
        the run's heal check proves it rejoins."""
        from cometbft_tpu.libs.db import MemDB
        from cometbft_tpu.light.bundle import (
            Bundle, DirBundleSource, check_name,
        )
        from cometbft_tpu.light.client import Client, TrustOptions
        from cometbft_tpu.light.provider import HTTPProvider, MockProvider
        from cometbft_tpu.light.store import LightStore
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.types import cmttime

        name = node.name
        url = f"http://127.0.0.1:{self.rpc_ports[name]}"
        rpc = HTTPClient(url, timeout=5)
        # Let the chain cross a few CMTPU_BUNDLE_INTERVAL=2 boundaries.
        self.wait_height(name, 4, timeout=420)
        res = rpc.call("light_bundle")
        if not res.get("enabled"):
            raise AssertionError(
                f"{name}: bundle_cold_sync armed but origin disabled: {res}"
            )
        bname = res["name"]
        data = base64.b64decode(res["bundle"])
        check_name(bname, data)
        boundary = int(res["height"])
        bundle = Bundle.decode(data)
        # Origin counters ride inside light_gateway_stats (peeked — the
        # light_bundle call above already constructed the origin).
        try:
            origin_stats = rpc.call("light_gateway_stats").get("bundle")
        except Exception:
            origin_stats = None
        # Everything a client will need once the node is dead: the trust
        # anchor light block and the node's own claim for the checkpoint.
        live = HTTPProvider("e2e-manifest", rpc)
        lb1 = live.light_block(1)
        trust = TrustOptions(
            period_ns=int(3600 * 10**9), height=1, hash=lb1.hash(),
        )
        expected = rpc.block(boundary)["block_id"]["hash"].upper()
        # The flat-dir artifact exactly as `cmd bundle export` lays it out.
        out_dir = os.path.join(self.home, f"{name}_bundles")
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, f"{bname}.bundle"), "wb") as f:
            f.write(data)
        with open(os.path.join(out_dir, "index.json"), "w") as f:
            json.dump({
                "chain_id": "e2e-manifest",
                "interval": 2,
                "latest": bname,
                "bundles": {str(boundary): bname},
            }, f)
        # Origin goes DOWN.
        proc = self.procs[name]
        proc.send_signal(signal.SIGKILL)
        proc.wait()

        results: list = [None] * n_clients
        barrier = threading.Barrier(n_clients)

        def cold_sync(i: int) -> None:
            try:
                barrier.wait(timeout=30)
                client = Client(
                    "e2e-manifest", trust,
                    MockProvider(
                        "e2e-manifest", {1: lb1, boundary: bundle.anchor}
                    ),
                    [], LightStore(MemDB()),
                    bundle_source=DirBundleSource(out_dir),
                )
                lb = client.verify_light_block_at_height(
                    boundary, cmttime.now()
                )
                results[i] = ("ok", lb.hash().hex().upper(),
                              dict(client.gateway_stats))
            except Exception as exc:
                results[i] = ("error", repr(exc))

        threads = [
            threading.Thread(target=cold_sync, args=(i,), daemon=True)
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        try:
            bad = [r for r in results if r is None or r[0] != "ok"]
            if bad:
                raise AssertionError(
                    f"{name}: bundle cold-sync failures: {bad}"
                )
            hashes = {r[1] for r in results}
            if hashes != {expected}:
                raise AssertionError(
                    f"{name}: bundle cold-sync hash disagreement at "
                    f"{boundary}: {hashes} vs node's {expected}"
                )
            syncs = sum(r[2]["bundle_syncs"] for r in results)
            rejects = sum(r[2]["bundle_rejects"] for r in results)
            if syncs != n_clients or rejects:
                raise AssertionError(
                    f"{name}: swarm did not take the bundle path cleanly: "
                    f"syncs={syncs} rejects={rejects}"
                )
        finally:
            time.sleep(1.0)
            self.procs[name] = self._launch(idx)
        return {
            "clients": n_clients,
            "height": boundary,
            "name": bname,
            "bundle_bytes": len(data),
            "hash": expected,
            "bundle_syncs": syncs,
            "origin": origin_stats,
        }

    def _vote_batch_check(self, name: str, after_height: int) -> dict:
        """Zero-valid-vote-loss probe for the vote_batch perturbation: scan
        commits minted after the restart until one carries the armed
        validator's BLOCK_ID_FLAG_COMMIT signature.  A widened window plus
        injected faults may slow admission (degraded tiers, retries) but a
        single lost valid precommit would show up here as the signature
        never landing.  Non-validator nodes have no precommit to lose —
        recorded and skipped."""
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.types.block import BLOCK_ID_FLAG_COMMIT

        ref = self.manifest.nodes[0].name
        ref_cli = HTTPClient(
            f"http://127.0.0.1:{self.rpc_ports[ref]}", timeout=5
        )
        deadline = time.time() + 300
        val_info: dict = {}
        while time.time() < deadline and not val_info.get("address"):
            try:
                val_info = HTTPClient(
                    f"http://127.0.0.1:{self.rpc_ports[name]}", timeout=5
                ).status()["validator_info"]
            except Exception:
                time.sleep(1.0)
        addr = (val_info.get("address") or "").upper()
        if not addr or int(val_info.get("voting_power", "0") or 0) <= 0:
            self.log(f"vote_batch {name}: not a validator; sig probe skipped")
            return {"validator": False, "signed": False}
        scanned = 0
        probe = after_height + 1
        while time.time() < deadline:
            h = self._height(ref)
            while probe <= h:
                sh = ref_cli.commit(probe).get("signed_header") or {}
                for s in (sh.get("commit") or {}).get("signatures", []):
                    if (
                        (s.get("validator_address") or "").upper() == addr
                        and int(s.get("block_id_flag", 0)) == BLOCK_ID_FLAG_COMMIT
                    ):
                        self.log(
                            f"vote_batch {name}: precommit landed at height "
                            f"{probe} ({scanned} commits scanned)"
                        )
                        return {
                            "validator": True,
                            "signed": True,
                            "height": probe,
                            "commits_scanned": scanned + 1,
                        }
                scanned += 1
                probe += 1
            time.sleep(1.0)
        raise AssertionError(
            f"{name}: no post-restart commit signature within the "
            f"vote_batch window ({scanned} commits after height "
            f"{after_height}) — a valid precommit was lost or the node "
            f"never rejoined"
        )

    def _tx_flood(
        self,
        node: ManifestNode,
        duration_s: float = 6.0,
        honest_senders: int = 3,
        honest_rounds: int = 5,
        commit_bound: int = 10,
    ) -> dict:
        """One hostile signer floods `node` with signed envelopes while
        well-behaved signers submit at a civil rate (>= 10:1 offered-load
        ratio).  Asserts QoS end to end: every honest tx is accepted by
        admission AND committed within `commit_bound` blocks of the flood
        start, while the spammer's excess is rate-limited/shed (non-zero
        ingress shed counter delta on the flooded node)."""
        from cometbft_tpu.crypto import ed25519
        from cometbft_tpu.mempool.ingress import encode_envelope
        from cometbft_tpu.rpc.client import HTTPClient

        name = node.name
        url = f"http://127.0.0.1:{self.rpc_ports[name]}"
        cli = HTTPClient(url, timeout=5)
        before = cli.call("ingress_stats")
        if not before.get("enabled"):
            raise AssertionError(f"{name}: ingress pipeline not enabled")
        seed = max(self.manifest.seed, 0)
        spammer = ed25519.gen_priv_key_from_secret(b"e2e-spam-%d" % seed)
        honest = [
            ed25519.gen_priv_key_from_secret(b"e2e-honest-%d-%d" % (seed, i))
            for i in range(honest_senders)
        ]
        start_h = self._height(name)
        stop = threading.Event()
        spam_sent = [0]

        def spam() -> None:
            scli = HTTPClient(url, timeout=3)
            k = 0
            while not stop.is_set():
                tx = encode_envelope(
                    spammer, b"spam/%d/%d=x" % (seed, k), priority=2, nonce=k
                )
                try:
                    scli.call("broadcast_tx_async", tx="0x" + tx.hex())
                    spam_sent[0] += 1
                except Exception:
                    pass
                k += 1
                time.sleep(0.002)

        spam_thread = threading.Thread(target=spam, daemon=True)
        spam_thread.start()
        honest_txs: list[bytes] = []
        interval = duration_s / (honest_rounds + 1)
        for j in range(honest_rounds):
            time.sleep(interval)
            for i, priv in enumerate(honest):
                tx = encode_envelope(
                    priv, b"honest/%d/%d/%d=x" % (seed, i, j), priority=3, nonce=j
                )
                res = cli.call("broadcast_tx_sync", tx="0x" + tx.hex())
                if int(res.get("code", -1)) != 0:
                    stop.set()
                    raise AssertionError(
                        f"{name}: honest tx rejected during flood: {res}"
                    )
                honest_txs.append(tx)
        time.sleep(interval)
        stop.set()
        spam_thread.join(timeout=5)
        after = cli.call("ingress_stats")
        delta = {
            k: after[k] - before.get(k, 0)
            for k in after
            if isinstance(after.get(k), int) and isinstance(before.get(k, 0), int)
        }
        if delta.get("shed_total", 0) <= 0:
            raise AssertionError(
                f"{name}: flood of {spam_sent[0]} spam txs was never shed: {delta}"
            )
        # Commit-within-bound: scan node 0's chain for every honest tx.
        first = self.manifest.nodes[0].name
        end_h = start_h + commit_bound
        self.wait_height(first, end_h, timeout=420)
        cli0 = HTTPClient(f"http://127.0.0.1:{self.rpc_ports[first]}", timeout=5)
        want = {base64.b64encode(t).decode() for t in honest_txs}
        seen: set[str] = set()
        for h in range(start_h, end_h + 1):
            blk = cli0.block(h)
            if blk.get("block"):
                seen.update(blk["block"]["data"]["txs"] or [])
        missing = want - seen
        if missing:
            raise AssertionError(
                f"{name}: {len(missing)}/{len(want)} honest txs not committed "
                f"within {commit_bound} blocks of the flood"
            )
        return {
            "spam_offered": spam_sent[0],
            "honest_offered": len(honest_txs),
            "honest_committed": len(want),
            "commit_bound_blocks": commit_bound,
            "ingress_delta": delta,
            "lane_depths_after": after.get("lane_depths"),
        }

    def _recv_flood(self, node: ManifestNode, duration_s: float = 6.0) -> dict:
        """Gossip-side recv flood: pump legacy txs into every OTHER node so
        mempool gossip saturates `node`'s inbound p2p connections while
        consensus block parts keep arriving on the same sockets.  Asserts
        the prioritized demux is live on the target (recvq_stats RPC),
        that the chain keeps advancing DURING the flood (the serialized
        recv path's failure mode was zero progress), and that the
        per-class counters show both mempool and consensus traffic was
        delivered through the queues."""
        from cometbft_tpu.loadtime import make_payload
        from cometbft_tpu.rpc.client import HTTPClient

        name = node.name
        cli = HTTPClient(f"http://127.0.0.1:{self.rpc_ports[name]}", timeout=5)
        before = cli.call("recvq_stats")
        if not before.get("enabled"):
            raise AssertionError(f"{name}: recv demux not enabled")
        others = [n.name for n in self.manifest.nodes if n.name != name] or [name]
        start_h = self._height(name)
        stop = threading.Event()
        offered = [0]

        def flood(target: str) -> None:
            fcli = HTTPClient(
                f"http://127.0.0.1:{self.rpc_ports[target]}", timeout=3
            )
            k = 0
            while not stop.is_set():
                tx = make_payload(k, time.time_ns())
                try:
                    fcli.call("broadcast_tx_async", tx="0x" + tx.hex())
                    offered[0] += 1
                except Exception:
                    pass
                k += 1
                time.sleep(0.002)

        threads = [
            threading.Thread(target=flood, args=(t,), daemon=True)
            for t in others
        ]
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        end_h = self._height(name)
        after = cli.call("recvq_stats")
        delta = {
            k: after[k] - before.get(k, 0)
            for k in after
            if isinstance(after.get(k), int) and isinstance(before.get(k, 0), int)
        }
        if end_h <= start_h:
            raise AssertionError(
                f"{name}: no commit during a {duration_s}s recv flood "
                f"({offered[0]} txs offered) — consensus bytes starved"
            )
        if delta.get("mempool_delivered", 0) <= 0:
            raise AssertionError(
                f"{name}: flood never reached the recv demux: {delta}"
            )
        if delta.get("consensus_delivered", 0) <= 0:
            raise AssertionError(
                f"{name}: no consensus traffic through the demux during "
                f"the flood: {delta}"
            )
        return {
            "flood_offered": offered[0],
            "flood_senders": len(others),
            "blocks_during_flood": end_h - start_h,
            "recvq_delta": delta,
            "max_delay_us_after": after.get("max_delay_us", 0),
            "promoted_during": delta.get("promoted_total", 0),
        }

    # -- the run ----------------------------------------------------------

    def _run_sim(self) -> dict:
        """network = "sim": one in-process virtual-clock scenario instead of
        OS processes. The scenario enforces the same core invariants the
        real runner does (target height + hash agreement); its resolved
        schedule is kept for the repro artifact."""
        from cometbft_tpu.simnet.scenario import run_scenario

        sim = self.manifest.sim
        self.log(
            f"simnet: {sim['validators']} validators, "
            f"{sim['blocks']} blocks, seed {sim['seed']}, "
            f"{len(sim['partitions'])} partitions, {len(sim['churn'])} churns"
        )
        report = run_scenario(dict(sim))
        self.sim_schedule = report.get("schedule")
        if not report.get("hash_agreement", True):
            raise AssertionError(
                f"simnet hash disagreement at height {report['agreed_height']}"
            )
        if not report.get("safety_ok", True):
            raise AssertionError(
                "simnet SAFETY VIOLATION: conflicting honest commits at "
                f"heights {report['conflicting_heights']}"
            )
        if not report["ok"]:
            # Height never reached: the stall signature (run_matrix maps
            # TimeoutError to `stalled`, same as a wall-clock wait_height).
            raise TimeoutError(
                f"simnet: height {sim['blocks'] + 1} not reached "
                f"(node0 at {report['height_node0']} after "
                f"{report['sim_time_s']} sim-s)"
            )
        self.log(
            f"simnet: height {report['height_node0']} in "
            f"{report['sim_time_s']} sim-s / {report['wall_time_s']} wall-s "
            f"({report['accel']}x), {report['events']} events"
        )
        return {
            "network": "sim",
            "nodes": report["validators"],
            "final_heights": {
                "min": report["heights_min"], "max": report["heights_max"]
            },
            **{
                k: report[k]
                for k in (
                    "seed", "agreed_height", "agreed_hash", "stragglers",
                    "sim_time_s", "wall_time_s", "accel", "events",
                    "counters", "block_hashes", "safety_ok", "evidence",
                    "recovery", "joins",
                )
            },
        }

    def run(self) -> dict:
        if self.manifest.network == "sim":
            return self._run_sim()
        self.setup()
        self.start()
        stop = threading.Event()
        pump = threading.Thread(target=self._load_pump, args=(stop,), daemon=True)
        try:
            first = self.manifest.nodes[0].name
            h0 = self.wait_height(first, self.manifest.initial_height + 2)
            pump.start()
            churn_report = None
            if self.manifest.validator_churn:
                churn_report = self.churn_validators()
            # Second start wave, in join order (runner/start.go sorts by
            # start_at the same way).
            late = sorted(
                (
                    (i, n)
                    for i, n in enumerate(self.manifest.nodes)
                    if n.start_at > 0
                ),
                key=lambda t: t[1].start_at,
            )
            for i, node in late:
                self._launch_late(i, node)
            for node in self.manifest.nodes:
                for kind in node.perturb:
                    self.perturb(node, kind)
            target = max(
                h0 + self.manifest.target_blocks,
                max((n.start_at for n in self.manifest.nodes), default=0) + 2,
            )
            heights = {
                n.name: self.wait_height(n.name, target, timeout=420)
                for n in self.manifest.nodes
            }
            # hash agreement at a common committed height (runner/test.go)
            from cometbft_tpu.rpc.client import HTTPClient

            common = min(heights.values())
            hashes = {
                n.name: HTTPClient(
                    f"http://127.0.0.1:{self.rpc_ports[n.name]}", timeout=5
                ).block(common)["block_id"]["hash"]
                for n in self.manifest.nodes
            }
            if len(set(hashes.values())) != 1:
                raise AssertionError(f"hash disagreement at {common}: {hashes}")
            light_report = None
            if self.manifest.light_client:
                light_report = self.verify_light_client(common)
                if light_report["hash"].lower() != \
                        next(iter(hashes.values())).lower():
                    raise AssertionError(
                        f"light client hash mismatch at {common}: "
                        f"{light_report['hash']} vs {hashes}"
                    )
            report = {
                "nodes": len(self.manifest.nodes),
                "perturbations": sum(len(n.perturb) for n in self.manifest.nodes),
                "late_joins": len(late),
                "backend": self.manifest.backend,
                "app": self.manifest.app,
                "final_heights": heights,
                "agreed_height": common,
                "agreed_hash": next(iter(hashes.values())),
            }
            if self._fault_armed:
                report["backend_faults"] = sorted(self._fault_armed)
            if self._light_swarms:
                report["concurrent_light_clients"] = self._light_swarms
            if self._light_gateways:
                report["light_gateway"] = self._light_gateways
            if self._tx_floods:
                report["tx_flood"] = self._tx_floods
            if self._vote_batches:
                report["vote_batch"] = self._vote_batches
            if self._mixed_loads:
                report["mixed_load"] = self._mixed_loads
            if self._recv_floods:
                report["recv_flood"] = self._recv_floods
            if self._bundle_syncs:
                report["bundle_cold_sync"] = self._bundle_syncs
            if churn_report is not None:
                report["validator_churn"] = churn_report
            if light_report is not None:
                report["light_client"] = light_report
            self.log(json.dumps(report))
            return report
        finally:
            stop.set()
            for proc in list(self.procs.values()) + list(self.app_procs.values()):
                if proc.poll() is None:
                    proc.send_signal(signal.SIGKILL)
                    proc.wait()
            for f in self._log_files:
                try:
                    f.close()
                except OSError:
                    pass

    def node_logs(self) -> dict[str, str]:
        """Per-node log paths (repro artifacts reference these)."""
        out = {}
        for i, node in enumerate(self.manifest.nodes):
            for suffix in ("node", "app"):
                p = os.path.join(self.home, f"node{i}", f"{suffix}.log")
                if os.path.exists(p):
                    out[f"{node.name}.{suffix}"] = p
        return out

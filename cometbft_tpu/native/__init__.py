"""Native host-tier crypto: C batch ed25519 verification + C Merkle trees.

The reference's CPU story rests on curve25519-voi's batch verifier
(crypto/ed25519/ed25519.go:196-228): one random-linear-combination equation
evaluated as a multi-scalar multiplication, ~an order of magnitude fewer
field multiplications than per-signature verification.  This package is
that tier for the TPU framework's device-less hosts: `ed25519_msm.c`
(radix-51 field arithmetic, ZIP-215 decompression, Pippenger MSM) and
`sha256_merkle.c` (RFC-6962 tree with the whole level loop in C), built
on first use with gcc into `_build/libcmtpu_native.so` and driven via
ctypes.  Falls back cleanly (available() -> False) when no compiler is
present; semantics are anchored by cometbft_tpu/crypto/ed25519_pure.py
and the pure merkle tree, tested bit-exact in tests/test_native.py.

Soundness: the batch equation uses independent 128-bit random nonzero
coefficients, so a batch that verifies without being valid has probability
~2^-128 (same construction as the reference's verifier).  On batch failure
the wrapper bisects; with z_i != 0 the randomized single-signature check
is EXACTLY the cofactored ZIP-215 check ([8][z](sB - R - hA) == id iff
[8](sB - R - hA) == id for 0 < z < L), so the recovered bitmap is exact.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ("ed25519_msm.c", "sha256_merkle.c", "fe_ifma.c")
_SO_PATH = os.path.join(_HERE, "_build", "libcmtpu_native.so")

L = 2**252 + 27742317777372353535851937790883648493

_lock = threading.Lock()
# The C MSM uses a static bucket table; serialize calls into it.
_msm_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> str | None:
    srcs = [os.path.join(_HERE, s) for s in _SOURCES]
    try:
        src_mtime = max(os.path.getmtime(s) for s in srcs)
        if os.path.exists(_SO_PATH) and os.path.getmtime(_SO_PATH) >= src_mtime:
            return _SO_PATH
        os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
        tmp = _SO_PATH + f".tmp.{os.getpid()}"
        subprocess.run(
            ["gcc", "-O3", "-fPIC", "-shared", "-o", tmp, *srcs],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO_PATH)
        return _SO_PATH
    except Exception:
        return None


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        if os.environ.get("CMTPU_NATIVE", "1") == "0":
            _tried = True
            return None
        path = _build()
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
                lib.cmtpu_ed25519_precheck.restype = ctypes.c_long
                lib.cmtpu_ed25519_precheck.argtypes = [
                    ctypes.c_long, ctypes.c_char_p, ctypes.c_char_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ]
                lib.cmtpu_ed25519_check_subset.restype = ctypes.c_int
                lib.cmtpu_ed25519_check_subset.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_long,
                    ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
                ]
                lib.cmtpu_ge_size.restype = ctypes.c_long
                lib.cmtpu_merkle_root.restype = None
                lib.cmtpu_merkle_root.argtypes = [
                    ctypes.c_long, ctypes.c_char_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p,
                ]
                lib.cmtpu_sha256_batch.restype = None
                lib.cmtpu_sha256_batch.argtypes = [
                    ctypes.c_long, ctypes.c_char_p, ctypes.c_void_p,
                    ctypes.c_void_p,
                ]
                lib.cmtpu_merkle_levels.restype = None
                lib.cmtpu_merkle_levels.argtypes = [
                    ctypes.c_long, ctypes.c_char_p, ctypes.c_void_p,
                    ctypes.c_void_p,
                ]
                lib.cmtpu_merkle_aunts.restype = None
                lib.cmtpu_merkle_aunts.argtypes = [
                    ctypes.c_long, ctypes.c_void_p, ctypes.c_long,
                    ctypes.c_void_p, ctypes.c_void_p,
                ]
                lib.cmtpu_sha512_batch.restype = None
                lib.cmtpu_sha512_batch.argtypes = [
                    ctypes.c_long, ctypes.c_char_p, ctypes.c_void_p,
                    ctypes.c_void_p,
                ]
                lib.cmtpu_ed25519_scalar_prep.restype = None
                lib.cmtpu_ed25519_scalar_prep.argtypes = [
                    ctypes.c_long, ctypes.c_void_p, ctypes.c_char_p,
                    ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p,
                ]
                lib.cmtpu_sha256_pack.restype = None
                lib.cmtpu_sha256_pack.argtypes = [
                    ctypes.c_long, ctypes.c_char_p, ctypes.c_void_p,
                    ctypes.c_long, ctypes.c_void_p, ctypes.c_void_p,
                ]
                _lib = lib
            except OSError:
                _lib = None
        _tried = True
        return _lib


def available() -> bool:
    """Blocking: builds the library on first call if needed (seconds of gcc).
    Latency-sensitive callers should use ready() + ensure_built_async()."""
    return _load() is not None


def ready():
    """Non-blocking: the loaded library, or None if not (yet) built.  Never
    triggers a compile — pair with ensure_built_async() from hot paths."""
    return _lib if _tried else None


def ensure_built_async() -> None:
    """Kick the build/load off a daemon thread so first-use verification
    paths never stall behind gcc (the same first-call-stall discipline as
    sidecar/backend.py's jax probing)."""
    if _tried:
        return
    threading.Thread(target=_load, name="cmtpu-native-build", daemon=True).start()


def batch_verify(
    pubs: list[bytes], msgs: list[bytes], sigs: list[bytes]
) -> tuple[bool, list[bool]]:
    """ZIP-215 batch verification with an exact per-signature bitmap.

    One MSM when everything is valid (the overwhelmingly common case);
    bisection recovers per-signature attribution on failure.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(pubs)
    bits = [False] * n
    if n == 0:
        return False, bits

    # Length gate (the kernel seam accepts raw triples).
    cand = [
        i for i in range(n) if len(pubs[i]) == 32 and len(sigs[i]) == 64
    ]
    m = len(cand)
    if m == 0:
        return False, bits

    pub_buf = b"".join(pubs[i] for i in cand)
    sig_buf = b"".join(sigs[i] for i in cand)
    ge_size = lib.cmtpu_ge_size()
    a_neg = ctypes.create_string_buffer(m * ge_size)
    r_neg = ctypes.create_string_buffer(m * ge_size)
    dec_ok = ctypes.create_string_buffer(m)
    lib.cmtpu_ed25519_precheck(m, pub_buf, sig_buf, a_neg, r_neg, dec_ok)

    # Challenges h = SHA512(R||A||M), then all scalar work (s<L check,
    # h mod L, z odd, zh = z*h, ssum accumulation) in one C pass.
    chal_buf = b"".join(
        sigs[i][:32] + pubs[i] + msgs[i] for i in cand
    )
    offs = _offsets((64 + len(msgs[i]) for i in cand), m)
    digests = ctypes.create_string_buffer(64 * m)
    lib.cmtpu_sha512_batch(m, chal_buf, offs, digests)

    rand = os.urandom(16 * m)
    z_buf = ctypes.create_string_buffer(32 * m)
    zh_buf = ctypes.create_string_buffer(32 * m)
    ssum_buf = ctypes.create_string_buffer(32)
    lib.cmtpu_ed25519_scalar_prep(
        m, digests, sig_buf, rand, z_buf, zh_buf, ssum_buf, dec_ok
    )
    okflags = dec_ok.raw  # decompress AND s-range survivors
    eligible = [j for j in range(m) if okflags[j]]
    if not eligible:
        return False, bits

    zb = z_buf.raw
    zhb = zh_buf.raw

    def check(subset: list[int], ssum: bytes) -> bool:
        idx = (ctypes.c_int64 * len(subset))(*subset)
        with _msm_lock:
            return bool(
                lib.cmtpu_ed25519_check_subset(
                    a_neg, r_neg, idx, len(subset), ssum, zb, zhb,
                )
            )

    if check(eligible, ssum_buf.raw):
        for j in eligible:
            bits[cand[j]] = True
        return all(bits), bits

    # Batch failed: bisect.  Subset ssums need the integers — parse them
    # once, only on this (rare, adversarial) path.
    z_int = {
        j: int.from_bytes(zb[32 * j : 32 * j + 32], "little") for j in eligible
    }
    s_int = {
        j: int.from_bytes(sigs[cand[j]][32:], "little") for j in eligible
    }

    def settle(subset: list[int]) -> None:
        ssum = 0
        for j in subset:
            ssum += z_int[j] * s_int[j]
        if check(subset, (ssum % L).to_bytes(32, "little")):
            for j in subset:
                bits[cand[j]] = True
            return
        if len(subset) == 1:
            return  # exact: randomized single == cofactored ZIP-215 check
        mid = len(subset) // 2
        settle(subset[:mid])
        settle(subset[mid:])

    mid = len(eligible) // 2
    if eligible[:mid]:
        settle(eligible[:mid])
    settle(eligible[mid:])
    return all(bits), bits


def _offsets(lengths, n: int):
    """uint64[n+1] cumulative offsets as a ctypes array from an iterable of
    n lengths — vectorized; the obvious python accumulation loop costs
    ~10 ms at 64k entries on a small host, which was a visible slice of
    the hybrid tier's merkle overlap."""
    import numpy as np

    offs = (ctypes.c_uint64 * (n + 1))()
    view = np.frombuffer(offs, np.uint64)
    np.cumsum(np.fromiter(lengths, np.uint64, n), out=view[1:])
    return offs


def _leaf_offsets(leaves: list[bytes]):
    return _offsets((len(v) for v in leaves), len(leaves))


def merkle_root(leaves: list[bytes]) -> bytes:
    """RFC-6962 root, identical to crypto/merkle hash_from_byte_slices."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(leaves)
    if n == 0:
        return hashlib.sha256(b"").digest()
    buf = b"".join(leaves)
    offs = _leaf_offsets(leaves)
    scratch = ctypes.create_string_buffer(32 * n)
    out = ctypes.create_string_buffer(32)
    lib.cmtpu_merkle_root(n, buf, offs, scratch, out)
    return out.raw


def merkle_proof_parts(
    leaves: list[bytes],
) -> tuple[bytes, list[bytes], bytes, int, "list[int]"]:
    """Everything proofs_from_byte_slices needs, hashed in one C pass:
    (root, leaf_hashes, packed_aunts, stride, counts) where leaf i's aunts
    are packed_aunts[i*stride : i*stride + 32*counts[i]] in 32-byte nodes,
    ordered sibling-first (crypto/merkle/proof.go:35-49 shape)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(leaves)
    if n == 0:
        return hashlib.sha256(b"").digest(), [], b"", 0, []
    buf = b"".join(leaves)
    offs = _leaf_offsets(leaves)

    total_nodes = 0
    size = n
    depth = 0
    while True:
        total_nodes += size
        if size == 1:
            break
        size = (size + 1) // 2
        depth += 1
    levels = ctypes.create_string_buffer(32 * total_nodes)
    lib.cmtpu_merkle_levels(n, buf, offs, levels)
    lraw = levels.raw  # one copy out of ctypes; .raw re-copies per access
    root = lraw[32 * (total_nodes - 1) : 32 * total_nodes]
    leaf_hashes = [lraw[32 * i : 32 * i + 32] for i in range(n)]
    stride = 32 * max(depth, 1)
    aunts = ctypes.create_string_buffer(n * stride)
    counts = (ctypes.c_int32 * n)()
    lib.cmtpu_merkle_aunts(n, levels, max(depth, 1), aunts, counts)
    return root, leaf_hashes, aunts.raw, stride, list(counts)


def sha256_batch(msgs: list[bytes]) -> list[bytes]:
    """Batch SHA-256 without per-call interpreter dispatch."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(msgs)
    if n == 0:
        return []
    buf = b"".join(msgs)
    offs = (ctypes.c_uint64 * (n + 1))()
    acc = 0
    for i, msg in enumerate(msgs):
        offs[i] = acc
        acc += len(msg)
    offs[n] = acc
    out = ctypes.create_string_buffer(32 * n)
    lib.cmtpu_sha256_batch(n, buf, offs, out)
    return [out.raw[32 * i : 32 * i + 32] for i in range(n)]

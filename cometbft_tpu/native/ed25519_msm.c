/* Native host-tier ed25519 batch verification.
 *
 * The reference's CPU batch path (crypto/ed25519/ed25519.go:196-228,
 * curve25519-voi BatchVerifier) wins over per-signature verification by
 * checking ONE random-linear-combination equation
 *
 *     [8]( [sum z_i s_i mod L] B  -  sum [z_i] R_i  -  sum [z_i h_i mod L] A_i ) == identity
 *
 * with 128-bit random z_i, evaluated as a single multi-scalar
 * multiplication (Pippenger bucket method).  This file is the TPU-framework
 * analog for hosts without a device: radix-51 field arithmetic, extended
 * twisted-Edwards points, ZIP-215 decompression (non-canonical y accepted,
 * x=0 with sign bit rejected — crypto/ed25519/ed25519.go:27-29 semantics,
 * anchored by cometbft_tpu/crypto/ed25519_pure.py), and a variable-time MSM.
 * Scalar arithmetic mod L (hashing, z*h products, the B coefficient) stays
 * in Python, which also drives bisection on batch failure to recover the
 * per-signature bitmap the BatchVerifier seam promises.
 *
 * Variable-time throughout: verification handles public data only.
 */

#include <stdint.h>
#include <string.h>
#include <stddef.h>

typedef uint64_t u64;
typedef __uint128_t u128;
typedef uint8_t u8;

#define MASK51 ((1ULL << 51) - 1)

typedef struct { u64 v[5]; } fe;
typedef struct { fe X, Y, Z, T; } ge; /* extended: x=X/Z y=Y/Z T=XY/Z */

static const fe FE_ONE = {{1, 0, 0, 0, 0}};

/* d = -121665/121666 mod p, radix-51 */
static const fe FE_D = {{
    929955233495203ULL, 466365720129213ULL, 1662059464998953ULL,
    2033849074728123ULL, 1442794654840575ULL}};
/* 2d mod p */
static const fe FE_2D = {{
    1859910466990425ULL, 932731440258426ULL, 1072319116312658ULL,
    1815898335770999ULL, 633789495995903ULL}};
/* sqrt(-1) mod p */
static const fe FE_SQRTM1 = {{
    1718705420411056ULL, 234908883556509ULL, 2233514472574048ULL,
    2117202627021982ULL, 765476049583133ULL}};

/* base point B, affine, radix-51 */
static const fe FE_BX = {{
    1738742601995546ULL, 1146398526822698ULL, 2070867633025821ULL,
    562264141797630ULL, 587772402128613ULL}};
static const fe FE_BY = {{
    1801439850948184ULL, 1351079888211148ULL, 450359962737049ULL,
    900719925474099ULL, 1801439850948198ULL}};

static void fe_add(fe *h, const fe *f, const fe *g) {
    for (int i = 0; i < 5; i++) h->v[i] = f->v[i] + g->v[i];
}

/* h = f + 4p - g: subtrahend limbs up to 2^53 stay positive.  Callers
 * fe_carry the result before it feeds a multiplication (mul/sq need
 * limbs < 2^53; an uncarried sub output can reach ~2^53.6). */
static void fe_sub(fe *h, const fe *f, const fe *g) {
    h->v[0] = f->v[0] + 0x1FFFFFFFFFFFB4ULL - g->v[0];
    h->v[1] = f->v[1] + 0x1FFFFFFFFFFFFCULL - g->v[1];
    h->v[2] = f->v[2] + 0x1FFFFFFFFFFFFCULL - g->v[2];
    h->v[3] = f->v[3] + 0x1FFFFFFFFFFFFCULL - g->v[3];
    h->v[4] = f->v[4] + 0x1FFFFFFFFFFFFCULL - g->v[4];
}

static void fe_carry(fe *h) {
    u64 c;
    c = h->v[0] >> 51; h->v[0] &= MASK51; h->v[1] += c;
    c = h->v[1] >> 51; h->v[1] &= MASK51; h->v[2] += c;
    c = h->v[2] >> 51; h->v[2] &= MASK51; h->v[3] += c;
    c = h->v[3] >> 51; h->v[3] &= MASK51; h->v[4] += c;
    c = h->v[4] >> 51; h->v[4] &= MASK51; h->v[0] += c * 19;
    c = h->v[0] >> 51; h->v[0] &= MASK51; h->v[1] += c;
}

static void fe_mul(fe *h, const fe *f, const fe *g) {
    u64 f0 = f->v[0], f1 = f->v[1], f2 = f->v[2], f3 = f->v[3], f4 = f->v[4];
    u64 g0 = g->v[0], g1 = g->v[1], g2 = g->v[2], g3 = g->v[3], g4 = g->v[4];
    u64 g1_19 = 19 * g1, g2_19 = 19 * g2, g3_19 = 19 * g3, g4_19 = 19 * g4;

    u128 t0 = (u128)f0 * g0 + (u128)f1 * g4_19 + (u128)f2 * g3_19 +
              (u128)f3 * g2_19 + (u128)f4 * g1_19;
    u128 t1 = (u128)f0 * g1 + (u128)f1 * g0 + (u128)f2 * g4_19 +
              (u128)f3 * g3_19 + (u128)f4 * g2_19;
    u128 t2 = (u128)f0 * g2 + (u128)f1 * g1 + (u128)f2 * g0 +
              (u128)f3 * g4_19 + (u128)f4 * g3_19;
    u128 t3 = (u128)f0 * g3 + (u128)f1 * g2 + (u128)f2 * g1 +
              (u128)f3 * g0 + (u128)f4 * g4_19;
    u128 t4 = (u128)f0 * g4 + (u128)f1 * g3 + (u128)f2 * g2 +
              (u128)f3 * g1 + (u128)f4 * g0;

    u64 r0, r1, r2, r3, r4, c;
    t1 += (u64)(t0 >> 51); r0 = (u64)t0 & MASK51;
    t2 += (u64)(t1 >> 51); r1 = (u64)t1 & MASK51;
    t3 += (u64)(t2 >> 51); r2 = (u64)t2 & MASK51;
    t4 += (u64)(t3 >> 51); r3 = (u64)t3 & MASK51;
    c = (u64)(t4 >> 51);   r4 = (u64)t4 & MASK51;
    r0 += c * 19;
    r1 += r0 >> 51; r0 &= MASK51;
    h->v[0] = r0; h->v[1] = r1; h->v[2] = r2; h->v[3] = r3; h->v[4] = r4;
}

static void fe_sq(fe *h, const fe *f) {
    u64 f0 = f->v[0], f1 = f->v[1], f2 = f->v[2], f3 = f->v[3], f4 = f->v[4];
    u64 f0_2 = 2 * f0, f1_2 = 2 * f1;
    u64 f3_19 = 19 * f3, f4_19 = 19 * f4;

    u128 t0 = (u128)f0 * f0 + (u128)f1_2 * f4_19 + (u128)(2 * f2) * f3_19;
    u128 t1 = (u128)f0_2 * f1 + (u128)f2 * f4_19 * 2 + (u128)f3 * f3_19;
    u128 t2 = (u128)f0_2 * f2 + (u128)f1 * f1 + (u128)(2 * f3) * f4_19;
    u128 t3 = (u128)f0_2 * f3 + (u128)f1_2 * f2 + (u128)f4 * f4_19;
    u128 t4 = (u128)f0_2 * f4 + (u128)f1_2 * f3 + (u128)f2 * f2;

    u64 r0, r1, r2, r3, r4, c;
    t1 += (u64)(t0 >> 51); r0 = (u64)t0 & MASK51;
    t2 += (u64)(t1 >> 51); r1 = (u64)t1 & MASK51;
    t3 += (u64)(t2 >> 51); r2 = (u64)t2 & MASK51;
    t4 += (u64)(t3 >> 51); r3 = (u64)t3 & MASK51;
    c = (u64)(t4 >> 51);   r4 = (u64)t4 & MASK51;
    r0 += c * 19;
    r1 += r0 >> 51; r0 &= MASK51;
    h->v[0] = r0; h->v[1] = r1; h->v[2] = r2; h->v[3] = r3; h->v[4] = r4;
}

/* ignores bit 255 (sign bit handled by the caller); value may be >= p
 * (ZIP-215 rule 1: non-canonical y is reduced, not rejected) */
static void fe_frombytes(fe *h, const u8 s[32]) {
    u64 w0, w1, w2, w3;
    memcpy(&w0, s, 8); memcpy(&w1, s + 8, 8);
    memcpy(&w2, s + 16, 8); memcpy(&w3, s + 24, 8);
    h->v[0] = w0 & MASK51;
    h->v[1] = ((w0 >> 51) | (w1 << 13)) & MASK51;
    h->v[2] = ((w1 >> 38) | (w2 << 26)) & MASK51;
    h->v[3] = ((w2 >> 25) | (w3 << 39)) & MASK51;
    h->v[4] = (w3 >> 12) & MASK51; /* drops bit 255 (the sign bit) */
}

/* canonical little-endian encoding (full reduction mod p, top bit clear) */
static void fe_tobytes(u8 s[32], const fe *f) {
    fe t = *f;
    fe_carry(&t);
    fe_carry(&t);
    /* limbs now < 2^51; conditionally subtract p */
    u64 q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    u64 c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;
    u64 w0 = t.v[0] | (t.v[1] << 51);
    u64 w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    u64 w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    u64 w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    memcpy(s, &w0, 8); memcpy(s + 8, &w1, 8);
    memcpy(s + 16, &w2, 8); memcpy(s + 24, &w3, 8);
}

static int fe_iszero(const fe *f) {
    u8 s[32];
    fe_tobytes(s, f);
    u8 acc = 0;
    for (int i = 0; i < 32; i++) acc |= s[i];
    return acc == 0;
}

static int fe_eq(const fe *f, const fe *g) {
    fe t;
    fe_sub(&t, f, g);
    return fe_iszero(&t);
}

static int fe_isodd(const fe *f) {
    u8 s[32];
    fe_tobytes(s, f);
    return s[0] & 1;
}

static void fe_neg(fe *h, const fe *f) {
    fe zero = {{0, 0, 0, 0, 0}};
    fe_sub(h, &zero, f);
    fe_carry(h);
}

/* f^(2^252 - 3)  ==  f^((p-5)/8): binary chain over 2^250-1 */
static void fe_pow2523(fe *out, const fe *z) {
    fe t0, t1, t2;
    int i;
    fe_sq(&t0, z);                                   /* 2 */
    fe_sq(&t1, &t0); fe_sq(&t1, &t1);                /* 8 */
    fe_mul(&t1, z, &t1);                             /* 9 */
    fe_mul(&t0, &t0, &t1);                           /* 11 */
    fe_sq(&t0, &t0);                                 /* 22 */
    fe_mul(&t0, &t1, &t0);                           /* 2^5-1 */
    fe_sq(&t1, &t0);
    for (i = 1; i < 5; i++) fe_sq(&t1, &t1);
    fe_mul(&t0, &t1, &t0);                           /* 2^10-1 */
    fe_sq(&t1, &t0);
    for (i = 1; i < 10; i++) fe_sq(&t1, &t1);
    fe_mul(&t1, &t1, &t0);                           /* 2^20-1 */
    fe_sq(&t2, &t1);
    for (i = 1; i < 20; i++) fe_sq(&t2, &t2);
    fe_mul(&t1, &t2, &t1);                           /* 2^40-1 */
    fe_sq(&t1, &t1);
    for (i = 1; i < 10; i++) fe_sq(&t1, &t1);
    fe_mul(&t0, &t1, &t0);                           /* 2^50-1 */
    fe_sq(&t1, &t0);
    for (i = 1; i < 50; i++) fe_sq(&t1, &t1);
    fe_mul(&t1, &t1, &t0);                           /* 2^100-1 */
    fe_sq(&t2, &t1);
    for (i = 1; i < 100; i++) fe_sq(&t2, &t2);
    fe_mul(&t1, &t2, &t1);                           /* 2^200-1 */
    fe_sq(&t1, &t1);
    for (i = 1; i < 50; i++) fe_sq(&t1, &t1);
    fe_mul(&t0, &t1, &t0);                           /* 2^250-1 */
    fe_sq(&t0, &t0); fe_sq(&t0, &t0);                /* 2^252-4 */
    fe_mul(out, &t0, z);                             /* 2^252-3 */
}

static const ge GE_ID = {{{0,0,0,0,0}}, {{1,0,0,0,0}}, {{1,0,0,0,0}}, {{0,0,0,0,0}}};

/* unified add-2008-hwcd-3 for a=-1: complete for all curve points
 * (including small-order), so bucket accumulation needs no special cases */
static void ge_add(ge *r, const ge *p, const ge *q) {
    fe A, B, C, D, E, F, G, H, t1, t2;
    fe_sub(&t1, &p->Y, &p->X);
    fe_sub(&t2, &q->Y, &q->X);
    fe_carry(&t1); fe_carry(&t2);
    fe_mul(&A, &t1, &t2);
    fe_add(&t1, &p->Y, &p->X);
    fe_add(&t2, &q->Y, &q->X);
    fe_mul(&B, &t1, &t2);
    fe_mul(&C, &p->T, &q->T);
    fe_mul(&C, &C, &FE_2D);
    fe_mul(&D, &p->Z, &q->Z);
    fe_add(&D, &D, &D);
    fe_sub(&E, &B, &A); fe_carry(&E);
    fe_sub(&F, &D, &C); fe_carry(&F);
    fe_add(&G, &D, &C);
    fe_add(&H, &B, &A);
    fe_mul(&r->X, &E, &F);
    fe_mul(&r->Y, &G, &H);
    fe_mul(&r->Z, &F, &G);
    fe_mul(&r->T, &E, &H);
}

/* dedicated doubling (dbl-2008-hwcd), 4M+4S */
static void ge_dbl(ge *r, const ge *p) {
    fe A, B, C, D, E, F, G, H, t;
    fe_sq(&A, &p->X);
    fe_sq(&B, &p->Y);
    fe_sq(&C, &p->Z);
    fe_add(&C, &C, &C);
    fe_neg(&D, &A);
    fe_add(&t, &p->X, &p->Y); fe_carry(&t);
    fe_sq(&t, &t);
    fe_sub(&t, &t, &A); fe_sub(&t, &t, &B); fe_carry(&t);
    E = t;
    fe_add(&G, &D, &B);
    fe_sub(&F, &G, &C); fe_carry(&F);
    fe_sub(&H, &D, &B); fe_carry(&H);
    fe_mul(&r->X, &E, &F);
    fe_mul(&r->Y, &G, &H);
    fe_mul(&r->Z, &F, &G);
    fe_mul(&r->T, &E, &H);
}

static void ge_neg(ge *r, const ge *p) {
    fe_neg(&r->X, &p->X);
    r->Y = p->Y;
    r->Z = p->Z;
    fe_neg(&r->T, &p->T);
}

/* ZIP-215 decompression, split so the fixed exponentiation can run
 * 8-wide on IFMA hosts (fe_ifma.c): phase A derives u, v, v3 and the
 * exponentiation input u*v^7; phase C finishes from pow = (u v^7)^((p-5)/8). */
typedef struct {
    fe u, v, v3, y, powin;
    int sign;
} dec_mid;

static void decompress_phase_a(dec_mid *d, const u8 s[32]) {
    fe t;
    d->sign = s[31] >> 7;
    fe_frombytes(&d->y, s);
    fe_sq(&d->u, &d->y);
    fe_mul(&d->v, &d->u, &FE_D);
    fe_sub(&d->u, &d->u, &FE_ONE); fe_carry(&d->u);   /* u = y^2 - 1 */
    fe_add(&d->v, &d->v, &FE_ONE);                    /* v = d y^2 + 1 */
    fe_sq(&d->v3, &d->v);
    fe_mul(&d->v3, &d->v3, &d->v);                    /* v^3 */
    fe_sq(&t, &d->v3);
    fe_mul(&t, &t, &d->v);
    fe_mul(&d->powin, &t, &d->u);                     /* u v^7 */
}

static int decompress_phase_c(ge *h, const dec_mid *d, const fe *pow) {
    fe x, vxx, check;
    fe_mul(&x, pow, &d->v3);
    fe_mul(&x, &x, &d->u);                 /* u v^3 (u v^7)^((p-5)/8) */
    fe_sq(&vxx, &x);
    fe_mul(&vxx, &vxx, &d->v);
    fe_sub(&check, &vxx, &d->u);
    if (!fe_iszero(&check)) {
        fe_add(&check, &vxx, &d->u);
        if (!fe_iszero(&check)) return 0;
        fe_mul(&x, &x, &FE_SQRTM1);
    }
    if (fe_iszero(&x)) {
        if (d->sign) return 0;             /* x=0 with sign bit set */
    } else if (fe_isodd(&x) != d->sign) {
        fe_neg(&x, &x);
    }
    h->X = x;
    h->Y = d->y;
    h->Z = FE_ONE;
    fe_mul(&h->T, &x, &d->y);
    return 1;
}

static int ge_frombytes_zip215(ge *h, const u8 s[32]) {
    dec_mid d;
    fe pow;
    decompress_phase_a(&d, s);
    fe_pow2523(&pow, &d.powin);
    return decompress_phase_c(h, &d, &pow);
}

/* radix-51 <-> radix-52 bridges for the IFMA lane layout */
static void fe_to52(const fe *f, u64 out[5]) {
    u8 b[32];
    u64 w[4];
    fe_tobytes(b, f);
    memcpy(w, b, 32);
    out[0] = w[0] & ((1ULL << 52) - 1);
    out[1] = ((w[0] >> 52) | (w[1] << 12)) & ((1ULL << 52) - 1);
    out[2] = ((w[1] >> 40) | (w[2] << 24)) & ((1ULL << 52) - 1);
    out[3] = ((w[2] >> 28) | (w[3] << 36)) & ((1ULL << 52) - 1);
    out[4] = w[3] >> 16;
}

static void fe_from52(const u64 in[5], fe *f) {
    u8 b[32];
    u64 w[4];
    /* limbs may be non-canonical (< 2^52); fold into 256-bit then load.
     * Total value < 2^256+eps... IFMA output limbs are < 2^52 so the
     * packed value fits 260 bits; fold the top 4 bits via 2^256 mod p:
     * simpler: combine as two 130-bit halves through fe arithmetic-free
     * byte packing requires full canonicality, so reduce with bigint-ish
     * carries first: value = sum in[k] 2^52k < 2^260; we use the fe
     * radix-51 loader on the low 255 bits and add the high part times
     * 2^255 mod p = 19. */
    u64 l[5] = {in[0], in[1], in[2], in[3], in[4]};
    /* pack low 255 bits */
    w[0] = l[0] | (l[1] << 52);
    w[1] = (l[1] >> 12) | (l[2] << 40);
    w[2] = (l[2] >> 24) | (l[3] << 28);
    w[3] = (l[3] >> 36) | (l[4] << 16);
    u64 top = l[4] >> 48; /* bits >= 2^256... wait: l4 weight 2^208 */
    memcpy(b, w, 32);
    b[31] &= 0x7F;
    u64 bit255 = (w[3] >> 63) & 1;
    fe_frombytes(f, b);
    /* add back bits 255.. : value_hi = top*2^256 + bit255*2^255
     * 2^255 == 19, 2^256 == 38 (mod p) */
    fe add = {{bit255 * 19 + top * 38, 0, 0, 0, 0}};
    fe_add(f, f, &add);
    fe_carry(f);
}

static int ge_is_identity(const ge *p) {
    return fe_iszero(&p->X) && fe_eq(&p->Y, &p->Z);
}

/* ---- exported API (ctypes) ---- */

/* fe_ifma.c: 8-wide x^((p-5)/8) on AVX-512 IFMA hosts */
extern void cmtpu_fe8_pow2523(const u64 *in, u64 *out);
extern int cmtpu_have_ifma(void);

/* Decompress pubkeys and R components, negated, for the batch equation.
 * pubs: n*32, sigs: n*64 (R||s).  Aneg/Rneg: n ge slots (opaque to Python).
 * ok[i] = 1 if both decompressed; NOT final validity — the s < L range
 * check runs in cmtpu_ed25519_scalar_prep, which clears ok[i] for
 * out-of-range s.  Returns the number of ok entries.
 *
 * On IFMA hosts the per-point sqrt exponentiation — the bulk of
 * decompression — runs 8 points per dispatch (4 signatures x {A, R}). */
long cmtpu_ed25519_precheck(long n, const u8 *pubs, const u8 *sigs,
                            ge *Aneg, ge *Rneg, u8 *ok) {
    static int have_ifma = -1;
    if (have_ifma < 0) have_ifma = cmtpu_have_ifma();
    long good = 0;
    if (!have_ifma) {
        for (long i = 0; i < n; i++) {
            ge A, R;
            if (ge_frombytes_zip215(&A, pubs + 32 * i) &&
                ge_frombytes_zip215(&R, sigs + 64 * i)) {
                ge_neg(&Aneg[i], &A);
                ge_neg(&Rneg[i], &R);
                ok[i] = 1;
                good++;
            } else {
                ok[i] = 0;
            }
        }
        return good;
    }
    for (long base = 0; base < n; base += 4) {
        long cnt = n - base < 4 ? n - base : 4;
        dec_mid mid[8];
        u64 lanes_in[40], lanes_out[40];
        memset(lanes_in, 0, sizeof lanes_in);
        for (long j = 0; j < cnt; j++) {
            decompress_phase_a(&mid[2 * j], pubs + 32 * (base + j));
            decompress_phase_a(&mid[2 * j + 1], sigs + 64 * (base + j));
            fe_to52(&mid[2 * j].powin, lanes_in + 5 * (2 * j));
            fe_to52(&mid[2 * j + 1].powin, lanes_in + 5 * (2 * j + 1));
        }
        cmtpu_fe8_pow2523(lanes_in, lanes_out);
        for (long j = 0; j < cnt; j++) {
            long i = base + j;
            fe powA, powR;
            ge A, R;
            fe_from52(lanes_out + 5 * (2 * j), &powA);
            fe_from52(lanes_out + 5 * (2 * j + 1), &powR);
            if (decompress_phase_c(&A, &mid[2 * j], &powA) &&
                decompress_phase_c(&R, &mid[2 * j + 1], &powR)) {
                ge_neg(&Aneg[i], &A);
                ge_neg(&Rneg[i], &R);
                ok[i] = 1;
                good++;
            } else {
                ok[i] = 0;
            }
        }
    }
    return good;
}

static int pick_window(long npoints) {
    if (npoints < 32) return 4;
    if (npoints < 128) return 5;
    if (npoints < 512) return 7;
    if (npoints < 2048) return 9;
    if (npoints < 8192) return 10;
    if (npoints < 32768) return 11;
    return 12;
}

static int get_digit(const u8 *sc, int pos, int c) {
    int byte = pos >> 3, shift = pos & 7;
    uint32_t v = sc[byte];
    if (byte + 1 < 32) v |= (uint32_t)sc[byte + 1] << 8;
    if (byte + 2 < 32) v |= (uint32_t)sc[byte + 2] << 16;
    return (v >> shift) & ((1 << c) - 1);
}

static ge BUCKETS[1 << 12];

/* Check  [8]( [ssum]B + sum [z_i]Rneg_i + sum [zh_i]Aneg_i ) == identity
 * over the m-entry subset idx of the prechecked points.
 * ssum: 32 bytes; z,zh: n*32 bytes (indexed by idx).  Returns 1 if holds. */
int cmtpu_ed25519_check_subset(const ge *Aneg, const ge *Rneg,
                               const int64_t *idx, long m,
                               const u8 *ssum, const u8 *z, const u8 *zh) {
    long npoints = 2 * m + 1;
    int c = pick_window(npoints);
    int nbuckets = (1 << c) - 1;
    int nwin = (253 + c - 1) / c;
    ge acc = GE_ID, Bp;
    Bp.X = FE_BX; Bp.Y = FE_BY; Bp.Z = FE_ONE;
    fe_mul(&Bp.T, &FE_BX, &FE_BY);

    for (int w = nwin - 1; w >= 0; w--) {
        if (w != nwin - 1)
            for (int k = 0; k < c; k++) ge_dbl(&acc, &acc);
        int pos = w * c;
        for (int b = 0; b < nbuckets; b++) BUCKETS[b] = GE_ID;
        int d = get_digit(ssum, pos, c);
        int used = 0;
        if (d) {
            ge_add(&BUCKETS[d - 1], &BUCKETS[d - 1], &Bp);
            used = 1;
        }
        for (long j = 0; j < m; j++) {
            long i = idx[j];
            d = get_digit(z + 32 * i, pos, c);
            if (d) { ge_add(&BUCKETS[d - 1], &BUCKETS[d - 1], &Rneg[i]); used = 1; }
            d = get_digit(zh + 32 * i, pos, c);
            if (d) { ge_add(&BUCKETS[d - 1], &BUCKETS[d - 1], &Aneg[i]); used = 1; }
        }
        if (!used) continue;
        ge run = GE_ID, wsum = GE_ID;
        for (int b = nbuckets - 1; b >= 0; b--) {
            ge_add(&run, &run, &BUCKETS[b]);
            ge_add(&wsum, &wsum, &run);
        }
        ge_add(&acc, &acc, &wsum);
    }
    ge_dbl(&acc, &acc);
    ge_dbl(&acc, &acc);
    ge_dbl(&acc, &acc);
    return ge_is_identity(&acc);
}

long cmtpu_ge_size(void) { return (long)sizeof(ge); }

/* ---- scalar arithmetic mod L (batch-equation coefficient prep) ----
 *
 * L = 2^252 + 27742317777372353535851937790883648493.  Values are 4x64-bit
 * little-endian limbs; products/reductions via unsigned __int128 and the
 * fold 2^252 == -C (mod L). */

static const u64 SC_L[4] = {
    0x5812631A5CF5D3EDULL, 0x14DEF9DEA2F79CD6ULL, 0, 0x1000000000000000ULL};
/* C = L - 2^252 (125 bits) */
static const u64 SC_C[2] = {0x5812631A5CF5D3EDULL, 0x14DEF9DEA2F79CD6ULL};

/* a[n] >> 252, into out[m] (caller sizes m for the true width) */
static void sc_shr252(const u64 *a, int n, u64 *out, int m) {
    for (int i = 0; i < m; i++) {
        u64 lo = (3 + i < n) ? (a[3 + i] >> 60) : 0;
        u64 hi = (4 + i < n) ? (a[4 + i] << 4) : 0;
        out[i] = lo | hi;
    }
}

/* out[4] = a & (2^252 - 1) */
static void sc_lo252(const u64 *a, int n, u64 out[4]) {
    for (int i = 0; i < 4; i++) out[i] = (i < n) ? a[i] : 0;
    out[3] &= (1ULL << 60) - 1;
}

/* out[n+2] = a[n] * C (C is 2 limbs) */
static void sc_mul_c(const u64 *a, int n, u64 *out) {
    for (int i = 0; i < n + 2; i++) out[i] = 0;
    for (int i = 0; i < n; i++) {
        u128 carry = 0;
        for (int j = 0; j < 2; j++) {
            u128 cur = (u128)out[i + j] + (u128)a[i] * SC_C[j] + carry;
            out[i + j] = (u64)cur;
            carry = cur >> 64;
        }
        int k = i + 2;
        while (carry) {
            u128 cur = (u128)out[k] + carry;
            out[k] = (u64)cur;
            carry = cur >> 64;
            k++;
        }
    }
}

/* r (4 limbs) = x (8 limbs, < 2^512) mod L.
 *
 * Signed folding on 2^252 == -C (mod L), C = L - 2^252 (126 bits):
 *   x = plus - m_lo + m2_lo - m3          with
 *   m  = (x  >> 252) * C   (<= 386 bits)
 *   m2 = (m  >> 252) * C   (<= 260 bits)
 *   m3 = (m2 >> 252) * C   (<= 134 bits, already < 2^252)
 * so  x ≡ (plus + m2_lo) + 8L - (m_lo + m3)  with every term < 2^253,
 * then a bounded run of conditional subtracts normalizes into [0, L). */
static void sc_reduce512(u64 r[4], const u64 x[8]) {
    u64 plus[4], m[7], m_lo[4], m_hi[3], m2[5], m2_lo[4], m2_hi[1], m3[3];
    u64 hi[5];
    sc_lo252(x, 8, plus);
    sc_shr252(x, 8, hi, 5);          /* <= 260 bits */
    sc_mul_c(hi, 5, m);              /* <= 386 bits, 7 limbs */
    sc_lo252(m, 7, m_lo);
    sc_shr252(m, 7, m_hi, 3);        /* <= 134 bits */
    sc_mul_c(m_hi, 3, m2);           /* <= 260 bits, 5 limbs */
    sc_lo252(m2, 5, m2_lo);
    sc_shr252(m2, 5, m2_hi, 1);      /* <= 8 bits */
    sc_mul_c(m2_hi, 1, m3);          /* <= 134 bits, 3 limbs, < 2^252 */

    /* acc = plus + m2_lo + 8L - m_lo - m3, all in 5 limbs */
    u64 acc[5] = {0, 0, 0, 0, 0};
    u128 carry = 0;
    /* 8L = 2^255 + 8C */
    u64 eightl[5];
    eightl[0] = SC_C[0] << 3;
    eightl[1] = (SC_C[1] << 3) | (SC_C[0] >> 61);
    eightl[2] = SC_C[1] >> 61;
    eightl[3] = 1ULL << 63;
    eightl[4] = 0;
    for (int i = 0; i < 5; i++) {
        u128 t = carry + eightl[i];
        if (i < 4) t += (u128)plus[i] + m2_lo[i];
        acc[i] = (u64)t;
        carry = t >> 64;
    }
    /* single 5-limb subtrahend (m_lo + m3), then one borrow chain */
    u64 sub5[5] = {0, 0, 0, 0, 0};
    carry = 0;
    for (int i = 0; i < 5; i++) {
        u128 t = carry;
        if (i < 4) t += m_lo[i];
        if (i < 3) t += m3[i];
        sub5[i] = (u64)t;
        carry = t >> 64;
    }
    u64 borrow_bit = 0;
    for (int i = 0; i < 5; i++) {
        u128 t = (u128)acc[i] - sub5[i] - borrow_bit;
        acc[i] = (u64)t;
        borrow_bit = (t >> 64) ? 1 : 0;
    }
    /* acc < 8L + 2^253 < 11*L: bounded conditional subtracts */
    for (int rep = 0; rep < 12; rep++) {
        int ge_l;
        if (acc[4]) {
            ge_l = 1;
        } else {
            ge_l = 1;
            for (int i = 3; i >= 0; i--) {
                if (acc[i] > SC_L[i]) { ge_l = 1; break; }
                if (acc[i] < SC_L[i]) { ge_l = 0; break; }
            }
        }
        if (!ge_l) break;
        borrow_bit = 0;
        for (int i = 0; i < 5; i++) {
            u128 t = (u128)acc[i] - ((i < 4) ? SC_L[i] : 0) - borrow_bit;
            acc[i] = (u64)t;
            borrow_bit = (t >> 64) ? 1 : 0;
        }
    }
    r[0] = acc[0]; r[1] = acc[1]; r[2] = acc[2]; r[3] = acc[3];
}

static void sc_mul(u64 r[4], const u64 a[4], const u64 b[4]) {
    u64 t[8] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 cur = (u128)t[i + j] + (u128)a[i] * b[j] + carry;
            t[i + j] = (u64)cur;
            carry = cur >> 64;
        }
        t[i + 4] = (u64)carry;
    }
    sc_reduce512(r, t);
}

static void sc_add(u64 r[4], const u64 a[4], const u64 b[4]) {
    u64 t[8] = {0};
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 cur = (u128)a[i] + b[i] + carry;
        t[i] = (u64)cur;
        carry = cur >> 64;
    }
    t[4] = (u64)carry;
    sc_reduce512(r, t);
}

/* s < L, strict (the RFC 8032 / ZIP-215 s-range check) */
static int sc_lt_l(const u64 s[4]) {
    for (int i = 3; i >= 0; i--) {
        if (s[i] < SC_L[i]) return 1;
        if (s[i] > SC_L[i]) return 0;
    }
    return 0; /* equal */
}

/* Batch scalar prep: for each entry i with ok[i] set on input (decompress
 * passed), check s < L (clearing ok[i] otherwise), compute
 *   h_i = digest_i mod L          (64-byte SHA-512 output)
 *   z_i = z16_i | 1               (forced odd, 128-bit)
 *   zh_i = z_i * h_i mod L
 * and accumulate ssum = sum z_i * s_i mod L over surviving entries.
 * Buffers are all little-endian; z32/zh32 are the MSM coefficient arrays. */
void cmtpu_ed25519_scalar_prep(long n, const u8 *digests, const u8 *sigs,
                               const u8 *z16, u8 *z32, u8 *zh32,
                               u8 *ssum32, u8 *ok) {
    u64 ssum[4] = {0, 0, 0, 0};
    for (long i = 0; i < n; i++) {
        if (!ok[i]) continue;
        u64 s[4];
        memcpy(s, sigs + 64 * i + 32, 32);
        if (!sc_lt_l(s)) { ok[i] = 0; continue; }
        u64 d[8], h[4], z[4] = {0, 0, 0, 0}, zh[4], zs[4];
        memcpy(d, digests + 64 * i, 64);
        sc_reduce512(h, d);
        memcpy(z, z16 + 16 * i, 16);
        z[0] |= 1;
        sc_mul(zh, z, h);
        sc_mul(zs, z, s);
        sc_add(ssum, ssum, zs);
        memcpy(z32 + 32 * i, z, 32);
        memcpy(zh32 + 32 * i, zh, 32);
    }
    memcpy(ssum32, ssum, 32);
}

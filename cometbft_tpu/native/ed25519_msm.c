/* Native host-tier ed25519 batch verification.
 *
 * The reference's CPU batch path (crypto/ed25519/ed25519.go:196-228,
 * curve25519-voi BatchVerifier) wins over per-signature verification by
 * checking ONE random-linear-combination equation
 *
 *     [8]( [sum z_i s_i mod L] B  -  sum [z_i] R_i  -  sum [z_i h_i mod L] A_i ) == identity
 *
 * with 128-bit random z_i, evaluated as a single multi-scalar
 * multiplication (Pippenger bucket method).  This file is the TPU-framework
 * analog for hosts without a device: radix-51 field arithmetic, extended
 * twisted-Edwards points, ZIP-215 decompression (non-canonical y accepted,
 * x=0 with sign bit rejected — crypto/ed25519/ed25519.go:27-29 semantics,
 * anchored by cometbft_tpu/crypto/ed25519_pure.py), and a variable-time MSM.
 * Scalar arithmetic mod L (hashing, z*h products, the B coefficient) stays
 * in Python, which also drives bisection on batch failure to recover the
 * per-signature bitmap the BatchVerifier seam promises.
 *
 * Variable-time throughout: verification handles public data only.
 */

#include <stdint.h>
#include <string.h>
#include <stddef.h>

typedef uint64_t u64;
typedef __uint128_t u128;
typedef uint8_t u8;

#define MASK51 ((1ULL << 51) - 1)

typedef struct { u64 v[5]; } fe;
typedef struct { fe X, Y, Z, T; } ge; /* extended: x=X/Z y=Y/Z T=XY/Z */

static const fe FE_ONE = {{1, 0, 0, 0, 0}};

/* d = -121665/121666 mod p, radix-51 */
static const fe FE_D = {{
    929955233495203ULL, 466365720129213ULL, 1662059464998953ULL,
    2033849074728123ULL, 1442794654840575ULL}};
/* 2d mod p */
static const fe FE_2D = {{
    1859910466990425ULL, 932731440258426ULL, 1072319116312658ULL,
    1815898335770999ULL, 633789495995903ULL}};
/* sqrt(-1) mod p */
static const fe FE_SQRTM1 = {{
    1718705420411056ULL, 234908883556509ULL, 2233514472574048ULL,
    2117202627021982ULL, 765476049583133ULL}};

/* base point B, affine, radix-51 */
static const fe FE_BX = {{
    1738742601995546ULL, 1146398526822698ULL, 2070867633025821ULL,
    562264141797630ULL, 587772402128613ULL}};
static const fe FE_BY = {{
    1801439850948184ULL, 1351079888211148ULL, 450359962737049ULL,
    900719925474099ULL, 1801439850948198ULL}};

static void fe_add(fe *h, const fe *f, const fe *g) {
    for (int i = 0; i < 5; i++) h->v[i] = f->v[i] + g->v[i];
}

/* h = f + 4p - g: subtrahend limbs up to 2^53 stay positive.  Callers
 * fe_carry the result before it feeds a multiplication (mul/sq need
 * limbs < 2^53; an uncarried sub output can reach ~2^53.6). */
static void fe_sub(fe *h, const fe *f, const fe *g) {
    h->v[0] = f->v[0] + 0x1FFFFFFFFFFFB4ULL - g->v[0];
    h->v[1] = f->v[1] + 0x1FFFFFFFFFFFFCULL - g->v[1];
    h->v[2] = f->v[2] + 0x1FFFFFFFFFFFFCULL - g->v[2];
    h->v[3] = f->v[3] + 0x1FFFFFFFFFFFFCULL - g->v[3];
    h->v[4] = f->v[4] + 0x1FFFFFFFFFFFFCULL - g->v[4];
}

static void fe_carry(fe *h) {
    u64 c;
    c = h->v[0] >> 51; h->v[0] &= MASK51; h->v[1] += c;
    c = h->v[1] >> 51; h->v[1] &= MASK51; h->v[2] += c;
    c = h->v[2] >> 51; h->v[2] &= MASK51; h->v[3] += c;
    c = h->v[3] >> 51; h->v[3] &= MASK51; h->v[4] += c;
    c = h->v[4] >> 51; h->v[4] &= MASK51; h->v[0] += c * 19;
    c = h->v[0] >> 51; h->v[0] &= MASK51; h->v[1] += c;
}

static void fe_mul(fe *h, const fe *f, const fe *g) {
    u64 f0 = f->v[0], f1 = f->v[1], f2 = f->v[2], f3 = f->v[3], f4 = f->v[4];
    u64 g0 = g->v[0], g1 = g->v[1], g2 = g->v[2], g3 = g->v[3], g4 = g->v[4];
    u64 g1_19 = 19 * g1, g2_19 = 19 * g2, g3_19 = 19 * g3, g4_19 = 19 * g4;

    u128 t0 = (u128)f0 * g0 + (u128)f1 * g4_19 + (u128)f2 * g3_19 +
              (u128)f3 * g2_19 + (u128)f4 * g1_19;
    u128 t1 = (u128)f0 * g1 + (u128)f1 * g0 + (u128)f2 * g4_19 +
              (u128)f3 * g3_19 + (u128)f4 * g2_19;
    u128 t2 = (u128)f0 * g2 + (u128)f1 * g1 + (u128)f2 * g0 +
              (u128)f3 * g4_19 + (u128)f4 * g3_19;
    u128 t3 = (u128)f0 * g3 + (u128)f1 * g2 + (u128)f2 * g1 +
              (u128)f3 * g0 + (u128)f4 * g4_19;
    u128 t4 = (u128)f0 * g4 + (u128)f1 * g3 + (u128)f2 * g2 +
              (u128)f3 * g1 + (u128)f4 * g0;

    u64 r0, r1, r2, r3, r4, c;
    t1 += (u64)(t0 >> 51); r0 = (u64)t0 & MASK51;
    t2 += (u64)(t1 >> 51); r1 = (u64)t1 & MASK51;
    t3 += (u64)(t2 >> 51); r2 = (u64)t2 & MASK51;
    t4 += (u64)(t3 >> 51); r3 = (u64)t3 & MASK51;
    c = (u64)(t4 >> 51);   r4 = (u64)t4 & MASK51;
    r0 += c * 19;
    r1 += r0 >> 51; r0 &= MASK51;
    h->v[0] = r0; h->v[1] = r1; h->v[2] = r2; h->v[3] = r3; h->v[4] = r4;
}

static void fe_sq(fe *h, const fe *f) {
    u64 f0 = f->v[0], f1 = f->v[1], f2 = f->v[2], f3 = f->v[3], f4 = f->v[4];
    u64 f0_2 = 2 * f0, f1_2 = 2 * f1;
    u64 f3_19 = 19 * f3, f4_19 = 19 * f4;

    u128 t0 = (u128)f0 * f0 + (u128)f1_2 * f4_19 + (u128)(2 * f2) * f3_19;
    u128 t1 = (u128)f0_2 * f1 + (u128)f2 * f4_19 * 2 + (u128)f3 * f3_19;
    u128 t2 = (u128)f0_2 * f2 + (u128)f1 * f1 + (u128)(2 * f3) * f4_19;
    u128 t3 = (u128)f0_2 * f3 + (u128)f1_2 * f2 + (u128)f4 * f4_19;
    u128 t4 = (u128)f0_2 * f4 + (u128)f1_2 * f3 + (u128)f2 * f2;

    u64 r0, r1, r2, r3, r4, c;
    t1 += (u64)(t0 >> 51); r0 = (u64)t0 & MASK51;
    t2 += (u64)(t1 >> 51); r1 = (u64)t1 & MASK51;
    t3 += (u64)(t2 >> 51); r2 = (u64)t2 & MASK51;
    t4 += (u64)(t3 >> 51); r3 = (u64)t3 & MASK51;
    c = (u64)(t4 >> 51);   r4 = (u64)t4 & MASK51;
    r0 += c * 19;
    r1 += r0 >> 51; r0 &= MASK51;
    h->v[0] = r0; h->v[1] = r1; h->v[2] = r2; h->v[3] = r3; h->v[4] = r4;
}

/* ignores bit 255 (sign bit handled by the caller); value may be >= p
 * (ZIP-215 rule 1: non-canonical y is reduced, not rejected) */
static void fe_frombytes(fe *h, const u8 s[32]) {
    u64 w0, w1, w2, w3;
    memcpy(&w0, s, 8); memcpy(&w1, s + 8, 8);
    memcpy(&w2, s + 16, 8); memcpy(&w3, s + 24, 8);
    h->v[0] = w0 & MASK51;
    h->v[1] = ((w0 >> 51) | (w1 << 13)) & MASK51;
    h->v[2] = ((w1 >> 38) | (w2 << 26)) & MASK51;
    h->v[3] = ((w2 >> 25) | (w3 << 39)) & MASK51;
    h->v[4] = (w3 >> 12) & MASK51; /* drops bit 255 (the sign bit) */
}

/* canonical little-endian encoding (full reduction mod p, top bit clear) */
static void fe_tobytes(u8 s[32], const fe *f) {
    fe t = *f;
    fe_carry(&t);
    fe_carry(&t);
    /* limbs now < 2^51; conditionally subtract p */
    u64 q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    u64 c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;
    u64 w0 = t.v[0] | (t.v[1] << 51);
    u64 w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    u64 w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    u64 w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    memcpy(s, &w0, 8); memcpy(s + 8, &w1, 8);
    memcpy(s + 16, &w2, 8); memcpy(s + 24, &w3, 8);
}

static int fe_iszero(const fe *f) {
    u8 s[32];
    fe_tobytes(s, f);
    u8 acc = 0;
    for (int i = 0; i < 32; i++) acc |= s[i];
    return acc == 0;
}

static int fe_eq(const fe *f, const fe *g) {
    fe t;
    fe_sub(&t, f, g);
    return fe_iszero(&t);
}

static int fe_isodd(const fe *f) {
    u8 s[32];
    fe_tobytes(s, f);
    return s[0] & 1;
}

static void fe_neg(fe *h, const fe *f) {
    fe zero = {{0, 0, 0, 0, 0}};
    fe_sub(h, &zero, f);
    fe_carry(h);
}

/* f^(2^252 - 3)  ==  f^((p-5)/8): binary chain over 2^250-1 */
static void fe_pow2523(fe *out, const fe *z) {
    fe t0, t1, t2;
    int i;
    fe_sq(&t0, z);                                   /* 2 */
    fe_sq(&t1, &t0); fe_sq(&t1, &t1);                /* 8 */
    fe_mul(&t1, z, &t1);                             /* 9 */
    fe_mul(&t0, &t0, &t1);                           /* 11 */
    fe_sq(&t0, &t0);                                 /* 22 */
    fe_mul(&t0, &t1, &t0);                           /* 2^5-1 */
    fe_sq(&t1, &t0);
    for (i = 1; i < 5; i++) fe_sq(&t1, &t1);
    fe_mul(&t0, &t1, &t0);                           /* 2^10-1 */
    fe_sq(&t1, &t0);
    for (i = 1; i < 10; i++) fe_sq(&t1, &t1);
    fe_mul(&t1, &t1, &t0);                           /* 2^20-1 */
    fe_sq(&t2, &t1);
    for (i = 1; i < 20; i++) fe_sq(&t2, &t2);
    fe_mul(&t1, &t2, &t1);                           /* 2^40-1 */
    fe_sq(&t1, &t1);
    for (i = 1; i < 10; i++) fe_sq(&t1, &t1);
    fe_mul(&t0, &t1, &t0);                           /* 2^50-1 */
    fe_sq(&t1, &t0);
    for (i = 1; i < 50; i++) fe_sq(&t1, &t1);
    fe_mul(&t1, &t1, &t0);                           /* 2^100-1 */
    fe_sq(&t2, &t1);
    for (i = 1; i < 100; i++) fe_sq(&t2, &t2);
    fe_mul(&t1, &t2, &t1);                           /* 2^200-1 */
    fe_sq(&t1, &t1);
    for (i = 1; i < 50; i++) fe_sq(&t1, &t1);
    fe_mul(&t0, &t1, &t0);                           /* 2^250-1 */
    fe_sq(&t0, &t0); fe_sq(&t0, &t0);                /* 2^252-4 */
    fe_mul(out, &t0, z);                             /* 2^252-3 */
}

static const ge GE_ID = {{{0,0,0,0,0}}, {{1,0,0,0,0}}, {{1,0,0,0,0}}, {{0,0,0,0,0}}};

/* unified add-2008-hwcd-3 for a=-1: complete for all curve points
 * (including small-order), so bucket accumulation needs no special cases */
static void ge_add(ge *r, const ge *p, const ge *q) {
    fe A, B, C, D, E, F, G, H, t1, t2;
    fe_sub(&t1, &p->Y, &p->X);
    fe_sub(&t2, &q->Y, &q->X);
    fe_carry(&t1); fe_carry(&t2);
    fe_mul(&A, &t1, &t2);
    fe_add(&t1, &p->Y, &p->X);
    fe_add(&t2, &q->Y, &q->X);
    fe_mul(&B, &t1, &t2);
    fe_mul(&C, &p->T, &q->T);
    fe_mul(&C, &C, &FE_2D);
    fe_mul(&D, &p->Z, &q->Z);
    fe_add(&D, &D, &D);
    fe_sub(&E, &B, &A); fe_carry(&E);
    fe_sub(&F, &D, &C); fe_carry(&F);
    fe_add(&G, &D, &C);
    fe_add(&H, &B, &A);
    fe_mul(&r->X, &E, &F);
    fe_mul(&r->Y, &G, &H);
    fe_mul(&r->Z, &F, &G);
    fe_mul(&r->T, &E, &H);
}

/* dedicated doubling (dbl-2008-hwcd), 4M+4S */
static void ge_dbl(ge *r, const ge *p) {
    fe A, B, C, D, E, F, G, H, t;
    fe_sq(&A, &p->X);
    fe_sq(&B, &p->Y);
    fe_sq(&C, &p->Z);
    fe_add(&C, &C, &C);
    fe_neg(&D, &A);
    fe_add(&t, &p->X, &p->Y); fe_carry(&t);
    fe_sq(&t, &t);
    fe_sub(&t, &t, &A); fe_sub(&t, &t, &B); fe_carry(&t);
    E = t;
    fe_add(&G, &D, &B);
    fe_sub(&F, &G, &C); fe_carry(&F);
    fe_sub(&H, &D, &B); fe_carry(&H);
    fe_mul(&r->X, &E, &F);
    fe_mul(&r->Y, &G, &H);
    fe_mul(&r->Z, &F, &G);
    fe_mul(&r->T, &E, &H);
}

static void ge_neg(ge *r, const ge *p) {
    fe_neg(&r->X, &p->X);
    r->Y = p->Y;
    r->Z = p->Z;
    fe_neg(&r->T, &p->T);
}

/* ZIP-215 decompression: returns 1 on success */
static int ge_frombytes_zip215(ge *h, const u8 s[32]) {
    fe u, v, v3, vxx, check, x, y;
    int sign = s[31] >> 7;
    fe_frombytes(&y, s);
    fe_sq(&u, &y);
    fe_mul(&v, &u, &FE_D);
    fe_sub(&u, &u, &FE_ONE); fe_carry(&u);       /* u = y^2 - 1 */
    fe_add(&v, &v, &FE_ONE);                      /* v = d y^2 + 1 */

    fe_sq(&v3, &v);
    fe_mul(&v3, &v3, &v);                         /* v^3 */
    fe_sq(&x, &v3);
    fe_mul(&x, &x, &v);
    fe_mul(&x, &x, &u);                           /* u v^7 */
    fe_pow2523(&x, &x);                           /* (u v^7)^((p-5)/8) */
    fe_mul(&x, &x, &v3);
    fe_mul(&x, &x, &u);                           /* u v^3 (u v^7)^((p-5)/8) */

    fe_sq(&vxx, &x);
    fe_mul(&vxx, &vxx, &v);
    fe_sub(&check, &vxx, &u);
    if (!fe_iszero(&check)) {
        fe_add(&check, &vxx, &u);
        if (!fe_iszero(&check)) return 0;
        fe_mul(&x, &x, &FE_SQRTM1);
    }
    if (fe_iszero(&x)) {
        if (sign) return 0;                       /* x=0 with sign bit set */
    } else if (fe_isodd(&x) != sign) {
        fe_neg(&x, &x);
    }
    h->X = x;
    h->Y = y;
    h->Z = FE_ONE;
    fe_mul(&h->T, &x, &y);
    return 1;
}

static int ge_is_identity(const ge *p) {
    return fe_iszero(&p->X) && fe_eq(&p->Y, &p->Z);
}

/* ---- exported API (ctypes) ---- */

/* Decompress pubkeys and R components, negated, for the batch equation.
 * pubs: n*32, sigs: n*64 (R||s).  Aneg/Rneg: n ge slots (opaque to Python).
 * ok[i] = 1 if both decompressed (s-range is checked Python-side).
 * Returns the number of ok entries. */
long cmtpu_ed25519_precheck(long n, const u8 *pubs, const u8 *sigs,
                            ge *Aneg, ge *Rneg, u8 *ok) {
    long good = 0;
    for (long i = 0; i < n; i++) {
        ge A, R;
        if (ge_frombytes_zip215(&A, pubs + 32 * i) &&
            ge_frombytes_zip215(&R, sigs + 64 * i)) {
            ge_neg(&Aneg[i], &A);
            ge_neg(&Rneg[i], &R);
            ok[i] = 1;
            good++;
        } else {
            ok[i] = 0;
        }
    }
    return good;
}

static int pick_window(long npoints) {
    if (npoints < 32) return 4;
    if (npoints < 128) return 5;
    if (npoints < 512) return 7;
    if (npoints < 2048) return 9;
    if (npoints < 8192) return 10;
    if (npoints < 32768) return 11;
    return 12;
}

static int get_digit(const u8 *sc, int pos, int c) {
    int byte = pos >> 3, shift = pos & 7;
    uint32_t v = sc[byte];
    if (byte + 1 < 32) v |= (uint32_t)sc[byte + 1] << 8;
    if (byte + 2 < 32) v |= (uint32_t)sc[byte + 2] << 16;
    return (v >> shift) & ((1 << c) - 1);
}

static ge BUCKETS[1 << 12];

/* Check  [8]( [ssum]B + sum [z_i]Rneg_i + sum [zh_i]Aneg_i ) == identity
 * over the m-entry subset idx of the prechecked points.
 * ssum: 32 bytes; z,zh: n*32 bytes (indexed by idx).  Returns 1 if holds. */
int cmtpu_ed25519_check_subset(const ge *Aneg, const ge *Rneg,
                               const int64_t *idx, long m,
                               const u8 *ssum, const u8 *z, const u8 *zh) {
    long npoints = 2 * m + 1;
    int c = pick_window(npoints);
    int nbuckets = (1 << c) - 1;
    int nwin = (253 + c - 1) / c;
    ge acc = GE_ID, Bp;
    Bp.X = FE_BX; Bp.Y = FE_BY; Bp.Z = FE_ONE;
    fe_mul(&Bp.T, &FE_BX, &FE_BY);

    for (int w = nwin - 1; w >= 0; w--) {
        if (w != nwin - 1)
            for (int k = 0; k < c; k++) ge_dbl(&acc, &acc);
        int pos = w * c;
        for (int b = 0; b < nbuckets; b++) BUCKETS[b] = GE_ID;
        int d = get_digit(ssum, pos, c);
        int used = 0;
        if (d) {
            ge_add(&BUCKETS[d - 1], &BUCKETS[d - 1], &Bp);
            used = 1;
        }
        for (long j = 0; j < m; j++) {
            long i = idx[j];
            d = get_digit(z + 32 * i, pos, c);
            if (d) { ge_add(&BUCKETS[d - 1], &BUCKETS[d - 1], &Rneg[i]); used = 1; }
            d = get_digit(zh + 32 * i, pos, c);
            if (d) { ge_add(&BUCKETS[d - 1], &BUCKETS[d - 1], &Aneg[i]); used = 1; }
        }
        if (!used) continue;
        ge run = GE_ID, wsum = GE_ID;
        for (int b = nbuckets - 1; b >= 0; b--) {
            ge_add(&run, &run, &BUCKETS[b]);
            ge_add(&wsum, &wsum, &run);
        }
        ge_add(&acc, &acc, &wsum);
    }
    ge_dbl(&acc, &acc);
    ge_dbl(&acc, &acc);
    ge_dbl(&acc, &acc);
    return ge_is_identity(&acc);
}

long cmtpu_ge_size(void) { return (long)sizeof(ge); }

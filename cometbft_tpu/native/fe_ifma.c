/* 8-way field exponentiation over GF(2^255-19) with AVX-512 IFMA.
 *
 * ZIP-215 decompression needs one fixed exponentiation x^((p-5)/8) per
 * point (~254 squarings) — half the cost of the whole batch-verify on
 * hosts without a device.  The chain is identical for every point, so
 * eight decompressions run in lockstep on 512-bit lanes: radix-2^52
 * limbs, vpmadd52{lo,hi}uq accumulating the 104-bit partial products
 * (the instructions IFMA exists for).  Runtime-dispatched: the scalar
 * radix-51 path in ed25519_msm.c remains the fallback.
 *
 * Layout: fe8 = 5 vectors; vector k holds limb k of 8 independent field
 * elements.  Limbs < 2^52; products fold at 2^260 == 608 (mod p)
 * (2^260 = 2^5 * 2^255 and 2^255 == 19).
 */

#if defined(__x86_64__)

#include <immintrin.h>
#include <stdint.h>
#include <string.h>

typedef uint64_t u64;
typedef __uint128_t u128;

#define TGT __attribute__((target("avx512f,avx512dq,avx512vl,avx512ifma")))

typedef struct { __m512i l[5]; } fe8;

#define MASK52 ((1ULL << 52) - 1)

TGT static inline __m512i mul52lo(__m512i acc, __m512i a, __m512i b) {
    return _mm512_madd52lo_epu64(acc, a, b);
}
TGT static inline __m512i mul52hi(__m512i acc, __m512i a, __m512i b) {
    return _mm512_madd52hi_epu64(acc, a, b);
}

/* h = f * g (8 lanes).  Full 10-limb accumulation (every t[k] stays well
 * under 2^56, so 64-bit lanes never wrap), one carry chain to bring every
 * limb under 2^52, then the high half folds down with x608
 * (t[k] + 608*t[k+5] < 2^52 + 2^61.3), and two more carry rounds leave
 * all limbs strictly < 2^52 — the IFMA operand requirement (vpmadd52
 * reads only the low 52 bits of each operand). */
TGT static void fe8_mul(fe8 *h, const fe8 *f, const fe8 *g) {
    const __m512i mask = _mm512_set1_epi64(MASK52);
    const __m512i c608 = _mm512_set1_epi64(608);
    __m512i t[10], c;
    for (int i = 0; i < 10; i++) t[i] = _mm512_setzero_si512();

    for (int i = 0; i < 5; i++) {
        for (int j = 0; j < 5; j++) {
            int k = i + j;
            t[k] = mul52lo(t[k], f->l[i], g->l[j]);
            t[k + 1] = mul52hi(t[k + 1], f->l[i], g->l[j]);
        }
    }
    /* normalize the full product to limbs < 2^52 */
    for (int k = 0; k < 9; k++) {
        c = _mm512_srli_epi64(t[k], 52);
        t[k] = _mm512_and_si512(t[k], mask);
        t[k + 1] = _mm512_add_epi64(t[k + 1], c);
    }
    /* t[9] overflow has weight 2^520 = (2^260)^2 == 608^2 */
    c = _mm512_srli_epi64(t[9], 52);
    t[9] = _mm512_and_si512(t[9], mask);
    t[0] = _mm512_add_epi64(
        t[0], _mm512_mullo_epi64(c, _mm512_set1_epi64(608 * 608)));
    /* fold the high half: weight 2^(52(k+5)) = 2^(52k) * 2^260 == 608 */
    for (int k = 0; k < 5; k++)
        t[k] = _mm512_add_epi64(t[k], _mm512_mullo_epi64(t[k + 5], c608));
    /* three carry rounds (fold-first so limb 0 is masked after its fold;
     * the third absorbs the corner where a round-2 carry leaves a limb at
     * exactly 2^52) */
    for (int round = 0; round < 3; round++) {
        c = _mm512_srli_epi64(t[4], 52);
        t[4] = _mm512_and_si512(t[4], mask);
        t[0] = _mm512_add_epi64(t[0], _mm512_mullo_epi64(c, c608));
        for (int k = 0; k < 4; k++) {
            c = _mm512_srli_epi64(t[k], 52);
            t[k] = _mm512_and_si512(t[k], mask);
            t[k + 1] = _mm512_add_epi64(t[k + 1], c);
        }
    }
    for (int k = 0; k < 5; k++) h->l[k] = t[k];
}

TGT static void fe8_sq(fe8 *h, const fe8 *f) { fe8_mul(h, f, f); }

/* out = z^(2^252 - 3), the (p-5)/8 exponent chain (matches fe_pow2523) */
TGT static void fe8_pow2523(fe8 *out, const fe8 *z) {
    fe8 t0, t1, t2;
    int i;
    fe8_sq(&t0, z);
    fe8_sq(&t1, &t0); fe8_sq(&t1, &t1);
    fe8_mul(&t1, z, &t1);
    fe8_mul(&t0, &t0, &t1);
    fe8_sq(&t0, &t0);
    fe8_mul(&t0, &t1, &t0);
    fe8_sq(&t1, &t0);
    for (i = 1; i < 5; i++) fe8_sq(&t1, &t1);
    fe8_mul(&t0, &t1, &t0);
    fe8_sq(&t1, &t0);
    for (i = 1; i < 10; i++) fe8_sq(&t1, &t1);
    fe8_mul(&t1, &t1, &t0);
    fe8_sq(&t2, &t1);
    for (i = 1; i < 20; i++) fe8_sq(&t2, &t2);
    fe8_mul(&t1, &t2, &t1);
    fe8_sq(&t1, &t1);
    for (i = 1; i < 10; i++) fe8_sq(&t1, &t1);
    fe8_mul(&t0, &t1, &t0);
    fe8_sq(&t1, &t0);
    for (i = 1; i < 50; i++) fe8_sq(&t1, &t1);
    fe8_mul(&t1, &t1, &t0);
    fe8_sq(&t2, &t1);
    for (i = 1; i < 100; i++) fe8_sq(&t2, &t2);
    fe8_mul(&t1, &t2, &t1);
    fe8_sq(&t1, &t1);
    for (i = 1; i < 50; i++) fe8_sq(&t1, &t1);
    fe8_mul(&t0, &t1, &t0);
    fe8_sq(&t0, &t0); fe8_sq(&t0, &t0);
    fe8_mul(out, &t0, z);
}

/* Batched u^((p-5)/8): in/out as 8 field elements in radix-52 limb-major
 * layout (limb k of lane j at in[5*j + k]), values fully reduced. */
TGT static void fe8_load(fe8 *h, const u64 *in) {
    u64 tmp[8];
    for (int k = 0; k < 5; k++) {
        for (int j = 0; j < 8; j++) tmp[j] = in[5 * j + k];
        h->l[k] = _mm512_loadu_si512((const void *)tmp);
    }
}

TGT static void fe8_store(u64 *out, const fe8 *h) {
    u64 tmp[8];
    for (int k = 0; k < 5; k++) {
        _mm512_storeu_si512((void *)tmp, h->l[k]);
        for (int j = 0; j < 8; j++) out[5 * j + k] = tmp[j];
    }
}

TGT void cmtpu_fe8_pow2523(const u64 *in, u64 *out) {
    fe8 z, r;
    fe8_load(&z, in);
    fe8_pow2523(&r, &z);
    fe8_store(out, &r);
}

int cmtpu_have_ifma(void) {
    return __builtin_cpu_supports("avx512ifma") &&
           __builtin_cpu_supports("avx512dq") &&
           __builtin_cpu_supports("avx512vl");
}

#else
typedef unsigned long long u64x;
void cmtpu_fe8_pow2523(const void *in, void *out) { (void)in; (void)out; }
int cmtpu_have_ifma(void) { return 0; }
#endif

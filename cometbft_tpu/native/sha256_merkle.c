/* Native RFC-6962 Merkle tree (reference: crypto/merkle/tree.go:11-27).
 *
 * The Python host tier pays ~1.5us of interpreter/hashlib dispatch per node
 * on top of the ~0.3us of actual compression work; at 64k leaves (131k
 * hashes) that overhead IS the cost.  This file keeps the whole
 * level-synchronous tree loop in C: leaf = SHA256(0x00 || data),
 * inner = SHA256(0x01 || left || right), odd node promoted — identical to
 * the split-point recursion (tree.go:68-98 proves the equivalence).
 */

#include <stdint.h>
#include <string.h>
#include <stddef.h>

#if defined(__x86_64__)
#include <immintrin.h>
#define CMTPU_X86 1
#endif

typedef uint8_t u8;
typedef uint32_t u32;
typedef uint64_t u64;

static const u32 K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

#define ROR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha256_block_soft(u32 st[8], const u8 *p) {
    u32 w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((u32)p[4 * i] << 24) | ((u32)p[4 * i + 1] << 16) |
               ((u32)p[4 * i + 2] << 8) | (u32)p[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        u32 s0 = ROR(w[i - 15], 7) ^ ROR(w[i - 15], 18) ^ (w[i - 15] >> 3);
        u32 s1 = ROR(w[i - 2], 17) ^ ROR(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    u32 a = st[0], b = st[1], c = st[2], d = st[3];
    u32 e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 64; i++) {
        u32 S1 = ROR(e, 6) ^ ROR(e, 11) ^ ROR(e, 25);
        u32 ch = (e & f) ^ (~e & g);
        u32 t1 = h + S1 + ch + K[i] + w[i];
        u32 S0 = ROR(a, 2) ^ ROR(a, 13) ^ ROR(a, 22);
        u32 mj = (a & b) ^ (a & c) ^ (b & c);
        u32 t2 = S0 + mj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

#ifdef CMTPU_X86
/* SHA-NI one-block compression (state in the ABEF/CDGH arrangement the
 * sha256rnds2 instruction wants).  ~5-10x the portable rounds on cores
 * with the extension; runtime-dispatched below. */
__attribute__((target("sha,sse4.1")))
static void sha256_block_ni(u32 st[8], const u8 *p) {
    const __m128i SHUF = _mm_set_epi64x(0x0c0d0e0f08090a0bULL,
                                        0x0405060700010203ULL);
    __m128i T = _mm_loadu_si128((const __m128i *)&st[0]);   /* DCBA */
    __m128i S1 = _mm_loadu_si128((const __m128i *)&st[4]);  /* HGFE */
    T = _mm_shuffle_epi32(T, 0xB1);                         /* CDAB */
    S1 = _mm_shuffle_epi32(S1, 0x1B);                       /* EFGH */
    __m128i S0 = _mm_alignr_epi8(T, S1, 8);                 /* ABEF */
    S1 = _mm_blend_epi16(S1, T, 0xF0);                      /* CDGH */
    const __m128i ABEF = S0, CDGH = S1;

    __m128i M0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(p + 0)), SHUF);
    __m128i M1 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(p + 16)), SHUF);
    __m128i M2 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(p + 32)), SHUF);
    __m128i M3 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(p + 48)), SHUF);
    __m128i MSG, TMP;

#define RND4(M, k)                                                      \
    MSG = _mm_add_epi32(M, _mm_loadu_si128((const __m128i *)&K[k]));    \
    S1 = _mm_sha256rnds2_epu32(S1, S0, MSG);                            \
    MSG = _mm_shuffle_epi32(MSG, 0x0E);                                 \
    S0 = _mm_sha256rnds2_epu32(S0, S1, MSG)
/* After processing group i (message reg Mcur, predecessor Mprev):
 * complete W for group i+1 (Mnext = msg2(Mnext + alignr(Mcur,Mprev), Mcur))
 * and start group i+3's schedule (Mprev = msg1(Mprev, Mcur)). */
#define SCHED(Mnext, Mprev, Mcur)                                       \
    TMP = _mm_alignr_epi8(Mcur, Mprev, 4);                              \
    Mnext = _mm_add_epi32(Mnext, TMP);                                  \
    Mnext = _mm_sha256msg2_epu32(Mnext, Mcur);                          \
    Mprev = _mm_sha256msg1_epu32(Mprev, Mcur)

    RND4(M0, 0);
    RND4(M1, 4);  M0 = _mm_sha256msg1_epu32(M0, M1);
    RND4(M2, 8);  M1 = _mm_sha256msg1_epu32(M1, M2);
    RND4(M3, 12); SCHED(M0, M2, M3);
    RND4(M0, 16); SCHED(M1, M3, M0);
    RND4(M1, 20); SCHED(M2, M0, M1);
    RND4(M2, 24); SCHED(M3, M1, M2);
    RND4(M3, 28); SCHED(M0, M2, M3);
    RND4(M0, 32); SCHED(M1, M3, M0);
    RND4(M1, 36); SCHED(M2, M0, M1);
    RND4(M2, 40); SCHED(M3, M1, M2);
    RND4(M3, 44); SCHED(M0, M2, M3);
    RND4(M0, 48); SCHED(M1, M3, M0);
    RND4(M1, 52); SCHED(M2, M0, M1);
    RND4(M2, 56); SCHED(M3, M1, M2);
    RND4(M3, 60);
#undef RND4
#undef SCHED

    S0 = _mm_add_epi32(S0, ABEF);
    S1 = _mm_add_epi32(S1, CDGH);
    T = _mm_shuffle_epi32(S0, 0x1B);                        /* FEBA */
    S1 = _mm_shuffle_epi32(S1, 0xB1);                       /* DCHG */
    S0 = _mm_blend_epi16(T, S1, 0xF0);                      /* DCBA */
    S1 = _mm_alignr_epi8(S1, T, 8);                         /* HGFE */
    _mm_storeu_si128((__m128i *)&st[0], S0);
    _mm_storeu_si128((__m128i *)&st[4], S1);
}
#endif

static int g_has_sha_ni = -1;

#ifdef CMTPU_X86
#include <cpuid.h>
/* CPUID leaf 7 EBX bit 29 = SHA extensions.  Probed directly because
 * __builtin_cpu_supports("sha") only exists from gcc 11. */
static int detect_sha_ni(void) {
    unsigned int a, b, c, d;
    if (!__get_cpuid_count(7, 0, &a, &b, &c, &d)) return 0;
    return (b >> 29) & 1;
}
#endif

static void sha256_block(u32 st[8], const u8 *p) {
#ifdef CMTPU_X86
    if (g_has_sha_ni < 0) g_has_sha_ni = detect_sha_ni();
    if (g_has_sha_ni) { sha256_block_ni(st, p); return; }
#endif
    sha256_block_soft(st, p);
}

static void sha256(const u8 *msg, u64 len, u8 out[32]) {
    u32 st[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                 0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    u64 i = 0;
    for (; i + 64 <= len; i += 64) sha256_block(st, msg + i);
    u8 tail[128];
    u64 rem = len - i;
    memcpy(tail, msg + i, rem);
    tail[rem] = 0x80;
    u64 padlen = (rem + 9 <= 64) ? 64 : 128;
    memset(tail + rem + 1, 0, padlen - rem - 9);
    u64 bits = len * 8;
    for (int j = 0; j < 8; j++) tail[padlen - 1 - j] = (u8)(bits >> (8 * j));
    sha256_block(st, tail);
    if (padlen == 128) sha256_block(st, tail + 64);
    for (int j = 0; j < 8; j++) {
        out[4 * j] = (u8)(st[j] >> 24);
        out[4 * j + 1] = (u8)(st[j] >> 16);
        out[4 * j + 2] = (u8)(st[j] >> 8);
        out[4 * j + 3] = (u8)st[j];
    }
}

/* leaves: concatenated leaf bytes; offs[n+1] byte offsets into buf.
 * scratch: caller-provided n*32 bytes.  out: 32 bytes.  n >= 1. */
void cmtpu_merkle_root(long n, const u8 *buf, const u64 *offs, u8 *scratch,
                       u8 *out) {
    u8 tmp[1 + 64];
    for (long i = 0; i < n; i++) {
        u64 len = offs[i + 1] - offs[i];
        if (len <= 64) {
            tmp[0] = 0x00;
            memcpy(tmp + 1, buf + offs[i], len);
            sha256(tmp, len + 1, scratch + 32 * i);
        } else {
            /* rare: leaf > 64 bytes; hash prefix+data without copying by
             * streaming two segments */
            u8 big[1 + 1024];
            if (len <= 1024) {
                big[0] = 0x00;
                memcpy(big + 1, buf + offs[i], len);
                sha256(big, len + 1, scratch + 32 * i);
            } else {
                /* arbitrarily long leaf: one-shot heap-free streaming */
                u32 st[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                             0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
                u8 blk[64];
                blk[0] = 0x00;
                u64 total = len + 1;
                u64 filled = 1, pos = 0;
                while (pos < len) {
                    u64 take = 64 - filled;
                    if (take > len - pos) take = len - pos;
                    memcpy(blk + filled, buf + offs[i] + pos, take);
                    filled += take; pos += take;
                    if (filled == 64) { sha256_block(st, blk); filled = 0; }
                }
                u8 tail2[128];
                memcpy(tail2, blk, filled);
                tail2[filled] = 0x80;
                u64 padlen = (filled + 9 <= 64) ? 64 : 128;
                memset(tail2 + filled + 1, 0, padlen - filled - 9);
                u64 bits = total * 8;
                for (int j = 0; j < 8; j++)
                    tail2[padlen - 1 - j] = (u8)(bits >> (8 * j));
                sha256_block(st, tail2);
                if (padlen == 128) sha256_block(st, tail2 + 64);
                for (int j = 0; j < 8; j++) {
                    scratch[32 * i + 4 * j] = (u8)(st[j] >> 24);
                    scratch[32 * i + 4 * j + 1] = (u8)(st[j] >> 16);
                    scratch[32 * i + 4 * j + 2] = (u8)(st[j] >> 8);
                    scratch[32 * i + 4 * j + 3] = (u8)st[j];
                }
            }
        }
    }
    u8 inner[65];
    inner[0] = 0x01;
    long lvl = n;
    while (lvl > 1) {
        long nxt = 0;
        for (long i = 0; i + 1 < lvl; i += 2) {
            memcpy(inner + 1, scratch + 32 * i, 32);
            memcpy(inner + 33, scratch + 32 * (i + 1), 32);
            sha256(inner, 65, scratch + 32 * nxt);
            nxt++;
        }
        if (lvl & 1) {
            memmove(scratch + 32 * nxt, scratch + 32 * (lvl - 1), 32);
            nxt++;
        }
        lvl = nxt;
    }
    memcpy(out, scratch, 32);
}

/* Plain batch SHA-256 over n variable-length messages (offs[n+1]). */
void cmtpu_sha256_batch(long n, const u8 *buf, const u64 *offs, u8 *out) {
    for (long i = 0; i < n; i++)
        sha256(buf + offs[i], offs[i + 1] - offs[i], out + 32 * i);
}

/* ---- SHA-512 (batch challenge hashing for the ed25519 batch path) ---- */

static const u64 K512[80] = {
    0x428A2F98D728AE22ULL, 0x7137449123EF65CDULL, 0xB5C0FBCFEC4D3B2FULL,
    0xE9B5DBA58189DBBCULL, 0x3956C25BF348B538ULL, 0x59F111F1B605D019ULL,
    0x923F82A4AF194F9BULL, 0xAB1C5ED5DA6D8118ULL, 0xD807AA98A3030242ULL,
    0x12835B0145706FBEULL, 0x243185BE4EE4B28CULL, 0x550C7DC3D5FFB4E2ULL,
    0x72BE5D74F27B896FULL, 0x80DEB1FE3B1696B1ULL, 0x9BDC06A725C71235ULL,
    0xC19BF174CF692694ULL, 0xE49B69C19EF14AD2ULL, 0xEFBE4786384F25E3ULL,
    0x0FC19DC68B8CD5B5ULL, 0x240CA1CC77AC9C65ULL, 0x2DE92C6F592B0275ULL,
    0x4A7484AA6EA6E483ULL, 0x5CB0A9DCBD41FBD4ULL, 0x76F988DA831153B5ULL,
    0x983E5152EE66DFABULL, 0xA831C66D2DB43210ULL, 0xB00327C898FB213FULL,
    0xBF597FC7BEEF0EE4ULL, 0xC6E00BF33DA88FC2ULL, 0xD5A79147930AA725ULL,
    0x06CA6351E003826FULL, 0x142929670A0E6E70ULL, 0x27B70A8546D22FFCULL,
    0x2E1B21385C26C926ULL, 0x4D2C6DFC5AC42AEDULL, 0x53380D139D95B3DFULL,
    0x650A73548BAF63DEULL, 0x766A0ABB3C77B2A8ULL, 0x81C2C92E47EDAEE6ULL,
    0x92722C851482353BULL, 0xA2BFE8A14CF10364ULL, 0xA81A664BBC423001ULL,
    0xC24B8B70D0F89791ULL, 0xC76C51A30654BE30ULL, 0xD192E819D6EF5218ULL,
    0xD69906245565A910ULL, 0xF40E35855771202AULL, 0x106AA07032BBD1B8ULL,
    0x19A4C116B8D2D0C8ULL, 0x1E376C085141AB53ULL, 0x2748774CDF8EEB99ULL,
    0x34B0BCB5E19B48A8ULL, 0x391C0CB3C5C95A63ULL, 0x4ED8AA4AE3418ACBULL,
    0x5B9CCA4F7763E373ULL, 0x682E6FF3D6B2B8A3ULL, 0x748F82EE5DEFB2FCULL,
    0x78A5636F43172F60ULL, 0x84C87814A1F0AB72ULL, 0x8CC702081A6439ECULL,
    0x90BEFFFA23631E28ULL, 0xA4506CEBDE82BDE9ULL, 0xBEF9A3F7B2C67915ULL,
    0xC67178F2E372532BULL, 0xCA273ECEEA26619CULL, 0xD186B8C721C0C207ULL,
    0xEADA7DD6CDE0EB1EULL, 0xF57D4F7FEE6ED178ULL, 0x06F067AA72176FBAULL,
    0x0A637DC5A2C898A6ULL, 0x113F9804BEF90DAEULL, 0x1B710B35131C471BULL,
    0x28DB77F523047D84ULL, 0x32CAAB7B40C72493ULL, 0x3C9EBE0A15C9BEBCULL,
    0x431D67C49C100D4CULL, 0x4CC5D4BECB3E42B6ULL, 0x597F299CFC657E2AULL,
    0x5FCB6FAB3AD6FAECULL, 0x6C44198C4A475817ULL,
};
static const u64 H512[8] = {
    0x6A09E667F3BCC908ULL, 0xBB67AE8584CAA73BULL, 0x3C6EF372FE94F82BULL,
    0xA54FF53A5F1D36F1ULL, 0x510E527FADE682D1ULL, 0x9B05688C2B3E6C1FULL,
    0x1F83D9ABFB41BD6BULL, 0x5BE0CD19137E2179ULL};

#define ROR64(x, n) (((x) >> (n)) | ((x) << (64 - (n))))

static void sha512_block(u64 st[8], const u8 *p) {
    u64 w[80];
    for (int i = 0; i < 16; i++) {
        u64 v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | p[8 * i + j];
        w[i] = v;
    }
    for (int i = 16; i < 80; i++) {
        u64 s0 = ROR64(w[i - 15], 1) ^ ROR64(w[i - 15], 8) ^ (w[i - 15] >> 7);
        u64 s1 = ROR64(w[i - 2], 19) ^ ROR64(w[i - 2], 61) ^ (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    u64 a = st[0], b = st[1], c = st[2], d = st[3];
    u64 e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 80; i++) {
        u64 S1 = ROR64(e, 14) ^ ROR64(e, 18) ^ ROR64(e, 41);
        u64 ch = (e & f) ^ (~e & g);
        u64 t1 = h + S1 + ch + K512[i] + w[i];
        u64 S0 = ROR64(a, 28) ^ ROR64(a, 34) ^ ROR64(a, 39);
        u64 mj = (a & b) ^ (a & c) ^ (b & c);
        u64 t2 = S0 + mj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

static void sha512(const u8 *msg, u64 len, u8 out[64]) {
    u64 st[8];
    memcpy(st, H512, sizeof st);
    u64 i = 0;
    for (; i + 128 <= len; i += 128) sha512_block(st, msg + i);
    u8 tail[256];
    u64 rem = len - i;
    memcpy(tail, msg + i, rem);
    tail[rem] = 0x80;
    u64 padlen = (rem + 17 <= 128) ? 128 : 256;
    memset(tail + rem + 1, 0, padlen - rem - 17);
    memset(tail + padlen - 16, 0, 8); /* high 64 bits of the 128-bit length */
    u64 bits = len * 8;
    for (int j = 0; j < 8; j++) tail[padlen - 1 - j] = (u8)(bits >> (8 * j));
    sha512_block(st, tail);
    if (padlen == 256) sha512_block(st, tail + 128);
    for (int j = 0; j < 8; j++)
        for (int k = 0; k < 8; k++)
            out[8 * j + k] = (u8)(st[j] >> (56 - 8 * k));
}

/* Batch SHA-512 over n variable-length messages (offs[n+1]); out n*64. */
void cmtpu_sha512_batch(long n, const u8 *buf, const u64 *offs, u8 *out) {
    for (long i = 0; i < n; i++)
        sha512(buf + offs[i], offs[i + 1] - offs[i], out + 64 * i);
}

/* Inclusion-proof support (crypto/merkle/proof.go:35-49): build every tree
 * level into `levels` (leaf level first; each level of size s followed by
 * one of size (s+1)/2, odd node copied up), then gather each leaf's aunts
 * bottom-up.  aunts: stride 32*max_depth bytes per leaf; counts[i] = number
 * of aunts for leaf i (a promoted odd node contributes none at that level).
 * Caller sizes `levels` to 32 * (sum of all level sizes). */
void cmtpu_merkle_levels(long n, const u8 *buf, const u64 *offs, u8 *levels) {
    u8 tmp[1 + 64];
    u8 *cur = levels;
    for (long i = 0; i < n; i++) {
        u64 len = offs[i + 1] - offs[i];
        if (len <= 64) {
            tmp[0] = 0x00;
            memcpy(tmp + 1, buf + offs[i], len);
            sha256(tmp, len + 1, cur + 32 * i);
        } else {
            u8 big[1 + 4096];
            if (len <= 4096) {
                big[0] = 0x00;
                memcpy(big + 1, buf + offs[i], len);
                sha256(big, len + 1, cur + 32 * i);
            } else {
                /* fall back: leaf-hash via the scratch streaming path in
                 * cmtpu_merkle_root's shape; leaves this large do not occur
                 * in block data (txs are size-bounded), keep it simple */
                u64 one_off[2] = {0, len};
                u8 unused_scratch[32];
                (void)unused_scratch;
                cmtpu_merkle_root(1, buf + offs[i], one_off, cur + 32 * i,
                                  cur + 32 * i);
            }
        }
    }
    long size = n;
    u8 inner[65];
    inner[0] = 0x01;
    while (size > 1) {
        u8 *nxt = cur + 32 * size;
        long out_i = 0;
        for (long i = 0; i + 1 < size; i += 2) {
            memcpy(inner + 1, cur + 32 * i, 32);
            memcpy(inner + 33, cur + 32 * (i + 1), 32);
            sha256(inner, 65, nxt + 32 * out_i);
            out_i++;
        }
        if (size & 1) {
            memcpy(nxt + 32 * out_i, cur + 32 * (size - 1), 32);
            out_i++;
        }
        cur = nxt;
        size = out_i;
    }
}

void cmtpu_merkle_aunts(long n, const u8 *levels, long max_depth, u8 *aunts,
                        int32_t *counts) {
    /* level start offsets (in nodes) */
    long starts[64], sizes[64], nlevels = 0;
    long size = n, acc = 0;
    while (1) {
        starts[nlevels] = acc;
        sizes[nlevels] = size;
        acc += size;
        nlevels++;
        if (size == 1) break;
        size = (size + 1) / 2;
    }
    for (long i = 0; i < n; i++) {
        long idx = i, cnt = 0;
        u8 *dst = aunts + (u64)i * 32 * max_depth;
        for (long l = 0; l + 1 < nlevels; l++) {
            long sib = idx ^ 1;
            if (sib < sizes[l]) {
                memcpy(dst + 32 * cnt,
                       levels + 32 * (starts[l] + sib), 32);
                cnt++;
            }
            idx >>= 1;
        }
        counts[i] = (int32_t)cnt;
    }
}

/* Device-path leaf packing: SHA-256-pad n messages straight into the
 * lane-major big-endian word layout [bmax, 16, n] the TPU Merkle kernel
 * consumes (ops/sha256_kernel.pack_messages).  The numpy path pays an
 * 8 MB strided transpose at 64k leaves; here padding and transpose fuse
 * in one pass, tiled so the per-tile scratch stays cache-resident and
 * every out write is a contiguous run of lanes. */
#include <stdlib.h>

void cmtpu_sha256_pack(long n, const u8 *flat, const u64 *offs, long bmax,
                       u32 *out, int32_t *nblocks) {
    enum { T = 64 };
    long tile = n < T ? n : T;
    u8 *scratch = (u8 *)malloc((size_t)tile * (size_t)bmax * 64);
    if (!scratch) { /* caller pre-zeroed nothing; signal via nblocks */
        for (long i = 0; i < n; i++) nblocks[i] = -1;
        return;
    }
    const long row_sz = bmax * 64;
    for (long base = 0; base < n; base += T) {
        long t = n - base < T ? n - base : T;
        memset(scratch, 0, (size_t)t * row_sz);
        for (long j = 0; j < t; j++) {
            long i = base + j;
            u64 len = offs[i + 1] - offs[i];
            long nb = (long)((len + 8) / 64 + 1);
            nblocks[i] = (int32_t)nb;
            u8 *row = scratch + j * row_sz;
            memcpy(row, flat + offs[i], len);
            row[len] = 0x80;
            u64 bits = len * 8;
            u8 *p = row + nb * 64 - 8;
            for (int k = 0; k < 8; k++)
                p[k] = (u8)(bits >> (8 * (7 - k)));
        }
        for (long bw = 0; bw < bmax * 16; bw++) {
            u32 *dst = out + bw * n + base;
            const u8 *src = scratch + bw * 4;
            for (long j = 0; j < t; j++) {
                const u8 *q = src + j * row_sz;
                dst[j] = ((u32)q[0] << 24) | ((u32)q[1] << 16) |
                         ((u32)q[2] << 8) | (u32)q[3];
            }
        }
    }
    free(scratch);
}

"""Header-vs-state validation (reference: state/validation.go).

validate_block is the call site that batch-verifies every applied block's
LastCommit through the TPU backend (state/validation.go:92
LastValidators.VerifyCommit).
"""

from __future__ import annotations

from cometbft_tpu.state.state import State, median_time
from cometbft_tpu.types.block import Block


def validate_block(state: State, block: Block) -> None:
    """state/validation.go:15-150."""
    block.validate_basic()
    # Header-vs-state checks.
    h = block.header
    if h.version != state.version_consensus:
        raise ValueError(
            f"wrong Block.Header.Version. Expected {state.version_consensus}, got {h.version}"
        )
    if h.chain_id != state.chain_id:
        raise ValueError(
            f"wrong Block.Header.ChainID. Expected {state.chain_id}, got {h.chain_id}"
        )
    if state.last_block_height == 0 and h.height != state.initial_height:
        raise ValueError(
            f"wrong Block.Header.Height. Expected {state.initial_height} (initial height), got {h.height}"
        )
    if state.last_block_height > 0 and h.height != state.last_block_height + 1:
        raise ValueError(
            f"wrong Block.Header.Height. Expected {state.last_block_height + 1}, got {h.height}"
        )
    if h.last_block_id != state.last_block_id:
        raise ValueError(
            f"wrong Block.Header.LastBlockID. Expected {state.last_block_id}, got {h.last_block_id}"
        )
    if h.app_hash != state.app_hash:
        raise ValueError(
            f"wrong Block.Header.AppHash. Expected {state.app_hash.hex().upper()}, got {h.app_hash.hex()}"
        )
    if h.consensus_hash != state.consensus_params.hash():
        raise ValueError("wrong Block.Header.ConsensusHash")
    if h.last_results_hash != state.last_results_hash:
        raise ValueError("wrong Block.Header.LastResultsHash")
    if h.validators_hash != state.validators.hash():
        raise ValueError("wrong Block.Header.ValidatorsHash")
    if h.next_validators_hash != state.next_validators.hash():
        raise ValueError("wrong Block.Header.NextValidatorsHash")

    # LastCommit — the TPU-batched hot path (state/validation.go:86-97).
    if h.height == state.initial_height:
        if block.last_commit and len(block.last_commit.signatures) != 0:
            raise ValueError("initial block can't have LastCommit signatures")
    else:
        state.last_validators.verify_commit(
            state.chain_id, state.last_block_id, h.height - 1, block.last_commit
        )

    if len(h.proposer_address) != 20:
        raise ValueError(
            f"expected ProposerAddress size 20, got {len(h.proposer_address)}"
        )
    if not state.validators.has_address(h.proposer_address):
        raise ValueError(
            f"block.Header.ProposerAddress {h.proposer_address.hex().upper()} is not a validator"
        )

    # Block time (state/validation.go:113-140).
    if h.height > state.initial_height:
        if not h.time.after(state.last_block_time):
            raise ValueError(
                f"block time {h.time} not greater than last block time {state.last_block_time}"
            )
        expected = median_time(block.last_commit, state.last_validators)
        if h.time != expected:
            raise ValueError(f"invalid block time. Expected {expected}, got {h.time}")
    elif h.height == state.initial_height:
        if h.time != state.last_block_time:
            raise ValueError(
                f"block time {h.time} is not equal to genesis time {state.last_block_time}"
            )
    else:
        raise ValueError(
            f"block height {h.height} lower than initial height {state.initial_height}"
        )

    # Evidence size cap.
    ev_bytes = sum(len(ev.bytes()) for ev in block.evidence)
    if ev_bytes > state.consensus_params.evidence.max_bytes:
        raise ValueError(
            f"total evidence in block = {ev_bytes}B, max = {state.consensus_params.evidence.max_bytes}B"
        )

"""State execution layer (reference: state/)."""

from cometbft_tpu.state.state import State, make_genesis_state, median_time
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.state.execution import BlockExecutor

__all__ = ["State", "StateStore", "BlockExecutor", "make_genesis_state", "median_time"]

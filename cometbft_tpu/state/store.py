"""State persistence: state, ABCI responses, validator sets, consensus params
per height (reference: state/store.go).

Validator sets are loadable per height (needed by the evidence pool and the
light client), with the reference's sparse storage: full sets are written
only when they change; other heights store a pointer to the last-changed
height (state/store.go saveValidatorsInfo).
"""

from __future__ import annotations

import json

from cometbft_tpu.libs.db import DB
from cometbft_tpu.state.state import State
from cometbft_tpu.types.block import BlockID, Consensus
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.params import ConsensusParams
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.wire import proto as wire

_STATE_KEY = b"stateKey"
_PRUNED_TO_KEY = b"stateStorePrunedToKey"


def _validators_key(height: int) -> bytes:
    return b"validatorsKey:%d" % height


def _params_key(height: int) -> bytes:
    return b"consensusParamsKey:%d" % height


def _abci_responses_key(height: int) -> bytes:
    return b"abciResponsesKey:%d" % height


class StateStore:
    """state/store.go dbStore."""

    def __init__(self, db: DB, discard_abci_responses: bool = False):
        self._db = db
        # storage.discard_abci_responses: keep ONLY the latest height's
        # responses (still needed by the handshake's ran-Commit-but-didn't-
        # save-state replay) — /block_results for older heights is gone
        # (state/store.go Options.DiscardABCIResponses).
        self.discard_abci_responses = discard_abci_responses
        # Pruned floor: checkpoints below this height are gone; new pointer
        # records must target the migrated checkpoint AT this height, or a
        # save after pruning would write a dangling reference.
        raw = self._db.get(_PRUNED_TO_KEY)
        self._pruned_to = int(raw) if raw else 0

    # -- state ---------------------------------------------------------------

    def save(self, state: State) -> None:
        """state/store.go Save: state + next-validators + params."""
        next_height = state.last_block_height + 1
        if next_height == 1:
            next_height = state.initial_height
            # genesis: save base validator records
            self._save_validators_info(next_height, next_height, state.validators)
        self._save_validators_info(
            next_height + 1, state.last_height_validators_changed, state.next_validators
        )
        self._save_params_info(
            next_height, state.last_height_consensus_params_changed, state.consensus_params
        )
        self._db.set(_STATE_KEY, _encode_state(state))

    def load(self) -> State | None:
        raw = self._db.get(_STATE_KEY)
        if raw is None:
            return None
        return _decode_state(raw)

    def bootstrap(self, state: State) -> None:
        """state/store.go Bootstrap (statesync entry)."""
        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height
        if height > 1 and state.last_validators and not state.last_validators.is_nil_or_empty():
            self._save_validators_info(height - 1, height - 1, state.last_validators)
        self._save_validators_info(height, height, state.validators)
        self._save_validators_info(height + 1, height + 1, state.next_validators)
        self._save_params_info(
            height, state.last_height_consensus_params_changed, state.consensus_params
        )
        self._db.set(_STATE_KEY, _encode_state(state))

    # -- validators per height ----------------------------------------------

    def _save_validators_info(
        self, height: int, last_height_changed: int, vals: ValidatorSet
    ) -> None:
        if last_height_changed > height:
            raise ValueError("lastHeightChanged cannot be greater than valInfo height")
        if height == last_height_changed:
            payload = {"h": height, "set": vals.encode().hex()}
        else:
            # Never point below the pruned floor (the checkpoint there was
            # migrated to the floor height by prune_states).
            payload = {"h": max(last_height_changed, self._pruned_to)}
        self._db.set(_validators_key(height), json.dumps(payload).encode())

    def load_validators(self, height: int) -> ValidatorSet:
        """state/store.go LoadValidators with pointer-chasing + the reference's
        IncrementProposerPriority restoration (priority is recomputed from the
        stored checkpoint by offsetting rounds)."""
        raw = self._db.get(_validators_key(height))
        if raw is None:
            raise NoValidatorsError(height)
        info = json.loads(raw)
        if "set" in info:
            return ValidatorSet.decode(bytes.fromhex(info["set"]))
        last_changed = info["h"]
        raw2 = self._db.get(_validators_key(last_changed))
        if raw2 is None:
            raise NoValidatorsError(height)
        info2 = json.loads(raw2)
        if "set" not in info2:
            raise NoValidatorsError(height)
        vals = ValidatorSet.decode(bytes.fromhex(info2["set"]))
        vals.increment_proposer_priority(height - last_changed)
        return vals

    # -- consensus params per height ------------------------------------------

    def _save_params_info(
        self, height: int, last_height_changed: int, params: ConsensusParams
    ) -> None:
        if height == last_height_changed:
            payload = {"h": height, "params": params.encode().hex()}
        else:
            payload = {"h": max(last_height_changed, self._pruned_to)}
        self._db.set(_params_key(height), json.dumps(payload).encode())

    def load_consensus_params(self, height: int) -> ConsensusParams:
        raw = self._db.get(_params_key(height))
        if raw is None:
            raise NoParamsError(height)
        info = json.loads(raw)
        if "params" in info:
            return ConsensusParams.decode(bytes.fromhex(info["params"]))
        raw2 = self._db.get(_params_key(info["h"]))
        if raw2 is None:
            raise NoParamsError(height)
        info2 = json.loads(raw2)
        return ConsensusParams.decode(bytes.fromhex(info2["params"]))

    # -- ABCI responses -------------------------------------------------------

    def save_abci_responses(self, height: int, responses: dict) -> None:
        """state/store.go SaveABCIResponses: {deliver_txs, end_block, begin_block}
        stored for reindexing and /block_results; under discard mode only the
        latest height survives (store.go:344)."""
        if self.discard_abci_responses:
            self._db.delete(_abci_responses_key(height - 1))
        self._db.set(_abci_responses_key(height), json.dumps(responses).encode())

    def load_abci_responses(self, height: int) -> dict | None:
        raw = self._db.get(_abci_responses_key(height))
        return json.loads(raw) if raw else None

    def prune_states(self, retain_height: int) -> None:
        """state/store.go PruneStates. Keys are textual "prefix:height", so a
        full prefix scan with numeric parsing is required (bytewise ranges
        over decimal strings would skip e.g. ':2'..':9' when pruning to 10).

        Validator-set and params records are stored SPARSELY: unchanged
        heights hold a pointer to the last-changed checkpoint, which may sit
        below retain_height. The checkpoint is migrated to retain_height as
        a full record BEFORE deleting (the reference's PruneStates does the
        same), or every retained pointer would dangle."""
        if retain_height <= 0:
            raise ValueError("height must be greater than 0")
        # Migrate checkpoints the retained range depends on. A failed load
        # ABORTS the prune (the reference errors out too): silently
        # proceeding would delete every record the retained range needs.
        vals = self.load_validators(retain_height)
        self._db.set(
            _validators_key(retain_height),
            json.dumps({"h": retain_height, "set": vals.encode().hex()}).encode(),
        )
        params = self.load_consensus_params(retain_height)
        self._db.set(
            _params_key(retain_height),
            json.dumps(
                {"h": retain_height, "params": params.encode().hex()}
            ).encode(),
        )
        self._pruned_to = max(self._pruned_to, retain_height)
        self._db.set(_PRUNED_TO_KEY, str(self._pruned_to).encode())
        for prefix in (b"validatorsKey:", b"consensusParamsKey:", b"abciResponsesKey:"):
            for k, raw in list(self._db.iterator(prefix, prefix + b"\xff")):
                try:
                    h = int(k.rsplit(b":", 1)[1])
                except Exception:
                    continue
                if h < retain_height:
                    self._db.delete(k)
                elif h > retain_height and prefix != b"abciResponsesKey:":
                    # Retained pointer records that referenced a deleted
                    # checkpoint now chase the migrated one.  NOTE: proposer-
                    # priority restoration after this rewrite is order-
                    # preserving but not always bit-exact — each increment
                    # re-applies rescale+shift, which composes exactly only
                    # while rescaling never clips.  Safe for consensus
                    # (priorities are excluded from validator hashes, and
                    # the live proposer comes from the state record, not
                    # historical loads); only historical
                    # load_validators().proposer can diverge post-prune.
                    try:
                        info = json.loads(raw)
                    except ValueError:
                        continue
                    ptr = info.get("h")
                    if (
                        isinstance(ptr, int)
                        and ptr < retain_height
                        and "set" not in info
                        and "params" not in info
                    ):
                        self._db.set(
                            k, json.dumps({"h": retain_height}).encode()
                        )


class NoValidatorsError(Exception):
    def __init__(self, height: int):
        super().__init__(f"could not find validator set for height #{height}")


class NoParamsError(Exception):
    def __init__(self, height: int):
        super().__init__(f"could not find consensus params for height #{height}")


# -- state codec (JSON for readability; stable field set) ---------------------


def _encode_state(s: State) -> bytes:
    return json.dumps(
        {
            "chain_id": s.chain_id,
            "initial_height": s.initial_height,
            "last_block_height": s.last_block_height,
            "last_block_id": {
                "hash": s.last_block_id.hash.hex(),
                "psh_total": s.last_block_id.part_set_header.total,
                "psh_hash": s.last_block_id.part_set_header.hash.hex(),
            },
            "last_block_time": [s.last_block_time.seconds, s.last_block_time.nanos],
            "next_validators": s.next_validators.encode().hex() if s.next_validators else "",
            "validators": s.validators.encode().hex() if s.validators else "",
            "last_validators": s.last_validators.encode().hex() if s.last_validators else "",
            "last_height_validators_changed": s.last_height_validators_changed,
            "consensus_params": s.consensus_params.encode().hex(),
            "last_height_consensus_params_changed": s.last_height_consensus_params_changed,
            "last_results_hash": s.last_results_hash.hex(),
            "app_hash": s.app_hash.hex(),
            "version_block": s.version_consensus.block,
            "version_app": s.version_consensus.app,
        }
    ).encode()


def _decode_state(raw: bytes) -> State:
    from cometbft_tpu.types.block import PartSetHeader

    d = json.loads(raw)
    return State(
        chain_id=d["chain_id"],
        initial_height=d["initial_height"],
        last_block_height=d["last_block_height"],
        last_block_id=BlockID(
            hash=bytes.fromhex(d["last_block_id"]["hash"]),
            part_set_header=PartSetHeader(
                d["last_block_id"]["psh_total"],
                bytes.fromhex(d["last_block_id"]["psh_hash"]),
            ),
        ),
        last_block_time=Time(*d["last_block_time"]),
        next_validators=ValidatorSet.decode(bytes.fromhex(d["next_validators"]))
        if d["next_validators"]
        else None,
        validators=ValidatorSet.decode(bytes.fromhex(d["validators"]))
        if d["validators"]
        else None,
        last_validators=ValidatorSet.decode(bytes.fromhex(d["last_validators"]))
        if d["last_validators"]
        else ValidatorSet(),
        last_height_validators_changed=d["last_height_validators_changed"],
        consensus_params=ConsensusParams.decode(bytes.fromhex(d["consensus_params"])),
        last_height_consensus_params_changed=d["last_height_consensus_params_changed"],
        last_results_hash=bytes.fromhex(d["last_results_hash"]),
        app_hash=bytes.fromhex(d["app_hash"]),
        version_consensus=Consensus(d["version_block"], d["version_app"]),
    )

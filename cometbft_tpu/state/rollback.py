"""One-block state rollback for the `rollback` CLI (reference:
state/rollback.go Rollback).

Overwrites state at height n with the state as of height n-1: the prior
block's header supplies LastBlockID/time, the validator-set triple shifts
back one step, and AppHash/LastResultsHash come from the latest block (they
are only agreed upon in the following block).
"""

from __future__ import annotations

from cometbft_tpu.state.state import State
from cometbft_tpu.types.block import Consensus


def rollback_state(state_store, block_store) -> tuple[int, bytes]:
    """state/rollback.go:15-125. Returns (new_height, new_app_hash)."""
    invalid_state = state_store.load()
    if invalid_state is None or invalid_state.is_empty():
        raise ValueError("no state found")
    height = block_store.height()
    # Non-atomic persistence: the block store may be one ahead; the state is
    # already the one to keep (rollback.go:29-36).
    if height == invalid_state.last_block_height + 1:
        return invalid_state.last_block_height, invalid_state.app_hash
    if height != invalid_state.last_block_height:
        raise ValueError(
            f"statestore height ({invalid_state.last_block_height}) is not one below "
            f"or equal to blockstore height ({height})"
        )
    rollback_height = invalid_state.last_block_height - 1
    rollback_block = block_store.load_block_meta(rollback_height)
    if rollback_block is None:
        raise ValueError(f"block at height {rollback_height} not found")
    latest_block = block_store.load_block_meta(invalid_state.last_block_height)
    if latest_block is None:
        raise ValueError(f"block at height {invalid_state.last_block_height} not found")

    previous_last_validator_set = state_store.load_validators(rollback_height)
    previous_params = state_store.load_consensus_params(rollback_height + 1)

    val_change_height = invalid_state.last_height_validators_changed
    if val_change_height > rollback_height:
        val_change_height = rollback_height + 1
    params_change_height = invalid_state.last_height_consensus_params_changed
    if params_change_height > rollback_height:
        params_change_height = rollback_height + 1

    rolled = State(
        chain_id=invalid_state.chain_id,
        initial_height=invalid_state.initial_height,
        last_block_height=rollback_block.header.height,
        last_block_id=rollback_block.block_id,
        last_block_time=rollback_block.header.time,
        next_validators=invalid_state.validators,
        validators=invalid_state.last_validators,
        last_validators=previous_last_validator_set,
        last_height_validators_changed=val_change_height,
        consensus_params=previous_params,
        last_height_consensus_params_changed=params_change_height,
        last_results_hash=latest_block.header.last_results_hash,
        app_hash=latest_block.header.app_hash,
        version_consensus=Consensus(
            block=invalid_state.version_consensus.block,
            app=previous_params.version.app,
        ),
    )
    state_store.save(rolled)
    return rolled.last_block_height, rolled.app_hash

"""BlockExecutor: proposal creation and ApplyBlock pipeline
(reference: state/execution.go).

ApplyBlock = validate → BeginBlock/DeliverTx*/EndBlock over the consensus
ABCI connection → save responses → update state (validator/param updates
with the +1 delay) → app Commit under mempool lock → prune → fire events
(state/execution.go:194-280).
"""

from __future__ import annotations

import base64

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import fail
from cometbft_tpu.state.state import State
from cometbft_tpu.state.validation import validate_block
from cometbft_tpu.types import events as ev
from cometbft_tpu.types.block import Block, BlockID, Commit
from cometbft_tpu.types.results import results_hash
from cometbft_tpu.types.validator import Validator


class BlockExecutor:
    """state/execution.go:42-90."""

    def __init__(
        self,
        state_store,
        app_conn_consensus,
        mempool,
        evidence_pool,
        block_store=None,
        event_bus=None,
        logger=None,
    ):
        self.state_store = state_store
        self.proxy_app = app_conn_consensus
        self.mempool = mempool
        self.evpool = evidence_pool
        self.block_store = block_store
        self.event_bus = event_bus
        self.logger = logger

    # -- proposal path -------------------------------------------------------

    def create_proposal_block(
        self, height: int, state: State, commit: Commit | None, proposer_addr: bytes
    ) -> Block:
        """state/execution.go:100-150: reap mempool, pass through the app's
        PrepareProposal, assemble the block."""
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence, ev_size = (
            self.evpool.pending_evidence(state.consensus_params.evidence.max_bytes)
            if self.evpool
            else ([], 0)
        )
        # MaxDataBytes accounting (types/block.go MaxDataBytes).
        max_data_bytes = max_data_bytes_for(max_bytes, ev_size, state.validators.size())
        txs = self.mempool.reap_max_bytes_max_gas(max_data_bytes, max_gas)
        local_last_commit = self._build_last_commit_info(state, commit)
        rpp = self.proxy_app.prepare_proposal(
            abci.RequestPrepareProposal(
                max_tx_bytes=max_data_bytes,
                txs=list(txs),
                local_last_commit=local_last_commit,
                misbehavior=_abci_evidence(evidence),
                height=height,
                time_seconds=0,
                proposer_address=proposer_addr,
            )
        )
        return state.make_block(height, list(rpp.txs), commit, evidence, proposer_addr)

    def process_proposal(self, block: Block, state: State) -> bool:
        """state/execution.go:152-178."""
        resp = self.proxy_app.process_proposal(
            abci.RequestProcessProposal(
                txs=list(block.data.txs),
                proposed_last_commit=self._build_last_commit_info(
                    state, block.last_commit
                ),
                misbehavior=_abci_evidence(block.evidence),
                hash=block.hash() or b"",
                height=block.header.height,
                next_validators_hash=block.header.next_validators_hash,
                proposer_address=block.header.proposer_address,
            )
        )
        return resp.is_accepted()

    # -- apply path ----------------------------------------------------------

    def validate_block(self, state: State, block: Block) -> None:
        """state/execution.go:180-192: header/commit checks + evidence check."""
        validate_block(state, block)
        if self.evpool:
            self.evpool.check_evidence(block.evidence)

    def apply_block(
        self, state: State, block_id: BlockID, block: Block
    ) -> tuple[State, int]:
        """state/execution.go:194-280. Returns (new_state, retain_height)."""
        self.validate_block(state, block)
        abci_responses = self._exec_block_on_proxy_app(state, block)
        fail.fail()  # kill-point: block executed, responses unsaved (execution.go:212)
        # Save ABCI responses for /block_results + reindexing.
        self.state_store.save_abci_responses(
            block.header.height, _encode_responses(abci_responses)
        )
        fail.fail()  # kill-point: responses saved, state not updated (execution.go:219)
        validator_updates = abci_responses["end_block"].validator_updates
        _validate_validator_updates(validator_updates, state.consensus_params)
        new_state = _update_state(
            state, block_id, block, abci_responses, validator_updates
        )
        # Lock mempool, commit app, update mempool (state/execution.go:288-330).
        fail.fail()  # kill-point: before app Commit (execution.go:255)
        app_hash, retain_height = self._commit(new_state, block, abci_responses)
        fail.fail()  # kill-point: app committed, state unsaved (execution.go:263)
        new_state.app_hash = app_hash
        self.state_store.save(new_state)
        # Evidence pool update (prune committed/expired evidence).
        if self.evpool:
            self.evpool.update(new_state, block.evidence)
        self._fire_events(block, block_id, abci_responses, validator_updates)
        return new_state, retain_height

    def _commit(self, state: State, block: Block, abci_responses) -> tuple[bytes, int]:
        """state/execution.go:288-330: flush mempool conn, app Commit with
        mempool locked, then mempool.Update with DeliverTx results."""
        self.mempool.lock()
        try:
            self.mempool.flush_app_conn()
            res = self.proxy_app.commit()
            deliver_txs = abci_responses["deliver_txs"]
            self.mempool.update(
                block.header.height,
                list(block.data.txs),
                deliver_txs,
                None,
                None,
            )
            return res.data, res.retain_height
        finally:
            self.mempool.unlock()

    def _exec_block_on_proxy_app(self, state: State, block: Block) -> dict:
        """state/execution.go:336-410: BeginBlock, DeliverTx xN, EndBlock."""
        commit_info = self._build_last_commit_info(state, block.last_commit)
        byz_vals = _abci_evidence(block.evidence)
        begin = self.proxy_app.begin_block(
            abci.RequestBeginBlock(
                hash=block.hash() or b"",
                header=block.header,
                last_commit_info=commit_info,
                byzantine_validators=byz_vals,
            )
        )
        deliver_txs = []
        for tx in block.data.txs:
            deliver_txs.append(self.proxy_app.deliver_tx(abci.RequestDeliverTx(tx=tx)))
        end = self.proxy_app.end_block(
            abci.RequestEndBlock(height=block.header.height)
        )
        return {"begin_block": begin, "deliver_txs": deliver_txs, "end_block": end}

    def _build_last_commit_info(
        self, state: State, commit: Commit | None
    ) -> abci.CommitInfo:
        """getBeginBlockValidatorInfo (state/execution.go:420-460): match the
        commit's signatures against the validator set at that height."""
        if commit is None or state.last_block_height == 0:
            return abci.CommitInfo()
        return build_last_commit_info(commit, state.last_validators)

    def _fire_events(self, block, block_id, abci_responses, validator_updates) -> None:
        """state/execution.go fireEvents: NewBlock, NewBlockHeader, per-Tx,
        ValidatorSetUpdates."""
        if self.event_bus is None:
            return
        begin = abci_responses["begin_block"]
        end = abci_responses["end_block"]
        self.event_bus.publish_new_block(
            ev.EventDataNewBlock(
                block=block,
                block_id=block_id,
                result_begin_block=begin,
                result_end_block=end,
            ),
            events=list(begin.events) + list(end.events),
        )
        self.event_bus.publish_new_block_header(
            ev.EventDataNewBlockHeader(
                header=block.header,
                num_txs=len(block.data.txs),
                result_begin_block=begin,
                result_end_block=end,
            )
        )
        for i, tx in enumerate(block.data.txs):
            res = abci_responses["deliver_txs"][i]
            self.event_bus.publish_tx(
                ev.EventDataTx(
                    height=block.header.height, tx=tx, index=i, result=res
                ),
                events=res.events,
            )
        if validator_updates:
            self.event_bus.publish_validator_set_updates(
                ev.EventDataValidatorSetUpdates(validator_updates=validator_updates)
            )


def build_last_commit_info(commit: Commit | None, vals) -> abci.CommitInfo:
    """Positional commit-sig ↔ validator matching for BeginBlock
    (state/execution.go getBeginBlockValidatorInfo); `vals` must be the
    validator set of the commit's height (historical on replay)."""
    if commit is None or vals is None:
        return abci.CommitInfo()
    votes = []
    for i, cs in enumerate(commit.signatures):
        if i >= vals.size():
            break
        val = vals.validators[i]
        votes.append(
            abci.VoteInfo(
                validator_address=val.address,
                validator_power=val.voting_power,
                signed_last_block=not cs.is_absent(),
            )
        )
    return abci.CommitInfo(round=commit.round, votes=votes)


def max_data_bytes_for(max_bytes: int, evidence_bytes: int, vals_count: int) -> int:
    """types/block.go MaxDataBytes approximation: block max minus header,
    commit, and evidence overheads."""
    from cometbft_tpu.types.block import (
        MAX_COMMIT_OVERHEAD_BYTES,
        MAX_COMMIT_SIG_BYTES,
        MAX_HEADER_BYTES,
    )

    if max_bytes == -1:
        from cometbft_tpu.types.params import MAX_BLOCK_SIZE_BYTES

        max_bytes = MAX_BLOCK_SIZE_BYTES
    commit_bytes = MAX_COMMIT_OVERHEAD_BYTES + MAX_COMMIT_SIG_BYTES * vals_count
    data = max_bytes - MAX_HEADER_BYTES - commit_bytes - evidence_bytes - 64
    return max(data, 0)


def _validate_validator_updates(updates: list, params) -> None:
    """state/validation.go validateValidatorUpdates."""
    for vu in updates:
        if vu.power < 0:
            raise ValueError(f"voting power can't be negative {vu}")
        if vu.power == 0:
            continue
        if vu.pub_key.type() not in params.validator.pub_key_types:
            raise ValueError(
                f"validator {vu} is using pubkey {vu.pub_key.type()}, which is "
                f"unsupported for consensus"
            )


def _update_state(
    state: State, block_id: BlockID, block: Block, abci_responses, validator_updates
) -> State:
    """state/execution.go:241 updateState."""
    n_val_set = state.next_validators.copy()
    last_height_vals_changed = state.last_height_validators_changed
    if validator_updates:
        changes = [
            Validator.new(vu.pub_key, vu.power) for vu in validator_updates
        ]
        n_val_set.update_with_change_set(changes)
        last_height_vals_changed = block.header.height + 1 + 1
    n_val_set.increment_proposer_priority(1)

    params = state.consensus_params
    last_height_params_changed = state.last_height_consensus_params_changed
    param_updates = abci_responses["end_block"].consensus_param_updates
    if param_updates is not None:
        params = params.update(param_updates)
        params.validate_basic()
        last_height_params_changed = block.header.height + 1

    from dataclasses import replace

    version = state.version_consensus
    if params.version.app != version.app:
        from cometbft_tpu.types.block import Consensus

        version = Consensus(block=version.block, app=params.version.app)

    return State(
        chain_id=state.chain_id,
        initial_height=state.initial_height,
        last_block_height=block.header.height,
        last_block_id=block_id,
        last_block_time=block.header.time,
        next_validators=n_val_set,
        validators=state.next_validators.copy(),
        last_validators=state.validators.copy(),
        last_height_validators_changed=last_height_vals_changed,
        consensus_params=params,
        last_height_consensus_params_changed=last_height_params_changed,
        last_results_hash=results_hash(abci_responses["deliver_txs"]),
        app_hash=b"",
        version_consensus=version,
    )


def _abci_evidence(evidence: list) -> list:
    """Evidence → abci.Misbehavior (types/evidence.go ABCI conversion)."""
    out = []
    for evd in evidence:
        from cometbft_tpu.types.evidence import (
            DuplicateVoteEvidence,
            LightClientAttackEvidence,
        )

        if isinstance(evd, DuplicateVoteEvidence):
            out.append(
                abci.Misbehavior(
                    type=abci.MISBEHAVIOR_DUPLICATE_VOTE,
                    validator_address=evd.vote_a.validator_address,
                    validator_power=evd.validator_power,
                    height=evd.height(),
                    time_seconds=evd.timestamp.seconds,
                    total_voting_power=evd.total_voting_power,
                )
            )
        elif isinstance(evd, LightClientAttackEvidence):
            for v in evd.byzantine_validators:
                out.append(
                    abci.Misbehavior(
                        type=abci.MISBEHAVIOR_LIGHT_CLIENT_ATTACK,
                        validator_address=v.address,
                        validator_power=v.voting_power,
                        height=evd.height(),
                        time_seconds=evd.timestamp.seconds,
                        total_voting_power=evd.total_voting_power,
                    )
                )
    return out


def _encode_responses(abci_responses: dict) -> dict:
    """JSON-able form of the ABCI responses for the state store. Must be
    COMPLETE enough to re-run updateState from storage alone: the handshake's
    ran-Commit-but-didn't-save-state replay path (consensus/replay.go:420
    mock app) rebuilds EndBlock validator/param updates from here."""

    def enc_events(events):
        return [
            {
                "type": e.type,
                "attributes": [
                    {"key": a.key, "value": a.value, "index": a.index}
                    for a in e.attributes
                ],
            }
            for e in events
        ]

    def enc_tx(r):
        return {
            "code": r.code,
            "data": base64.b64encode(r.data).decode(),
            "log": r.log,
            "gas_wanted": r.gas_wanted,
            "gas_used": r.gas_used,
            "events": enc_events(r.events),
        }

    from cometbft_tpu.crypto.encoding import pub_key_to_proto

    end = abci_responses["end_block"]
    return {
        "deliver_txs": [enc_tx(r) for r in abci_responses["deliver_txs"]],
        "end_block": {
            "validator_updates": [
                {
                    "pub_key": base64.b64encode(pub_key_to_proto(vu.pub_key)).decode(),
                    "power": vu.power,
                }
                for vu in end.validator_updates
            ],
            "consensus_param_updates": _enc_param_updates(
                end.consensus_param_updates
            ),
        },
        "begin_block": {},
    }


def _enc_param_updates(updates) -> dict | None:
    """Section-wise JSON of an abci.ConsensusParams-shaped update. The object
    is PARTIAL by contract (ConsensusParams.update getattr-guards each
    section), so it can't be run through ConsensusParams.encode()."""
    if updates is None:
        return None
    out = {}
    block = getattr(updates, "block", None)
    if block is not None:
        out["block"] = {"max_bytes": block.max_bytes, "max_gas": block.max_gas}
    evidence = getattr(updates, "evidence", None)
    if evidence is not None:
        out["evidence"] = {
            "max_age_num_blocks": evidence.max_age_num_blocks,
            "max_age_duration_ns": evidence.max_age_duration_ns,
            "max_bytes": evidence.max_bytes,
        }
    validator = getattr(updates, "validator", None)
    if validator is not None:
        out["validator"] = {"pub_key_types": list(validator.pub_key_types)}
    version = getattr(updates, "version", None)
    if version is not None:
        out["version"] = {"app": version.app}
    return out


def _dec_param_updates(raw: dict | None):
    if not raw:
        return None
    from types import SimpleNamespace

    ns = SimpleNamespace(block=None, evidence=None, validator=None, version=None)
    if "block" in raw:
        ns.block = SimpleNamespace(**raw["block"])
    if "evidence" in raw:
        ns.evidence = SimpleNamespace(**raw["evidence"])
    if "validator" in raw:
        ns.validator = SimpleNamespace(**raw["validator"])
    if "version" in raw:
        ns.version = SimpleNamespace(**raw["version"])
    return ns


def decode_responses(raw: dict) -> dict:
    """Inverse of _encode_responses: rebuild the in-memory ABCI response
    objects the replay/mock-app path feeds back through updateState."""

    def dec_events(items):
        return [
            abci.Event(
                type=e["type"],
                attributes=[
                    abci.EventAttribute(a["key"], a["value"], a["index"])
                    for a in e["attributes"]
                ],
            )
            for e in items
        ]

    def dec_tx(d):
        return abci.ResponseDeliverTx(
            code=d["code"],
            data=base64.b64decode(d["data"]),
            log=d["log"],
            gas_wanted=d["gas_wanted"],
            gas_used=d["gas_used"],
            events=dec_events(d.get("events", [])),
        )

    from cometbft_tpu.crypto.encoding import pub_key_from_proto

    end = raw.get("end_block") or {}
    vus = end.get("validator_updates") or []
    if isinstance(vus, int):
        # Legacy round-1 records stored only a count — not enough to rebuild
        # updateState. Degrading to [] would silently drop validator updates
        # and diverge from committed validators_hash; fail loudly instead.
        raise RuntimeError(
            "stored ABCI responses use the legacy summary format and cannot "
            "be replayed; reset the node or re-sync"
        )
    param_updates = _dec_param_updates(end.get("consensus_param_updates"))
    return {
        "deliver_txs": [dec_tx(d) for d in raw.get("deliver_txs", [])],
        "end_block": abci.ResponseEndBlock(
            validator_updates=[
                abci.ValidatorUpdate(
                    pub_key=pub_key_from_proto(base64.b64decode(vu["pub_key"])),
                    power=vu["power"],
                )
                for vu in vus
            ],
            consensus_param_updates=param_updates,
        ),
        "begin_block": abci.ResponseBeginBlock(),
    }

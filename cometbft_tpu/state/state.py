"""sm.State: deterministic chain-state snapshot (reference: state/state.go).

Carries everything needed to validate the next block: the three validator
sets (last/current/next — the +1 delay from EndBlock updates), consensus
params, last block info, app hash, and last results hash.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield, replace

from cometbft_tpu.types.block import Block, BlockID, Commit, Consensus, Data, Header
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.genesis import GenesisDoc
from cometbft_tpu.types.params import ConsensusParams
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet

BLOCK_PROTOCOL = 11  # version/version.go BlockProtocol


@dataclass
class State:
    """state/state.go:47-80."""

    chain_id: str = ""
    initial_height: int = 1
    last_block_height: int = 0
    last_block_id: BlockID = dfield(default_factory=BlockID)
    last_block_time: Time = dfield(default_factory=Time)
    next_validators: ValidatorSet | None = None
    validators: ValidatorSet | None = None
    last_validators: ValidatorSet | None = None
    last_height_validators_changed: int = 0
    consensus_params: ConsensusParams = dfield(default_factory=ConsensusParams)
    last_height_consensus_params_changed: int = 0
    last_results_hash: bytes = b""
    app_hash: bytes = b""
    version_consensus: Consensus = dfield(
        default_factory=lambda: Consensus(block=BLOCK_PROTOCOL, app=0)
    )

    def copy(self) -> "State":
        return State(
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            last_block_height=self.last_block_height,
            last_block_id=self.last_block_id,
            last_block_time=self.last_block_time,
            next_validators=self.next_validators.copy() if self.next_validators else None,
            validators=self.validators.copy() if self.validators else None,
            last_validators=self.last_validators.copy() if self.last_validators else None,
            last_height_validators_changed=self.last_height_validators_changed,
            consensus_params=self.consensus_params,
            last_height_consensus_params_changed=self.last_height_consensus_params_changed,
            last_results_hash=self.last_results_hash,
            app_hash=self.app_hash,
            version_consensus=self.version_consensus,
        )

    def is_empty(self) -> bool:
        return self.validators is None

    def make_block(
        self,
        height: int,
        txs: list,
        last_commit: Commit | None,
        evidence: list,
        proposer_address: bytes,
    ) -> Block:
        """state/state.go:234-263 MakeBlock."""
        if height == self.initial_height:
            timestamp = self.last_block_time
        else:
            timestamp = median_time(last_commit, self.last_validators)
        from cometbft_tpu.types.evidence import evidence_list_hash

        header = Header(
            version=self.version_consensus,
            chain_id=self.chain_id,
            height=height,
            time=timestamp,
            last_block_id=self.last_block_id,
            last_commit_hash=last_commit.hash() if last_commit else b"",
            data_hash=Data(txs=list(txs)).hash(),
            validators_hash=self.validators.hash(),
            next_validators_hash=self.next_validators.hash(),
            consensus_hash=self.consensus_params.hash(),
            app_hash=self.app_hash,
            last_results_hash=self.last_results_hash,
            evidence_hash=evidence_list_hash(evidence),
            proposer_address=proposer_address,
        )
        block = Block(
            header=header,
            data=Data(txs=list(txs)),
            evidence=list(evidence),
            last_commit=last_commit,
        )
        return block


def median_time(commit: Commit, validators: ValidatorSet) -> Time:
    """Weighted median of commit timestamps by voting power
    (state/state.go:269-286 + types/time WeightedMedian): the median is the
    smallest timestamp t such that the power of signers with time <= t
    reaches half the counted total."""
    weighted: list[tuple[int, int]] = []  # (unix_nanos, power)
    total = 0
    for cs in commit.signatures:
        if cs.is_absent():
            continue
        _, val = validators.get_by_address(cs.validator_address)
        if val is not None:
            total += val.voting_power
            weighted.append((cs.timestamp.unix_nanos(), val.voting_power))
    weighted.sort()
    median = total // 2
    for nanos, power in weighted:
        if median <= power:
            return Time(nanos // 10**9, nanos % 10**9)
        median -= power
    return Time()


def make_genesis_state(gen_doc: GenesisDoc) -> State:
    """state/state.go MakeGenesisState."""
    err = _validate_genesis(gen_doc)
    if err:
        raise ValueError(err)
    if gen_doc.validators:
        vals = [Validator.new(v.pub_key, v.power) for v in gen_doc.validators]
        validator_set = ValidatorSet(vals)
        next_validator_set = validator_set.copy_increment_proposer_priority(1)
    else:
        validator_set = ValidatorSet()
        next_validator_set = ValidatorSet()
    return State(
        chain_id=gen_doc.chain_id,
        initial_height=gen_doc.initial_height,
        last_block_height=0,
        last_block_id=BlockID(),
        last_block_time=gen_doc.genesis_time,
        next_validators=next_validator_set,
        validators=validator_set,
        last_validators=ValidatorSet(),
        last_height_validators_changed=gen_doc.initial_height,
        consensus_params=gen_doc.consensus_params,
        last_height_consensus_params_changed=gen_doc.initial_height,
        app_hash=gen_doc.app_hash,
    )


def _validate_genesis(gen_doc: GenesisDoc) -> str | None:
    if not gen_doc.chain_id:
        return "genesis doc must include non-empty chain_id"
    return None

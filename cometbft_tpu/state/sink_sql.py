"""SQL event sink (reference: state/indexer/sink/psql/psql.go + schema.sql).

The reference ships a PostgreSQL event sink selected by ``indexer = "psql"``:
a WRITE-ONLY sink — blocks, tx_results (protobuf-encoded), events, and
indexed attributes land in relational tables for external SQL consumers,
while the node's own /tx_search, /block_search and getTxByHash report
"not supported via the postgres event sink" (psql.go:236-253).

This is that sink on sqlite (the analog available in-image): identical
table/view shapes (schema.sql — BIGSERIAL/BYTEA/TIMESTAMPTZ mapped to their
sqlite spellings), the same meta-events (block.height on blocks, tx.hash +
tx.height on transactions, psql.go:162,216-218), the same
only-indexed-attributes rule (attr.Index gate, psql.go:110-112), the same
quiet-duplicate semantics (ON CONFLICT DO NOTHING, psql.go:155,209), and
the same query refusals.

Two deliberate divergences:
  - IndexTxEvents creates the block row if the header has not been indexed
    yet (the reference errors, psql.go:195 — it can, because its indexer
    service is single-threaded; this node's tx and header pumps are
    independent threads, so ordering is not guaranteed);
  - the event bus hands the sink FLATTENED composite keys ("type.key" ->
    values), so an event with N attributes becomes N single-attribute
    events rows rather than the reference's one events row with N
    attributes rows — external consumers grouping by event instance should
    group on (block_id, tx_id, type) instead of events.rowid.
"""

from __future__ import annotations

import sqlite3
import threading
import time

from cometbft_tpu.types.tx import tx_hash

_SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  height     INTEGER NOT NULL,
  chain_id   TEXT NOT NULL,
  created_at TEXT NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain ON blocks(height, chain_id);

CREATE TABLE IF NOT EXISTS tx_results (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id   INTEGER NOT NULL REFERENCES blocks(rowid),
  "index"    INTEGER NOT NULL,
  created_at TEXT NOT NULL,
  tx_hash    TEXT NOT NULL,
  tx_result  BLOB NOT NULL,
  UNIQUE (block_id, "index")
);

CREATE TABLE IF NOT EXISTS events (
  rowid    INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id INTEGER NOT NULL REFERENCES blocks(rowid),
  tx_id    INTEGER NULL REFERENCES tx_results(rowid),
  type     TEXT NOT NULL
);

CREATE TABLE IF NOT EXISTS attributes (
  event_id      INTEGER NOT NULL REFERENCES events(rowid),
  key           TEXT NOT NULL,
  composite_key TEXT NOT NULL,
  value         TEXT NULL,
  UNIQUE (event_id, key)
);

CREATE VIEW IF NOT EXISTS event_attributes AS
  SELECT block_id, tx_id, type, key, composite_key, value
  FROM events LEFT JOIN attributes ON (events.rowid = attributes.event_id);

CREATE VIEW IF NOT EXISTS block_events AS
  SELECT blocks.rowid as block_id, height, chain_id, type, key, composite_key, value
  FROM blocks JOIN event_attributes ON (blocks.rowid = event_attributes.block_id)
  WHERE event_attributes.tx_id IS NULL;

CREATE VIEW IF NOT EXISTS tx_events AS
  SELECT height, "index", chain_id, type, key, composite_key, value,
         tx_results.created_at
  FROM blocks JOIN tx_results ON (blocks.rowid = tx_results.block_id)
  JOIN event_attributes ON (tx_results.rowid = event_attributes.tx_id)
  WHERE event_attributes.tx_id IS NOT NULL;
"""


class SinkQueryUnsupportedError(Exception):
    """The psql sink refuses node-local queries (psql.go:236-253)."""


class SqlEventSink:
    def __init__(self, path: str, chain_id: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.executescript(_SCHEMA)
        self._conn.commit()
        self._chain_id = chain_id
        self._mtx = threading.Lock()

    # -- write side ---------------------------------------------------------

    def _block_row(self, cur, height: int) -> int:
        cur.execute(
            "INSERT OR IGNORE INTO blocks (height, chain_id, created_at) "
            "VALUES (?, ?, ?)",
            (height, self._chain_id, _now()),
        )
        cur.execute(
            "SELECT rowid FROM blocks WHERE height = ? AND chain_id = ?",
            (height, self._chain_id),
        )
        return cur.fetchone()[0]

    def _insert_events(self, cur, block_id: int, tx_id, events: dict) -> None:
        """events: composite-key dict ("type.key" -> [values]) as carried by
        the event bus; split exactly like makeIndexedEvent (psql.go:128-138).
        Every attribute that reaches the bus was flagged for indexing
        upstream, matching the attr.Index gate."""
        for composite_key, values in events.items():
            dot = composite_key.find(".")
            etype = composite_key if dot < 0 else composite_key[:dot]
            key = None if dot < 0 else composite_key[dot + 1 :]
            if not etype:
                continue  # psql.go:99-101 skips empty types
            for value in values:
                cur.execute(
                    "INSERT INTO events (block_id, tx_id, type) VALUES (?, ?, ?)",
                    (block_id, tx_id, etype),
                )
                eid = cur.lastrowid
                if key is not None:
                    cur.execute(
                        "INSERT OR IGNORE INTO attributes "
                        "(event_id, key, composite_key, value) VALUES (?, ?, ?, ?)",
                        (eid, key, composite_key, str(value)),
                    )

    def index_block(self, height: int, events: dict) -> None:
        """IndexBlockEvents (psql.go:141-176): block row + block.height
        meta-event + the header's begin/end-block events."""
        with self._mtx:
            cur = self._conn.cursor()
            block_id = self._block_row(cur, height)
            self._insert_events(
                cur, block_id, None, {"block.height": [str(height)]}
            )
            self._insert_events(cur, block_id, None, events)
            self._conn.commit()

    def index_tx(self, height: int, index: int, tx: bytes, result, events: dict) -> None:
        """IndexTxEvents (psql.go:178-233): tx_result row (wire-encoded) +
        tx.hash/tx.height meta-events + the tx's own events."""
        from cometbft_tpu.abci.wire import _enc_resp_body
        from cometbft_tpu.wire import proto as wire

        h = tx_hash(tx).hex().upper()
        # abci.TxResult wire shape (abci/types.proto): height=1, index=2,
        # tx=3, result=4 — what the reference proto.Marshal's (psql.go:183).
        result_data = (
            wire.field_varint(1, height)
            + wire.field_varint(2, index)
            + wire.field_bytes(3, tx)
            + wire.field_message(4, _enc_resp_body(result), emit_empty=True)
        )
        with self._mtx:
            cur = self._conn.cursor()
            block_id = self._block_row(cur, height)
            cur.execute(
                'INSERT OR IGNORE INTO tx_results (block_id, "index", '
                "created_at, tx_hash, tx_result) VALUES (?, ?, ?, ?, ?)",
                (block_id, index, _now(), h, result_data),
            )
            if cur.rowcount == 0:
                self._conn.commit()
                return  # duplicate: quietly succeed (psql.go:209-211)
            tx_id = cur.lastrowid
            self._insert_events(
                cur, block_id, tx_id,
                {"tx.hash": [h], "tx.height": [str(height)]},
            )
            self._insert_events(cur, block_id, tx_id, events)
            self._conn.commit()

    def stop(self) -> None:
        self._conn.close()

    # -- IndexerService adapters (tx_indexer / block_indexer duck types) ----

    def tx_indexer(self) -> "_TxAdapter":
        return _TxAdapter(self)

    def block_indexer(self) -> "_BlockAdapter":
        return _BlockAdapter(self)

    # -- read side: refused, like the reference sink ------------------------

    def search(self, query: str):
        raise SinkQueryUnsupportedError(
            "tx search is not supported via the psql event sink"
        )

    def get(self, h: bytes):
        raise SinkQueryUnsupportedError(
            "getTxByHash is not supported via the psql event sink"
        )

    def has_block(self, height: int):
        raise SinkQueryUnsupportedError(
            "hasBlock is not supported via the psql event sink"
        )


class _TxAdapter:
    def __init__(self, sink: SqlEventSink):
        self._sink = sink

    def index(self, height, index, tx, result, result_events) -> None:
        self._sink.index_tx(height, index, tx, result, result_events)

    def get(self, h: bytes):
        return self._sink.get(h)

    def search(self, query: str):
        return self._sink.search(query)


class _BlockAdapter:
    def __init__(self, sink: SqlEventSink):
        self._sink = sink

    def index(self, height, events) -> None:
        self._sink.index_block(height, events)

    def search(self, query: str):
        return self._sink.search(query)


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

"""Transaction & block event indexing (reference: state/txindex/kv/kv.go,
state/indexer/block/kv/kv.go, state/txindex/indexer_service.go).

The IndexerService subscribes to the EventBus and indexes every committed
tx (by hash, plus composite event keys for /tx_search) and block header
events (for /block_search).
"""

from __future__ import annotations

import base64
import json
import threading

from cometbft_tpu.libs.db import DB
from cometbft_tpu.libs.pubsub import Query
from cometbft_tpu.types import events as ev
from cometbft_tpu.types.tx import tx_hash


class KVTxIndexer:
    """state/txindex/kv/kv.go: primary record tx.hash -> TxResult, secondary
    records eventkey/value/height/index -> hash."""

    def __init__(self, db: DB):
        self._db = db

    def index(self, height: int, index: int, tx: bytes, result, result_events: dict) -> None:
        h = tx_hash(tx)
        record = {
            "hash": h.hex().upper(),
            "height": str(height),
            "index": index,
            "tx": base64.b64encode(tx).decode(),
            "tx_result": {
                "code": result.code,
                "data": base64.b64encode(result.data).decode(),
                "log": result.log,
                "gas_wanted": str(result.gas_wanted),
                "gas_used": str(result.gas_used),
            },
            "events": {k: [str(x) for x in v] for k, v in result_events.items()},
        }
        self._db.set(b"tx:" + h, json.dumps(record).encode())
        for key, values in result_events.items():
            for v in values:
                self._db.set(
                    b"txev:%s=%s:%016d:%08d" % (key.encode(), str(v).encode(), height, index),
                    h,
                )

    def get(self, h: bytes) -> dict | None:
        raw = self._db.get(b"tx:" + h)
        return json.loads(raw) if raw else None

    def search(self, query: str) -> list[dict]:
        """Condition-driven scan (kv.go match): supports key=value AND ... plus
        tx.height ranges via the pubsub Query semantics."""
        q = Query(query)
        results: list[dict] = []
        seen: set[bytes] = set()
        # tx.hash has a PRIMARY record, not a secondary event key: resolve
        # it directly — case-insensitively, and WITHOUT applying the other
        # conditions, exactly like the reference's hash fast path
        # (kv.go:211-224 returns the Get result unconditionally).
        hash_eq = next(
            (c for c in q.conditions if c.op == "=" and c.key == "tx.hash"), None
        )
        if hash_eq is not None:
            try:
                rec = self.get(bytes.fromhex(hash_eq.value))
            except ValueError:
                return []
            return [rec] if rec else []
        # Start from the first condition with a secondary index — tx.height
        # has none (it lives on the primary record), so it cannot drive the
        # scan.
        eq = next(
            (c for c in q.conditions if c.op == "=" and c.key != "tx.height"),
            None,
        )
        if eq is not None:
            prefix = b"txev:%s=%s:" % (eq.key.encode(), eq.value.encode())
            for _, h in self._db.iterator(prefix, prefix + b"\xff"):
                if h in seen:
                    continue
                seen.add(h)
                rec = self.get(h)
                if rec and self._matches(rec, q):
                    results.append(rec)
        else:
            for k, raw in self._db.iterator(b"tx:", b"tx;"):
                rec = json.loads(raw)
                if self._matches(rec, q):
                    results.append(rec)
        results.sort(key=lambda r: (int(r["height"]), r["index"]))
        return results

    def _matches(self, rec: dict, q: Query) -> bool:
        attrs = {
            "tx.hash": [rec["hash"]],
            "tx.height": [rec["height"]],
        }
        for key, values in rec.get("events", {}).items():
            attrs.setdefault(key, []).extend(values)
        # re-materialize indexed event attrs from secondary keys is expensive;
        # store them on the record instead (see index()).
        return q.matches(attrs)


class KVBlockIndexer:
    """state/indexer/block/kv/kv.go: block.height by event attributes."""

    def __init__(self, db: DB):
        self._db = db

    def index(self, height: int, events: dict) -> None:
        self._db.set(b"blk:%016d" % height, json.dumps(events).encode())
        for key, values in events.items():
            for v in values:
                self._db.set(
                    b"blkev:%s=%s:%016d" % (key.encode(), str(v).encode(), height), b"%d" % height
                )

    def search(self, query: str) -> list[int]:
        q = Query(query)
        heights = []
        for k, raw in self._db.iterator(b"blk:", b"blk;"):
            height = int(k.split(b":")[1])
            attrs = {"block.height": [str(height)]}
            for key, values in json.loads(raw).items():
                attrs.setdefault(key, []).extend(values)
            if q.matches(attrs):
                heights.append(height)
        return sorted(heights)


class NullTxIndexer:
    def index(self, *a, **k):
        pass

    def get(self, h):
        return None

    def search(self, query):
        return []


class IndexerService:
    """state/txindex/indexer_service.go: EventBus → indexers."""

    def __init__(self, tx_indexer, block_indexer, event_bus):
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.event_bus = event_bus
        self._running = False
        self._threads: list[threading.Thread] = []

    def start(self) -> None:
        self._running = True
        tx_sub = self.event_bus.subscribe("indexer-tx", ev.EVENT_QUERY_TX, 1000)
        hdr_sub = self.event_bus.subscribe(
            "indexer-hdr", ev.EVENT_QUERY_NEW_BLOCK_HEADER, 1000
        )

        def tx_pump():
            while self._running:
                try:
                    msg = tx_sub.out.get(timeout=0.25)
                except Exception:
                    continue
                d = msg.data
                rec_events = {
                    k: v for k, v in msg.events.items() if k != ev.EVENT_TYPE_KEY
                }
                self.tx_indexer.index(d.height, d.index, d.tx, d.result, rec_events)

        def hdr_pump():
            while self._running:
                try:
                    msg = hdr_sub.out.get(timeout=0.25)
                except Exception:
                    continue
                d = msg.data
                evs = {k: v for k, v in msg.events.items() if k != ev.EVENT_TYPE_KEY}
                self.block_indexer.index(d.header.height, evs)

        for target in (tx_pump, hdr_pump):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False

"""proxy.AppConns: the four named ABCI connections multiplexed over one
client (reference: proxy/app_conn.go:17-56, proxy/multi_app_conn.go).
"""

from __future__ import annotations

from cometbft_tpu.abci.client import Client, ClientCreator, LocalClientCreator


class AppConns:
    """proxy/multi_app_conn.go: consensus/mempool/query/snapshot connections."""

    def __init__(self, creator: ClientCreator):
        self._creator = creator
        self.consensus: Client | None = None
        self.mempool: Client | None = None
        self.query: Client | None = None
        self.snapshot: Client | None = None

    def start(self) -> None:
        self.query = self._creator.new_abci_client()
        self.snapshot = self._creator.new_abci_client()
        self.mempool = self._creator.new_abci_client()
        self.consensus = self._creator.new_abci_client()

    def stop(self) -> None:
        # multi_app_conn.OnStop: each connection owns a socket + reader
        # thread (socket/gRPC transports) that must be torn down, not
        # dropped — dropping leaks the thread and the app-side connection.
        for client in (self.consensus, self.mempool, self.query, self.snapshot):
            close = getattr(client, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
        self.consensus = self.mempool = self.query = self.snapshot = None


def new_app_conns(creator: ClientCreator) -> AppConns:
    conns = AppConns(creator)
    return conns


def local_client_creator(app) -> LocalClientCreator:
    return LocalClientCreator(app)

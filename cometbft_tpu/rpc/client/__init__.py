"""RPC client library (reference: rpc/client/{http,local,mock}).

HTTPClient speaks JSON-RPC 2.0 over HTTP POST to a node's RPC server;
LocalClient calls a node's route table in-process (rpc/client/local — zero
serialization overhead, used by the light proxy and tests); MockClient wraps
canned responses."""

from __future__ import annotations

import itertools
import json
import urllib.request


class RPCClientError(Exception):
    def __init__(self, code, message, data=None):
        self.code = code
        self.data = data
        super().__init__(f"RPC error {code}: {message} {data or ''}")


class BaseClient:
    """Route-method surface shared by all clients (rpc/client/interface.go)."""

    def call(self, method: str, **params):
        raise NotImplementedError

    # -- info ---------------------------------------------------------------
    def status(self):
        return self.call("status")

    def health(self):
        return self.call("health")

    def net_info(self):
        return self.call("net_info")

    def genesis(self):
        return self.call("genesis")

    def abci_info(self):
        return self.call("abci_info")

    def abci_query(self, path: str, data: bytes, height: int = 0, prove: bool = False):
        return self.call(
            "abci_query", path=path, data=data.hex(), height=str(height), prove=prove
        )

    # -- history ------------------------------------------------------------
    def block(self, height: int | None = None):
        return self.call("block", **_h(height))

    def block_by_hash(self, block_hash: bytes):
        return self.call("block_by_hash", hash="0x" + block_hash.hex())

    def block_results(self, height: int | None = None):
        return self.call("block_results", **_h(height))

    def commit(self, height: int | None = None):
        return self.call("commit", **_h(height))

    def header(self, height: int | None = None):
        return self.call("header", **_h(height))

    def blockchain(self, min_height: int, max_height: int):
        return self.call(
            "blockchain", minHeight=str(min_height), maxHeight=str(max_height)
        )

    def validators(self, height: int | None = None, page: int = 1, per_page: int = 30):
        return self.call(
            "validators", **_h(height), page=str(page), per_page=str(per_page)
        )

    def consensus_params(self, height: int | None = None):
        return self.call("consensus_params", **_h(height))

    def tx(self, tx_hash: bytes, prove: bool = False):
        return self.call("tx", hash="0x" + tx_hash.hex(), prove=prove)

    def tx_search(self, query: str, prove: bool = False, page: int = 1, per_page: int = 30):
        return self.call(
            "tx_search", query=query, prove=prove, page=str(page), per_page=str(per_page)
        )

    def block_search(self, query: str, page: int = 1, per_page: int = 30):
        return self.call("block_search", query=query, page=str(page), per_page=str(per_page))

    # -- tx submission -------------------------------------------------------
    def broadcast_tx_async(self, tx: bytes):
        return self.call("broadcast_tx_async", tx="0x" + tx.hex())

    def broadcast_tx_sync(self, tx: bytes):
        return self.call("broadcast_tx_sync", tx="0x" + tx.hex())

    def broadcast_tx_commit(self, tx: bytes):
        return self.call("broadcast_tx_commit", tx="0x" + tx.hex())

    def broadcast_evidence(self, ev):
        import base64

        from cometbft_tpu.types.evidence import encode_evidence

        raw = ev if isinstance(ev, (bytes, bytearray)) else encode_evidence(ev)
        return self.call("broadcast_evidence", evidence=base64.b64encode(bytes(raw)).decode())

    # -- consensus introspection ---------------------------------------------
    def consensus_state(self):
        return self.call("consensus_state")

    def dump_consensus_state(self):
        return self.call("dump_consensus_state")

    def unconfirmed_txs(self, limit: int = 30):
        return self.call("unconfirmed_txs", limit=str(limit))

    def num_unconfirmed_txs(self):
        return self.call("num_unconfirmed_txs")


def _h(height):
    return {} if height is None else {"height": str(height)}


class HTTPClient(BaseClient):
    """rpc/client/http: JSON-RPC 2.0 over HTTP POST."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        if base_url.startswith("tcp://"):
            base_url = "http://" + base_url[len("tcp://"):]
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._ids = itertools.count(1)

    def call(self, method: str, **params):
        body = json.dumps(
            {
                "jsonrpc": "2.0",
                "id": next(self._ids),
                "method": method,
                "params": params,
            }
        ).encode()
        req = urllib.request.Request(
            self.base_url,
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            payload = json.loads(resp.read())
        if payload.get("error"):
            err = payload["error"]
            raise RPCClientError(err.get("code"), err.get("message"), err.get("data"))
        return payload["result"]


class LocalClient(BaseClient):
    """rpc/client/local: direct route-table dispatch against a Node."""

    def __init__(self, routes_or_node):
        if hasattr(routes_or_node, "rpc_routes"):
            self._routes = routes_or_node.rpc_routes()
        else:
            self._routes = routes_or_node

    def call(self, method: str, **params):
        fn = self._routes.get(method)
        if fn is None:
            raise RPCClientError(-32601, f"method {method} not found")
        return fn(**params)


class MockClient(BaseClient):
    """rpc/client/mock: canned per-method results for tests."""

    def __init__(self, responses: dict):
        self.responses = responses
        self.calls = []

    def call(self, method: str, **params):
        self.calls.append((method, params))
        res = self.responses.get(method)
        if callable(res):
            return res(**params)
        if res is None:
            raise RPCClientError(-32601, f"no mock for {method}")
        return res

"""Minimal gRPC broadcast API (reference: rpc/grpc/types.proto service
BroadcastAPI + rpc/grpc/api.go): exactly two rpcs, Ping and BroadcastTx,
served when config.rpc.grpc_laddr is set (node/node.go startRPC's grpcListener
branch). BroadcastTx has BroadcastTxCommit semantics — CheckTx admission then
wait for the tx's DeliverTx in a committed block — which this server reuses
from the JSON-RPC route table so both surfaces stay behaviorally identical.

Same grpcio bytes-passthrough approach as abci/grpc.py: hand-encoded
gogoproto-compatible messages, no generated stubs.
"""

from __future__ import annotations

import base64
from concurrent import futures

import grpc

from cometbft_tpu.wire import proto as wire

_SERVICE = "tendermint.rpc.grpc.BroadcastAPI"


def _dec_request_broadcast_tx(data: bytes) -> bytes:
    f = wire.decode_fields(data)
    return wire.get_bytes(f, 1)


def _enc_response_broadcast_tx(check_tx: dict, deliver_tx: dict) -> bytes:
    """ResponseBroadcastTx{abci.ResponseCheckTx check_tx = 1;
    abci.ResponseDeliverTx deliver_tx = 2} from the JSON-RPC route's dict
    shapes (code int, data b64, log/codespace str, gas_* decimal strings)."""
    from cometbft_tpu.abci import types as abci
    from cometbft_tpu.abci import wire as abci_wire

    def _b64(v: str) -> bytes:
        return base64.b64decode(v) if v else b""

    ct = abci.ResponseCheckTx(
        code=int(check_tx.get("code", 0)),
        data=_b64(check_tx.get("data", "")),
        log=str(check_tx.get("log", "")),
        codespace=str(check_tx.get("codespace", "")),
    )
    dt = abci.ResponseDeliverTx(
        code=int(deliver_tx.get("code", 0)),
        data=_b64(deliver_tx.get("data", "")),
        log=str(deliver_tx.get("log", "")),
        gas_wanted=int(deliver_tx.get("gas_wanted", "0") or 0),
        gas_used=int(deliver_tx.get("gas_used", "0") or 0),
    )
    return wire.field_message(
        1, abci_wire._enc_resp_body(ct), emit_empty=True
    ) + wire.field_message(2, abci_wire._enc_resp_body(dt), emit_empty=True)


class GrpcBroadcastServer:
    """Serves Ping and BroadcastTx over gRPC against the node's JSON-RPC
    route table (the closures carry the Environment)."""

    def __init__(self, routes_map: dict, addr: str):
        self._routes = routes_map
        self.addr = addr
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers((_Handler(self),))
        self.bound: str | None = None

    def start(self) -> str:
        target = self.addr.split("://", 1)[-1]
        port = self._server.add_insecure_port(target)
        if port == 0:
            # grpcio reports bind failure by returning port 0 instead of
            # raising; fail fast so a node with an occupied grpc_laddr does
            # not come up "healthy" with no listener.
            raise OSError(f"cannot bind grpc broadcast server to {self.addr}")
        host = target.rsplit(":", 1)[0] or "127.0.0.1"
        self.bound = f"{host}:{port}"
        self._server.start()
        return self.bound

    def stop(self) -> None:
        self._server.stop(grace=0.2)

    def _broadcast_tx(self, raw_tx: bytes, context) -> bytes:
        try:
            res = self._routes["broadcast_tx_commit"](tx="0x" + raw_tx.hex())
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")
        return _enc_response_broadcast_tx(
            res.get("check_tx", {}), res.get("deliver_tx", {})
        )


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, server: GrpcBroadcastServer):
        self._server = server

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == f"/{_SERVICE}/Ping":
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: b"",  # ResponsePing{}
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )
        if method == f"/{_SERVICE}/BroadcastTx":
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._server._broadcast_tx(req, ctx),
                request_deserializer=_dec_request_broadcast_tx,
                response_serializer=lambda b: b,
            )
        return None


def broadcast_client(addr: str, connect_timeout: float = 10.0):
    """rpc/grpc/client.go StartGRPCClient analog: returns (ping, broadcast_tx)
    callables. broadcast_tx(tx bytes) -> (check_tx, deliver_tx) decoded
    field dicts."""
    from cometbft_tpu.abci import wire as abci_wire

    channel = grpc.insecure_channel(addr.split("://", 1)[-1])
    try:
        grpc.channel_ready_future(channel).result(timeout=connect_timeout)
    except grpc.FutureTimeoutError:
        channel.close()
        raise ConnectionError(f"cannot connect to grpc broadcast API at {addr}")
    ping_stub = channel.unary_unary(
        f"/{_SERVICE}/Ping",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )

    def _dec_resp(data: bytes):
        f = wire.decode_fields(data)
        ct = abci_wire._dec_resp_body("ResponseCheckTx", wire.get_bytes(f, 1))
        dt = abci_wire._dec_resp_body("ResponseDeliverTx", wire.get_bytes(f, 2))
        return ct, dt

    tx_stub = channel.unary_unary(
        f"/{_SERVICE}/BroadcastTx",
        request_serializer=lambda tx: wire.field_bytes(1, tx),
        response_deserializer=_dec_resp,
    )

    def ping() -> None:
        ping_stub(b"", timeout=connect_timeout)

    def broadcast_tx(tx: bytes):
        return tx_stub(tx, timeout=60.0)

    return ping, broadcast_tx

"""RPC / API layer (reference: rpc/, 8,640 LoC)."""

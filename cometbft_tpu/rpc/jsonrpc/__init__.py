"""JSON-RPC 2.0 transport (reference: rpc/jsonrpc/)."""

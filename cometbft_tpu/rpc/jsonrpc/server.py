"""JSON-RPC 2.0 server over HTTP POST, GET-with-query-args, and WebSocket
(reference: rpc/jsonrpc/server/http_json_handler.go, http_uri_handler.go,
ws_handler.go).

Stdlib-only: ThreadingHTTPServer + a minimal RFC 6455 WebSocket upgrade for
the subscription stream. Route functions receive (ctx, **params) and return
JSON-able dicts; errors map to JSON-RPC error objects.
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct
import threading
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str | None = None):
        self.code = code
        self.message = message
        self.data = data
        super().__init__(message)


PARSE_ERROR = -32700
INVALID_REQUEST = -32600
METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
INTERNAL_ERROR = -32603


class JSONRPCServer:
    """Serves a route table: {method_name: callable(ctx, **params)}."""

    def __init__(self, routes: dict, host: str = "127.0.0.1", port: int = 26657,
                 ws_manager=None, logger=None):
        self.routes = routes
        self.host = host
        self.port = port
        self.ws_manager = ws_manager
        self.logger = logger
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    response = server.handle_json_body(body, ws=None)
                except Exception:
                    response = _error_response(None, INTERNAL_ERROR, "internal error",
                                               traceback.format_exc())
                self._respond(response)

            def do_GET(self):
                if self.headers.get("Upgrade", "").lower() == "websocket":
                    server._handle_websocket(self)
                    return
                parsed = urllib.parse.urlparse(self.path)
                method = parsed.path.strip("/")
                if not method:
                    self._respond(_list_methods_html(server.routes))
                    return
                params = {
                    k: _coerce_uri_param(v[0])
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                response = server.handle_call(None, method, params, rpc_id=-1, ws=None)
                self._respond(response)

            def _respond(self, payload):
                if isinstance(payload, (dict, list)):
                    data = json.dumps(payload, indent=2).encode()
                    ctype = "application/json"
                else:
                    data = payload if isinstance(payload, bytes) else str(payload).encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        if self.port == 0:
            self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    # -- dispatch -------------------------------------------------------------

    def handle_json_body(self, body: bytes, ws):
        try:
            req = json.loads(body)
        except Exception:
            return _error_response(None, PARSE_ERROR, "parse error", None)
        if isinstance(req, list):
            return [self._handle_single(r, ws) for r in req]
        return self._handle_single(req, ws)

    def _handle_single(self, req: dict, ws):
        if not isinstance(req, dict):
            return _error_response(
                None, INVALID_REQUEST, "request must be an object", None
            )
        rpc_id = req.get("id")
        method = req.get("method", "")
        params = req.get("params")
        # None and the common client default `[]` both mean "no params";
        # non-empty positional lists are rejected below with the specific
        # INVALID_PARAMS message.
        params = {} if params is None or params == [] else params
        if not isinstance(method, str) or not isinstance(params, (dict, list)):
            return _error_response(rpc_id, INVALID_REQUEST, "malformed request", None)
        if isinstance(params, list):
            return _error_response(
                rpc_id, INVALID_PARAMS, "positional params not supported", None
            )
        return self.handle_call(None, method, params, rpc_id, ws)

    def handle_call(self, ctx, method: str, params: dict, rpc_id, ws):
        fn = self.routes.get(method)
        if fn is None:
            return _error_response(rpc_id, METHOD_NOT_FOUND, "method not found", method)
        try:
            if ws is not None:
                result = fn(ws=ws, **params) if _wants_ws(fn) else fn(**params)
            else:
                result = fn(**params)
            return {"jsonrpc": "2.0", "id": rpc_id, "result": result}
        except RPCError as e:
            return _error_response(rpc_id, e.code, e.message, e.data)
        except TypeError as e:
            return _error_response(rpc_id, INVALID_PARAMS, "invalid params", str(e))
        except Exception as e:
            return _error_response(rpc_id, INTERNAL_ERROR, str(e), traceback.format_exc())

    # -- websocket (rpc/jsonrpc/server/ws_handler.go) -------------------------

    def _handle_websocket(self, handler: BaseHTTPRequestHandler) -> None:
        key = handler.headers.get("Sec-WebSocket-Key", "")
        accept = base64.b64encode(
            hashlib.sha1((key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode()).digest()
        ).decode()
        handler.send_response(101, "Switching Protocols")
        handler.send_header("Upgrade", "websocket")
        handler.send_header("Connection", "Upgrade")
        handler.send_header("Sec-WebSocket-Accept", accept)
        handler.end_headers()
        conn = WSConnection(handler.connection, self)
        if self.ws_manager is not None:
            self.ws_manager.add(conn)
        try:
            conn.serve()
        finally:
            if self.ws_manager is not None:
                self.ws_manager.remove(conn)
            # The socket left websocket framing; letting the HTTP/1.1
            # keep-alive loop reparse leftover bytes as a request would pin
            # the thread on a dead (or hostile) connection.
            handler.close_connection = True
            try:
                handler.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


def _wants_ws(fn) -> bool:
    import inspect

    return "ws" in inspect.signature(fn).parameters


class WSConnection:
    """One websocket client: frame codec + outbound event queue."""

    def __init__(self, sock: socket.socket, server: JSONRPCServer):
        self.sock = sock
        self.server = server
        self.remote = f"{sock.getpeername()}"
        self._send_mtx = threading.Lock()
        self.open = True

    def serve(self) -> None:
        while self.open:
            msg = self._read_frame()
            if msg is None:
                break
            response = self.server.handle_json_body(msg, ws=self)
            self.send_json(response)

    def send_json(self, obj) -> None:
        self._write_frame(json.dumps(obj).encode())

    # Bound inbound frames: a header may CLAIM up to 2^64 bytes; reading it
    # would pin the connection thread and accumulate unbounded memory.
    MAX_FRAME = 16 * 1024 * 1024

    def _read_frame(self):
        try:
            hdr = self._read_exact(2)
            if hdr is None:
                return None
            b1, b2 = hdr
            opcode = b1 & 0x0F
            masked = b2 & 0x80
            length = b2 & 0x7F
            if length == 126:
                length = struct.unpack(">H", self._read_exact(2))[0]
            elif length == 127:
                length = struct.unpack(">Q", self._read_exact(8))[0]
            if length > self.MAX_FRAME:
                self.open = False
                return None
            mask = self._read_exact(4) if masked else b"\x00" * 4
            payload = bytearray(self._read_exact(length) or b"")
            for i in range(len(payload)):
                payload[i] ^= mask[i % 4]
            if opcode == 0x8:  # close
                self.open = False
                return None
            if opcode == 0x9:  # ping -> pong
                self._write_frame(bytes(payload), opcode=0xA)
                return self._read_frame()
            return bytes(payload)
        except Exception:
            self.open = False
            return None

    def _read_exact(self, n: int):
        data = b""
        while len(data) < n:
            chunk = self.sock.recv(n - len(data))
            if not chunk:
                return None
            data += chunk
        return data

    def _write_frame(self, payload: bytes, opcode: int = 0x1) -> None:
        with self._send_mtx:
            header = bytes([0x80 | opcode])
            ln = len(payload)
            if ln < 126:
                header += bytes([ln])
            elif ln < 1 << 16:
                header += bytes([126]) + struct.pack(">H", ln)
            else:
                header += bytes([127]) + struct.pack(">Q", ln)
            try:
                self.sock.sendall(header + payload)
            except Exception:
                self.open = False


def _error_response(rpc_id, code: int, message: str, data):
    err = {"code": code, "message": message}
    if data is not None:
        err["data"] = data
    return {"jsonrpc": "2.0", "id": rpc_id, "error": err}


class QuotedStr(str):
    """A URI param that arrived quoted. The reference's URI handler treats a
    quoted string for a []byte param as the RAW string bytes (not base64, as
    JSON-POST []byte params are) — rpc/jsonrpc/server/http_uri_handler.go."""


def _coerce_uri_param(v: str):
    """GET params arrive as strings; mimic the reference's URI param parsing
    (quoted strings, 0x-hex, bools, numbers)."""
    if v.startswith('"') and v.endswith('"'):
        return QuotedStr(v[1:-1])
    if v in ("true", "false"):
        return v == "true"
    return v


def _list_methods_html(routes: dict) -> bytes:
    items = "".join(f"<a href=\"/{m}\">/{m}</a></br>" for m in sorted(routes))
    return f"<html><body>Available endpoints:<br>{items}</body></html>".encode()

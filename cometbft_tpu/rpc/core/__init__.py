"""RPC core routes (reference: rpc/core/routes.go + rpc/core/*.go).

The Environment carries node handles (rpc/core/env.go:199); `routes(env)`
builds the 30+ method table served by the JSON-RPC server. JSON shapes
mirror the reference's response objects (heights as strings, hashes as
upper-hex, bytes base64 where the reference uses base64).
"""

from __future__ import annotations

import base64
from dataclasses import dataclass, field as dfield

from cometbft_tpu.rpc.jsonrpc.server import RPCError
from cometbft_tpu.types import cmttime
from cometbft_tpu.types.events import (
    EVENT_TYPE_KEY,
    EventBus,
)
from cometbft_tpu.libs.pubsub import Query


@dataclass
class Environment:
    """rpc/core/env.go Environment: every handle RPC needs."""

    config: object = None
    state_store: object = None
    block_store: object = None
    consensus_state: object = None
    consensus_reactor: object = None  # peer round-state introspection
    mempool: object = None
    ingress: object = None  # IngressPipeline when QoS admission is wired
    evidence_pool: object = None
    event_bus: EventBus | None = None
    genesis_doc: object = None
    priv_validator_pub_key: object = None
    node_info: dict = dfield(default_factory=dict)
    tx_indexer: object = None
    block_indexer: object = None
    proxy_app_query: object = None
    p2p_peers: object = None  # switch-like: .peers() / .node_info()
    # Light-client gateway accessor: a zero-arg callable returning the
    # node's LightGateway (constructing it on first use) or None when
    # disabled — lazy so serving unrelated RPC never builds the gateway.
    light_gateway: object = None
    # Checkpoint-bundle origin accessor: callable(build=True) returning
    # the node's BundleOrigin (build=False peeks without constructing) or
    # None when CMTPU_BUNDLE=0.
    bundle_origin: object = None
    is_listening: bool = True


def _hexu(b: bytes) -> str:
    return b.hex().upper()


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _block_id_json(bid) -> dict:
    return {
        "hash": _hexu(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": _hexu(bid.part_set_header.hash),
        },
    }


def _header_json(h) -> dict:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": h.time.rfc3339(),
        "last_block_id": _block_id_json(h.last_block_id),
        "last_commit_hash": _hexu(h.last_commit_hash),
        "data_hash": _hexu(h.data_hash),
        "validators_hash": _hexu(h.validators_hash),
        "next_validators_hash": _hexu(h.next_validators_hash),
        "consensus_hash": _hexu(h.consensus_hash),
        "app_hash": _hexu(h.app_hash),
        "last_results_hash": _hexu(h.last_results_hash),
        "evidence_hash": _hexu(h.evidence_hash),
        "proposer_address": _hexu(h.proposer_address),
    }


def _commit_json(c) -> dict:
    out = {
        "height": str(c.height),
        "round": c.round,
        "block_id": _block_id_json(c.block_id),
        "signatures": [
            {
                "block_id_flag": s.block_id_flag,
                "validator_address": _hexu(s.validator_address),
                "timestamp": s.timestamp.rfc3339(),
                "signature": _b64(s.signature) if s.signature else None,
            }
            for s in c.signatures
        ],
    }
    if c.agg_signature:
        out["agg_signature"] = _b64(c.agg_signature)
        out["agg_bitmap"] = _b64(c.agg_bitmap)
    return out


def _block_json(b) -> dict:
    from cometbft_tpu.types.evidence import encode_evidence

    return {
        "header": _header_json(b.header),
        "data": {"txs": [_b64(tx) for tx in b.data.txs]},
        "evidence": {"evidence": [len(b.evidence) and None or None] and []},
        "last_commit": _commit_json(b.last_commit) if b.last_commit else None,
    }


def _validator_json(v) -> dict:
    return {
        "address": _hexu(v.address),
        "pub_key": {
            "type": "tendermint/PubKeyEd25519",
            "value": _b64(v.pub_key.bytes()),
        },
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }


def routes(env: Environment) -> dict:
    """rpc/core/routes.go: the 31-route table."""

    # ---- info routes -------------------------------------------------------

    def health():
        return {}

    def status():
        """rpc/core/status.go."""
        bs = env.block_store
        latest_height = bs.height() if bs else 0
        latest_meta = bs.load_block_meta(latest_height) if latest_height else None
        pub = env.priv_validator_pub_key
        val_info = {}
        if pub is not None:
            val_info = {
                "address": _hexu(pub.address()),
                "pub_key": {
                    "type": "tendermint/PubKeyEd25519",
                    "value": _b64(pub.bytes()),
                },
                "voting_power": "0",
            }
            if env.consensus_state is not None:
                vals = env.consensus_state.rs.validators
                if vals is not None:
                    _, val = vals.get_by_address(pub.address())
                    if val:
                        val_info["voting_power"] = str(val.voting_power)
        return {
            "node_info": env.node_info,
            "sync_info": {
                "latest_block_hash": _hexu(latest_meta.block_id.hash) if latest_meta else "",
                "latest_app_hash": _hexu(latest_meta.header.app_hash) if latest_meta else "",
                "latest_block_height": str(latest_height),
                "latest_block_time": latest_meta.header.time.rfc3339() if latest_meta else "",
                "earliest_block_height": str(bs.base() if bs else 0),
                "catching_up": False,
            },
            "validator_info": val_info,
        }

    def net_info():
        peers = env.p2p_peers.peers() if env.p2p_peers else []
        return {
            "listening": env.is_listening,
            "listeners": [],
            "n_peers": str(len(peers)),
            "peers": [
                {
                    "node_info": getattr(p, "node_info_json", lambda: {})(),
                    "is_outbound": getattr(p, "is_outbound", False),
                    "remote_ip": getattr(p, "remote_ip", ""),
                }
                for p in peers
            ],
        }

    def genesis():
        import json as _json

        return {"genesis": _json.loads(env.genesis_doc.to_json())}

    def genesis_chunked(chunk="0"):
        import json as _json

        data = env.genesis_doc.to_json().encode()
        chunk_size = 16 * 1024 * 1024
        chunks = [data[i : i + chunk_size] for i in range(0, len(data), chunk_size)] or [b""]
        idx = int(chunk)
        if idx < 0 or idx >= len(chunks):
            raise RPCError(INTERNAL := -32603, f"there are {len(chunks)} chunks", None)
        return {"chunk": str(idx), "total": str(len(chunks)), "data": _b64(chunks[idx])}

    # ---- block routes ------------------------------------------------------

    def _normalize_height(height) -> int:
        bs = env.block_store
        if height is None or height == "":
            return bs.height()
        h = int(height)
        if h <= 0:
            raise RPCError(-32603, "height must be greater than 0", None)
        if h > bs.height():
            raise RPCError(
                -32603,
                f"height {h} must be less than or equal to the current blockchain height {bs.height()}",
                None,
            )
        if h < bs.base():
            raise RPCError(
                -32603, f"height {h} is not available, lowest height is {bs.base()}", None
            )
        return h

    def block(height=None):
        h = _normalize_height(height)
        blk = env.block_store.load_block(h)
        meta = env.block_store.load_block_meta(h)
        if blk is None:
            return {"block_id": None, "block": None}
        return {"block_id": _block_id_json(meta.block_id), "block": _block_json(blk)}

    def block_by_hash(hash=""):
        raw = _parse_hash(hash)
        blk = env.block_store.load_block_by_hash(raw)
        if blk is None:
            return {"block_id": None, "block": None}
        meta = env.block_store.load_block_meta(blk.header.height)
        return {"block_id": _block_id_json(meta.block_id), "block": _block_json(blk)}

    def header(height=None):
        h = _normalize_height(height)
        meta = env.block_store.load_block_meta(h)
        return {"header": _header_json(meta.header) if meta else None}

    def header_by_hash(hash=""):
        raw = _parse_hash(hash)
        blk = env.block_store.load_block_by_hash(raw)
        return {"header": _header_json(blk.header) if blk else None}

    def commit(height=None):
        h = _normalize_height(height)
        meta = env.block_store.load_block_meta(h)
        if meta is None:
            return {"signed_header": None, "canonical": False}
        if h == env.block_store.height():
            c = env.block_store.load_seen_commit(h)
            canonical = False
        else:
            c = env.block_store.load_block_commit(h)
            canonical = True
        return {
            "signed_header": {
                "header": _header_json(meta.header),
                "commit": _commit_json(c) if c else None,
            },
            "canonical": canonical,
        }

    def block_results(height=None):
        h = _normalize_height(height)
        resp = env.state_store.load_abci_responses(h)
        if resp is None:
            raise RPCError(-32603, f"could not find results for height #{h}", None)
        return {
            "height": str(h),
            "txs_results": resp.get("deliver_txs", []),
            "begin_block_events": [],
            "end_block_events": [],
            "validator_updates": [],
            "consensus_param_updates": None,
        }

    def blockchain(minHeight=None, maxHeight=None):
        """rpc/core/blocks.go BlockchainInfo: metas in [min, max], newest first,
        max 20."""
        bs = env.block_store
        max_h = int(maxHeight) if maxHeight else bs.height()
        max_h = min(max_h, bs.height())
        min_h = int(minHeight) if minHeight else max(1, max_h - 19)
        min_h = max(min_h, bs.base())
        min_h = max(min_h, max_h - 19)
        if min_h > max_h:
            raise RPCError(
                -32603, f"min height {min_h} can't be greater than max height {max_h}", None
            )
        metas = []
        for h in range(max_h, min_h - 1, -1):
            m = bs.load_block_meta(h)
            if m:
                metas.append(
                    {
                        "block_id": _block_id_json(m.block_id),
                        "block_size": str(m.block_size),
                        "header": _header_json(m.header),
                        "num_txs": str(m.num_txs),
                    }
                )
        return {"last_height": str(bs.height()), "block_metas": metas}

    def validators(height=None, page="1", per_page="30"):
        h = _normalize_height(height)
        vals = env.state_store.load_validators(h)
        page_i, per_page_i = max(1, int(page)), min(100, max(1, int(per_page)))
        start = (page_i - 1) * per_page_i
        sel = vals.validators[start : start + per_page_i]
        return {
            "block_height": str(h),
            "validators": [_validator_json(v) for v in sel],
            "count": str(len(sel)),
            "total": str(vals.size()),
        }

    def consensus_params(height=None):
        h = _normalize_height(height)
        p = env.state_store.load_consensus_params(h)
        return {
            "block_height": str(h),
            "consensus_params": {
                "block": {"max_bytes": str(p.block.max_bytes), "max_gas": str(p.block.max_gas)},
                "evidence": {
                    "max_age_num_blocks": str(p.evidence.max_age_num_blocks),
                    "max_age_duration": str(p.evidence.max_age_duration_ns),
                    "max_bytes": str(p.evidence.max_bytes),
                },
                "validator": {"pub_key_types": list(p.validator.pub_key_types)},
                "version": {"app": str(p.version.app)},
            },
        }

    def dump_consensus_state():
        from cometbft_tpu.consensus.cstypes import STEP_NAMES

        cs = env.consensus_state
        rs = cs.rs
        # Per-round vote-set bitmaps up to the live round: the stall
        # forensics dump — which validators' votes each node holds per
        # round — is what makes a round-livelock diagnosable from a
        # repro.json alone (rpc/core/consensus.go DumpConsensusState).
        votes = []
        if rs.votes is not None:
            for r in range(rs.round + 1):
                pv = rs.votes.prevotes(r)
                pc = rs.votes.precommits(r)
                votes.append(
                    {
                        "round": r,
                        "prevotes_bit_array": repr(pv.bit_array()) if pv else "",
                        "precommits_bit_array": repr(pc.bit_array()) if pc else "",
                    }
                )
        peers = []
        reactor = env.consensus_reactor
        if reactor is not None:
            for peer_id, ps in list(
                getattr(reactor, "peer_states", {}).items()
            ):
                peers.append(
                    {
                        "node_address": peer_id,
                        "peer_state": {
                            "height": str(ps.height),
                            "round": ps.round,
                            "step": STEP_NAMES.get(ps.step, ps.step),
                            "proposal": ps.proposal,
                            "proposal_pol_round": ps.proposal_pol_round,
                        },
                    }
                )
        return {
            "round_state": {
                "height": str(rs.height),
                "round": rs.round,
                "step": rs.step,
                "step_name": STEP_NAMES.get(rs.step, str(rs.step)),
                "start_time": rs.start_time.rfc3339(),
                "proposal_block_hash": _hexu(rs.proposal_block.hash()) if rs.proposal_block else "",
                "locked_block_hash": _hexu(rs.locked_block.hash()) if rs.locked_block else "",
                "locked_round": rs.locked_round,
                "valid_block_hash": _hexu(rs.valid_block.hash()) if rs.valid_block else "",
                "valid_round": rs.valid_round,
                "height_vote_set": votes,
                "validators": {
                    "validators": [_validator_json(v) for v in rs.validators.validators]
                    if rs.validators
                    else [],
                },
            },
            # Stall forensics: a proposal that arrives on time at the
            # switch but seconds late at the state machine shows up here
            # as a deep message queue.
            "msg_queue_depth": cs._queue.qsize(),
            "peers": peers,
            # Accountability forensics: same counters as the evidence_*
            # gauges, so a soak assertion and a live dump read one source.
            "evidence_stats": (
                env.evidence_pool.stats_snapshot()
                if env.evidence_pool is not None else None
            ),
        }

    def consensus_state():
        cs = env.consensus_state
        rs = cs.rs
        return {
            "round_state": {
                "height/round/step": f"{rs.height}/{rs.round}/{rs.step}",
                "start_time": rs.start_time.rfc3339(),
                "proposal_block_hash": _hexu(rs.proposal_block.hash()) if rs.proposal_block else "",
                "locked_block_hash": _hexu(rs.locked_block.hash()) if rs.locked_block else "",
                "valid_block_hash": _hexu(rs.valid_block.hash()) if rs.valid_block else "",
            }
        }

    # ---- tx routes ---------------------------------------------------------

    def _decode_tx_param(tx) -> bytes:
        from cometbft_tpu.rpc.jsonrpc.server import QuotedStr

        if isinstance(tx, (bytes, bytearray)):
            return bytes(tx)
        if isinstance(tx, QuotedStr):
            # URI `tx="k1=v1"`: raw string bytes (http_uri_handler.go).
            return str(tx).encode()
        if isinstance(tx, str):
            if tx.startswith("0x"):
                return bytes.fromhex(tx[2:])
            return base64.b64decode(tx)
        raise RPCError(-32602, "invalid tx param", None)

    def broadcast_tx_async(tx=""):
        raw = _decode_tx_param(tx)
        env.mempool.check_tx(raw)
        from cometbft_tpu.types.tx import tx_hash

        return {"code": 0, "data": "", "log": "", "codespace": "", "hash": _hexu(tx_hash(raw))}

    def broadcast_tx_sync(tx=""):
        raw = _decode_tx_param(tx)
        result = {}
        done = __import__("threading").Event()

        def cb(res):
            result["res"] = res
            done.set()

        env.mempool.check_tx(raw, callback=cb)
        # Same deadline source as broadcast_tx_commit (config/config.go
        # TimeoutBroadcastTxCommit) instead of a hard-coded 5s.
        timeout = (
            env.config.rpc.timeout_broadcast_tx_commit if env.config else 10.0
        )
        if not done.wait(timeout):
            raise RPCError(
                -32603,
                f"timed out waiting for tx to be included in the mempool "
                f"(after {timeout}s)",
                None,
            )
        res = result["res"]
        from cometbft_tpu.types.tx import tx_hash

        return {
            "code": res.code,
            "data": _b64(res.data),
            "log": res.log,
            "codespace": res.codespace,
            "hash": _hexu(tx_hash(raw)),
        }

    def broadcast_tx_commit(tx=""):
        """rpc/core/mempool.go BroadcastTxCommit: subscribe to EventTx, submit,
        wait for DeliverTx."""
        import queue as _q

        raw = _decode_tx_param(tx)
        from cometbft_tpu.types.tx import tx_hash

        txh = tx_hash(raw)
        q = Query(f"{EVENT_TYPE_KEY}='Tx' AND tx.hash='{_hexu(txh)}'")
        sub = env.event_bus.subscribe(f"mempool-{_hexu(txh)[:16]}", q, 16)
        try:
            sync_res = broadcast_tx_sync(tx=tx)
            if sync_res["code"] != 0:
                return {
                    "check_tx": sync_res,
                    "deliver_tx": {},
                    "hash": _hexu(txh),
                    "height": "0",
                }
            timeout = env.config.rpc.timeout_broadcast_tx_commit if env.config else 10.0
            try:
                msg = sub.out.get(timeout=timeout)
                data = msg.data
                return {
                    "check_tx": sync_res,
                    "deliver_tx": {
                        "code": data.result.code,
                        "data": _b64(data.result.data),
                        "log": data.result.log,
                        "gas_wanted": str(data.result.gas_wanted),
                        "gas_used": str(data.result.gas_used),
                    },
                    "hash": _hexu(txh),
                    "height": str(data.height),
                }
            except _q.Empty:
                raise RPCError(-32603, "timed out waiting for tx to be included in a block", None)
        finally:
            try:
                env.event_bus.unsubscribe(f"mempool-{_hexu(txh)[:16]}", q)
            except Exception:
                pass

    def unconfirmed_txs(limit="30"):
        txs = env.mempool.reap_max_txs(int(limit))
        return {
            "n_txs": str(len(txs)),
            "total": str(env.mempool.size()),
            "total_bytes": str(env.mempool.size_bytes()),
            "txs": [_b64(t) for t in txs],
        }

    def num_unconfirmed_txs():
        return {
            "n_txs": str(env.mempool.size()),
            "total": str(env.mempool.size()),
            "total_bytes": str(env.mempool.size_bytes()),
        }

    def check_tx(tx=""):
        raw = _decode_tx_param(tx)
        from cometbft_tpu.abci import types as abci

        res = env.proxy_app_query.check_tx(abci.RequestCheckTx(tx=raw))
        return {"code": res.code, "data": _b64(res.data), "log": res.log,
                "gas_wanted": str(res.gas_wanted)}

    def ingress_stats():
        """QoS ingress counters (admission/rejection/shed/preverify) for
        operators and the e2e tx_flood perturbation's delta checks."""
        if env.ingress is None:
            return {"enabled": False}
        return {"enabled": True, **env.ingress.stats()}

    def recvq_stats():
        """Recv-demux counters (per-class deliveries, sheds, promotions,
        queue depth) aggregated across peer connections — operators and
        the e2e recv_flood perturbation's delta checks."""
        fn = getattr(env.p2p_peers, "recvq_stats", None)
        if fn is None:
            return {"enabled": False}
        return fn()

    # ---- light-client gateway (light/gateway.py) ---------------------------

    def _light_gateway():
        accessor = env.light_gateway
        g = accessor() if callable(accessor) else accessor
        if g is None:
            raise RPCError(-32603, "light gateway disabled", None)
        return g

    def light_sync(trusted_height="0", target_height="0"):
        """Descent plan (pivot + target light blocks, wire-encoded) for a
        skipping verification the CLIENT re-runs locally — the gateway is
        an untrusted accelerator, never an arbiter."""
        from cometbft_tpu.light.gateway import GatewayError

        g = _light_gateway()
        try:
            blocks = g.sync_plan(int(trusted_height), int(target_height))
        except GatewayError as e:
            raise RPCError(-32603, f"light_sync: {e}", None)
        return {
            "heights": [str(b.height) for b in blocks],
            "blocks": [_b64(b.encode()) for b in blocks],
        }

    def light_proof(height="0", anchor_height="0"):
        """Target light block + MMR inclusion proofs for the target header
        and the caller's trust anchor under one accumulator root."""
        from cometbft_tpu.light.gateway import GatewayError

        g = _light_gateway()
        try:
            p = g.prove(int(height), anchor_height=int(anchor_height))
        except GatewayError as e:
            raise RPCError(-32603, f"light_proof: {e}", None)
        out = {
            "size": str(p["size"]),
            "root": _hexu(p["root"]),
            "light_block": _b64(p["light_block"].encode()),
            "target": {
                "index": str(p["target"]["index"]),
                "aunts": [_hexu(a) for a in p["target"]["aunts"]],
            },
            "proof_bytes": str(p["bytes"]),
        }
        if "anchor" in p:
            out["anchor"] = {
                "index": str(p["anchor"]["index"]),
                "aunts": [_hexu(a) for a in p["anchor"]["aunts"]],
            }
        return out

    def light_bundle(height="0"):
        """Latest checkpoint bundle at or below `height` (0 = newest),
        content-addressed: `name` is the hex SHA-256 of the returned
        bytes, so any cache between this origin and the client is
        verifiable end-to-end."""
        from cometbft_tpu.light.bundle import BundleError

        accessor = env.bundle_origin
        o = accessor() if callable(accessor) else accessor
        if o is None:
            return {"enabled": False}
        try:
            name, data, boundary = o.get_encoded(int(height))
        except BundleError as e:
            raise RPCError(-32603, f"light_bundle: {e}", None)
        return {
            "enabled": True,
            "name": name,
            "height": str(boundary),
            "bundle": _b64(data),
        }

    def light_gateway_stats():
        """Gateway counters (sessions, plan cache, proofs) for operators
        and the e2e swarm perturbations' delta checks.  Bundle-origin
        counters ride along when the origin already exists — peeked, not
        built: a stats scrape never constructs the origin."""
        accessor = env.light_gateway
        g = accessor() if callable(accessor) else accessor
        if g is None:
            out = {"enabled": False}
        else:
            out = {"enabled": True, **g.stats()}
        peek = env.bundle_origin
        o = peek(build=False) if callable(peek) else None
        if o is not None:
            out["bundle"] = o.stats()
        return out

    def tx(hash="", prove=False):
        if env.tx_indexer is None:
            raise RPCError(-32603, "transaction indexing is disabled", None)
        raw = _parse_hash(hash)
        res = env.tx_indexer.get(raw)
        if res is None:
            raise RPCError(-32603, f"tx ({_hexu(raw)}) not found", None)
        out = dict(res)
        if prove:
            from cometbft_tpu.types.tx import txs_proof

            blk = env.block_store.load_block(int(out["height"]))
            idx = int(out["index"])
            proof = txs_proof(blk.data.txs, idx)
            out["proof"] = {
                "root_hash": _hexu(proof.root_hash),
                "data": _b64(proof.data),
                "proof": proof.proof.to_proto(),
            }
        return out

    def tx_search(query="", prove=False, page="1", per_page="30", order_by="asc"):
        if env.tx_indexer is None:
            raise RPCError(-32603, "transaction indexing is disabled", None)
        results = env.tx_indexer.search(query)
        if order_by == "desc":
            results = list(reversed(results))
        page_i, per_page_i = max(1, int(page)), min(100, max(1, int(per_page)))
        start = (page_i - 1) * per_page_i
        sel = results[start : start + per_page_i]
        return {"txs": sel, "total_count": str(len(results))}

    def block_search(query="", page="1", per_page="30", order_by="asc"):
        if env.block_indexer is None:
            raise RPCError(-32603, "block indexing is disabled", None)
        heights = env.block_indexer.search(query)
        if order_by == "desc":
            heights = list(reversed(heights))
        page_i, per_page_i = max(1, int(page)), min(100, max(1, int(per_page)))
        sel = heights[(page_i - 1) * per_page_i :][:per_page_i]
        blocks = []
        for h in sel:
            m = env.block_store.load_block_meta(h)
            blk = env.block_store.load_block(h)
            if m and blk:
                blocks.append({"block_id": _block_id_json(m.block_id), "block": _block_json(blk)})
        return {"blocks": blocks, "total_count": str(len(heights))}

    # ---- abci --------------------------------------------------------------

    def abci_info():
        from cometbft_tpu.abci import types as abci

        res = env.proxy_app_query.info(abci.RequestInfo())
        return {
            "response": {
                "data": res.data,
                "version": res.version,
                "app_version": str(res.app_version),
                "last_block_height": str(res.last_block_height),
                "last_block_app_hash": _b64(res.last_block_app_hash),
            }
        }

    def abci_query(path="", data="", height="0", prove=False):
        from cometbft_tpu.abci import types as abci

        raw = bytes.fromhex(data[2:]) if isinstance(data, str) and data.startswith("0x") else (
            bytes.fromhex(data) if isinstance(data, str) else bytes(data)
        )
        res = env.proxy_app_query.query(
            abci.RequestQuery(data=raw, path=path, height=int(height), prove=bool(prove))
        )
        out = {
            "response": {
                "code": res.code,
                "log": res.log,
                "info": res.info,
                "index": str(res.index),
                "key": _b64(res.key),
                "value": _b64(res.value),
                "height": str(res.height),
                "codespace": res.codespace,
            }
        }
        if res.proof_ops:
            out["response"]["proofOps"] = {
                "ops": [
                    {"type": op.type, "key": _b64(op.key), "data": _b64(op.data)}
                    for op in res.proof_ops
                ]
            }
        return out

    # ---- evidence ----------------------------------------------------------

    def broadcast_evidence(evidence=""):
        from cometbft_tpu.types.evidence import decode_evidence

        raw = base64.b64decode(evidence) if isinstance(evidence, str) else bytes(evidence)
        ev = decode_evidence(raw)
        env.evidence_pool.add_evidence(ev)
        return {"hash": _hexu(ev.hash())}

    # ---- events (websocket) ------------------------------------------------

    def subscribe(query="", ws=None):
        """rpc/core/events.go Subscribe — websocket-only."""
        if ws is None:
            raise RPCError(-32603, "subscribe requires a websocket connection", None)
        q = Query(query)
        sub = env.event_bus.subscribe(ws.remote, q, 100)

        import threading as _t

        def pump():
            while ws.open and not sub.canceled.is_set():
                try:
                    msg = sub.out.get(timeout=0.25)
                except Exception:
                    continue
                ws.send_json(
                    {
                        "jsonrpc": "2.0",
                        "id": f"{query}#event",
                        "result": {
                            "query": query,
                            "data": {"type": _event_type(msg), "value": _event_value(msg)},
                            "events": msg.events,
                        },
                    }
                )

        _t.Thread(target=pump, daemon=True).start()
        return {}

    def unsubscribe(query="", ws=None):
        if ws is None:
            raise RPCError(-32603, "unsubscribe requires a websocket connection", None)
        env.event_bus.unsubscribe(ws.remote, Query(query))
        return {}

    def unsubscribe_all(ws=None):
        if ws is None:
            raise RPCError(-32603, "unsubscribe_all requires a websocket connection", None)
        env.event_bus.unsubscribe_all(ws.remote)
        return {}

    table = {
        "health": health,
        "status": status,
        "net_info": net_info,
        "genesis": genesis,
        "genesis_chunked": genesis_chunked,
        "blockchain": blockchain,
        "block": block,
        "block_by_hash": block_by_hash,
        "header": header,
        "header_by_hash": header_by_hash,
        "block_results": block_results,
        "commit": commit,
        "validators": validators,
        "consensus_params": consensus_params,
        "dump_consensus_state": dump_consensus_state,
        "consensus_state": consensus_state,
        "unconfirmed_txs": unconfirmed_txs,
        "num_unconfirmed_txs": num_unconfirmed_txs,
        "tx": tx,
        "tx_search": tx_search,
        "block_search": block_search,
        "broadcast_tx_async": broadcast_tx_async,
        "broadcast_tx_sync": broadcast_tx_sync,
        "broadcast_tx_commit": broadcast_tx_commit,
        "check_tx": check_tx,
        "ingress_stats": ingress_stats,
        "recvq_stats": recvq_stats,
        "light_sync": light_sync,
        "light_proof": light_proof,
        "light_bundle": light_bundle,
        "light_gateway_stats": light_gateway_stats,
        "abci_info": abci_info,
        "abci_query": abci_query,
        "broadcast_evidence": broadcast_evidence,
        "subscribe": subscribe,
        "unsubscribe": unsubscribe,
        "unsubscribe_all": unsubscribe_all,
    }

    # ---- unsafe dev routes (routes.go AddUnsafeRoutes, rpc/core/dev.go +
    # net.go UnsafeDialSeeds/UnsafeDialPeers) — only with config.rpc.unsafe.
    if getattr(getattr(env.config, "rpc", None), "unsafe", False):

        def dial_seeds(seeds=()):
            if env.p2p_peers is None:
                raise RPCError(-32603, "p2p layer unavailable", None)
            for s in seeds:
                env.p2p_peers.dial_peer(s)
            return {"log": "Dialing seeds in progress. See /net_info for details"}

        def dial_peers(peers=(), persistent=False, **_kw):
            if env.p2p_peers is None:
                raise RPCError(-32603, "p2p layer unavailable", None)
            for p in peers:
                if persistent:
                    env.p2p_peers.add_persistent_peers([p])
                env.p2p_peers.dial_peer(p)
            return {"log": "Dialing peers in progress. See /net_info for details"}

        def unsafe_flush_mempool():
            env.mempool.flush()
            return {}

        table["dial_seeds"] = dial_seeds
        table["dial_peers"] = dial_peers
        table["unsafe_flush_mempool"] = unsafe_flush_mempool
    return table


def _parse_hash(h) -> bytes:
    if isinstance(h, (bytes, bytearray)):
        return bytes(h)
    if isinstance(h, str):
        if h.startswith("0x"):
            return bytes.fromhex(h[2:])
        try:
            return bytes.fromhex(h)
        except ValueError:
            return base64.b64decode(h)
    raise RPCError(-32602, "invalid hash param", None)


def _event_type(msg) -> str:
    types = msg.events.get(EVENT_TYPE_KEY, [])
    return f"tendermint/event/{types[0]}" if types else ""


def _event_value(msg):
    data = msg.data
    if hasattr(data, "height") and hasattr(data, "tx"):
        return {
            "TxResult": {
                "height": str(data.height),
                "index": data.index,
                "tx": base64.b64encode(data.tx).decode(),
                "result": {"code": data.result.code, "log": data.result.log},
            }
        }
    if hasattr(data, "block"):
        blk = data.block
        return {"block": {"header": _header_json(blk.header)}} if blk else {}
    return {}

"""JSON <-> types decoding for RPC payloads (the inverse of rpc/core's
serializers; reference shape: rpc/core/types/responses.go + types JSON).

Used by the RPC client library and the light client's HTTP provider."""

from __future__ import annotations

import base64

from cometbft_tpu.types.block import (
    BlockID,
    Commit,
    CommitSig,
    Consensus,
    Header,
    PartSetHeader,
    SignedHeader,
)
from cometbft_tpu.types.cmttime import Time


def _hx(s: str | None) -> bytes:
    return bytes.fromhex(s) if s else b""


def block_id_from_json(d: dict | None) -> BlockID:
    if not d:
        return BlockID()
    parts = d.get("parts") or {}
    return BlockID(
        hash=_hx(d.get("hash")),
        part_set_header=PartSetHeader(
            total=int(parts.get("total", 0)), hash=_hx(parts.get("hash"))
        ),
    )


def header_from_json(d: dict) -> Header:
    ver = d.get("version") or {}
    return Header(
        version=Consensus(int(ver.get("block", 0)), int(ver.get("app", 0))),
        chain_id=d.get("chain_id", ""),
        height=int(d.get("height", 0)),
        time=Time.parse_rfc3339(d["time"]) if d.get("time") else Time(),
        last_block_id=block_id_from_json(d.get("last_block_id")),
        last_commit_hash=_hx(d.get("last_commit_hash")),
        data_hash=_hx(d.get("data_hash")),
        validators_hash=_hx(d.get("validators_hash")),
        next_validators_hash=_hx(d.get("next_validators_hash")),
        consensus_hash=_hx(d.get("consensus_hash")),
        app_hash=_hx(d.get("app_hash")),
        last_results_hash=_hx(d.get("last_results_hash")),
        evidence_hash=_hx(d.get("evidence_hash")),
        proposer_address=_hx(d.get("proposer_address")),
    )


def commit_from_json(d: dict) -> Commit:
    sigs = []
    for s in d.get("signatures", []):
        sigs.append(
            CommitSig(
                block_id_flag=int(s.get("block_id_flag", 1)),
                validator_address=_hx(s.get("validator_address")),
                timestamp=(
                    Time.parse_rfc3339(s["timestamp"]) if s.get("timestamp") else Time()
                ),
                signature=(
                    base64.b64decode(s["signature"]) if s.get("signature") else b""
                ),
            )
        )
    return Commit(
        height=int(d.get("height", 0)),
        round=int(d.get("round", 0)),
        block_id=block_id_from_json(d.get("block_id")),
        signatures=sigs,
        agg_signature=(
            base64.b64decode(d["agg_signature"]) if d.get("agg_signature") else b""
        ),
        agg_bitmap=(
            base64.b64decode(d["agg_bitmap"]) if d.get("agg_bitmap") else b""
        ),
    )


def signed_header_from_json(d: dict) -> SignedHeader:
    return SignedHeader(
        header=header_from_json(d["header"]),
        commit=commit_from_json(d["commit"]),
    )
